"""Telemetry export: JSONL event stream + Prometheus text exposition.

The JSONL stream extends ``PhaseLogger``'s sidecar grammar — every line
is ``{"event": <name>, "t": <monotonic seconds>, **fields}`` — so a
run's obs stream and its phase log speak the same dialect and a single
reader (:func:`read_events`) serves both.  Obs-specific events:

* ``obs_goodput``  — a goodput breakdown (``scope``: phase label or
  ``"run"``), fields from ``Timeline.goodput()``.
* ``obs_mfu``      — an ``mfu.mfu_record`` dict.
* ``obs_snapshot`` — a full ``MetricsRegistry.snapshot()``.
* ``obs_serve``    — serve engine stats (latency percentiles included).

:func:`prometheus_text` renders a registry snapshot in the Prometheus
text exposition format (cumulative ``le`` buckets, ``_sum``/``_count``)
so a scrape endpoint or a file-based textfile collector can serve it
without any new dependency.
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Iterator


class EventWriter:
    """Line-buffered JSONL appender in the PhaseLogger sidecar grammar.

    Safe to construct with ``path=None`` (all writes become no-ops), so
    call sites never need their own ``if telemetry`` guards.
    """

    def __init__(self, path: str | None,
                 clock=time.perf_counter) -> None:
        self.path = path
        self.clock = clock
        self._fh = open(path, "a", buffering=1) if path else None

    def emit(self, event: str, **fields: Any) -> None:
        if self._fh is None:
            return
        rec = {"event": event, "t": self.clock(), **fields}
        # allow_nan=False because json would otherwise emit the literal
        # ``NaN`` — valid to json.loads but poison to strict readers
        # (jq, browsers); _json_default cannot intercept floats (they
        # are natively serializable), so non-finite floats route through
        # the ValueError path and get scrubbed to None.
        try:
            line = json.dumps(rec, default=_json_default, allow_nan=False)
        except ValueError:
            line = json.dumps(_scrub(rec), default=_json_default,
                              allow_nan=False)
        self._fh.write(line + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _scrub(o: Any):
    """Recursively replace non-finite floats with None (cold path: only
    runs when a record actually contains one)."""
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, dict):
        return {k: _scrub(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_scrub(v) for v in o]
    return o


def _json_default(o: Any):
    """Last-resort encoder: inf/nan → None (JSON has no inf), arrays and
    numpy scalars → python."""
    if isinstance(o, float):
        return None if not math.isfinite(o) else o
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        return tolist()
    item = getattr(o, "item", None)
    if item is not None:
        return item()
    return str(o)


def read_events(path: str, event: str | None = None) -> Iterator[dict]:
    """Yield event dicts from a JSONL sidecar (PhaseLogger or obs),
    optionally filtered by event name.  Tolerates a torn final line
    (a killed run mid-write) by skipping undecodable lines."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if event is None or rec.get("event") == event:
                yield rec


def _prom_name(key: str) -> tuple[str, str]:
    """Split a registry key ``name{a=b}`` into (metric name, label part
    incl. braces or empty), quoting label values per the exposition
    format."""
    if "{" not in key:
        return key, ""
    name, _, rest = key.partition("{")
    inner = rest.rstrip("}")
    quoted = ",".join(
        f'{k}="{v}"' for k, _, v in
        (pair.partition("=") for pair in inner.split(","))
    )
    return name, "{" + quoted + "}"


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(snapshot: dict) -> str:
    """Render a ``MetricsRegistry.snapshot()`` in Prometheus text
    format.  Histogram buckets are emitted cumulatively with ``le``
    upper bounds plus the ``+Inf`` bucket, ``_sum`` and ``_count``."""
    lines: list[str] = []
    for key, v in sorted(snapshot.get("counters", {}).items()):
        name, labels = _prom_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}_total{labels} {_fmt(v)}")
    for key, v in sorted(snapshot.get("gauges", {}).items()):
        name, labels = _prom_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {_fmt(v)}")
    for key, h in sorted(snapshot.get("histograms", {}).items()):
        name, labels = _prom_name(key)
        base = labels[1:-1] if labels else ""
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lab = f'{base},le="{_fmt(float(bound))}"' if base \
                else f'le="{_fmt(float(bound))}"'
            lines.append(f"{name}_bucket{{{lab}}} {cum}")
        lab = f'{base},le="+Inf"' if base else 'le="+Inf"'
        lines.append(f"{name}_bucket{{{lab}}} {h['count']}")
        lines.append(f"{name}_sum{labels} {_fmt(h['sum'])}")
        lines.append(f"{name}_count{labels} {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")
