"""Real multi-process distributed paths: 2 OS processes rendezvous through
jax.distributed (CPU backend), covering bootstrap's distributed branch, the
``process_count() > 1`` loader branch, and cross-process gradient psum —
the launch path the reference covers with torch.multiprocessing.spawn
(reference CNN/main.py:202)."""

import re

import pytest

from distributed_deep_learning_tpu.runtime.launch import (free_port,
                                                          launch_local)


@pytest.mark.slow
def test_two_process_cli_data_mode():
    """`mlp -m data -r 2 --spawn` semantics: both ranks finish rc=0 and the
    coordinator prints the reference log grammar."""
    res = launch_local(2, ["mlp", "-e", "1", "-b", "64", "-m", "data",
                           "-r", "2"],
                       extra_env={"DDL_DATA_LIMIT": "512"}, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)
    # rank 1 is not the coordinator: no phase logs
    assert "train epoch" not in res[1].stdout


@pytest.mark.slow
def test_two_process_gradients_stay_synchronised():
    """The distributed selftest: per-rank param checksums after fused-psum
    steps must be bit-identically equal (quirk Q1 — silently diverging
    replicas — is impossible by construction)."""
    res = launch_local(
        2, [], module="distributed_deep_learning_tpu.runtime.selftest",
        timeout=420)
    lines = [next(ln for ln in r.stdout.splitlines()
                  if ln.startswith("SELFTEST")) for r in res]
    parsed = [dict(kv.split("=") for kv in ln.split()[1:]) for ln in lines]
    assert [p["rank"] for p in parsed] == ["0", "1"]
    assert all(p["world"] == "2" for p in parsed)
    assert parsed[0]["loss"] == parsed[1]["loss"]
    assert parsed[0]["checksum"] == parsed[1]["checksum"]


def test_free_port_is_bindable():
    import socket

    port = free_port()
    with socket.socket() as s:
        s.bind(("127.0.0.1", port))


@pytest.mark.slow
def test_failing_rank_output_is_surfaced():
    """A rank that dies with copious output must not deadlock the launch;
    its log tail appears in the RuntimeError (review regression: rank-order
    pipe draining could block on a full 64KB buffer)."""
    with pytest.raises(RuntimeError, match="ranks failed"):
        launch_local(2, [], module="tests.helpers.noisy_rank",
                     force_cpu=True, timeout=60)
