"""Mixture-of-Experts: top-2 gating semantics + expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_deep_learning_tpu.models.moe import (MoEMLP,
                                                      MoETransformerLayer,
                                                      moe_param_rules,
                                                      top2_gating)
from distributed_deep_learning_tpu.parallel.tensor_parallel import (
    param_specs, shard_params)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh


def test_top2_gating_routes_to_two_experts():
    logits = jnp.array([[5.0, 2.0, 0.0, -1.0],
                        [0.0, 1.0, 4.0, 3.0]])
    dispatch, combine, aux = top2_gating(logits, capacity=2)
    # token 0 → experts 0 and 1; token 1 → experts 2 and 3
    assert float(dispatch[0, 0].sum()) == 1.0
    assert float(dispatch[0, 1].sum()) == 1.0
    assert float(dispatch[0, 2].sum()) == 0.0
    assert float(dispatch[1, 2].sum()) == 1.0
    assert float(dispatch[1, 3].sum()) == 1.0
    # combine weights normalised over the two experts
    np.testing.assert_allclose(float(combine[0].sum()), 1.0, rtol=1e-6)
    assert np.isfinite(float(aux))


def test_top2_gating_capacity_drop():
    # 4 tokens all prefer expert 0; capacity 2 → two tokens dropped there
    logits = jnp.tile(jnp.array([[5.0, 1.0, 0.0, 0.0]]), (4, 1))
    dispatch, combine, _ = top2_gating(logits, capacity=2)
    assert float(dispatch[:, 0].sum()) == 2.0  # only 2 slots used
    # expert 1 (everyone's 2nd choice) also fills its 2 slots, first-come
    assert float(dispatch[:, 1].sum()) == 2.0
    assert float(dispatch[:2, 1].sum()) == 2.0  # tokens 0,1 claim them
    # tokens 2,3 are fully dropped: zero combine weight everywhere
    assert float(combine[2:].sum()) == 0.0


def test_moe_mlp_matches_dense_expert_computation():
    """With ample capacity, each token's output must equal
    gate1·FFN_e1(x) + gate2·FFN_e2(x) computed densely."""
    model = MoEMLP(num_experts=4, mlp_dim=32, capacity_factor=8.0)
    x = jax.random.normal(jax.random.key(0), (2, 4, 16))
    variables = model.init(jax.random.key(1), x)
    out = model.apply(variables, x)
    p = variables["params"]

    tokens = np.asarray(x.reshape(8, 16))
    logits = tokens @ np.asarray(p["router"]["kernel"]) + np.asarray(
        p["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    w_in, w_out = np.asarray(p["w_in"]), np.asarray(p["w_out"])

    expected = np.zeros_like(tokens)
    for g in range(8):
        order = np.argsort(-probs[g])
        e1, e2 = order[0], order[1]
        g1, g2 = probs[g, e1], probs[g, e2]
        g1, g2 = g1 / (g1 + g2), g2 / (g1 + g2)
        for e, w in ((e1, g1), (e2, g2)):
            h = np.asarray(jax.nn.gelu(jnp.asarray(tokens[g] @ w_in[e])))
            expected[g] += w * (h @ w_out[e])
    np.testing.assert_allclose(np.asarray(out).reshape(8, 16), expected,
                               rtol=1e-4, atol=1e-5)


def test_moe_aux_loss_sown():
    model = MoEMLP(num_experts=4, mlp_dim=32)
    x = jax.random.normal(jax.random.key(2), (2, 4, 16))
    variables = model.init(jax.random.key(3), x)
    _, state = model.apply({"params": variables["params"]}, x,
                           mutable=["losses"])
    (aux,) = state["losses"]["moe_aux_loss"]
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_expert_parallel_matches_replicated():
    mesh = build_mesh({"expert": 4, "data": 2})
    model = MoEMLP(num_experts=4, mlp_dim=32, capacity_factor=4.0)
    x = jax.random.normal(jax.random.key(4), (4, 8, 16))
    variables = model.init(jax.random.key(5), x)
    expected = model.apply(variables, x)

    rules = moe_param_rules()
    params = shard_params(variables["params"], mesh, rules)
    w_in = params["w_in"]
    assert w_in.addressable_shards[0].data.shape[0] == 1  # 1 expert/device

    spec_tree = jax.tree.map(lambda s: NamedSharding(mesh, s),
                             param_specs(variables["params"], rules))
    fn = jax.jit(lambda p, x: model.apply({"params": p}, x),
                 in_shardings=(spec_tree, NamedSharding(mesh, P("data"))),
                 out_shardings=NamedSharding(mesh, P("data")))
    got = fn(params, jax.device_put(x, NamedSharding(mesh, P("data"))))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


def test_moe_transformer_layer_trains():
    model = MoETransformerLayer(num_heads=2, num_experts=4, mlp_dim=32)
    x = jax.random.normal(jax.random.key(6), (2, 8, 16))
    variables = model.init(jax.random.key(7), x)

    def loss(p):
        out, state = model.apply({"params": p}, x, train=False,
                                 mutable=["losses"])
        (aux,) = state["losses"]["moe"]["moe_aux_loss"]
        return jnp.mean(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(variables["params"])
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # router must receive gradient (differentiable through combine weights)
    assert np.abs(np.asarray(grads["moe"]["router"]["kernel"])).sum() > 0


def test_moe_lm_trains_via_cli():
    """The 'moe' workload: MLM with routed experts, aux loss in the
    gradient objective."""
    import os
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec, run_workload

    os.environ["DDL_DATA_LIMIT"] = "256"
    try:
        argv = ["-l", "2", "-s", "32", "-e", "1", "-b", "32", "-m", "data"]
        _, history = run_workload(get_spec("moe"),
                                  parse_args(argv, workload="moe"))
    finally:
        os.environ.pop("DDL_DATA_LIMIT", None)
    assert history[-1].phase == "test"
    assert all(np.isfinite(h.loss) for h in history)


def test_moe_lm_expert_parallel_cli():
    import os
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec, run_workload

    os.environ["DDL_DATA_LIMIT"] = "256"
    try:
        argv = ["-l", "2", "-s", "32", "-e", "1", "-b", "32", "-m", "data",
                "--mesh", "data=2,expert=4"]
        _, history = run_workload(get_spec("moe"),
                                  parse_args(argv, workload="moe"))
    finally:
        os.environ.pop("DDL_DATA_LIMIT", None)
    assert all(np.isfinite(h.loss) for h in history)


def test_aux_loss_reaches_gradient():
    """The router must receive gradient from the aux loss through the
    train-state convention (not only through combine weights)."""
    import optax
    from distributed_deep_learning_tpu.models.moe import MoELM
    from distributed_deep_learning_tpu.train.state import create_train_state

    model = MoELM(vocab_size=32, num_layers=2, d_model=16, num_heads=2,
                  mlp_dim=32, num_experts=4, aux_loss_weight=1.0)
    toks = jnp.asarray(np.random.default_rng(0).integers(1, 32, (4, 8)))
    state = create_train_state(model, jax.random.key(0), toks[:1],
                               optax.adam(1e-3))

    def total_loss(p):
        pred, _, aux = state.apply_fn(p, state.model_state, toks, train=True)
        return aux  # aux alone: gradient flows only via the losses sow

    g = jax.grad(total_loss)(state.params)
    router_g = g["moe_layer_1"]["moe"]["router"]["kernel"]
    assert np.abs(np.asarray(router_g)).sum() > 0
