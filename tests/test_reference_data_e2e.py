"""Real-data end-to-end proof per reference workload (VERDICT r4 item 7):
committed CSV fixtures (MQTT, PdM) and a generated VOC-style XML+JPG tree
(PCB) driven through ALL FOUR modes via the CLI, asserting the reference
log grammar and real learning on the planted signals — closing the loop on
C13-C15 against ``/root/reference/src/pytorch/{MLP,CNN,LSTM}/dataset.py``
semantics with actual file parsing (native C++ CSV reader, stdlib
ElementTree, PIL decode + native crop/resize) on the path.
"""

import os

import numpy as np
import pytest

from distributed_deep_learning_tpu.utils.config import parse_args
from distributed_deep_learning_tpu.workloads import get_spec, run_workload

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
MODES = ("sequential", "data", "model", "pipeline")


def _run(workload, argv, limit=1024, capsys=None):
    config = parse_args(argv, workload=workload)
    old = os.environ.get("DDL_DATA_LIMIT")
    os.environ["DDL_DATA_LIMIT"] = str(limit)
    try:
        return run_workload(get_spec(workload), config)
    finally:
        if old is None:
            os.environ.pop("DDL_DATA_LIMIT", None)
        else:
            os.environ["DDL_DATA_LIMIT"] = old


def _grammar_ok(out: str) -> None:
    """The reference's quote-delimited phase-line grammar."""
    import re

    assert re.search(r'"train epoch 1 ends at .* with accuracy', out), out
    assert re.search(r'"validation epoch 1 ends at .* with accuracy', out)
    assert re.search(r'"test ends at .* with accuracy', out)


def _phases(history):
    return [h.phase for h in history]


@pytest.fixture(scope="module")
def pcb_root(tmp_path_factory):
    """VOC-style tree: Annotations/<class>/*.xml + images/<class>/*.jpg
    (reference ``CNN/dataset.py:71-111`` layout), generated JPEGs whose
    mean colour encodes the class so the CNN can learn it."""
    from PIL import Image

    root = tmp_path_factory.mktemp("pcb")
    rng = np.random.default_rng(5)
    classes = [f"defect_{i}" for i in range(6)]
    for ci, cls in enumerate(classes):
        (root / "Annotations" / cls).mkdir(parents=True)
        (root / "images" / cls).mkdir(parents=True)
        for i in range(2):
            arr = rng.integers(0, 60, (100, 100, 3)).astype(np.uint8)
            arr[..., ci % 3] += np.uint8(40 * (1 + ci // 3))  # class signal
            Image.fromarray(arr).save(root / "images" / cls / f"im{i}.jpg")
            boxes = "".join(
                f"<object><bndbox><xmin>{x0}</xmin><ymin>{y0}</ymin>"
                f"<xmax>{x0 + 40}</xmax><ymax>{y0 + 40}</ymax>"
                "</bndbox></object>"
                for x0, y0 in ((5, 5), (50, 50)))
            (root / "Annotations" / cls / f"im{i}.xml").write_text(
                f"<annotation>{boxes}</annotation>")
    return str(root)


# --- MLP on the committed MQTT CSV (C13) -----------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_mlp_real_csv_all_modes(mode, capsys):
    argv = ["-e", "2", "-b", "32", "-m", mode,
            "--data-dir", os.path.join(FIXTURES, "mqtt")]
    if mode in ("model", "pipeline"):
        argv += ["-l", "2", "--nstages", "2", "-e", "1"]
        argv[1] = "1"
    _, history = _run("mlp", argv)
    assert _phases(history)[-1] == "test"
    assert all(np.isfinite(h.loss) for h in history)
    _grammar_ok(capsys.readouterr().out)


def test_mlp_learns_planted_csv_signal():
    _, history = _run("mlp", ["-e", "6", "-b", "32", "-m", "sequential",
                              "--data-dir", os.path.join(FIXTURES, "mqtt")])
    train = [h for h in history if h.phase == "train"]
    assert train[-1].accuracy > train[0].accuracy
    assert train[-1].accuracy > 40.0


# --- CNN on the generated PCB VOC tree (C14) --------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_cnn_real_voc_all_modes(mode, pcb_root, capsys):
    argv = ["-e", "1", "-b", "16", "-m", mode, "--data-dir", pcb_root]
    if mode in ("model", "pipeline"):
        argv += ["-l", "2", "--nstages", "2"]
    _, history = _run("cnn", argv, limit=48)
    assert _phases(history)[-1] == "test"
    assert all(np.isfinite(h.loss) for h in history)
    _grammar_ok(capsys.readouterr().out)


def test_cnn_augmentation_doubles_real_samples(pcb_root):
    from distributed_deep_learning_tpu.data.pcb import PCBDataset

    ds = PCBDataset(root=pcb_root, seed=0)
    # 6 classes x 2 images x 2 boxes = 24 physical samples, doubled
    assert len(ds) == 48


# --- LSTM on the committed windowed PdM CSV (C15) ---------------------------

@pytest.mark.parametrize("mode", MODES)
def test_lstm_real_csv_all_modes(mode, capsys):
    argv = ["-e", "1", "-b", "32", "-m", mode,
            "--data-dir", os.path.join(FIXTURES, "pdm")]
    if mode in ("model", "pipeline"):
        argv += ["-l", "2", "--nstages", "2"]
    _, history = _run("lstm", argv)
    assert _phases(history)[-1] == "test"
    assert all(np.isfinite(h.loss) for h in history)
    _grammar_ok(capsys.readouterr().out)


def test_lstm_loss_improves_on_real_csv():
    _, history = _run("lstm", ["-e", "3", "-b", "32", "-m", "sequential",
                               "--data-dir", os.path.join(FIXTURES, "pdm")])
    train = [h for h in history if h.phase == "train"]
    assert train[-1].loss < train[0].loss


def test_explicit_data_dir_fails_loudly(tmp_path):
    """--data-dir pointing nowhere must raise, not silently fall back to
    the synthetic twin."""
    with pytest.raises(FileNotFoundError):
        _run("mlp", ["-e", "1", "-b", "32", "--data-dir",
                     str(tmp_path / "nope")])
