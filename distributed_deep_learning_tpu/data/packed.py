"""Packed pre-decoded sample cache: mmap'd batches at device rate.

The eager image path (PIL decode + native resize, :mod:`.imagefolder` /
:mod:`.pcb`) delivers ~35 img/s/chip on the CI box while the TPU train
step consumes ~2,400 (``BENCH_r05.json``) — at ImageNet scale the HOST is
the binding constraint.  Decode work is also *identical every epoch*: the
same file decodes to the same pixels.  So it is done ONCE, offline: a
packing pass walks any dataset exposing the ``ArrayDataset`` contract
(``__len__``/``batch``) — images, tabular windows, token rows — through
its own (threaded) decode machinery and writes one flat binary artifact;
training memory-maps it and assembles batches with a single fancy-index
slab gather per batch, zero per-sample Python work.  This is the
``data/tokens.py`` offline-artifact pattern generalised from token arrays
to every sample family.

Artifact layout (little-endian, version 1)::

    [0:8)    magic  b"DDLPACK" + version byte
    [8:16)   uint64 header length H
    [16:16+H) JSON header: shapes, dtypes, block offsets, source metadata
    features @ features_offset   (num_samples, *feature_shape) C-order
    targets  @ targets_offset    (num_samples, *target_shape)  C-order
    index    @ index_offset      int64 (num_samples,) per-sample byte
                                 offsets into the features block

Samples are fixed-stride today, but readers go through the index, so a
future version can pack ragged samples without breaking the magic/header
contract.  Floats that are exactly uint8-representable (decoded images at
their native size) can be stored as ``uint8`` (4x smaller artifact) and
are converted back on read — bit-identical either way; anything else
stays in its source dtype.  Truncated or foreign files fail loudly
(:class:`PackedFormatError`) — a half-written cache must never train.

Determinism: the reader is a plain ``ArrayDataset``, so the seeded
epoch permutation, split composition (:mod:`.splits`) and the
checkpoint loader-position sidecar replay (:meth:`.loader.DeviceLoader.
iter_batches`) all apply unchanged — packed and eager runs of the same
seed see the same batches in the same order, bit for bit.
"""

from __future__ import annotations

import json
import os

import numpy as np

from distributed_deep_learning_tpu.data.datasets import ArrayDataset

MAGIC = b"DDLPACK"
VERSION = 1
#: conventional artifact extension (any path works)
PACKED_EXTENSION = ".ddlpack"
_ALIGN = 64  # block alignment: slab reads start on a cache-line boundary


class PackedFormatError(ValueError):
    """The file is not a (complete, current-version) packed cache."""


def _align(offset: int) -> int:
    return (offset + _ALIGN - 1) // _ALIGN * _ALIGN


def _uint8_exact(arr: np.ndarray) -> bool:
    """True when ``arr`` round-trips through uint8 bit-exactly."""
    if arr.dtype == np.uint8:
        return True
    if not np.issubdtype(arr.dtype, np.floating):
        return False
    return bool(np.all((arr >= 0) & (arr <= 255) &
                       (arr == np.trunc(arr))))


def pack_dataset(dataset, path: str | os.PathLike, *,
                 dtype: str = "auto", chunk_size: int = 256,
                 indices: np.ndarray | None = None,
                 meta: dict | None = None) -> dict:
    """Pack ``dataset`` (anything with ``__len__``/``batch``) into ``path``.

    ``dtype`` controls the feature block: ``"auto"`` stores uint8 when the
    probe chunk is exactly uint8-representable (decoded images), source
    dtype otherwise; ``"uint8"`` forces it (and errors on any sample that
    would be quantised — lossy packing must be impossible to do by
    accident); ``"source"`` always keeps the source dtype.  ``indices``
    packs a subset (e.g. one split) in the given order.  Writes are
    atomic (tmp file + rename): a crash mid-pack leaves no artifact.

    Returns the header dict of the written artifact.
    """
    if dtype not in ("auto", "uint8", "source"):
        raise ValueError(f"dtype must be auto|uint8|source, got {dtype!r}")
    idx = np.arange(len(dataset), dtype=np.int64) if indices is None \
        else np.asarray(indices, np.int64)
    n = len(idx)
    if n == 0:
        raise ValueError("refusing to pack an empty dataset")
    chunk_size = max(1, int(chunk_size))

    x0, y0 = dataset.batch(idx[:min(chunk_size, n)])
    x0, y0 = np.asarray(x0), np.asarray(y0)
    store_u8 = (dtype == "uint8") or (dtype == "auto" and _uint8_exact(x0))
    f_store = np.dtype(np.uint8) if store_u8 else x0.dtype
    f_out = x0.dtype  # what batch() must yield back (bit-identity contract)

    f_stride = int(np.prod(x0.shape[1:], dtype=np.int64)) * f_store.itemsize
    t_stride = int(np.prod(y0.shape[1:], dtype=np.int64)) * y0.dtype.itemsize
    header = {
        "version": VERSION,
        "num_samples": n,
        "feature_shape": [int(d) for d in x0.shape[1:]],
        "feature_dtype": f_store.name,
        "feature_out_dtype": f_out.name,
        "target_shape": [int(d) for d in y0.shape[1:]],
        "target_dtype": y0.dtype.name,
        "meta": dict(meta or {}),
    }
    # source metadata the workloads key model geometry off
    classes = getattr(dataset, "classes", None)
    if classes is not None:
        header["classes"] = [str(c) for c in classes]
    vocab = getattr(dataset, "vocab_size", None)
    if vocab is not None:
        header["vocab_size"] = int(vocab)

    # block offsets depend on the header's own JSON length (offset digit
    # counts feed back into it) — iterate to the fixed point, which exists
    # because lengths only ever grow and alignment absorbs small changes
    header.update(features_offset=0, targets_offset=0, index_offset=0,
                  total_bytes=0)
    for _ in range(8):
        hdr = json.dumps(header).encode()
        f_off = _align(16 + len(hdr))
        t_off = _align(f_off + n * f_stride)
        i_off = _align(t_off + n * t_stride)
        total = i_off + n * 8
        if (header["features_offset"], header["targets_offset"],
                header["index_offset"], header["total_bytes"]) == \
                (f_off, t_off, i_off, total):
            break
        header.update(features_offset=f_off, targets_offset=t_off,
                      index_offset=i_off, total_bytes=total)
    else:  # pragma: no cover - lengths are monotone, cannot happen
        raise AssertionError("packed header layout did not converge")

    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"

    def write_chunk(f, start: int, x: np.ndarray, y: np.ndarray) -> None:
        if store_u8 and x.dtype != np.uint8:
            if not _uint8_exact(x):
                raise ValueError(
                    "samples are not exactly uint8-representable; pack "
                    "with dtype='source' (or fix the decode path) — "
                    "silent quantisation would break packed/eager parity")
            x = x.astype(np.uint8)
        f.seek(f_off + start * f_stride)
        f.write(np.ascontiguousarray(x).tobytes())
        f.seek(t_off + start * t_stride)
        f.write(np.ascontiguousarray(y).tobytes())

    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC + bytes([VERSION]))
            f.write(np.uint64(len(hdr)).tobytes())
            f.write(hdr)
            write_chunk(f, 0, x0, y0)
            for start in range(len(x0), n, chunk_size):
                x, y = dataset.batch(idx[start:start + chunk_size])
                x, y = np.asarray(x), np.asarray(y)
                if x.shape[1:] != x0.shape[1:] or y.shape[1:] != y0.shape[1:]:
                    raise ValueError(
                        f"ragged samples at {start}: {x.shape[1:]} vs "
                        f"{x0.shape[1:]} — version-1 packs fixed shapes")
                write_chunk(f, start, x, y)
            f.seek(i_off)
            f.write((np.arange(n, dtype=np.int64) * f_stride).tobytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers never see a partial pack
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return header


def read_header(path: str | os.PathLike) -> dict:
    """Validated header of a packed cache (magic, version, completeness)."""
    path = os.fspath(path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(16)
        if len(head) < 16 or head[:7] != MAGIC:
            raise PackedFormatError(f"{path}: not a packed sample cache "
                                    f"(bad magic)")
        version = head[7]
        if version != VERSION:
            raise PackedFormatError(
                f"{path}: packed-cache version {version} != supported "
                f"{VERSION}; re-pack with this build of "
                "scripts/pack_dataset.py")
        hlen = int(np.frombuffer(head[8:16], np.uint64)[0])
        raw = f.read(hlen)
    if len(raw) < hlen:
        raise PackedFormatError(f"{path}: truncated header")
    try:
        header = json.loads(raw)
    except json.JSONDecodeError as exc:
        raise PackedFormatError(f"{path}: corrupt header ({exc})") from None
    if size != header.get("total_bytes"):
        raise PackedFormatError(
            f"{path}: {size} bytes on disk vs {header.get('total_bytes')} "
            "declared — truncated or partially-written cache (re-pack)")
    return header


class PackedDataset(ArrayDataset):
    """Memory-mapped reader over a :func:`pack_dataset` artifact.

    ``features``/``targets`` are live memmaps (no load-time copy; the OS
    page cache holds only what batches touch), and ``batch()`` is one
    fancy-index slab gather per array — the same ``native.take`` hot path
    every ArrayDataset uses, reading straight out of the mapping.  uint8-
    stored features convert back to their source dtype on the way out, so
    packed batches are bit-identical to the eager decode path's.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        self.header = h = read_header(self.path)
        n = int(h["num_samples"])
        feats = np.memmap(self.path, dtype=np.dtype(h["feature_dtype"]),
                          mode="r", offset=int(h["features_offset"]),
                          shape=(n, *map(int, h["feature_shape"])))
        tgts = np.memmap(self.path, dtype=np.dtype(h["target_dtype"]),
                         mode="r", offset=int(h["targets_offset"]),
                         shape=(n, *map(int, h["target_shape"])))
        self.index = np.memmap(self.path, dtype=np.int64, mode="r",
                               offset=int(h["index_offset"]), shape=(n,))
        stride = feats[0].nbytes
        if n and (int(self.index[0]) != 0
                  or int(self.index[-1]) != (n - 1) * stride):
            raise PackedFormatError(f"{self.path}: sample index disagrees "
                                    "with the feature block layout")
        self._out_dtype = np.dtype(h.get("feature_out_dtype",
                                         h["feature_dtype"]))
        if "classes" in h:
            self.classes = list(h["classes"])
            self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        if "vocab_size" in h:
            self.vocab_size = int(h["vocab_size"])
        super().__init__(feats, tgts)

    def batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        x, y = super().batch(indices)
        if x.dtype != self._out_dtype:
            x = x.astype(self._out_dtype)
        return x, y

    @property
    def nbytes(self) -> int:
        return int(self.header["total_bytes"])
