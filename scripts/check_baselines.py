"""Consistency gate between ``bench_baseline.json`` and the sentry bands.

The perf-regression sentry (``bench.py``) only defends a baseline key
when ``REGRESSION_BANDS`` carries a band for its suffix — a key the
bands don't know is silently unguarded, and a band no baseline matches
guards nothing.  Both drifts are one forgotten edit away (add a metric,
rename a key, retire a section), so this script fails fast when they
happen; ``tests/test_memory_obs.py`` runs it as a tier-1 test.

    python scripts/check_baselines.py            # rc 0 clean, 1 on drift

Checks:

* every banded baseline key's suffix matches a ``REGRESSION_BANDS``
  entry, OR the key sits on the explicit legacy allowlist (pre-sentry
  records kept for history: list values, one-off micro ratios);
* every allowlist entry still exists in the baseline file (a stale
  allowlist hides future drift);
* every band is well-formed (known mode, positive value);
* every band matches at least one baseline key (orphaned bands mean the
  metric was renamed or its section lost its ``_vs_baseline`` call);
* every baseline value is a FINITE number (a NaN/inf or stringly value
  makes every future ratio vacuously pass) — non-scalar records are
  allowed only for allowlisted history keys (``tpu:flash_best_blocks``
  is a block-shape list, not a metric);
* every band names an existing bench JSON-line section through
  ``bench.BAND_SECTIONS`` (a band whose section was renamed or removed
  would keep "guarding" a metric nothing ever measures again) — the
  ``fleet_rebalance`` bench section also runs this script and folds the
  verdict into its record, so the hygiene gate rides the bench path.
"""

from __future__ import annotations

import json
import math
import os
import sys

#: pre-sentry baseline keys kept for history (TPU harvest one-offs and
#: the legacy densenet v1 record) — tracked, not banded.  Adding a key
#: here is an explicit decision to leave it unguarded.
UNBANDED_ALLOWLIST = frozenset({
    "tpu:densenet_bc_train",
    "tpu:resnet50_mfu_v1",
    "tpu:flash_best_blocks",
    "tpu:flash_speedup_T2048_D64",
    "tpu:s2d_stem_speedup_b128",
    "tpu:gqa_flash_speedup_H8_Hkv2",
})

_MODES = ("higher", "lower_abs")


def check(baselines: dict, bands: dict,
          allow_unbanded: frozenset = UNBANDED_ALLOWLIST,
          band_sections: dict | None = None,
          section_keys: frozenset | None = None) -> list[str]:
    """All drift findings, empty when consistent (unit-testable core).

    ``band_sections`` / ``section_keys`` (both or neither) extend the
    check to band->section hygiene: every band suffix must map to a
    bench JSON-line section key that actually exists."""
    problems: list[str] = []
    for key in sorted(baselines):
        value = baselines[key]
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if not math.isfinite(value):
                problems.append(
                    f"baseline key {key!r} has non-finite value {value!r} "
                    "(every future ratio against it is vacuous)")
        elif key not in allow_unbanded:
            problems.append(
                f"baseline key {key!r} has non-numeric value {value!r} "
                "(only allowlisted history keys may carry non-scalar "
                "records)")
    for key in sorted(baselines):
        suffix = key.split(":", 1)[-1]
        if suffix in bands:
            continue
        if key in allow_unbanded:
            continue
        problems.append(
            f"baseline key {key!r} has no REGRESSION_BANDS entry for "
            f"suffix {suffix!r} (unguarded metric; add a band or "
            "allowlist it explicitly)")
    for key in sorted(allow_unbanded):
        if key not in baselines:
            problems.append(
                f"allowlist entry {key!r} is not in the baseline file "
                "(stale allowlist; remove it)")
    suffixes = {k.split(":", 1)[-1] for k in baselines}
    for suffix in sorted(bands):
        rule = bands[suffix]
        if (not isinstance(rule, (tuple, list)) or len(rule) != 2
                or rule[0] not in _MODES):
            problems.append(
                f"band {suffix!r} is malformed: {rule!r} (want "
                f"(mode, value) with mode in {_MODES})")
            continue
        if not isinstance(rule[1], (int, float)) or rule[1] <= 0:
            problems.append(
                f"band {suffix!r} has non-positive value {rule[1]!r}")
        if suffix not in suffixes:
            problems.append(
                f"band {suffix!r} matches no baseline key (orphaned "
                "band: metric renamed, or its section never calls "
                "_vs_baseline)")
        if band_sections is not None:
            section = band_sections.get(suffix)
            if section is None:
                problems.append(
                    f"band {suffix!r} has no BAND_SECTIONS entry (which "
                    "bench section does its metric ride in?)")
            elif section_keys is not None and section not in section_keys:
                problems.append(
                    f"band {suffix!r} maps to unknown bench section "
                    f"{section!r} (not in SECTION_KEYS: section renamed "
                    "or removed)")
    if band_sections is not None:
        for suffix in sorted(set(band_sections) - set(bands)):
            problems.append(
                f"BAND_SECTIONS entry {suffix!r} has no band (stale "
                "mapping; remove it)")
    return problems


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    path = (argv or [None])[0] if argv else None
    path = path or os.path.join(repo, "bench_baseline.json")

    import bench

    with open(path) as f:
        baselines = json.load(f)
    problems = check(baselines, bench.REGRESSION_BANDS,
                     band_sections=getattr(bench, "BAND_SECTIONS", None),
                     section_keys=getattr(bench, "SECTION_KEYS", None))
    for p in problems:
        print(f"check_baselines: {p}", file=sys.stderr)
    print(json.dumps({"baselines": len(baselines),
                      "bands": len(bench.REGRESSION_BANDS),
                      "problems": len(problems)}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
