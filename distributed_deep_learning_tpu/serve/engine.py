"""Continuous-batching decode engine: two programs, compiled once.

vLLM-style continuous batching mapped onto XLA's fixed-shape world:

* **Decode** is ONE compiled program for the engine's lifetime — a
  1-token step over ALL slots (the model's own tested single-sequence
  cached decode, ``vmap``-ed over the slot axis of the static slot
  table) followed by the shared sampling head.  Requests of any prompt
  length, arriving at any time, never change its shapes.
* **Prefill** is one compiled program PER POWER-OF-TWO BUCKET (a handful
  for the engine's lifetime): the prompt is padded to the bucket, run as
  one multi-token cached call, its position counters pinned back to the
  true length (:func:`..serve.cache.fix_counters` — padding leaves no
  numerical trace), and the filled cache written into the designated
  slot.  Slot index and true length are traced scalars, so one program
  serves every slot and every length inside a bucket.

Both programs take the slot table as a DONATED argument on accelerator
backends: the tick does not copy the cache in HBM, it updates it in
place (donation is skipped on CPU, which does not implement it and
would warn every call).

Compilation counts are PROVEN, not assumed: each program runs through
:class:`CountingJit`, whose counter increments at trace time only —
``tests/test_serve.py`` asserts the decode count stays 1 across a trace
of mixed lengths and staggered arrivals.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_deep_learning_tpu.models.transformer import (
    CausalLM, cached_apply, make_decode_model, sample_tokens,
    validate_sampling)
from distributed_deep_learning_tpu.obs import memory as obs_memory
from distributed_deep_learning_tpu.obs.metrics import MetricsRegistry
from distributed_deep_learning_tpu.obs.window import LiveSignals
from distributed_deep_learning_tpu.serve import cache as slot_cache
from distributed_deep_learning_tpu.serve import paged
from distributed_deep_learning_tpu.serve import quant
from distributed_deep_learning_tpu.serve import spec as spec_mod
from distributed_deep_learning_tpu.serve.load import slo_report
from distributed_deep_learning_tpu.serve.prefill import (chunk_tokens,
                                                         plan_chunks,
                                                         write_targets)
from distributed_deep_learning_tpu.serve.scheduler import (PagedScheduler,
                                                           Request,
                                                           SlotScheduler)


class CountingJit:
    """``jax.jit`` wrapper that counts traces.

    jit retraces exactly when a call presents a new (shape, dtype,
    static-arg) signature — i.e. when it must compile — so the trace
    count IS the compile count the tests assert on.  (A cache-evicted
    retrace would also count: the counter is conservative, never
    flattering.)
    """

    def __init__(self, fn, **jit_kwargs):
        self.traces = 0

        def counted(*args):
            self.traces += 1   # runs at trace time only
            return fn(*args)

        self._jit = jax.jit(counted, **jit_kwargs)

    def __call__(self, *args):
        return self._jit(*args)


@dataclasses.dataclass(frozen=True)
class TickReport:
    """What one engine tick produced, handed to ``run(on_tick=...)``
    BEFORE the tokens are recorded into the scheduler.

    This ordering is the crash-containment contract: a hook that raises
    (watchdog anomaly, injected fault) discards the tick's tokens, so a
    supervisor that replays from the committed streams regenerates them
    — greedy outputs stay bit-identical to a fault-free run.

    ``finite`` carries DEVICE-computed per-request flags (``isfinite``
    over the sampled hidden state): NaN/inf anywhere in a request's
    attention window poisons its flag, which is how KV corruption
    surfaces one tick after injection.  ``logprob`` is the chosen
    token's log-probability under the engine's own head — the drift
    signal canary comparison feeds on.
    """

    tick: int
    kind: str                      # "prefill" | "decode"
    elapsed_s: float
    emitted: list                  # [(uid, token), ...] in commit order
    finite: dict                   # uid -> bool
    logprob: dict                  # uid -> float
    slots: list                    # active slot indices this tick
    engine: object
    queue_depth: int = 0


@dataclasses.dataclass
class _CanaryState:
    """Live canary: candidate weights serving a slice of slots.

    The engine runs the SAME compiled decode program twice per tick —
    once with the stable params (canary slots' KV writes routed to
    trash), once with the candidate params (everyone else's writes
    trashed) — and merges tokens per slot.  Same shapes/dtypes both
    calls, so the trace count never moves.  Per canary slot per tick it
    feeds ``observe`` with the old-vs-new argmax agreement and chosen
    log-prob drift; the reload manager turns those into windowed
    signals and a promote/rollback verdict."""

    params: object
    slots: frozenset
    observe: Optional[Callable] = None
    compared: int = 0
    agreed: int = 0
    drift_sum: float = 0.0
    nonfinite: int = 0

    def note(self, agree: bool, drift: float, finite: bool,
             now: float) -> None:
        self.compared += 1
        self.agreed += int(agree)
        self.drift_sum += drift
        self.nonfinite += int(not finite)
        if self.observe is not None:
            self.observe(agree=agree, drift=drift, finite=finite, now=now)

    def summary(self) -> dict:
        return {
            "compared": self.compared,
            "agreed": self.agreed,
            "acceptance": (self.agreed / self.compared
                           if self.compared else None),
            "mean_abs_logprob_drift": (self.drift_sum / self.compared
                                       if self.compared else None),
            "nonfinite": self.nonfinite,
            "canary_slots": sorted(self.slots),
        }


def _check_swappable(old, new) -> None:
    """New params must be drop-in for the compiled programs: identical
    tree structure, per-leaf shape and dtype — anything else would
    retrace (or worse, silently reshape)."""
    old_l, old_t = jax.tree_util.tree_flatten(old)
    new_l, new_t = jax.tree_util.tree_flatten(new)
    if old_t != new_t:
        raise ValueError("swap_params: new params tree structure differs "
                         "from the engine's (cannot hot-swap)")
    for i, (a, b) in enumerate(zip(old_l, new_l)):
        if a.shape != b.shape or a.dtype != b.dtype:
            raise ValueError(
                f"swap_params: leaf {i} mismatch — engine has "
                f"{a.shape}/{a.dtype}, new params have {b.shape}/"
                f"{b.dtype}; hot swap requires identical geometry")


def default_buckets(max_len: int, floor: int = 8) -> tuple[int, ...]:
    """Powers of two from ``floor`` up to (and always including)
    ``max_len`` — the prefill shape vocabulary."""
    out = []
    b = floor
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class ServeEngine:
    """Continuous-batching server for a trained :class:`CausalLM`.

    ``run(requests)`` drives a whole trace; each tick advances every
    active slot by one token, retires rows on EOS or budget, and
    refills freed slots from the arrived queue — throughput tracks slot
    occupancy, not the slowest request.
    """

    def __init__(self, model: CausalLM, params, *, max_slots: int = 8,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 rng=None, donate: Optional[bool] = None,
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None):
        validate_sampling(top_k, top_p)
        quant.check_dtype("kv_dtype", kv_dtype)
        quant.check_dtype("weight_dtype", weight_dtype)
        if kv_dtype == "int8":
            raise ValueError(
                "kv_dtype='int8' requires the paged engine (PagedEngine /"
                " --paged): int8 KV stores per-position scales alongside "
                "the block pools; the v1 slot table supports bf16 only")
        self.kv_dtype, self.weight_dtype = kv_dtype, weight_dtype
        # the model's working precision, captured BEFORE the params go
        # to their at-rest form: every compiled impl dequantizes back to
        # this dtype at its top (XLA fuses the upcast into the matmuls)
        self.compute_dtype = jax.tree.leaves(params)[0].dtype
        if weight_dtype is not None:
            params = quant.quantize_weights(params, weight_dtype)
        self.model, self.params = model, params
        self.lm = make_decode_model(model)
        self.max_slots = int(max_slots)
        self.max_len = int(max_len if max_len is not None else model.max_len)
        if self.max_len > model.max_len:
            raise ValueError(f"max_len {self.max_len} exceeds the model's "
                             f"max_len {model.max_len}")
        if prefill_buckets is None:
            self.buckets = default_buckets(self.max_len)
        else:
            self.buckets = tuple(sorted({int(b) for b in prefill_buckets}))
            if not self.buckets or self.buckets[0] < 1:
                raise ValueError(f"bad prefill buckets {prefill_buckets}")
            if self.buckets[-1] > self.max_len:
                raise ValueError(f"prefill bucket {self.buckets[-1]} "
                                 f"exceeds max_len {self.max_len}")
            if self.buckets[-1] < self.max_len:
                # top bucket: any admissible prompt must fit some bucket
                self.buckets += (self.max_len,)
        self.eos_id = eos_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        # bucket padding uses the pad id (recorded invalid in the cache);
        # pad-free models pad with id 0 — those positions are causally
        # unreachable after the counter fixup, so the id never matters
        self.pad_fill = model.pad_id if model.pad_id is not None else 0
        self._key = rng if rng is not None else jax.random.key(0)
        if donate is None:
            donate = jax.default_backend() != "cpu"
        dk = {"donate_argnums": (1,)} if donate else {}
        self.slots = self._alloc_slots()
        # exact KV footprint by construction: the allocated cache pytree's
        # own shapes (what the analytic layers x 2 x slots x len x kv-heads
        # x head-dim computation must reproduce bit-exactly)
        self.kv_cache_bytes = obs_memory.pytree_bytes(self.slots)
        self._prefill = CountingJit(self._prefill_impl, **dk)
        self._decode = CountingJit(self._decode_impl, **dk)
        self.restarts = 0
        self.weight_swaps = 0

    # --- quantization shims (identity at full precision) ------------------
    def _alloc_slots(self):
        slots = slot_cache.allocate_slots(self.lm, self.max_slots,
                                          self.max_len)
        if self.kv_dtype == "bf16":
            slots = quant.cast_kv(slots, jnp.bfloat16)
        return slots

    def _wp(self, params):
        """At-rest params -> compute-dtype view (inside the jitted impl,
        so the upcast fuses into the consuming matmuls)."""
        if self.weight_dtype is None:
            return params
        return quant.dequantize_weights(params, self.compute_dtype)

    def _kv_in(self, cache):
        """Stored cache -> the model's working precision (the model's
        ``dynamic_update_slice`` writes are dtype-strict)."""
        if self.kv_dtype is None:
            return cache
        return quant.cast_kv(cache, self.compute_dtype)

    def _kv_out(self, cache):
        """Freshly-computed cache -> the slab's at-rest precision."""
        if self.kv_dtype is None:
            return cache
        return quant.cast_kv(cache, jnp.bfloat16)

    # --- the two compiled programs ---------------------------------------
    def _sample(self, params, hidden_last, key):
        """Sample tokens plus their log-probability and a finiteness
        flag per row.  ``params`` is an explicit TRACED argument — NOT a
        closure capture, which jit would bake into the compiled program
        as constants and hot weight swap would then silently miss."""
        toks, _ = sample_tokens(self.model, params, hidden_last, key,
                                temperature=self.temperature,
                                top_k=self.top_k, top_p=self.top_p)
        nl = self.model.logits_from({"params": params}, hidden_last)
        lp = jnp.take_along_axis(jax.nn.log_softmax(nl, axis=-1),
                                 toks[:, None], axis=-1)[:, 0]
        ok = jnp.isfinite(hidden_last).all(axis=-1)
        return toks, lp, ok

    def _prefill_impl(self, params, slots, tokens, slot, true_len, key):
        """(Pb,)-padded prompt -> slot ``slot`` filled, first token out."""
        params = self._wp(params)
        fresh = self._kv_in(slot_cache.fresh_slot(slots))
        hidden, new = cached_apply(self.lm, params, fresh, tokens[None])
        new = slot_cache.fix_counters(new, true_len)
        slots = slot_cache.write_slot(slots, self._kv_out(new), slot)
        # sample from the TRUE final position, not the padded tail
        h_last = jax.lax.dynamic_slice_in_dim(hidden[0], true_len - 1, 1)
        tok, lp, ok = self._sample(params, h_last, key)
        return slots, tok[0], lp[0], ok[0]

    def _decode_impl(self, params, slots, toks, key):
        """One token for every slot: the model's single-sequence cached
        decode vmapped over the slot axis, then one shared sampling."""
        params = self._wp(params)

        def one(per_slot, tok):
            c = self._kv_in(slot_cache.lift(per_slot))
            hidden, new = cached_apply(self.lm, params, c, tok[None, None])
            return slot_cache.unlift(self._kv_out(new)), hidden[0, 0]

        slots, h = jax.vmap(one)(slots, toks)     # h: (max_slots, d)
        toks, lp, ok = self._sample(params, h, key)
        return slots, toks, lp, ok

    # --- host side --------------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(f"prompt length {prompt_len} exceeds the top "
                         f"prefill bucket {self.buckets[-1]}")

    def _validate(self, req: Request) -> None:
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {req.uid}: prompt {len(req.prompt)} + "
                f"{req.max_new_tokens} new tokens exceeds the slot "
                f"capacity max_len={self.max_len}")
        self.bucket_for(len(req.prompt))

    def _next_key(self):
        if self.temperature == 0.0:
            return self._key           # unused by greedy sampling
        self._key, sub = jax.random.split(self._key)
        return sub

    # --- resilience seams -------------------------------------------------
    def reset(self) -> None:
        """Warm restart after a contained fault: FRESH slot caches (any
        poisoned KV dies here), SAME compiled programs — the new cache
        pytree has identical shapes, so no program retraces and
        ``decode_compiles`` stays where it was."""
        self.slots = self._alloc_slots()
        self.restarts += 1

    def swap_params(self, new_params) -> None:
        """Hot weight swap between ticks: same tree/shapes/dtypes slide
        into the already-compiled programs (params are traced arguments,
        never baked constants), so no recompile happens.  Incoming
        weights are published full-precision; a quantized engine takes
        them to its at-rest form FIRST, so the geometry check compares
        like with like."""
        if self.weight_dtype is not None:
            new_params = quant.quantize_weights(new_params,
                                                self.weight_dtype)
        _check_swappable(self.params, new_params)
        self.params = new_params
        self.weight_swaps += 1

    def run(self, requests: Iterable[Request], telemetry=None,
            on_tick: Optional[Callable] = None, admission=None) -> dict:
        """Serve a whole trace; returns ``{"results", "errors", "stats"}``.

        ``results`` maps uid -> generated token array; ``stats`` carries
        the throughput/occupancy/compile accounting the serving bench
        reports, plus a ``latency`` sub-dict (p50/p99 TTFT, inter-token,
        end-to-end seconds) from per-request histograms.  Latency anchors
        at the wall time a request's arrival tick is first REACHED — so
        TTFT includes queue wait under load, the user-visible number.

        ``telemetry`` (:class:`..obs.RunTelemetry`) routes the latency/
        queue instruments into the run-level registry and emits an
        ``obs_serve`` event; without it the engine keeps a private
        per-run registry (percentiles are reported either way).

        Validation is PER REQUEST at submit: an invalid request (oversize
        prompt, prompt + ``max_new_tokens`` beyond the slot capacity) is
        recorded under ``errors`` (uid -> message) and the rest of the
        batch completes — one bad request must not abort every other
        request already queued behind it.  (Malformed :class:`Request`
        construction still raises where the request is BUILT — that bug
        belongs to the caller, not the batch.)

        ``on_tick`` receives a :class:`TickReport` after every tick's
        compute but BEFORE its tokens are recorded — a raising hook
        discards the tick (the supervisor's containment seam).
        ``admission`` (:class:`..serve.admission.AdmissionController`)
        is consulted before each placement; shed requests land in
        ``errors`` with a ``"shed: ..."`` message.
        """
        sched = SlotScheduler(self.max_slots)
        n_req = 0
        errors: dict[int, str] = {}
        for req in requests:
            try:
                self._validate(req)
            except ValueError as e:
                errors[req.uid] = str(e)
                continue
            sched.submit(req)
            n_req += 1

        reg = telemetry.registry if telemetry is not None \
            else MetricsRegistry()
        h_ttft = reg.histogram("serve_ttft_seconds")
        h_itl = reg.histogram("serve_intertoken_seconds")
        h_e2e = reg.histogram("serve_e2e_seconds")
        h_tick = reg.histogram("serve_decode_tick_seconds")
        g_queue = reg.gauge("serve_queue_depth")
        g_occ = reg.gauge("serve_slot_occupancy")
        reg.gauge("serve_kv_cache_bytes").set(self.kv_cache_bytes)
        first_wall: dict[int, float] = {}  # uid -> first-token wall time

        tracer = getattr(telemetry, "tracer", None) \
            if telemetry is not None else None
        recorder = getattr(telemetry, "recorder", None) \
            if telemetry is not None else None
        live = LiveSignals()
        root_span: dict[int, int] = {}       # uid -> open request span
        last_tok_wall: dict[int, float] = {}  # uid -> last emit wall
        last_window_emit = -float("inf")

        def retire(req, now):
            """Observe a retired request's TTFT-anchored latencies."""
            arr = sched.arrival_wall.get(req.uid, now)
            h_e2e.observe(now - arr)
            n_tok = len(sched.finished[req.uid])
            fw = first_wall.pop(req.uid, None)
            if fw is not None and n_tok > 1:
                h_itl.observe((now - fw) / (n_tok - 1))
            last_tok_wall.pop(req.uid, None)
            if tracer is not None:
                rid = root_span.pop(req.uid, None)
                tracer.add("retire", now, now, req.trace_id, parent=rid,
                           track=f"req{req.uid}", tokens=n_tok)
                if rid is not None:
                    tracer.end(rid, t1=now)
            if recorder is not None:
                recorder.record("retire", uid=req.uid, tokens=n_tok)

        t_start = time.perf_counter()
        t_prefill = t_decode = 0.0
        tick = prefill_calls = decode_ticks = occupancy_sum = 0
        while sched.pending or sched.occupancy:
            sched.mark_arrivals(tick, time.perf_counter())
            g_queue.set(sched.queue_depth(tick))
            # admit every arrived request a free slot can take; a row
            # retired below frees its slot for the very next tick's admit
            while True:
                head = sched.peek(tick)
                if head is None:
                    break
                if admission is not None:
                    reason = admission.should_shed(
                        head, sched.queue_depth(tick))
                    if reason is not None:
                        shed_req = sched.drop_head(tick)
                        errors[shed_req.uid] = f"shed: {reason}"
                        if recorder is not None:
                            recorder.record("shed", uid=shed_req.uid,
                                            reason=reason)
                        continue
                placed = sched.place(tick)
                if placed is None:
                    break
                idx, req = placed
                if tracer is not None:
                    t_adm = time.perf_counter()
                    arr = sched.arrival_wall.get(req.uid, t_adm)
                    trk = f"req{req.uid}"
                    rid = tracer.begin("request", req.trace_id, track=trk,
                                       t0=arr, prompt_len=len(req.prompt),
                                       max_new_tokens=req.max_new_tokens)
                    root_span[req.uid] = rid
                    tracer.add("queued", arr, t_adm, req.trace_id,
                               parent=rid, track=trk)
                    tracer.add("admit", t_adm, t_adm, req.trace_id,
                               parent=rid, track=trk, slot=idx)
                if recorder is not None:
                    recorder.record("admit", uid=req.uid, slot=idx)
                pb = self.bucket_for(len(req.prompt))
                padded = np.full(pb, self.pad_fill, np.int32)
                padded[:len(req.prompt)] = req.prompt
                t0 = time.perf_counter()
                self.slots, tok, lp, okf = self._prefill(
                    self.params, self.slots, jnp.asarray(padded),
                    np.int32(idx), np.int32(len(req.prompt)),
                    self._next_key())
                first = int(tok)          # host fetch = device barrier
                now = time.perf_counter()
                t_prefill += now - t0
                prefill_calls += 1
                first_wall[req.uid] = now
                h_ttft.observe(now - sched.arrival_wall.get(req.uid, t0))
                live.observe_ttft(
                    now - sched.arrival_wall.get(req.uid, t0), now)
                last_tok_wall[req.uid] = now
                if tracer is not None:
                    tracer.add("prefill", t0, now, req.trace_id,
                               parent=root_span.get(req.uid),
                               track=f"req{req.uid}", bucket=pb,
                               prompt_len=len(req.prompt))
                if on_tick is not None:
                    on_tick(TickReport(
                        tick=tick, kind="prefill", elapsed_s=now - t0,
                        emitted=[(req.uid, first)],
                        finite={req.uid: bool(okf)},
                        logprob={req.uid: float(lp)},
                        slots=[idx], engine=self,
                        queue_depth=sched.queue_depth(tick)))
                done = sched.record(idx, first, self.eos_id)
                if done is not None:
                    retire(done, now)

            if not sched.occupancy:
                nxt = sched.next_arrival()
                if nxt is None:
                    break
                tick = max(tick, nxt)     # idle engine: jump to arrival
                continue

            occupancy_sum += sched.occupancy
            g_occ.set(sched.occupancy)
            t0 = time.perf_counter()
            self.slots, out, lp, okf = self._decode(
                self.params, self.slots,
                jnp.asarray(sched.last_tokens()), self._next_key())
            out = np.asarray(out)         # host fetch = device barrier
            lp, okf = np.asarray(lp), np.asarray(okf)
            now = time.perf_counter()
            t_decode += now - t0
            h_tick.observe(now - t0)
            decode_ticks += 1
            live.sample(sched.queue_depth(tick), sched.occupancy, now)
            if admission is not None:
                admission.observe(live, sched.queue_depth(tick), now)
                admission.apply(self)
            if tracer is not None:
                tracer.add("decode_tick", t0, now, "engine",
                           track="engine", slots=sched.occupancy)
            if on_tick is not None:
                act = sched.active_slots
                on_tick(TickReport(
                    tick=tick, kind="decode", elapsed_s=now - t0,
                    emitted=[(sched.slots[i].request.uid, int(out[i]))
                             for i in act],
                    finite={sched.slots[i].request.uid: bool(okf[i])
                            for i in act},
                    logprob={sched.slots[i].request.uid: float(lp[i])
                             for i in act},
                    slots=list(act), engine=self,
                    queue_depth=sched.queue_depth(tick)))
            for idx in sched.active_slots:
                r = sched.slots[idx].request
                lt = last_tok_wall.get(r.uid)
                if lt is not None:
                    live.observe_itl(now - lt, now)
                last_tok_wall[r.uid] = now
                if tracer is not None:
                    tracer.add("decode", t0, now, r.trace_id,
                               parent=root_span.get(r.uid),
                               track=f"req{r.uid}")
                done = sched.record(idx, int(out[idx]), self.eos_id)
                if done is not None:
                    retire(done, now)
            if telemetry is not None and now - last_window_emit >= 1.0:
                last_window_emit = now
                telemetry.writer.emit("obs_window", scope="serve",
                                      **live.signals(now))
            tick += 1

        total = time.perf_counter() - t_start
        tokens = int(sum(len(v) for v in sched.finished.values()))
        latency = {
            "ttft_p50_s": h_ttft.percentile(50),
            "ttft_p99_s": h_ttft.percentile(99),
            "ttft_mean_s": h_ttft.mean,
            "itl_p50_s": h_itl.percentile(50),
            "itl_p99_s": h_itl.percentile(99),
            "e2e_p50_s": h_e2e.percentile(50),
            "e2e_p99_s": h_e2e.percentile(99),
            "e2e_max_s": h_e2e.max if h_e2e.count else None,
            "measured_requests": h_e2e.count,
        }
        stats = {
            "requests": n_req,
            "rejected": len(errors),
            "generated_tokens": tokens,
            "tokens_per_sec": tokens / total if total else None,
            "total_seconds": total,
            "prefill_seconds": t_prefill,
            "decode_seconds": t_decode,
            "prefill_calls": prefill_calls,
            "decode_ticks": decode_ticks,
            "mean_slot_occupancy":
                occupancy_sum / decode_ticks if decode_ticks else 0.0,
            "max_slots": self.max_slots,
            "kv_cache_bytes": self.kv_cache_bytes,
            "kv_dtype": self.kv_dtype,
            "weight_dtype": self.weight_dtype,
            "prefill_compiles": self._prefill.traces,
            "decode_compiles": self._decode.traces,
            "restarts": self.restarts,
            "weight_swaps": self.weight_swaps,
            "buckets": list(self.buckets),
            "latency": latency,
            "window": live.signals(),
        }
        if telemetry is not None:
            telemetry.writer.emit("obs_serve", stats=stats)
        return {"results": sched.finished, "errors": errors, "stats": stats}


@dataclasses.dataclass
class _SpillRecord:
    """A preempted request parked on the host: its scheduler identity
    plus the slot image needed to resume bit-identically — the token
    stream, commit watermark, pending (emitted, unfed) token, and the
    whole-slot KV copy in the pools' at-rest representation.  Draft
    pools are deliberately NOT captured: a resumed request restarts
    speculation cold, which only costs acceptance (verification stays
    exact), never output tokens."""

    request: Request
    generated: list
    stream: list
    committed: int
    pendtok: int
    kv: object                       # parked pytree, pools' treedef
                                     # (host arrays, or device arrays on
                                     # the spill device under
                                     # migrate="device")
    seq: int                         # spill order, FIFO tiebreak
    digest: Optional[bytes] = None   # end-to-end integrity (device path)


class PagedEngine:
    """Paged continuous batching: prefix reuse, chunked prefill,
    speculative decoding — identical greedy outputs, fewer FLOPs.

    The three classic serving optimizations, mapped onto the same
    compile-once discipline as :class:`ServeEngine`:

    * **Paged KV with prefix reuse** (:mod:`.paged`) — cache leaves live
      in fixed-size block pools; each slot holds a block TABLE.  A
      rolling chain hash over token-prefix chunks indexes committed
      blocks, so a request whose prompt prefix was served before
      references those blocks instead of recomputing them (refcounted;
      copy-on-write the moment it diverges mid-block).  Tables and
      positions are device DATA, so program shapes never change.
    * **Chunked prefill** (:mod:`.prefill`) — prompts land in fixed-size
      chunks interleaved with decode ticks under a per-tick budget, so
      one long prompt stalls live streams by at most ~one chunk of
      compute instead of a whole prompt.
    * **Speculative decoding** (:mod:`.spec`) — a truncated-layer draft
      sharing the target's weights proposes ``spec_k`` tokens per round;
      the target scores all ``spec_k + 1`` positions in ONE batched
      cached forward and keeps the longest greedy-matching prefix.
      Greedy parity is exact (see :func:`.spec.greedy_accept`); only
      the forward count changes.

    Each device program (chunk prefill, decode, draft propose, verify,
    draft chunk, block copy) runs through :class:`CountingJit` and
    compiles exactly ONCE for the engine's lifetime — asserted by
    tests, not assumed.  The block pools, prefix index, and compiled
    programs persist across ``run()`` calls, so a later trace sharing
    prompts with an earlier one starts with a warm prefix cache.
    """

    def __init__(self, model: CausalLM, params, *, max_slots: int = 8,
                 max_len: Optional[int] = None, kv_block_size: int = 16,
                 num_blocks: Optional[int] = None, prefill_chunk: int = 32,
                 prefill_chunks_per_tick: int = 1,
                 draft_layers: Optional[int] = None, spec_k: int = 4,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 rng=None, donate: Optional[bool] = None,
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 preempt: bool = False,
                 spill_dir: Optional[str] = None,
                 migrate: str = "host"):
        validate_sampling(top_k, top_p)
        quant.check_dtype("kv_dtype", kv_dtype)
        quant.check_dtype("weight_dtype", weight_dtype)
        self.kv_dtype, self.weight_dtype = kv_dtype, weight_dtype
        # working precision, captured before params go at-rest (the
        # compiled impls dequantize back to it at their top — see
        # ServeEngine; same contract here)
        self.compute_dtype = jax.tree.leaves(params)[0].dtype
        if weight_dtype is not None:
            params = quant.quantize_weights(params, weight_dtype)
        self.model, self.params = model, params
        self.lm = make_decode_model(model)
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_len = int(max_len if max_len is not None else model.max_len)
        self.eos_id = eos_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self.pad_fill = model.pad_id if model.pad_id is not None else 0
        self._key = rng if rng is not None else jax.random.key(0)

        bs = int(kv_block_size)
        if bs < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {bs}")
        self.block_size = bs
        self.chunk = int(prefill_chunk)
        if self.chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.chunks_per_tick = max(1, int(prefill_chunks_per_tick))

        self.spec_k = int(spec_k)
        self.draft_layers = draft_layers
        if draft_layers is not None:
            if temperature != 0.0:
                raise ValueError("speculative decoding is greedy-only "
                                 "(acceptance is exact-match against the "
                                 "target argmax); set temperature=0")
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        # speculation writes up to spec_k positions past the stream tip,
        # so the slot's logical buffer gets that much headroom on top of
        # the serving cap, rounded up to whole blocks
        headroom = (self.spec_k + 1) if draft_layers is not None else 0
        self.padded_len = -(-(self.max_len + headroom) // bs) * bs
        if self.padded_len > model.max_len:
            raise ValueError(
                f"slot buffer {self.padded_len} (max_len {self.max_len} + "
                f"speculative headroom {headroom}, in whole blocks) "
                f"exceeds the model's max_len {model.max_len}; lower "
                f"max_len or spec_k")
        if self.chunk > self.padded_len:
            raise ValueError(f"prefill_chunk {self.chunk} exceeds the "
                             f"slot buffer {self.padded_len}")
        self.blocks_per_slot = self.padded_len // bs
        if num_blocks is None:
            # 1x for the live slots + 1x retention headroom so the
            # prefix index can keep blocks alive after their request
            num_blocks = 2 * self.max_slots * self.blocks_per_slot
        self.num_blocks = int(num_blocks)
        self.manager = paged.BlockManager(num_blocks, bs, self.max_slots,
                                          self.blocks_per_slot)
        if donate is None:
            donate = jax.default_backend() != "cpu"
        dk = {"donate_argnums": (1,)} if donate else {}
        ck = {"donate_argnums": (0,)} if donate else {}
        self.pools = paged.build_pools(self.lm, num_blocks + 1, bs,
                                       self.padded_len,
                                       kv_dtype=self.kv_dtype)
        self._chunk_prog = CountingJit(self._chunk_impl, **dk)
        self._decode = CountingJit(self._decode_impl, **dk)
        self._copy = CountingJit(self._copy_impl, **ck)
        if spill_dir is not None and not preempt:
            raise ValueError("spill_dir requires preempt=True (it is the "
                             "preemption spill audit directory)")
        self._preempt = bool(preempt)
        self.spill_dir = spill_dir
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        # preemption spill transport: "host" round-trips the slot image
        # through host numpy (always available); "device" parks it on
        # another local device via the chunked migration schedule — no
        # host copy on the hot path, digest-audited end to end.  The
        # npz audit (spill_dir) is written either way.
        if migrate not in ("host", "device"):
            raise ValueError(f"migrate must be 'host' or 'device', got "
                             f"{migrate!r}")
        if migrate == "device" and len(jax.local_devices()) < 2:
            raise ValueError(
                "migrate='device' needs a second local device to park "
                "spilled KV on; only 1 is visible (use migrate='host', "
                "or run under a multi-device mesh)")
        self.migrate_kind = migrate
        self._home_device = jax.local_devices()[0]
        self._spill_device = (jax.local_devices()[-1]
                              if migrate == "device" else None)
        #: fault-injection seam: callable payload -> payload applied to
        #: the spilled KV before the device hop (the ``migrate_drop``
        #: chaos kind); the resume-side digest check turns any
        #: corruption into a MigrationError the supervisor replays.
        self._migrate_chaos = None
        self._spill_moves = 0
        self._spill_move_bytes = 0
        self._spill_move_seconds = 0.0
        # spill gathers a whole slot WITHOUT donating the pools (they
        # must survive the read); unspill donates them like every other
        # pool-updating program
        self._spill = CountingJit(self._spill_impl)
        self._unspill = CountingJit(self._unspill_impl, **ck)
        if draft_layers is not None:
            self.draft_lm, self.draft_params = spec_mod.truncated_draft(
                self.lm, params, draft_layers)
            # the draft pool INHERITS kv_dtype: speculation gathers and
            # scatters through the same shims, so a mixed-precision pair
            # would silently double the draft's footprint
            self.draft_pools = paged.build_pools(self.draft_lm,
                                                 num_blocks + 1, bs,
                                                 self.padded_len,
                                                 kv_dtype=self.kv_dtype)
            self._draft = CountingJit(self._draft_impl, **dk)
            self._verify = CountingJit(self._verify_impl, **dk)
            self._draft_chunk = CountingJit(self._draft_chunk_impl, **dk)
            self._draft_copy = CountingJit(self._draft_copy_impl, **ck)
        # exact KV footprint: every allocated pool pytree (draft included
        # when speculating) — the paged analogue of ServeEngine's slots
        self.kv_cache_bytes = obs_memory.pytree_bytes(self.pools)
        if draft_layers is not None:
            self.kv_cache_bytes += obs_memory.pytree_bytes(self.draft_pools)
        self.restarts = 0
        self.weight_swaps = 0
        self._spec_enabled = draft_layers is not None
        self._base_chunks_per_tick = self.chunks_per_tick
        self._canary: Optional[_CanaryState] = None

    # --- quantization shims (identity at full precision) ------------------
    def _wp(self, params):
        """At-rest params -> compute-dtype view inside the jitted impl
        (the int8 upcast fuses into each consuming matmul; no full-
        precision weight copy exists between programs)."""
        if self.weight_dtype is None:
            return params
        return quant.dequantize_weights(params, self.compute_dtype)

    def _gather(self, pools, table, pos):
        """Gather one slot's logical cache and lift it to the model's
        working precision (int8 pools dequantize ``q * s`` in f32)."""
        got = paged.gather_slot(pools, table, pos)
        if self.kv_dtype is None:
            return got
        return quant.dequant_cache(got, self.compute_dtype)

    def _qspan(self, span):
        """Freshly-computed floating KV span -> the pools' at-rest
        representation (per-position-per-head int8 scales travel with
        the payload as one :class:`..serve.quant.QuantTensor`)."""
        if self.kv_dtype is None:
            return span
        return quant.quantize_cache_span(span, self.kv_dtype)

    # --- compiled programs (each traces exactly once) ---------------------
    def _sample(self, params, hidden_last, key):
        """Sample plus chosen-token log-prob and per-row finiteness.
        ``params`` is a traced argument, never a closure capture — the
        same program therefore serves ANY weights of identical geometry
        (hot swap, canary) without retracing."""
        toks, _ = sample_tokens(self.model, params, hidden_last, key,
                                temperature=self.temperature,
                                top_k=self.top_k, top_p=self.top_p)
        nl = self.model.logits_from({"params": params}, hidden_last)
        lp = jnp.take_along_axis(jax.nn.log_softmax(nl, axis=-1),
                                 toks[:, None], axis=-1)[:, 0]
        ok = jnp.isfinite(hidden_last).all(axis=-1)
        return toks, lp, ok

    def _chunk_impl(self, params, pools, tokens, table, pos, logit_idx,
                    wb, wo, key):
        """One prefill chunk for one slot: gather its logical cache,
        run the chunk through the model's multi-token cached forward,
        scatter the fresh KV span to its blocks (already-committed /
        padding positions routed to trash), and sample at ``logit_idx``
        (meaningful on the final chunk only — the caller ignores it
        otherwise; the extra 1-row head projection is noise)."""
        params = self._wp(params)
        cache = self._gather(pools, table, pos)
        hidden, new = cached_apply(self.lm, params, cache, tokens[None])
        span = paged.extract_span(new, pos, self.chunk)
        pools = paged.scatter_span(pools, self._qspan(span), wb, wo)
        h_last = jax.lax.dynamic_slice_in_dim(hidden[0], logit_idx, 1)
        tok, lp, ok = self._sample(params, h_last, key)
        return pools, tok[0], lp[0], ok[0]

    def _draft_chunk_impl(self, dparams, dpools, tokens, table, pos,
                          wb, wo):
        """The draft model's KV for the same chunk — speculation needs
        the draft's cache warm over the whole committed stream."""
        dparams = self._wp(dparams)
        cache = self._gather(dpools, table, pos)
        _, new = cached_apply(self.draft_lm, dparams, cache, tokens[None])
        span = paged.extract_span(new, pos, self.chunk)
        return paged.scatter_span(dpools, self._qspan(span), wb, wo)

    def _decode_impl(self, params, pools, tables, positions, toks,
                     wb, wo, key):
        """One token for every slot: gather each slot's logical cache
        from the pools, run the model's single-sequence cached decode
        (vmapped), scatter each slot's new KV position back, one shared
        sampling.  Free/prefilling slots run on garbage and write to
        trash; their sampled tokens are ignored by the host."""
        params = self._wp(params)

        def one(table, pos, tok):
            cache = self._gather(pools, table, pos)
            hidden, new = cached_apply(self.lm, params, cache,
                                       tok[None, None])
            return hidden[0, 0], paged.extract_span(new, pos, 1)

        h, spans = jax.vmap(one)(tables, positions, toks)
        kv = jax.tree_util.tree_map_with_path(
            lambda p, x: x if paged.is_counter(p) else x[:, 0], spans)
        pools = paged.scatter_span(pools, self._qspan(kv), wb, wo)
        toks, lp, ok = self._sample(params, h, key)
        return pools, toks, lp, ok

    def _draft_impl(self, dparams, dpools, tables, positions, toks,
                    wb, wo):
        """Draft proposal round: ``spec_k + 1`` greedy cached steps per
        slot (scan), writing draft KV at positions ``c .. c+k``.  The
        extra step exists to WRITE position ``c+k`` (its proposal is
        discarded) so an all-accept round leaves no KV hole."""
        T = self.spec_k + 1
        dparams = self._wp(dparams)

        def one(table, pos, tok):
            cache = self._gather(dpools, table, pos)

            def step(carry, _):
                c, t = carry
                hidden, c = cached_apply(self.draft_lm, dparams, c,
                                         t[None, None])
                nxt, _ = sample_tokens(self.draft_lm, dparams,
                                       hidden[0, 0][None],
                                       jax.random.key(0), temperature=0.0)
                nt = nxt[0].astype(t.dtype)
                return (c, nt), nt

            (cache, _), outs = jax.lax.scan(step, (cache, tok), None,
                                            length=T)
            return outs, paged.extract_span(cache, pos, T)

        outs, spans = jax.vmap(one)(tables, positions, toks)
        dpools = paged.scatter_span(dpools, self._qspan(spans), wb, wo)
        return dpools, outs[:, :self.spec_k]

    def _verify_impl(self, params, pools, tables, positions, toks, wb, wo):
        """Target verification: ONE batched ``spec_k + 1``-token cached
        forward per slot scores the pending token plus every draft
        proposal; returns the target's greedy choice at each position.
        This is the whole speedup: ``a + 1`` tokens per target forward
        instead of 1."""
        T = self.spec_k + 1
        params = self._wp(params)

        def one(table, pos, tk):
            cache = self._gather(pools, table, pos)
            hidden, new = cached_apply(self.lm, params, cache, tk[None])
            return hidden[0], paged.extract_span(new, pos, T)

        h, spans = jax.vmap(one)(tables, positions, toks)
        pools = paged.scatter_span(pools, self._qspan(spans), wb, wo)
        g, lp, _ = self._sample(params, h.reshape(-1, h.shape[-1]),
                                jax.random.key(0))
        ok = jnp.isfinite(h).all(axis=(1, 2))
        return (pools, g.reshape(tables.shape[0], T),
                lp.reshape(tables.shape[0], T), ok)

    def _copy_impl(self, pools, src, dst):
        return paged.copy_block(pools, src, dst)

    def _draft_copy_impl(self, dpools, src, dst):
        return paged.copy_block(dpools, src, dst)

    def _spill_impl(self, pools, table):
        """One slot's whole logical cache in its AT-REST representation
        (no dequant — an int8 pool spills int8 + scales, so the round
        trip back through :meth:`_unspill_impl` is bit-exact by
        construction).  The preemption read path."""
        return paged.gather_slot(pools, table, 0)

    def _unspill_impl(self, pools, kv, blocks, offsets):
        """Write a spilled slot image back: positions ``< committed``
        land in the resumed slot's fresh blocks, everything beyond is
        routed to trash by the host-built ``blocks`` vector."""
        kv = jax.tree_util.tree_map_with_path(
            lambda p, x: x if paged.is_counter(p) else x[0], kv)
        return paged.scatter_span(pools, kv, blocks, offsets)

    # --- host side --------------------------------------------------------
    def _cow(self, src: int, dst: int) -> None:
        """Device half of copy-on-write: duplicate the physical block in
        the target pools (and the draft pools, whose tables are shared,
        when speculation is on)."""
        s, d = np.int32(src), np.int32(dst)
        self.pools = self._copy(self.pools, s, d)
        if self.draft_layers is not None:
            self.draft_pools = self._draft_copy(self.draft_pools, s, d)

    def _make_writable(self, idx: int, lo_pos: int, hi_pos: int) -> int:
        """Run the manager's COW check over every logical block touched
        by positions ``[lo_pos, hi_pos]`` BEFORE computing scatter
        targets (the check may swap table entries).  Returns the number
        of blocks actually copied (0 on the common no-COW path), so the
        caller can attribute a COW span without timing the no-op case."""
        copies = 0
        for lg in range(lo_pos // self.block_size,
                        hi_pos // self.block_size + 1):
            pair = self.manager.writable(idx, lg)
            if pair is not None:
                self._cow(*pair)
                copies += 1
        return copies

    def _validate(self, req: Request) -> None:
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {req.uid}: prompt {len(req.prompt)} + "
                f"{req.max_new_tokens} new tokens exceeds the serving "
                f"capacity max_len={self.max_len}")
        # worst-case block need (zero prefix sharing) must fit the pool
        # — checked at SUBMIT so one impossible request lands in
        # ``errors`` instead of raising BlockPoolExhausted mid-run and
        # taking the whole batch with it (the v1/paged error-contract
        # unification the supervisor relies on)
        worst = -(-self._capacity_len(req) // self.block_size)
        if worst > self.num_blocks:
            raise ValueError(
                f"request {req.uid}: needs up to {worst} KV blocks "
                f"({self._capacity_len(req)} positions at block size "
                f"{self.block_size}) but the pool holds only "
                f"{self.num_blocks}")

    def _capacity_len(self, req: Request) -> int:
        """Stream positions a request may ever write — its whole block
        budget, reserved at admission (which is why the pool cannot
        deadlock: an admitted request never waits for blocks)."""
        extra = (self.spec_k + 1) if self.draft_layers is not None else 0
        return min(len(req.prompt) + req.max_new_tokens + extra,
                   self.padded_len)

    def _next_key(self):
        if self.temperature == 0.0:
            return self._key           # unused by greedy sampling
        self._key, sub = jax.random.split(self._key)
        return sub

    # --- resilience seams -------------------------------------------------
    def reset(self) -> None:
        """Warm restart after a contained fault: fresh block pools and
        a fresh block manager (so poisoned KV AND the prefix index that
        could resurrect it both die), SAME compiled programs — the new
        pools have identical shapes, so nothing retraces and
        ``decode_compiles`` stays put."""
        self._canary = None
        self.manager = paged.BlockManager(self.num_blocks, self.block_size,
                                          self.max_slots,
                                          self.blocks_per_slot)
        self.pools = paged.build_pools(self.lm, self.num_blocks + 1,
                                       self.block_size, self.padded_len,
                                       kv_dtype=self.kv_dtype)
        if self.draft_layers is not None:
            self.draft_pools = paged.build_pools(
                self.draft_lm, self.num_blocks + 1, self.block_size,
                self.padded_len, kv_dtype=self.kv_dtype)
        self.restarts += 1

    def swap_params(self, new_params) -> None:
        """Hot weight swap between ticks: geometry-checked params slide
        into the compiled programs (traced arguments, not baked
        constants) — no recompile.  The prefix index is flushed: its KV
        was computed under the old weights, and matching it under the
        new ones would mix generations.  Draft params re-derive from
        the new target (they share weights by construction).  A
        quantized engine takes the (full-precision) publish to its
        at-rest form first, so the geometry check compares like with
        like."""
        if self.weight_dtype is not None:
            new_params = quant.quantize_weights(new_params,
                                                self.weight_dtype)
        _check_swappable(self.params, new_params)
        self.params = new_params
        if self.draft_layers is not None:
            self.draft_lm, self.draft_params = spec_mod.truncated_draft(
                self.lm, new_params, self.draft_layers)
        self.manager.flush_index()
        self.weight_swaps += 1

    def set_spec_enabled(self, enabled: bool) -> bool:
        """Toggle speculative decoding at runtime (admission control's
        first degradation step).  Returns the effective state; always
        False when the engine has no draft.  Greedy OUTPUTS are
        unaffected either way — disabling only changes the forward
        count, and re-enabling after a gap merely costs acceptance
        (the draft's cache has holes; verification stays exact)."""
        if self.draft_layers is None:
            return False
        self._spec_enabled = bool(enabled)
        return self._spec_enabled

    def begin_canary(self, new_params, slots: Iterable[int],
                     observe: Optional[Callable] = None) -> None:
        """Route ``slots`` to candidate weights while everyone else
        stays on the stable ones — one extra call of the SAME compiled
        decode program per tick, old/new KV writes cross-routed to the
        trash block so neither generation's cache sees the other's."""
        if self._canary is not None:
            raise RuntimeError("a canary is already active")
        if self.draft_layers is not None:
            raise RuntimeError(
                "canary mode requires a non-speculative engine (the "
                "draft's shared cache cannot serve two weight sets)")
        if self.weight_dtype is not None:
            new_params = quant.quantize_weights(new_params,
                                                self.weight_dtype)
        _check_swappable(self.params, new_params)
        sl = frozenset(int(s) for s in slots)
        if not sl or not all(0 <= s < self.max_slots for s in sl):
            raise ValueError(f"canary slots {sorted(sl)} must be a "
                             f"non-empty subset of 0..{self.max_slots - 1}")
        if len(sl) >= self.max_slots:
            raise ValueError("canary cannot take every slot (no stable "
                             "traffic left to compare against)")
        self._canary = _CanaryState(params=new_params, slots=sl,
                                    observe=observe)

    def end_canary(self, promote: bool) -> dict:
        """Finish the canary: promote swaps the candidate in for ALL
        slots (prefix index flushed); rollback just drops it.  Either
        way returns the engine-side comparison summary."""
        if self._canary is None:
            raise RuntimeError("no canary is active")
        can, self._canary = self._canary, None
        if promote:
            self.swap_params(can.params)
        return can.summary()

    def _canary_decode(self, mgr, pos, toks, wb, wo, dec):
        """One decode tick under an active canary: two calls of the one
        compiled program.  Call A (stable params) trashes canary slots'
        KV writes; call B (candidate params) trashes everyone else's —
        each weight set's cache stays self-consistent.  Tokens merge
        per slot; canary slots contribute agreement/drift samples."""
        can = self._canary
        wb_old, wb_new = wb.copy(), wb.copy()
        for i in range(self.max_slots):
            if i in can.slots:
                wb_old[i] = paged.TRASH
            else:
                wb_new[i] = paged.TRASH
        tables_dev = jnp.asarray(mgr.tables)
        pos_dev, toks_dev = jnp.asarray(pos), jnp.asarray(toks)
        wo_dev = jnp.asarray(wo)
        key = self._next_key()
        self.pools, out_o, lp_o, ok_o = self._decode(
            self.params, self.pools, tables_dev, pos_dev, toks_dev,
            jnp.asarray(wb_old), wo_dev, key)
        self.pools, out_n, lp_n, ok_n = self._decode(
            can.params, self.pools, tables_dev, pos_dev, toks_dev,
            jnp.asarray(wb_new), wo_dev, key)
        out_o, lp_o, ok_o = (np.asarray(x) for x in (out_o, lp_o, ok_o))
        out_n, lp_n, ok_n = (np.asarray(x) for x in (out_n, lp_n, ok_n))
        now = time.perf_counter()
        out, lp, ok = out_o.copy(), lp_o.copy(), ok_o.copy()
        for i in can.slots:
            out[i], lp[i], ok[i] = out_n[i], lp_n[i], ok_n[i]
        for i in dec:
            if i in can.slots:
                drift = abs(float(lp_n[i]) - float(lp_o[i]))
                can.note(agree=int(out_o[i]) == int(out_n[i]),
                         drift=drift if np.isfinite(drift) else np.inf,
                         finite=bool(ok_n[i]), now=now)
        return out, lp, ok

    def run(self, requests: Iterable[Request], telemetry=None,
            keep_timeline: bool = False, on_tick: Optional[Callable] = None,
            admission=None) -> dict:
        """Serve a trace; returns ``{"results", "errors", "stats"}``
        (plus ``"timeline"`` when ``keep_timeline`` — one dict per tick
        with ``placed``/``chunks``/``decoded`` uid lists, the record the
        fairness and stall-bound tests assert on).

        ``stats`` carries the v1 throughput/latency accounting plus
        ``paged`` (block pool + prefix hit rate), ``spec`` (acceptance),
        and ``slo`` (attainment from per-request SLOs) sub-records.
        """
        sched = PagedScheduler(self.max_slots)
        mgr = self.manager
        bs = self.block_size
        n_req = 0
        errors: dict[int, str] = {}
        accepted: list[Request] = []
        for req in requests:
            try:
                self._validate(req)
            except ValueError as e:
                errors[req.uid] = str(e)
                continue
            sched.submit(req)
            accepted.append(req)
            n_req += 1

        reg = telemetry.registry if telemetry is not None \
            else MetricsRegistry()
        h_ttft = reg.histogram("serve_ttft_seconds")
        h_itl = reg.histogram("serve_intertoken_seconds")
        h_e2e = reg.histogram("serve_e2e_seconds")
        h_tick = reg.histogram("serve_decode_tick_seconds")
        h_chunks = reg.histogram("serve_chunks_per_tick")
        h_accept = reg.histogram("serve_spec_acceptance")
        g_queue = reg.gauge("serve_queue_depth")
        g_occ = reg.gauge("serve_slot_occupancy")
        g_blocks = reg.gauge("serve_kv_blocks_in_use")
        g_hit = reg.gauge("serve_prefix_hit_rate")
        reg.gauge("serve_kv_cache_bytes").set(self.kv_cache_bytes)

        # per-slot host state: the token stream (prompt + emitted), how
        # many positions hold committed KV, remaining chunk plans, and
        # the pending token (emitted, not yet fed)
        stream: dict[int, list] = {}
        committed: dict[int, int] = {}
        plans: dict[int, list] = {}
        pendtok: dict[int, int] = {}
        first_wall: dict[int, float] = {}
        ttft_s: dict[int, float] = {}
        e2e_s: dict[int, float] = {}
        timeline = [] if keep_timeline else None

        shared_tokens = prompt_tokens = 0
        chunk_calls = spec_rounds = proposed_total = accepted_total = 0
        decode_ticks = occupancy_sum = 0
        t_prefill = t_decode = 0.0

        tracer = getattr(telemetry, "tracer", None) \
            if telemetry is not None else None
        recorder = getattr(telemetry, "recorder", None) \
            if telemetry is not None else None
        live = LiveSignals()
        root_span: dict[int, int] = {}       # uid -> open request span
        last_tok_wall: dict[int, float] = {}  # uid -> last emit wall
        last_window_emit = -float("inf")
        slo_tripped = False
        if recorder is not None:
            # block-manager events (evictions, COW detaches) go straight
            # into the black box; cleared before run() returns because
            # the manager outlives the run
            mgr.on_event = (lambda kind, **f:
                            recorder.record("kv_" + kind, **f))

        def check_slo(req, now):
            """Compare measured latencies against the request's SLOs;
            breaches land in the flight recorder and the FIRST breach
            trips a dump (the black box for "why did we fall off SLO")."""
            nonlocal slo_tripped
            breaches = []
            t = ttft_s.get(req.uid)
            if req.slo_ttft_ms is not None and t is not None \
                    and t * 1e3 > req.slo_ttft_ms:
                breaches.append(("ttft", t * 1e3, req.slo_ttft_ms))
            e = e2e_s.get(req.uid)
            if req.slo_e2e_ms is not None and e is not None \
                    and e * 1e3 > req.slo_e2e_ms:
                breaches.append(("e2e", e * 1e3, req.slo_e2e_ms))
            for which, ms, slo in breaches:
                recorder.record("slo_breach", uid=req.uid, which=which,
                                measured_ms=ms, slo_ms=slo)
            if breaches and not slo_tripped:
                slo_tripped = True
                recorder.trip("slo_breach")

        def retire(req, idx, now):
            mgr.release(idx)
            for d in (stream, committed, plans, pendtok):
                d.pop(idx, None)
            arr = sched.arrival_wall.get(req.uid, now)
            e2e_s[req.uid] = now - arr
            h_e2e.observe(now - arr)
            n_tok = len(sched.finished[req.uid])
            fw = first_wall.pop(req.uid, None)
            if fw is not None and n_tok > 1:
                h_itl.observe((now - fw) / (n_tok - 1))
            last_tok_wall.pop(req.uid, None)
            if tracer is not None:
                rid = root_span.pop(req.uid, None)
                tracer.add("retire", now, now, req.trace_id, parent=rid,
                           track=f"req{req.uid}", tokens=n_tok, slot=idx)
                if rid is not None:
                    tracer.end(rid, t1=now)
            if recorder is not None:
                recorder.record("retire", uid=req.uid, slot=idx,
                                tokens=n_tok)
                check_slo(req, now)

        def emit(idx, token, now):
            """Record one generated token; True when the slot retired
            (EOS or budget — same truncation rules as v1/generate)."""
            uid = sched.slots[idx].request.uid
            lt = last_tok_wall.get(uid)
            if lt is not None:
                live.observe_itl(now - lt, now)
            last_tok_wall[uid] = now
            done = sched.record(idx, token, self.eos_id)
            if done is not None:
                retire(done, idx, now)
                return True
            return False

        def make_writable(idx, lo, hi):
            """COW check with span attribution: the no-copy common case
            costs one extra clock read only when tracing is on."""
            if tracer is None:
                self._make_writable(idx, lo, hi)
                return
            t0 = time.perf_counter()
            n = self._make_writable(idx, lo, hi)
            if n:
                req = sched.slots[idx].request
                tracer.add("cow", t0, time.perf_counter(), req.trace_id,
                           parent=root_span.get(req.uid),
                           track=f"req{req.uid}", copies=n)

        def run_chunk(idx, ev):
            nonlocal chunk_calls, t_prefill
            req = sched.slots[idx].request
            plan = plans[idx].pop(0)
            L = len(req.prompt)
            toks = chunk_tokens(stream[idx], plan, self.chunk,
                                self.pad_fill)
            rid = root_span.get(req.uid)
            make_writable(idx, committed[idx], plan.commit_to - 1)
            wb, wo, _ = write_targets(plan.feed_start, self.chunk,
                                      committed[idx], L,
                                      mgr.tables[idx], bs)
            table_dev = jnp.asarray(mgr.tables[idx])
            toks_dev = jnp.asarray(toks, jnp.int32)
            wb_dev, wo_dev = jnp.asarray(wb), jnp.asarray(wo)
            pos = np.int32(plan.feed_start)
            t0 = time.perf_counter()
            self.pools, tok, c_lp, c_ok = self._chunk_prog(
                self.params, self.pools, toks_dev, table_dev, pos,
                np.int32(max(plan.logit_index, 0)), wb_dev, wo_dev,
                self._next_key())
            if self.draft_layers is not None:
                self.draft_pools = self._draft_chunk(
                    self.draft_params, self.draft_pools, toks_dev,
                    table_dev, pos, wb_dev, wo_dev)
            committed[idx] = plan.commit_to
            mgr.register_committed(idx, stream[idx], committed[idx])
            chunk_calls += 1
            if ev is not None:
                ev["chunks"].append(req.uid)
            sched.note_chunk(idx)
            if plan.is_last:
                first = int(tok)       # host fetch = device barrier
                now = time.perf_counter()
                t_prefill += now - t0
                pendtok[idx] = first
                first_wall[req.uid] = now
                ttft_s[req.uid] = now - sched.arrival_wall.get(req.uid,
                                                               now)
                h_ttft.observe(ttft_s[req.uid])
                live.observe_ttft(ttft_s[req.uid], now)
                if tracer is not None:
                    tracer.add("prefill_chunk", t0, now, req.trace_id,
                               parent=rid, track=f"req{req.uid}",
                               feed_start=plan.feed_start,
                               commit_to=plan.commit_to, is_last=True)
                if on_tick is not None:
                    on_tick(TickReport(
                        tick=tick, kind="prefill", elapsed_s=now - t0,
                        emitted=[(req.uid, first)],
                        finite={req.uid: bool(c_ok)},
                        logprob={req.uid: float(c_lp)},
                        slots=[idx], engine=self,
                        queue_depth=sched.queue_depth(tick)))
                stream[idx].append(first)
                emit(idx, first, now)
            else:
                jax.block_until_ready(self.pools)
                t1 = time.perf_counter()
                t_prefill += t1 - t0
                if tracer is not None:
                    tracer.add("prefill_chunk", t0, t1, req.trace_id,
                               parent=rid, track=f"req{req.uid}",
                               feed_start=plan.feed_start,
                               commit_to=plan.commit_to, is_last=False)

        # --- priority preemption (opt-in): spilled-slot parking lot ----
        spilled: list[_SpillRecord] = []
        preempt_count = resume_count = spill_seq = 0

        def preempt_one(head, ev):
            """Spill ONE victim slot to make room for ``head``.  The
            victim is the lowest-priority decoding slot strictly below
            ``head`` (priority 0 is structurally unpreemptable: nothing
            outranks it), most-progressed first so the evicted work is
            the cheapest to finish later.  Returns False when no
            eligible victim exists."""
            nonlocal preempt_count, spill_seq
            cands = [i for i in sched.decoding_slots()
                     if sched.slots[i].request.priority > head.priority]
            if not cands:
                return False
            victim = sorted(
                cands,
                key=lambda i: (-sched.slots[i].request.priority,
                               len(sched.slots[i].generated), i))[0]
            t0_sp = time.perf_counter()
            kv_dev = self._spill(self.pools,
                                 jnp.asarray(mgr.tables[victim]))
            if self.migrate_kind == "device":
                # device-to-device handoff: digest the at-rest image,
                # then park it on the spill device via the chunked
                # migration schedule — no host copy, no barrier beyond
                # the digest read (which doubles as the audit)
                from distributed_deep_learning_tpu.serve import \
                    migrate as migrate_mod
                digest = migrate_mod.tree_digest(kv_dev)
                payload = kv_dev
                if self._migrate_chaos is not None:
                    payload = self._migrate_chaos(payload)
                kv = migrate_mod.offload(payload, self._spill_device)
                self._spill_moves += 1
                self._spill_move_bytes += migrate_mod.tree_bytes(kv_dev)
                self._spill_move_seconds += time.perf_counter() - t0_sp
            else:
                kv = jax.tree.map(np.asarray, kv_dev)  # host copy=barrier
                digest = None
            req, gen = sched.preempt(victim)
            mgr.release(victim)
            rec = _SpillRecord(request=req, generated=gen,
                               stream=stream.pop(victim),
                               committed=committed.pop(victim),
                               pendtok=pendtok.pop(victim),
                               kv=kv, seq=spill_seq, digest=digest)
            plans.pop(victim, None)
            spill_seq += 1
            spilled.append(rec)
            preempt_count += 1
            if self.spill_dir is not None:
                np.savez(os.path.join(
                    self.spill_dir, f"spill-{req.uid}-{rec.seq}.npz"),
                    **{f"leaf_{i:05d}": leaf for i, leaf in
                       enumerate(jax.tree.leaves(kv))})
            if ev is not None:
                ev["preempted"].append(req.uid)
            if recorder is not None:
                recorder.record("preempt", uid=req.uid, slot=victim,
                                committed=rec.committed,
                                by_uid=head.uid)
            return True

        def resume_one(ev):
            """Un-park the best spilled request (highest priority, then
            FIFO) into a free slot: fresh block budget, scatter the
            committed KV image back, restore the host stream state.
            Bit-identity holds because every committed position returns
            in its at-rest representation and greedy decode is batch-
            invariant.  False when no slot/budget is available."""
            nonlocal resume_count
            if not spilled or sched.occupancy >= self.max_slots:
                return False
            rec = min(spilled, key=lambda r: (r.request.priority, r.seq))
            need = self._capacity_len(rec.request)
            sp0 = paged.SharedPrefix([], None, 0, b"")
            if not mgr.can_admit(sp0, need):
                return False
            idx = sched.restore(rec.request, rec.generated)
            if idx is None:
                return False
            mgr.admit(idx, sp0, need)
            pidx = np.arange(self.padded_len)
            blocks = np.where(pidx < rec.committed,
                              mgr.tables[idx][pidx // bs],
                              paged.TRASH).astype(np.int32)
            offsets = (pidx % bs).astype(np.int32)
            if self.migrate_kind == "device":
                # hop the parked image back, then verify the round trip
                # end to end: a transfer lost or corrupted in EITHER
                # direction surfaces here, before anything is scattered
                # into the live pools
                from distributed_deep_learning_tpu.serve import \
                    migrate as migrate_mod
                t0_rs = time.perf_counter()
                kv_in = migrate_mod.offload(rec.kv, self._home_device)
                if rec.digest is not None and \
                        migrate_mod.tree_digest(kv_in) != rec.digest:
                    raise migrate_mod.MigrationError(
                        f"device spill/resume of request "
                        f"{rec.request.uid} failed its digest check — "
                        f"KV transfer lost or corrupted; replay from "
                        f"the ledger")
                self._spill_moves += 1
                self._spill_move_bytes += migrate_mod.tree_bytes(kv_in)
                self._spill_move_seconds += time.perf_counter() - t0_rs
                # the hop commits kv_in to the home device, but pools
                # born under a training mesh can live replicated across
                # it — match their placement or the scatter jit rejects
                # the mixed commitment
                kv_in = jax.device_put(
                    kv_in,
                    jax.tree.map(lambda l: l.sharding, self.pools))
            else:
                kv_in = jax.tree.map(jnp.asarray, rec.kv)
            self.pools = self._unspill(
                self.pools, kv_in,
                jnp.asarray(blocks), jnp.asarray(offsets))
            stream[idx] = rec.stream
            committed[idx] = rec.committed
            pendtok[idx] = rec.pendtok
            plans.pop(idx, None)
            mgr.register_committed(idx, stream[idx], committed[idx])
            spilled.remove(rec)
            resume_count += 1
            if ev is not None:
                ev["resumed"].append(rec.request.uid)
            if recorder is not None:
                recorder.record("resume", uid=rec.request.uid, slot=idx,
                                committed=rec.committed)
            return True

        t_start = time.perf_counter()
        tick = 0
        while sched.pending or sched.occupancy or spilled:
            sched.mark_arrivals(tick, time.perf_counter())
            g_queue.set(sched.queue_depth(tick))
            ev = ({"tick": tick, "placed": [], "chunks": [],
                   "decoded": [], "shed": [], "preempted": [],
                   "resumed": []} if keep_timeline else None)

            # admission: FIFO while a slot AND its whole block budget
            # are available (no partial admission, no pool deadlock);
            # an AdmissionController may shed the head first — placed
            # slots are never touched, so shedding cannot starve them
            while True:
                can_place = sched.occupancy < self.max_slots
                if not can_place and not self._preempt:
                    break              # legacy: a full house just waits
                head = sched.peek(tick)
                # resume politeness: a parked request was admitted once
                # already — it outranks any queue head of equal or lower
                # priority for the next free slot
                if self._preempt and spilled and can_place:
                    best = min(spilled,
                               key=lambda r: (r.request.priority, r.seq))
                    if head is None or \
                            best.request.priority <= head.priority:
                        if resume_one(ev):
                            continue
                if head is None:
                    break
                if admission is not None:
                    reason = admission.should_shed(
                        head, sched.queue_depth(tick))
                    if reason is not None:
                        shed_req = sched.drop_head(tick)
                        errors[shed_req.uid] = f"shed: {reason}"
                        if ev is not None:
                            ev["shed"].append(shed_req.uid)
                        if recorder is not None:
                            recorder.record("shed", uid=shed_req.uid,
                                            reason=reason)
                        continue
                if not can_place:
                    # slot pressure: evict a strictly-lower-priority
                    # victim so the head can take its slot — or stop if
                    # nothing outranked sits in one
                    if not preempt_one(head, ev):
                        break
                    continue
                t_adm = time.perf_counter()
                sp = mgr.match_prefix(head.prompt)
                need_ok = mgr.can_admit(sp, self._capacity_len(head))
                while not need_ok and self._preempt:
                    # make room by spilling strictly-lower-priority
                    # slots; each preemption shrinks the victim set, so
                    # this terminates.  Re-match after every eviction —
                    # releasing a victim can change the shareable prefix
                    if not preempt_one(head, ev):
                        break
                    sp = mgr.match_prefix(head.prompt)
                    need_ok = mgr.can_admit(sp,
                                            self._capacity_len(head))
                if not need_ok:
                    break              # wait for retirements to free KV
                idx, req = sched.place(tick)
                shared = mgr.admit(idx, sp, self._capacity_len(req))
                L = len(req.prompt)
                stream[idx] = [int(t) for t in req.prompt]
                committed[idx] = shared
                plans[idx] = plan_chunks(shared, L, self.chunk)
                sched.begin_prefill(idx, len(plans[idx]))
                shared_tokens += shared
                prompt_tokens += L
                if ev is not None:
                    ev["placed"].append(req.uid)
                if tracer is not None:
                    noww = time.perf_counter()
                    arr = sched.arrival_wall.get(req.uid, noww)
                    trk = f"req{req.uid}"
                    rid = tracer.begin("request", req.trace_id,
                                       track=trk, t0=arr, prompt_len=L,
                                       max_new_tokens=req.max_new_tokens)
                    root_span[req.uid] = rid
                    tracer.add("queued", arr, t_adm, req.trace_id,
                               parent=rid, track=trk)
                    aid = tracer.add("admit", t_adm, noww, req.trace_id,
                                     parent=rid, track=trk, slot=idx,
                                     shared_len=shared)
                    tracer.add("prefix_match", t_adm, noww, req.trace_id,
                               parent=aid, track=trk, shared_len=shared,
                               hit=shared > 0)
                if recorder is not None:
                    recorder.record("admit", uid=req.uid, slot=idx,
                                    shared_len=shared)

            if not sched.occupancy:
                nxt = sched.next_arrival()
                if nxt is None:
                    if spilled:
                        continue       # parked work only: resume next pass
                    break
                tick = max(tick, nxt)  # idle engine: jump to arrival
                continue
            occupancy_sum += sched.occupancy
            g_occ.set(sched.occupancy)

            # chunked prefill under the per-tick budget, round-robin
            budget = self.chunks_per_tick
            ran = 0
            while budget > 0 and sched.prefilling:
                for idx in sched.chunk_order():
                    if budget == 0:
                        break
                    if idx not in sched.prefilling:
                        continue       # finished earlier this pass
                    run_chunk(idx, ev)
                    budget -= 1
                    ran += 1
            h_chunks.observe(ran)

            # decode every tick: live streams advance regardless of how
            # much prefill work is queued — the stall bound
            dec = sched.decoding_slots()
            if dec:
                use_spec = (self.draft_layers is not None
                            and self._spec_enabled)
                if not use_spec:
                    toks = np.zeros(self.max_slots, np.int32)
                    pos = np.zeros(self.max_slots, np.int32)
                    wb = np.full(self.max_slots, paged.TRASH, np.int32)
                    wo = np.zeros(self.max_slots, np.int32)
                    for i in dec:
                        c = committed[i]
                        make_writable(i, c, c)
                        toks[i] = pendtok[i]
                        pos[i] = c
                        wb[i] = mgr.tables[i, c // bs]
                        wo[i] = c % bs
                    t0 = time.perf_counter()
                    if self._canary is not None:
                        out, lp_h, ok_h = self._canary_decode(
                            mgr, pos, toks, wb, wo, dec)
                    else:
                        self.pools, out, lp_h, ok_h = self._decode(
                            self.params, self.pools,
                            jnp.asarray(mgr.tables), jnp.asarray(pos),
                            jnp.asarray(toks), jnp.asarray(wb),
                            jnp.asarray(wo), self._next_key())
                        out = np.asarray(out)   # host fetch = barrier
                        lp_h, ok_h = np.asarray(lp_h), np.asarray(ok_h)
                    now = time.perf_counter()
                    t_decode += now - t0
                    h_tick.observe(now - t0)
                    decode_ticks += 1
                    if tracer is not None:
                        tracer.add("decode_tick", t0, now, "engine",
                                   track="engine", slots=len(dec))
                    if on_tick is not None:
                        on_tick(TickReport(
                            tick=tick, kind="decode", elapsed_s=now - t0,
                            emitted=[(sched.slots[i].request.uid,
                                      int(out[i])) for i in dec],
                            finite={sched.slots[i].request.uid:
                                    bool(ok_h[i]) for i in dec},
                            logprob={sched.slots[i].request.uid:
                                     float(lp_h[i]) for i in dec},
                            slots=list(dec), engine=self,
                            queue_depth=sched.queue_depth(tick)))
                    for i in dec:
                        tok = int(out[i])
                        committed[i] += 1
                        stream[i].append(tok)
                        mgr.register_committed(i, stream[i], committed[i])
                        pendtok[i] = tok
                        r = sched.slots[i].request
                        if ev is not None:
                            ev["decoded"].append(r.uid)
                        if tracer is not None:
                            tracer.add("decode", t0, now, r.trace_id,
                                       parent=root_span.get(r.uid),
                                       track=f"req{r.uid}")
                        emit(i, tok, now)
                else:
                    k = self.spec_k
                    T = k + 1
                    toks = np.zeros(self.max_slots, np.int32)
                    pos = np.zeros(self.max_slots, np.int32)
                    wb = np.full((self.max_slots, T), paged.TRASH,
                                 np.int32)
                    wo = np.zeros((self.max_slots, T), np.int32)
                    for i in dec:
                        c = committed[i]
                        make_writable(i, c, c + k)
                        toks[i] = pendtok[i]
                        pos[i] = c
                        pp = np.arange(c, c + T)
                        wb[i] = mgr.tables[i][pp // bs]
                        wo[i] = pp % bs
                    tables_dev = jnp.asarray(mgr.tables)
                    pos_dev = jnp.asarray(pos)
                    wb_dev, wo_dev = jnp.asarray(wb), jnp.asarray(wo)
                    t0 = time.perf_counter()
                    self.draft_pools, props = self._draft(
                        self.draft_params, self.draft_pools, tables_dev,
                        pos_dev, jnp.asarray(toks), wb_dev, wo_dev)
                    props = np.asarray(props)
                    verify_toks = np.concatenate(
                        [toks[:, None], props], axis=1).astype(np.int32)
                    self.pools, g, v_lp, v_ok = self._verify(
                        self.params, self.pools, tables_dev, pos_dev,
                        jnp.asarray(verify_toks), wb_dev, wo_dev)
                    g = np.asarray(g)       # host fetch = device barrier
                    v_lp, v_ok = np.asarray(v_lp), np.asarray(v_ok)
                    now = time.perf_counter()
                    t_decode += now - t0
                    h_tick.observe(now - t0)
                    decode_ticks += 1
                    spec_rounds += len(dec)
                    if tracer is not None:
                        tracer.add("decode_tick", t0, now, "engine",
                                   track="engine", slots=len(dec),
                                   speculative=True)
                    # acceptance decided BEFORE any state mutates, so
                    # the tick report (and a hook that rejects it) sees
                    # exactly what would be committed
                    acc = {i: spec_mod.greedy_accept(props[i], g[i])
                           for i in dec}
                    if on_tick is not None:
                        on_tick(TickReport(
                            tick=tick, kind="decode", elapsed_s=now - t0,
                            emitted=[(sched.slots[i].request.uid, int(t))
                                     for i in dec for t in acc[i][1]],
                            finite={sched.slots[i].request.uid:
                                    bool(v_ok[i]) for i in dec},
                            logprob={sched.slots[i].request.uid:
                                     float(v_lp[i, 0]) for i in dec},
                            slots=list(dec), engine=self,
                            queue_depth=sched.queue_depth(tick)))
                    for i in dec:
                        a, emitted = acc[i]
                        proposed_total += k
                        accepted_total += a
                        h_accept.observe(a / k if k else 0.0)
                        committed[i] += a + 1
                        r = sched.slots[i].request
                        if ev is not None:
                            ev["decoded"].append(r.uid)
                        if tracer is not None:
                            tracer.add("decode", t0, now, r.trace_id,
                                       parent=root_span.get(r.uid),
                                       track=f"req{r.uid}", accepted=a)
                        retired = False
                        for tok in emitted:
                            stream[i].append(tok)
                            if emit(i, tok, now):
                                retired = True
                                break
                        if not retired:
                            pendtok[i] = emitted[-1]
                            mgr.register_committed(i, stream[i],
                                                   committed[i])
            noww = time.perf_counter()
            live.sample(sched.queue_depth(tick), sched.occupancy, noww)
            if admission is not None:
                admission.observe(live, sched.queue_depth(tick), noww)
                admission.apply(self)
            if telemetry is not None and noww - last_window_emit >= 1.0:
                last_window_emit = noww
                telemetry.writer.emit("obs_window", scope="serve",
                                      **live.signals(noww))
            if ev is not None:
                timeline.append(ev)
            tick += 1

        total = time.perf_counter() - t_start
        tokens = int(sum(len(v) for v in sched.finished.values()))
        hit = shared_tokens / prompt_tokens if prompt_tokens else 0.0
        g_blocks.set(mgr.in_use)
        g_hit.set(hit)
        latency = {
            "ttft_p50_s": h_ttft.percentile(50),
            "ttft_p99_s": h_ttft.percentile(99),
            "ttft_mean_s": h_ttft.mean,
            "itl_p50_s": h_itl.percentile(50),
            "itl_p99_s": h_itl.percentile(99),
            "e2e_p50_s": h_e2e.percentile(50),
            "e2e_p99_s": h_e2e.percentile(99),
            "e2e_max_s": h_e2e.max if h_e2e.count else None,
            "measured_requests": h_e2e.count,
        }
        spec_stats = {
            "enabled": self.draft_layers is not None,
            "active_at_end": self._spec_enabled,
            "k": self.spec_k if self.draft_layers is not None else 0,
            "draft_layers": self.draft_layers,
            "rounds": spec_rounds,
            "proposed": proposed_total,
            "accepted": accepted_total,
            "acceptance_rate": (accepted_total / proposed_total)
            if proposed_total else None,
        }
        stats = {
            "engine": "paged",
            "requests": n_req,
            "rejected": len(errors),
            "generated_tokens": tokens,
            "tokens_per_sec": tokens / total if total else None,
            "total_seconds": total,
            "prefill_seconds": t_prefill,
            "decode_seconds": t_decode,
            "prefill_chunks": chunk_calls,
            "decode_ticks": decode_ticks,
            "mean_slot_occupancy":
                occupancy_sum / decode_ticks if decode_ticks else 0.0,
            "max_slots": self.max_slots,
            "kv_cache_bytes": self.kv_cache_bytes,
            "kv_dtype": self.kv_dtype,
            "weight_dtype": self.weight_dtype,
            "kv_block_size": bs,
            "prefill_chunk": self.chunk,
            "chunk_compiles": self._chunk_prog.traces,
            "decode_compiles": self._decode.traces,
            "copy_compiles": self._copy.traces,
            "restarts": self.restarts,
            "weight_swaps": self.weight_swaps,
            "verify_compiles": self._verify.traces
            if self.draft_layers is not None else 0,
            "draft_compiles": self._draft.traces
            if self.draft_layers is not None else 0,
            "paged": {
                **mgr.stats(),
                "prefix_hit_rate": hit,
                "shared_tokens": shared_tokens,
                "prompt_tokens": prompt_tokens,
                "prefill_tokens_computed": chunk_calls * self.chunk,
            },
            "spec": spec_stats,
            "preempt": {
                "enabled": self._preempt,
                "preemptions": preempt_count,
                "resumes": resume_count,
                "still_spilled": len(spilled),
                "spill_compiles": self._spill.traces,
                "unspill_compiles": self._unspill.traces,
                "spill_path": self.migrate_kind,
                # engine-lifetime device-hop accounting (0 under "host")
                "migration_moves": self._spill_moves,
                "migration_bytes": self._spill_move_bytes,
                "migration_seconds": round(self._spill_move_seconds, 6),
            },
            "slo": slo_report(accepted, ttft_s, e2e_s),
            "latency": latency,
            "window": live.signals(),
        }
        if recorder is not None:
            mgr.on_event = None
        if telemetry is not None:
            telemetry.writer.emit("obs_serve", stats=stats)
        out = {"results": sched.finished, "errors": errors, "stats": stats}
        if keep_timeline:
            out["timeline"] = timeline
        return out
