"""Fused linear + cross-entropy: the LM loss without (N, V) logits.

For a language model the output projection is the memory hot spot: logits
are ``(batch·seq, vocab)`` — at BERT/WMT scale (V = 30-32k) they dwarf
every activation in the network, and the standard path materialises them
TWICE (forward value + softmax in the backward).  This op fuses the
projection matmul with the cross-entropy reduction, scanning over vocab
blocks:

  forward   — per block: ``logits_blk = h @ W_blk`` (MXU-shaped), fold
              into running (max, sumexp) online-logsumexp accumulators and
              pick out each row's target logit when it falls in the block.
              Peak extra memory: ``(N, block)`` instead of ``(N, V)``.
  backward  — ``custom_vjp`` recomputes each block's logits and folds
              ``softmax_blk - onehot_blk`` into ``dh`` / ``dW`` block by
              block; same ``(N, block)`` bound.

This is the same blockwise-recompute trade the flash-attention kernel
makes for the (T, T) score matrix, applied to the (N, V) logit matrix —
plain ``lax.scan`` + matmuls rather than Pallas, because a scan of
MXU-shaped matmuls with fused elementwise tails is already the efficient
TPU schedule for this op.

Semantics match :func:`..train.objectives.token_cross_entropy`'s
convention: ``targets == ignore_id`` positions contribute nothing; the
result is the mean loss over the counted positions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _padded_blocks(table, block):
    """Pad the (V, d) table with zero rows to a block multiple and reshape
    to (nb, block, d); padded rows are masked to −∞ logits downstream, so
    ANY vocab size works at full block width (a largest-divisor snap would
    degenerate to block=1 on prime vocabs like GPT-2's 50257)."""
    V, d = table.shape
    block = min(block, V)
    pad = (-V) % block
    w = table.astype(jnp.float32)
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, d), jnp.float32)])
    return w.reshape(-1, block, d), block


def _block_logits(h32, wb, i, block, V):
    """One block's logits with vocab-padding rows masked to −∞."""
    logits = h32 @ wb.T                                      # (N, block)
    vocab_pos = i * block + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    return jnp.where(vocab_pos < V, logits, NEG_INF)


def _fwd(h, table, targets, ignore_id, block):
    """→ (per-position loss (N,), valid mask (N,)).

    h: (N, d) f32/bf16; table: (V, d) — the (tied) embedding layout;
    targets: (N,) int.
    """
    N, d = h.shape
    V = table.shape[0]
    h32 = h.astype(jnp.float32)
    w, block = _padded_blocks(table, block)
    nb = w.shape[0]

    def fold(carry, wb_i):
        m, s, tgt_logit = carry
        wb, i = wb_i
        logits = _block_logits(h32, wb, i, block, V)
        bmax = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, bmax)
        s = s * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(logits - new_m[:, None]), axis=-1)
        # target logit if it falls inside this block
        local = targets - i * block
        inside = (local >= 0) & (local < block)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, block - 1)[:, None], axis=1)[:, 0]
        tgt_logit = jnp.where(inside, picked, tgt_logit)
        return (new_m, s, tgt_logit), None

    m0 = jnp.full((N,), NEG_INF, jnp.float32)
    s0 = jnp.zeros((N,), jnp.float32)
    t0 = jnp.zeros((N,), jnp.float32)
    (m, s, tgt_logit), _ = lax.scan(fold, (m0, s0, t0),
                                    (w, jnp.arange(nb)))
    logz = m + jnp.log(s)
    valid = targets != ignore_id
    return jnp.where(valid, logz - tgt_logit, 0.0), valid


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(h, table, targets, ignore_id: int = 0,
                               block: int = 512):
    """Mean cross-entropy of ``softmax(h @ table.T)`` against ``targets``
    without materialising the (N, V) logits.

    ``h`` is (..., d) activations, ``table`` (V, d) (the embedding-table
    layout used by the tied heads in :mod:`..models.transformer`),
    ``targets`` (...,) int ids; ``ignore_id`` positions are excluded from
    the mean (the package's padding convention).
    """
    hf = h.reshape(-1, h.shape[-1])
    tf = targets.reshape(-1)
    losses, valid = _fwd(hf, table, tf, ignore_id, block)
    return jnp.sum(losses) / jnp.maximum(jnp.sum(valid), 1)


def _vjp_fwd(h, table, targets, ignore_id, block):
    return (fused_linear_cross_entropy(h, table, targets, ignore_id, block),
            (h, table, targets))


def _vjp_bwd(ignore_id, block, res, g):
    h, table, targets = res
    shape = h.shape
    h2 = h.reshape(-1, shape[-1]).astype(jnp.float32)
    tf = targets.reshape(-1)
    N, d = h2.shape
    V = table.shape[0]
    w, block = _padded_blocks(table, block)
    nb = w.shape[0]

    valid = tf != ignore_id
    # pass 1 (recompute): the logsumexp normalisers
    def lse(carry, wb_i):
        m, s = carry
        wb, i = wb_i
        logits = _block_logits(h2, wb, i, block, V)
        bmax = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, bmax)
        s = s * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(logits - new_m[:, None]), axis=-1)
        return (new_m, s), None

    (m, s), _ = lax.scan(lse, (jnp.full((N,), NEG_INF, jnp.float32),
                               jnp.zeros((N,), jnp.float32)),
                         (w, jnp.arange(nb)))
    logz = m + jnp.log(s)
    count = jnp.maximum(jnp.sum(valid), 1)
    scale = (g / count) * valid.astype(jnp.float32)       # (N,)

    # pass 2: dh and dW block by block — (softmax - onehot) folded in
    def bwd_block(dh, wb_i):
        wb, i = wb_i
        logits = _block_logits(h2, wb, i, block, V)
        p = jnp.exp(logits - logz[:, None])               # softmax block
        local = tf - i * block
        inside = (local >= 0) & (local < block)
        onehot = jax.nn.one_hot(jnp.where(inside, local, -1), block,
                                dtype=jnp.float32)
        delta = (p - onehot) * scale[:, None]             # (N, block)
        dh = dh + delta @ wb
        dwb = delta.T @ h2                                # (block, d)
        return dh, dwb

    dh0 = jnp.zeros_like(h2)
    dh, dw = lax.scan(bwd_block, dh0, (w, jnp.arange(nb)))
    # drop the vocab-padding rows (their p, hence delta, is exactly 0)
    return (dh.reshape(shape).astype(h.dtype),
            dw.reshape(-1, d)[:V].astype(table.dtype), None)


fused_linear_cross_entropy.defvjp(_vjp_fwd, _vjp_bwd)
