"""Headline benchmark: ResNet-50 bf16 train throughput (images/sec/chip) + MFU.

The driver-assigned north star (``BASELINE.json``: "ResNet-50/ImageNet
images/sec/chip") is the headline metric; the reference's own flagship CNN
(DenseNet-BC on 64x64 PCB crops) is kept as a secondary key.  Prints ONE
JSON line ``{"metric", "value", "unit", "vs_baseline", ...}`` with extra
keys: ``mfu`` (measured FLOP/s / chip peak bf16 FLOP/s, from XLA
``cost_analysis`` on the exact compiled train step), ``flops_per_image``,
``device_kind``, and ``secondary`` (the DenseNet number).

The reference publishes no numbers (BASELINE.md) — the baseline here is this
repo's own first recorded measurement per (platform, model) key, stored in
``bench_baseline.json``.  ``vs_baseline`` is value / stored-baseline.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Chip peak table + lookup now live with the MFU accounting in obs/mfu.py
# (ISSUE 7); re-exported here so existing `from bench import ...` users keep
# working.  The import is cheap — obs.mfu touches neither jax nor devices.
from distributed_deep_learning_tpu.obs.mfu import (  # noqa: E402,F401
    PEAK_BF16_FLOPS, chip_peak_flops)


def _devices_or_cpu_fallback():
    """First device probe; if the accelerator fails to init, re-exec on CPU.

    A tunneled TPU backend can be transiently UNAVAILABLE (observed in
    round 1's rc=1 bench run); the JSON line must print regardless, so on
    any init failure re-run this script once with ``JAX_PLATFORMS=cpu``.
    """
    import subprocess

    import jax

    try:
        return jax.devices()
    except Exception as exc:  # backend init failure — not recoverable in-proc
        if os.environ.get("BENCH_CPU_FALLBACK") == "1":
            raise
        if os.environ.get("BENCH_WORKER") == "1":
            # under orchestrate(): fail fast — the orchestrator owns the
            # CPU fallback attempt, and a grandchild here would escape its
            # watchdog kill
            raise
        print(f"bench: accelerator init failed ({type(exc).__name__}); "
              "retrying on CPU", file=sys.stderr)
        env = dict(os.environ, BENCH_CPU_FALLBACK="1", JAX_PLATFORMS="cpu")
        env.pop("BENCH_BATCH", None)
        env.pop("BENCH_BATCH_PER_CHIP", None)
        raise SystemExit(subprocess.call(
            [sys.executable, os.path.abspath(__file__)], env=env))


def _build_train_step(model, *, image_size, num_classes, batch, mesh):
    """The EXACT headline train-step setup: (train_step, state, x, y).

    Shared by the timing loop and the mfu_diag cost probe so the roofline
    numbers describe the same compiled program the throughput came from.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_deep_learning_tpu.data.loader import BATCH_AXES
    from distributed_deep_learning_tpu.train.objectives import (
        cross_entropy_loss)
    from distributed_deep_learning_tpu.train.state import create_train_state
    from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                          place_state)

    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.standard_normal(
        (batch, image_size, image_size, 3), dtype=np.float32))
    y = jax.nn.one_hot(jnp.asarray(rng.integers(0, num_classes, batch)),
                       num_classes)

    state = create_train_state(model, jax.random.key(0), x[:1],
                               optax.sgd(0.01, momentum=0.9))
    state = place_state(state, mesh)
    train_step, _ = make_step_fns(mesh, cross_entropy_loss)
    sh = NamedSharding(mesh, P(BATCH_AXES))
    x, y = jax.device_put(x, sh), jax.device_put(y, sh)
    return train_step, state, x, y


def _train_throughput(model, *, image_size, num_classes, batch, steps, mesh):
    """images/sec/chip + FLOPs/step for one jitted train step of ``model``.

    Sync via a host scalar fetch, NOT ``block_until_ready``: under tunneled
    device transports (axon) ``block_until_ready`` can return before the
    device work drains, flattering the clock by orders of magnitude; a
    device-to-host scalar read is an unfakeable end-to-end barrier.
    """
    n_chips = len(mesh.devices.flatten())
    train_step, state, x, y = _build_train_step(
        model, image_size=image_size, num_classes=num_classes, batch=batch,
        mesh=mesh)
    return _timed_steps(train_step, state, x, y, steps=steps,
                        n_chips=n_chips, batch=batch)


def _cost_analysis(compiled) -> dict:
    """Normalise ``compiled.cost_analysis()`` across JAX versions (dict,
    list-of-dicts, or None) — shared by the timing loop and mfu_diag."""
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return analysis or {}


def _timed_steps(train_step, state, x, y, *, steps, n_chips, batch):
    """Time ``steps`` dispatches of ``train_step``; see _train_throughput
    for the host-fetch sync rationale."""
    # AOT-compile once: the same executable serves cost_analysis AND the
    # timing loop (lower().compile() does not seed jit's dispatch cache, so
    # calling the jitted fn after it would compile a second time)
    step, flops_per_step = train_step, None
    try:
        compiled = train_step.lower(state, x, y).compile()
        # per-device module FLOPs x device count = whole-step FLOPs
        flops_per_step = float(
            _cost_analysis(compiled).get("flops", 0.0)) * n_chips or None
        step = compiled
    except Exception:
        pass  # cost model unavailable on this backend; mfu reported as null

    state, m = step(state, x, y)  # warmup (+ compile when AOT failed)
    float(m["loss"])
    state, m = step(state, x, y)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, x, y)
    float(m["loss"])
    dt = time.perf_counter() - t0

    return batch * steps / dt / n_chips, flops_per_step


def _lm_throughput(*, batch, seq_len, steps, mesh, dtype, remat=False,
                   vocab_size=32768, num_layers=12, d_model=768,
                   num_heads=12, mlp_dim=3072):
    """tokens/sec/chip + FLOPs/step for a CausalLM train step (flash
    attention + fused linear-cross-entropy head, weight-tied).

    ``remat`` wraps the forward in ``jax.checkpoint``: ``True`` is the
    whole-forward recompute-everything policy; a policy NAME from
    ``train.step.REMAT_POLICIES`` (e.g. ``"dots_no_batch"``) keeps
    matmul outputs so only elementwise chains recompute.  ~⅓ more FLOPs
    (less under the dots policies) buys the activation memory back, so
    larger per-chip batches fit — the lm_sweep validation section
    measures whether the trade raises MFU at T=2048."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_deep_learning_tpu.data.loader import BATCH_AXES
    from distributed_deep_learning_tpu.models.transformer import CausalLM
    from distributed_deep_learning_tpu.ops.attention_pallas import (
        make_attention_fn)

    n_chips = len(mesh.devices.flatten())
    on_tpu = mesh.devices.flatten()[0].platform == "tpu"
    model = CausalLM(vocab_size=vocab_size, num_layers=num_layers,
                     d_model=d_model, num_heads=num_heads, mlp_dim=mlp_dim,
                     dtype=dtype,
                     attention_fn=make_attention_fn() if on_tpu else None)
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(1, vocab_size, (batch, seq_len + 1)),
                       jnp.int32)

    params = model.init(jax.random.key(0), toks[:1, :-1])
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)

    def step(params, opt_state, toks):
        def loss_fn(p):
            h = model.apply(p, toks[:, :-1], train=True)
            return model.loss(p, h, toks[:, 1:])

        if remat:
            from distributed_deep_learning_tpu.train.step import (
                _remat_policy)

            policy = _remat_policy(remat if isinstance(remat, str)
                                   else "nothing")
            loss_fn = jax.checkpoint(loss_fn, policy=policy)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    sh = NamedSharding(mesh, P(BATCH_AXES))
    repl = NamedSharding(mesh, P())
    toks = jax.device_put(toks, sh)
    params, opt_state = jax.device_put((params, opt_state), repl)
    jstep = jax.jit(step, in_shardings=(repl, repl, sh),
                    out_shardings=(repl, repl, repl), donate_argnums=(0, 1))

    flops_per_step = None
    run = jstep
    try:
        compiled = jstep.lower(params, opt_state, toks).compile()
        flops_per_step = float(
            _cost_analysis(compiled).get("flops", 0.0)) * n_chips or None
        run = compiled
    except Exception:
        pass

    params, opt_state, loss = run(params, opt_state, toks)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = run(params, opt_state, toks)
    float(loss)
    dt = time.perf_counter() - t0
    return batch * seq_len * steps / dt / n_chips, flops_per_step


def _input_pipeline(*, mesh, dtype) -> dict | None:
    """End-to-end train throughput THROUGH the host input pipeline
    (VERDICT r4 item: the reference's data layer was its known bottleneck,
    ``CNN/dataset.py:90-107`` per-item ``.to(device)``; this repo fixed the
    design — batch-level gather + one sharded device_put + thread
    prefetch — and this section measures it instead of asserting it).

    Times the SAME DenseNet train step three ways: preloaded
    device-resident tensors (compute floor), a synthetic in-memory
    ArrayDataset through DeviceLoader+PrefetchLoader, and an
    ImageFolderDataset over freshly generated JPEG files (PIL decode +
    native C++ resize on the measured path).  ``stall_fraction`` =
    1 - preloaded_time/loader_time (0 = input fully hidden).
    """
    import tempfile

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_deep_learning_tpu.data.datasets import synthetic_pcb
    from distributed_deep_learning_tpu.data.loader import (BATCH_AXES,
                                                           DeviceLoader,
                                                           PrefetchLoader)
    from distributed_deep_learning_tpu.train.objectives import (
        cross_entropy_loss)
    from distributed_deep_learning_tpu.train.state import create_train_state
    from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                          place_state)
    from __graft_entry__ import _flagship
    import jax.numpy as jnp

    n_chips = len(mesh.devices.flatten())
    on_tpu = mesh.devices.flatten()[0].platform == "tpu"
    batch = int(os.environ.get("BENCH_INPUT_BATCH",
                               256 * n_chips if on_tpu else 8))
    steps = int(os.environ.get("BENCH_INPUT_STEPS", 12 if on_tpu else 2))
    n_rows = max(2 * batch, 512)

    ds = synthetic_pcb(n=n_rows)
    model = _flagship(dtype=dtype)
    state = create_train_state(model, jax.random.key(0),
                               jnp.ones((1, 64, 64, 3)),
                               optax.sgd(0.01, momentum=0.9))
    state = place_state(state, mesh)
    train_step, _ = make_step_fns(mesh, cross_entropy_loss)
    sh = NamedSharding(mesh, P(BATCH_AXES))

    def run_epochs(loader, n_steps):
        """Drive ``n_steps`` train steps from ``loader``, cycling epochs;
        returns seconds/step (host fetch at the end = device barrier)."""
        nonlocal state
        it, done = iter(loader), 0
        # warmup one batch (compile with these shapes)
        x, y = next(it)
        state, m = train_step(state, x, y)
        float(m["loss"])
        t0 = time.perf_counter()
        while done < n_steps:
            try:
                x, y = next(it)
            except StopIteration:
                it = iter(loader)
                continue
            state, m = train_step(state, x, y)
            done += 1
        float(m["loss"])
        return (time.perf_counter() - t0) / n_steps

    # --- floor: preloaded device tensors --------------------------------
    rng = np.random.default_rng(3)
    xh = rng.standard_normal((batch, 64, 64, 3), dtype=np.float32)
    yh = np.eye(6, dtype=np.float32)[rng.integers(0, 6, batch)]
    xd, yd = jax.device_put(xh, sh), jax.device_put(yh, sh)
    state, m = train_step(state, xd, yd)
    float(m["loss"])  # warm
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = train_step(state, xd, yd)
    float(m["loss"])
    t_pre = (time.perf_counter() - t0) / steps

    out: dict = {"batch": batch,
                 "preloaded_images_per_sec_per_chip":
                     round(batch / t_pre / n_chips, 2)}

    # --- synthetic twin through DeviceLoader + prefetch -----------------
    loader = PrefetchLoader(DeviceLoader(ds, np.arange(n_rows), batch, mesh,
                                         shuffle=True), depth=2)
    t_syn = run_epochs(loader, steps)
    out["synthetic"] = {
        "images_per_sec_per_chip": round(batch / t_syn / n_chips, 2),
        "stall_fraction": round(max(0.0, 1 - t_pre / t_syn), 4)}

    # --- ImageFolder over generated JPEGs (decode + resize measured) ----
    try:
        from PIL import Image

        from distributed_deep_learning_tpu.data.imagefolder import (
            ImageFolderDataset)

        with tempfile.TemporaryDirectory() as root:
            # enough files for at least one full batch (6 classes)
            per = max(85, -(-batch // 6))
            r2 = np.random.default_rng(4)
            for c in range(6):
                d = os.path.join(root, f"class{c}")
                os.makedirs(d)
                for i in range(per):
                    arr = r2.integers(0, 255, (72, 72, 3), dtype=np.uint8)
                    Image.fromarray(arr).save(
                        os.path.join(d, f"im{i}.jpg"))
            ifds = ImageFolderDataset(root, image_size=64,
                                      max_cached_images=1)
            n_use = (len(ifds) // batch) * batch
            if n_use:
                il = PrefetchLoader(
                    DeviceLoader(ifds, np.arange(n_use), batch, mesh,
                                 shuffle=True), depth=2)
                t_img = run_epochs(il, steps)
                out["imagefolder"] = {
                    "images_per_sec_per_chip":
                        round(batch / t_img / n_chips, 2),
                    "stall_fraction":
                        round(max(0.0, 1 - t_pre / t_img), 4)}

                # --- the same JPEGs through the packed mmap cache -------
                # (decode once offline, then zero per-sample Python work
                # per epoch — data/packed.py; the stall_fraction here is
                # the one --packed-cache training actually sees)
                from distributed_deep_learning_tpu.data.packed import (
                    PackedDataset, pack_dataset)

                cache = os.path.join(root, "cache.ddlpack")
                t0p = time.perf_counter()
                pack_dataset(ifds, cache)
                t_pack = time.perf_counter() - t0p
                pds = PackedDataset(cache)
                pl = PrefetchLoader(
                    DeviceLoader(pds, np.arange(n_use), batch, mesh,
                                 shuffle=True), depth=2)
                t_pk = run_epochs(pl, steps)
                out["packed"] = {
                    "images_per_sec_per_chip":
                        round(batch / t_pk / n_chips, 2),
                    "stall_fraction":
                        round(max(0.0, 1 - t_pre / t_pk), 4),
                    "pack_seconds": round(t_pack, 2)}
    except Exception as exc:
        print(f"bench: imagefolder input section failed "
              f"({type(exc).__name__}: {exc})", file=sys.stderr)
    return out


def _serving() -> dict | None:
    """Serving throughput A/B (ISSUE 2): the continuous-batching engine
    vs run-to-completion ``generate()`` on a seeded mixed-length trace —
    CPU-measurable like ``input_pipeline`` (host scheduling + XLA decode
    both run for real on the CI box; the TPU-shaped harvest lives in
    ``scripts/tpu_validation.py``'s ``serving`` section).  Reports
    tokens/sec both ways, the speedup, mean slot occupancy, and compile
    counts (decode must be 1 — the compile-once contract).

    The paged second generation (ISSUE 9) rides in the same section: a
    trace-driven SLO load (shared system prompts, Poisson arrivals,
    per-request deadlines) through the paged engine with a 1-layer
    speculative draft, A/B'd against the v1 engine on the same trace.
    Its three headline numbers — ``prefix_hit_rate``,
    ``slo_attainment``, ``spec_acceptance`` — are lifted to the top of
    the record for baseline tracking (``cpu:serving_*_v1``)."""
    from distributed_deep_learning_tpu.serve.bench import (
        paged_serving_bench, serving_bench)

    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", 32))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", 8))
    rec = serving_bench(n_requests=n_req, max_slots=slots)
    out = {
        "metric": "serving tokens/sec (mixed-length trace)",
        "engine_tokens_per_sec": rec["engine"]["tokens_per_sec"],
        "naive_tokens_per_sec": rec["naive"]["tokens_per_sec"],
        "speedup": rec["speedup"],
        "mean_slot_occupancy": rec["engine"]["mean_slot_occupancy"],
        "decode_compiles": rec["engine"]["decode_compiles"],
        "prefill_compiles": rec["engine"]["prefill_compiles"],
        "naive_compiles": rec["naive"]["compiles"],
        "naive_wasted_fraction": rec["naive"]["wasted_fraction"],
        "max_slots": slots,
        "requests": n_req,
    }
    p_req = int(os.environ.get("BENCH_SERVE_PAGED_REQUESTS", 12))
    draft = int(os.environ.get("BENCH_SERVE_DRAFT", 1))
    prec = paged_serving_bench(load_kw=dict(n_requests=p_req),
                               max_slots=slots,
                               draft_layers=draft or None)
    pe = prec["paged_engine"]
    out["paged"] = {
        "tokens_per_sec": pe["tokens_per_sec"],
        "speedup_vs_v1": prec.get("speedup_vs_v1"),
        "prefill_tokens_saved_frac": prec.get("prefill_tokens_saved_frac"),
        "cow_copies": pe["paged"]["cow_copies"],
        "chunk_compiles": pe["chunk_compiles"],
        "decode_compiles": pe["decode_compiles"],
        "verify_compiles": pe["verify_compiles"],
        "requests": p_req,
        "draft_layers": draft or None,
    }
    out["prefix_hit_rate"] = round(pe["prefix_hit_rate"], 4)
    out["slo_attainment"] = pe["slo_attainment"]
    out["spec_acceptance"] = round(pe["spec_acceptance"], 4) \
        if pe["spec_acceptance"] is not None else None
    # exact KV footprints (allocated cache pytree bytes, ISSUE 12) — the
    # denominators of every future "HBM saved per slot" claim
    out["kv_cache_bytes"] = rec["engine"]["kv_cache_bytes"]
    out["paged"]["kv_cache_bytes"] = pe["kv_cache_bytes"]
    return out


def _serving_quant() -> dict | None:
    """Quantized serving hot path A/B (ISSUE 14): the same trace through
    the paged engine at full precision and with int8 block pools + int8
    per-channel weights (serve/quant.py).  CPU-measurable: the shrink is
    exact allocated bytes (the ``kv_cache_bytes`` gauge on the REAL
    pools, scales included), the drift is the calibrated per-token
    greedy logprob bound, and throughput exercises the same
    quantize/dequant hot loop XLA compiles on TPU.  The
    block-table-aware flash-decode kernel itself
    (ops/paged_decode_pallas.py) harvests on TPU via
    ``scripts/tpu_validation.py``'s ``serving_quant`` section; CPU runs
    its interpret-mode parity in tests."""
    from distributed_deep_learning_tpu.serve.bench import (
        quantized_serving_bench)

    q_req = int(os.environ.get("BENCH_SERVE_QUANT_REQUESTS", 10))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", 8))
    rec = quantized_serving_bench(load_kw=dict(n_requests=q_req),
                                  max_slots=slots)
    return {
        "metric": "quantized serving A/B (int8 KV pools + int8 weights)",
        "kv_dtype": rec["kv_dtype"],
        "weight_dtype": rec["weight_dtype"],
        "tokens_per_sec": rec["quantized"]["tokens_per_sec"],
        "baseline_tokens_per_sec": rec["baseline"]["tokens_per_sec"],
        "kv_shrink_x": rec["kv_shrink_x"],
        "kv_bytes_per_slot": rec["quantized"]["kv_bytes_per_slot"],
        "baseline_kv_bytes_per_slot": rec["baseline"]["kv_bytes_per_slot"],
        "max_context_at_budget": rec["quantized"]["max_context_at_budget"],
        "baseline_max_context_at_budget":
            rec["baseline"]["max_context_at_budget"],
        "token_agreement": rec["token_agreement"],
        "logprob_drift": rec["logprob_drift"],
        "declared_drift_bound": rec["declared_drift_bound"],
        "decode_compiles": rec["quantized"]["decode_compiles"],
        "weight_bytes": rec["quantized"]["weight_bytes"],
        "requests": q_req,
        "max_slots": slots,
    }


def _serving_disagg() -> dict | None:
    """Disaggregated prefill/decode serving A/B (ISSUE 16): the same
    shared-prefix Poisson trace through the unified paged engine and
    through ``serve/disagg.py``'s prefill-pool + decode-pool split
    joined by device-to-device KV-block migration.  CPU-measurable: the
    mechanism being bought — per-role pool sizing, batched
    compile-once prefill off the decode device, migration overlapped
    with the next chunk — runs for real on the emulated multi-device
    host.  Baseline-tracked: the disagg/unified speedup, disagg
    tokens/sec and sync-measured migration GB/s; ``itl_p99_ratio``
    rides the record (must stay ~1 — disaggregation that trades
    inter-token latency for throughput is not a win), and
    ``token_agreement`` must be 1.0 (decode workers run the unified
    engine's own compiled program)."""
    import subprocess

    import jax

    d_req = int(os.environ.get("BENCH_SERVE_DISAGG_REQUESTS", 24))
    # seed 17's arrival pattern keeps the decode pool busy during
    # prefill bursts (the overlap the split exists to exploit); seed 0
    # happens to serialise the phases and measures mostly noise
    d_seed = int(os.environ.get("BENCH_SERVE_DISAGG_SEED", 17))
    if len(jax.devices()) < 2:
        # disaggregation needs one device per pool; the usual
        # CPU-fallback worker is single-device, so re-measure in a
        # child under the forced-host CPU mesh (XLA_FLAGS must land
        # before the child imports jax — same dance as _collectives)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "disagg_bench.py"),
             "--requests", str(d_req), "--seed", str(d_seed)],
            stdout=subprocess.PIPE, text=True, timeout=600, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"disagg_bench subprocess exited {proc.returncode}")
        rec = json.loads(proc.stdout)
        rec["fallback"] = "cpu-subprocess-2dev"
    else:
        from distributed_deep_learning_tpu.serve.bench import (
            disagg_serving_bench)

        rec = disagg_serving_bench(seed=d_seed,
                                   load_kw=dict(n_requests=d_req))
    return {
        "metric": "disaggregated prefill/decode serving A/B",
        "speedup": rec["speedup"],
        "tokens_per_sec": rec["disagg"]["tokens_per_sec"],
        "unified_tokens_per_sec": rec["unified"]["tokens_per_sec"],
        "itl_p99_ratio": rec["itl_p99_ratio"],
        "itl_p99_ms": round(1e3 * rec["disagg"]["itl_p99_s"], 3),
        "unified_itl_p99_ms": round(1e3 * rec["unified"]["itl_p99_s"], 3),
        "token_agreement": rec["token_agreement"],
        "migration_gbps": rec["migration_gbps"],
        "migration_ms_per_move": rec["migration_ms_per_move"],
        "int8_wire_shrink_x": rec["int8_wire_shrink_x"],
        "prefill_util": round(rec["disagg"]["prefill_util"], 4),
        "decode_compiles": rec["disagg"]["decode_compiles"],
        "chunk_compiles": rec["disagg"]["chunk_compiles"],
        "migrate_gather_compiles": rec["disagg"]["migrate_gather_compiles"],
        "migrate_scatter_compiles": rec["disagg"]["migrate_scatter_compiles"],
        "migration": rec["disagg"]["migration"],
        "prefill_workers": rec["prefill_workers"],
        "decode_workers": rec["decode_workers"],
        "prefill_streams": rec["prefill_streams"],
        "max_slots": rec["max_slots"],
        "requests": d_req,
        "seed": d_seed,
        "errors": rec["errors"],
        "fallback": rec.get("fallback"),
    }


def _resilience() -> dict | None:
    """Self-healing drill (ISSUE 3): detection latency of the anomaly
    sentinel, checkpoint-corruption fallback, and elastic recovery wall
    time, measured by the same code path ``scripts/chaos_drill.py``
    exposes.  CPU-measurable (host + XLA logic).  The sentinel is OFF in
    every other bench section, so the headline numbers are regression-free
    by construction; ``sentinel_overhead_frac`` quantifies what turning it
    on would cost on this (tiny, worst-case) model."""
    from distributed_deep_learning_tpu.utils.chaos import run_resilience_drill

    rec = run_resilience_drill(seed=int(os.environ.get("BENCH_CHAOS_SEED",
                                                       "0")))
    return {"metric": "self-healing drill (chaos-injected)", **rec}


def _serve_resilience() -> dict | None:
    """Serve-side self-healing drill (ISSUE 13): engine crash / NaN
    logits / corrupted KV block / stalled tick injected mid-decode under
    the supervisor (zero requests lost, bit-identical replay), slow-tick
    SLO load under admission control, and the hot weight-swap gauntlet
    (canary promote, canary rollback, bit-flipped publication rejected
    by the integrity manifest) — the same code path
    ``scripts/chaos_drill.py --scenario serve`` exposes.  One engine
    survives the whole gauntlet; ``decode_compiles`` staying 1 is part
    of the record."""
    from distributed_deep_learning_tpu.utils.chaos import (
        run_serve_resilience_drill)

    return run_serve_resilience_drill(
        seed=int(os.environ.get("BENCH_CHAOS_SEED", "0")))


def _fleet_resilience() -> dict | None:
    """Fleet-tier self-healing drill (ISSUE 15): three router-fronted
    paged replicas under a shared-prefix Poisson trace with priority
    classes — replica crash quarantined with zero-loss bit-identical
    cross-replica replay, straggler health-degraded, router flake
    survived, and priority preemption spilling low-priority KV to host
    and resuming it bit-identically (priority 0 never preempted) — the
    same code path ``scripts/chaos_drill.py --scenario fleet`` exposes.
    The replica engines survive the whole gauntlet; the surviving max
    ``decode_compiles`` staying 1 is part of the record."""
    from distributed_deep_learning_tpu.utils.chaos import (
        run_fleet_resilience_drill)

    return run_fleet_resilience_drill(
        seed=int(os.environ.get("BENCH_CHAOS_SEED", "0")))


def _fleet_rebalance() -> dict | None:
    """Live fleet rebalancing drill (ISSUE 18): mid-request slot
    evacuation off a degraded replica (digest-verified committed-KV
    migration, bit-identical resume over fp32 AND int8 pools), a
    corrupted evacuation payload rolled back by the digest with zero
    loss, a target crash mid-evacuation aborted and ledger-replayed,
    the elastic autoscaler's grow + drain-protocol shrink, and the
    ``scale_thrash`` hysteresis gauntlet — the same code path
    ``scripts/chaos_drill.py --scenario rebalance`` exposes.  Also runs
    ``scripts/check_baselines.py`` (the band/section hygiene gate) and
    folds its verdict into the record, so a band pointing at a
    nonexistent bench section fails HERE, where the bands are used."""
    import subprocess

    from distributed_deep_learning_tpu.utils.chaos import (
        run_rebalance_drill)

    record = run_rebalance_drill(
        seed=int(os.environ.get("BENCH_CHAOS_SEED", "0")))
    check = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "check_baselines.py")
    try:
        proc = subprocess.run([sys.executable, check],
                              capture_output=True, text=True, timeout=120)
        record["baseline_check_ok"] = proc.returncode == 0
        if proc.returncode != 0:
            record["baseline_check_errors"] = \
                proc.stdout.strip().splitlines()[-8:]
    except Exception as exc:  # the drill result stands on its own
        record["baseline_check_ok"] = None
        record["baseline_check_errors"] = [f"{type(exc).__name__}: {exc}"]
    n_scen = [s for s in record["scenarios"].values()
              if isinstance(s, dict)]
    record["scenarios_passed_frac"] = (
        sum(1 for s in n_scen if s.get("passed")) / len(n_scen)
        if n_scen else None)
    return record


def _autotune() -> dict | None:
    """Auto-parallelism planner (ISSUE 5): search the plan lattice for the
    MLP workload on this box's devices and report best-vs-default measured
    step time — CPU-measurable (the trials compile and run the real train
    step).  The chosen ``plan_hash`` is recorded so BENCH_*.json tracks
    plan churn across commits; the search space here is the cheap
    (mesh x remat) slice sized for the bench budget."""
    from distributed_deep_learning_tpu.tune.search import run_search
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec

    batch = int(os.environ.get("BENCH_AUTOTUNE_BATCH", 32))
    trials = int(os.environ.get("BENCH_AUTOTUNE_TRIALS", 6))
    spec = get_spec("mlp")
    config = parse_args(["-e", "1", "-b", str(batch), "-m", "data"],
                        workload="mlp")
    result = run_search(
        spec, config, trial_steps=2, max_trials=trials,
        space_options=dict(zero_options=("none", "fsdp"),
                           compress_options=("none",),
                           grad_accum_options=(1,)))
    from distributed_deep_learning_tpu.tune.artifact import plan_hash

    best_ms = 1e3 / result.best_sps if result.best_sps else None
    base_ms = 1e3 / result.baseline_sps if result.baseline_sps else None
    return {
        "metric": "autotuned plan vs hand default (mlp train step)",
        "plan_hash": plan_hash(result.best),
        "plan": result.best.describe(),
        "best_steps_per_sec": round(result.best_sps, 2),
        "best_examples_per_sec": round(result.best_sps * batch, 1),
        "baseline_steps_per_sec": round(result.baseline_sps, 2),
        "best_step_ms": round(best_ms, 3) if best_ms else None,
        "baseline_step_ms": round(base_ms, 3) if base_ms else None,
        "speedup": round(result.best_sps / result.baseline_sps, 4)
            if result.baseline_sps else None,
        "n_candidates": result.n_candidates,
        "n_pruned_analytic": result.n_pruned,
        "n_infeasible": result.n_infeasible,
        "rungs": result.rungs,
        "search_seconds": round(result.search_seconds, 2),
    }


def _memory_model() -> dict | None:
    """Memory-model calibration (ISSUE 12): compile the MLP workload's
    real train step at each remat corner of the lattice, read XLA's
    measured temp bytes, fit ``ACT_FRACTION``/``RECOMPUTE_COST``, and
    report predicted-vs-measured error for BOTH the analytic tables and
    the fitted constants — CPU-measurable (``memory_analysis()`` reports
    argument/temp bytes on the CPU backend too).  The calibrated mean
    error is tracked under ``{platform}:mem_model_error_v1`` with an
    absolute 25% ceiling; the uncalibrated analytic error rides in the
    record as the before/after evidence."""
    from distributed_deep_learning_tpu.tune.calibrate import run_calibration
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec

    batch = int(os.environ.get("BENCH_MEMORY_BATCH", 32))
    steps = int(os.environ.get("BENCH_MEMORY_STEPS", 2))
    spec = get_spec("mlp")
    config = parse_args(["-e", "1", "-b", str(batch), "-m", "data"],
                        workload="mlp")
    record = run_calibration(spec, config, steps=steps)
    errors = record["errors"]
    analytic, calibrated = errors["analytic"], errors["calibrated"]
    return {
        "metric": "analytic HBM model error vs XLA measured bytes "
                  "(mlp, remat/ZeRO corners)",
        "workload": "mlp",
        "calibration_key": record["key"],
        "constants": record["constants"],
        "corners_measured": calibrated["corners"] if calibrated else 0,
        "analytic_error_mean": analytic["mean"] if analytic else None,
        "analytic_error_max": analytic["max"] if analytic else None,
        "calibrated_error_mean": calibrated["mean"] if calibrated else None,
        "calibrated_error_max": calibrated["max"] if calibrated else None,
    }


def _reshard() -> dict | None:
    """Cross-topology reshard (ISSUE 6): redistribution bandwidth for the
    two paths — host-gather fallback vs chunked per-shard streaming — on
    a checkpoint-sized array moved across a REAL mesh change (N → N-2
    devices: 8→6 on the CI box, a non-power-of-2 target), plus the full
    shrink drill (kill 2, re-plan via tune/, reshard-restore, continue)
    timed end to end.  CPU-measurable (redistribution is slicing +
    device_put logic); the TPU-shaped harvest lives in
    ``scripts/tpu_validation.py``'s ``reshard`` section."""
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_deep_learning_tpu.reshard.redistribute import (
        redistribute_leaf)
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    devices = jax.devices()
    n = len(devices)
    m = n - 2 if n > 2 else 1
    mb = int(os.environ.get("BENCH_RESHARD_MB", 64))
    cols = 1024
    quantum = math.lcm(n, m)  # rows divide both source and target meshes
    rows = max(quantum,
               (mb * (1 << 20) // (4 * cols)) // quantum * quantum)
    host = np.random.default_rng(11).standard_normal(
        (rows, cols)).astype(np.float32)
    src = jax.device_put(jnp.asarray(host),
                         NamedSharding(build_mesh({"data": n}, devices),
                                       P("data")))
    dst = NamedSharding(build_mesh({"data": m}, devices[:m]), P("data"))
    gb = host.nbytes / (1 << 30)

    out: dict = {
        "metric": "cross-topology reshard (redistribution + shrink drill)",
        "array_mb": round(host.nbytes / (1 << 20), 1),
        "devices": f"{n}->{m}"}
    for method in ("gather", "chunked"):
        moved, _ = redistribute_leaf(src, dst, method=method)  # warm path
        jax.block_until_ready(moved)
        t0 = time.perf_counter()
        moved, _ = redistribute_leaf(src, dst, method=method)
        jax.block_until_ready(moved)
        dt = time.perf_counter() - t0
        out[f"{method}_seconds_per_gb"] = round(dt / gb, 4)
        out[f"{method}_gb_per_sec"] = round(gb / dt, 3)

    if n >= 8:
        from distributed_deep_learning_tpu.reshard.drill import (
            run_shrink_drill)

        drill = run_shrink_drill(
            seed=int(os.environ.get("BENCH_CHAOS_SEED", "0")),
            hidden=128, rows=512, min_leaf_size=2 ** 10)
        out["drill"] = {k: drill[k] for k in
                       ("plan", "plan_hash", "survivors", "restore_mode",
                        "restore_seconds", "drill_passed")}
    return out


def _observability() -> dict | None:
    """Telemetry overhead A/B (ISSUE 7): steps/sec with RunTelemetry
    attached vs the bare train loop, on the real ``_run_phase`` over a
    ~1 ms jitted step — the worst case for per-step instrumentation
    cost.  CPU-measurable (the hot path is host-side ``perf_counter``
    reads + dict adds either way).  The acceptance bar is overhead
    < 2%; the measured fraction is tracked under
    ``{platform}:obs_overhead_fraction_v1``."""
    from distributed_deep_learning_tpu.obs.bench import (overhead_bench,
                                                         trace_overhead_bench)

    steps = int(os.environ.get("BENCH_OBS_STEPS", 48))
    repeats = int(os.environ.get("BENCH_OBS_REPEATS", 5))
    rec = overhead_bench(steps=steps, repeats=repeats)
    # gen-2 increment (ISSUE 11): spans on vs off, same loop, same bar
    rec["trace"] = trace_overhead_bench(steps=steps, repeats=repeats)
    return rec


def _collectives() -> dict | None:
    """Quantized + ring-overlapped FSDP collectives (ISSUE 10): the
    ``scripts/comm_bench.py`` record — analytic wire bytes per method
    (the int8-vs-fp32 >= 3x gate), ring bit-parity and quantized
    numerics, the fused ``gather_matmul`` overlap fraction, and the
    explicit-FSDP-step loss parity against the ``parallel/zero.py``
    annotation path.  CPU-measurable (the ring schedule's win on host
    devices is never materialising the gathered operand); the wire-time
    harvest lives in ``scripts/tpu_validation.py``'s ``collectives``
    section."""
    import subprocess

    import jax

    steps = int(os.environ.get("BENCH_COMM_STEPS", 5))
    if len(jax.devices()) < 2:
        # single-device process (the usual CPU-fallback worker): the mesh
        # collectives need shards, so re-measure in a child with the
        # 8-way forced-host CPU mesh — XLA_FLAGS must be set before the
        # child imports jax, which is why this can't happen in-process
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "comm_bench.py"),
             "--steps", str(steps), "--parity-steps",
             os.environ.get("BENCH_COMM_PARITY_STEPS", "3")],
            stdout=subprocess.PIPE, text=True, timeout=600, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"comm_bench subprocess exited {proc.returncode}")
        rec = json.loads(proc.stdout)
        rec["fallback"] = "cpu-subprocess-8dev"
        return rec

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    import comm_bench

    return comm_bench.run(
        steps=steps,
        parity_steps=int(os.environ.get("BENCH_COMM_PARITY_STEPS", 3)))


def _attention_speedup(steps: int = 20) -> float | None:
    """Fused (Pallas flash) vs dense attention fwd+bwd at a long-context
    shape; returns flash/dense step-time ratio > 1 = flash faster.  TPU
    only (interpret mode on CPU measures nothing useful)."""
    import jax
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)
    from distributed_deep_learning_tpu.ops.attention_pallas import (
        flash_attention)

    B, T, H, D = 4, 2048, 8, 64
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16) for kk in ks)

    def time_fn(fn):
        loss = jax.jit(jax.grad(lambda q: jnp.sum(fn(q, k, v) ** 2)))
        float(jnp.sum(loss(q)))  # compile + warm, host-fetch sync
        t0 = time.perf_counter()
        for _ in range(steps):
            g = loss(q)
        float(jnp.sum(g))
        return (time.perf_counter() - t0) / steps

    try:
        t_dense = time_fn(lambda q, k, v: dot_product_attention(
            q, k, v, causal=True, dtype=jnp.bfloat16))
        t_flash = time_fn(lambda q, k, v: flash_attention(
            q, k, v, causal=True).astype(jnp.bfloat16))
        return t_dense / t_flash
    except Exception:
        return None


def _enable_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a repo-local dir.

    The tunneled transport makes every heavy compile cost 60-90 s; the
    round-5 window died mid-bench because the worker's five compiles
    outran its carved budget.  With the cache warm (populated by any
    prior run on the same shapes — including this session's validation
    batch), a full worker re-run compiles in seconds, so the driver's
    end-of-round bench completes inside any window the probe passes.
    ``BENCH_COMPILE_CACHE=0`` opts out."""
    if os.environ.get("BENCH_COMPILE_CACHE", "1") == "0":
        return
    try:
        import jax

        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:  # cache is an optimisation, never a blocker
        print(f"bench: compile cache unavailable ({type(exc).__name__})",
              file=sys.stderr)


def _time_left() -> float:
    """Seconds until the orchestrator's soft deadline (inf when unset).

    Optional sections consult this so the headline line always prints
    inside the watchdog window — shedding the DenseNet/LM/attention
    extras beats the whole attempt being killed mid-compile."""
    dl = os.environ.get("BENCH_DEADLINE")
    return float("inf") if not dl else float(dl) - time.time()


#: bench_baseline.json key carrying the best MEASURED TPU ResNet MFU
#: (seeded from the round-5 validation batch_sweep, per-chip batch 256;
#: updated by any later TPU run that beats it).  CPU-fallback lines
#: surface it so the driver-captured bench always carries a TPU MFU
#: datum (VERDICT r5 "Next round" #5b).
RECORDED_MFU_KEY = "tpu:resnet50_mfu_v1"


def _recorded_mfu(baselines: dict) -> float | None:
    """The best recorded TPU ResNet MFU, or None when never measured."""
    v = baselines.get(RECORDED_MFU_KEY)
    return float(v) if isinstance(v, (int, float)) and v > 0 else None


#: Every baseline-tracked value this run actually measured (key ->
#: value), recorded by ``_vs_baseline`` — what the regression sentry
#: walks.  A section that errored or was shed simply never lands here,
#: so the sentry only judges numbers that exist.
_MEASURED: dict[str, float] = {}

#: Noise-aware tolerance bands per baseline-key suffix (ISSUE 11).
#: ``("higher", band)``: the metric should stay >= baseline * (1-band);
#: the band is sized to each harness's observed run-to-run noise on a
#: loaded CI box (throughputs swing hard, analytic ratios barely move).
#: ``("lower_abs", ceiling)``: an absolute ceiling for
#: lower-is-better fractions — the obs overheads are ~0.01-0.02 with
#: noise of the same magnitude, so a ratio against a near-zero baseline
#: would be meaningless; the acceptance bar (2% + measurement slack)
#: is the honest gate.
REGRESSION_BANDS: dict[str, tuple[str, float]] = {
    "resnet50_224_train_v1": ("higher", 0.30),
    "densenet_bc_train_v2": ("higher", 0.30),
    "causal_lm_2048_train_v1": ("higher", 0.30),
    "serving_tokens_per_sec_v1": ("higher", 0.30),
    "serving_prefix_hit_rate_v1": ("higher", 0.10),
    "serving_slo_attainment_v1": ("higher", 0.25),
    "serving_spec_acceptance_v1": ("higher", 0.25),
    # quantized serving (ISSUE 14): the shrink is exact allocated bytes
    # at fixed geometry (deterministic — tight band); throughput rides
    # the usual CI wall-clock band; the drift ceiling is absolute — the
    # declared int8 bound (~0.02 on the calibrated probe) plus headroom,
    # because a ratio against a near-zero drift would be meaningless
    "serving_quant_kv_shrink_v1": ("higher", 0.05),
    "serving_quant_tokens_per_sec_v1": ("higher", 0.30),
    "serving_quant_logprob_drift_v1": ("lower_abs", 0.05),
    # disaggregated serving (ISSUE 16): the speedup and throughput ride
    # the wide CI wall-clock band (the A/B's two arms share one box, so
    # the RATIO is steadier than either arm, but single-core scheduling
    # noise still moves it); migration GB/s is a sync-measured
    # device_put rate — noisy on a loaded host.  The ITL ceiling is
    # absolute: disagg inter-token p99 beyond 2x unified's means the
    # handoff is backing up no matter what an earlier run recorded.
    "serving_disagg_speedup_v1": ("higher", 0.30),
    "serving_disagg_tokens_per_sec_v1": ("higher", 0.30),
    "serving_disagg_migration_gbps_v1": ("higher", 0.50),
    "serving_disagg_itl_p99_ratio_v1": ("lower_abs", 2.0),
    "autotune_mlp_steps_per_sec_v1": ("higher", 0.30),
    "reshard_chunked_gb_per_sec_v1": ("higher", 0.35),
    "comm_int8_bytes_reduction_v1": ("higher", 0.05),
    "comm_overlap_fraction_v1": ("higher", 0.40),
    "obs_overhead_fraction_v1": ("lower_abs", 0.025),
    "obs_trace_overhead_fraction_v1": ("lower_abs", 0.025),
    # predicted-vs-measured HBM model error after calibration (ISSUE 12):
    # the acceptance bar is <= 25% mean relative error on the calibrated
    # corners; a ratio against a near-zero baseline would be meaningless,
    # so the bar itself is the gate
    "mem_model_error_v1": ("lower_abs", 0.25),
    # serve self-healing drill (ISSUE 13): absolute bars, not ratios —
    # a fault the watchdog needs >3 ticks to see, a recovery past 5 s on
    # the tiny drill engine, or ANY lost request is a broken chain no
    # matter what an earlier run recorded.  Clean SLO attainment ratios
    # against its record with a wide band (wall-clock CI noise).
    "serve_resilience_detection_ticks_v1": ("lower_abs", 3.0),
    "serve_resilience_recovery_s_v1": ("lower_abs", 5.0),
    "serve_resilience_requests_lost_v1": ("lower_abs", 0.5),
    "serve_resilience_slo_attainment_v1": ("higher", 0.5),
    # fleet self-healing drill (ISSUE 15): same philosophy, fleet tier —
    # a replica crash the router needs >3 ticks to see, a failover
    # replay past 15 s on the tiny drill fleet, or ANY lost request is
    # a broken chain regardless of history
    "fleet_detection_ticks_v1": ("lower_abs", 3.0),
    "fleet_recovery_s_v1": ("lower_abs", 15.0),
    "fleet_requests_lost_v1": ("lower_abs", 0.5),
    "fleet_slo_attainment_v1": ("higher", 0.5),
    # live rebalancing drill (ISSUE 18): ANY lost request during an
    # evacuation / drain / rebalance fault is a broken chain, full
    # stop; per-slot evacuation latency has an absolute ceiling (the
    # tiny drill engine moves a handful of KV blocks — if that takes
    # >1 s something structural regressed, whatever history says); an
    # oscillating load must never move the fleet more than the
    # hysteresis allows; and every drill scenario must pass.
    "rebalance_requests_lost_v1": ("lower_abs", 0.5),
    "rebalance_evac_ms_v1": ("lower_abs", 1000.0),
    "rebalance_scale_events_v1": ("lower_abs", 6.5),
    "rebalance_scenarios_passed_v1": ("higher", 0.05),
}

#: Band-key suffix -> the bench JSON-line section its metric rides in
#: (ISSUE 18 satellite: ``scripts/check_baselines.py`` verifies every
#: ``REGRESSION_BANDS`` entry names a section that actually exists, so
#: a renamed/removed section can't leave its bands silently orphaned).
BAND_SECTIONS: dict[str, str] = {
    "resnet50_224_train_v1": "value",
    "densenet_bc_train_v2": "secondary",
    "causal_lm_2048_train_v1": "lm",
    "serving_tokens_per_sec_v1": "serving",
    "serving_prefix_hit_rate_v1": "serving",
    "serving_slo_attainment_v1": "serving",
    "serving_spec_acceptance_v1": "serving",
    "serving_quant_kv_shrink_v1": "serving_quant",
    "serving_quant_tokens_per_sec_v1": "serving_quant",
    "serving_quant_logprob_drift_v1": "serving_quant",
    "serving_disagg_speedup_v1": "serving_disagg",
    "serving_disagg_tokens_per_sec_v1": "serving_disagg",
    "serving_disagg_migration_gbps_v1": "serving_disagg",
    "serving_disagg_itl_p99_ratio_v1": "serving_disagg",
    "autotune_mlp_steps_per_sec_v1": "autotune",
    "reshard_chunked_gb_per_sec_v1": "reshard",
    "comm_int8_bytes_reduction_v1": "collectives",
    "comm_overlap_fraction_v1": "collectives",
    "obs_overhead_fraction_v1": "observability",
    "obs_trace_overhead_fraction_v1": "observability",
    "mem_model_error_v1": "memory_model",
    "serve_resilience_detection_ticks_v1": "serve_resilience",
    "serve_resilience_recovery_s_v1": "serve_resilience",
    "serve_resilience_requests_lost_v1": "serve_resilience",
    "serve_resilience_slo_attainment_v1": "serve_resilience",
    "fleet_detection_ticks_v1": "fleet_resilience",
    "fleet_recovery_s_v1": "fleet_resilience",
    "fleet_requests_lost_v1": "fleet_resilience",
    "fleet_slo_attainment_v1": "fleet_resilience",
    "rebalance_requests_lost_v1": "fleet_rebalance",
    "rebalance_evac_ms_v1": "fleet_rebalance",
    "rebalance_scale_events_v1": "fleet_rebalance",
    "rebalance_scenarios_passed_v1": "fleet_rebalance",
}

#: The section keys the bench JSON line actually carries (kept in sync
#: with the ``line`` dict ``main`` assembles) — the target universe
#: ``BAND_SECTIONS`` values must live in.
SECTION_KEYS: frozenset = frozenset({
    "value", "secondary", "lm", "input_pipeline", "serving",
    "serving_quant", "serving_disagg", "resilience", "serve_resilience",
    "fleet_resilience", "fleet_rebalance", "autotune", "reshard",
    "observability", "memory_model", "collectives",
    "flash_attention_speedup",
})


def regression_sentry(baselines: dict,
                      measured: dict | None = None) -> list[dict]:
    """Compare this run's measured values against their recorded
    baselines with per-metric tolerance bands; return one failure dict
    per breach (empty list = clean).

    A freshly seeded baseline compares at ratio 1.0 and can never fail —
    the first measurement defines the record, later runs defend it."""
    measured = _MEASURED if measured is None else measured
    failures: list[dict] = []
    for key in sorted(measured):
        value = measured[key]
        rule = REGRESSION_BANDS.get(key.split(":", 1)[-1])
        if rule is None:
            continue
        direction, band = rule
        if direction == "lower_abs":
            if value > band:
                failures.append({
                    "key": key, "value": value, "ceiling": band,
                    "kind": "absolute ceiling exceeded"})
            continue
        base = baselines.get(key)
        if not isinstance(base, (int, float)) or base <= 0:
            continue
        ratio = value / base
        if ratio < 1.0 - band:
            failures.append({
                "key": key, "value": value, "baseline": base,
                "ratio": round(ratio, 4), "band": band,
                "kind": "below tolerance band"})
    return failures


def regress_from(path: str) -> int:
    """The cheap CI gate (``BENCH_REGRESS_FROM=rec.json python
    bench.py``): judge a previously recorded bench JSON line against the
    current baselines WITHOUT running any benches.  Reads the line's
    ``measured`` map (every ``_vs_baseline`` datum of that run), applies
    the same tolerance bands, exits 3 on breach / 2 on an unusable
    record / 0 clean."""
    measured: dict[str, float] = {}
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if raw.startswith("{"):
                    measured.update(json.loads(raw).get("measured") or {})
    except (OSError, ValueError) as e:
        print(f"bench: cannot read record {path}: {e}", file=sys.stderr)
        return 2
    if not measured:
        print(f"bench: no 'measured' map in {path} (older record "
              "format? re-run bench.py to produce one)", file=sys.stderr)
        return 2
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")
    baselines = {}
    if os.path.exists(base_path):
        with open(base_path) as f:
            baselines = json.load(f)
    regs = regression_sentry(baselines, measured)
    for r in regs:
        print(f"bench: REGRESSION {r['key']}: {r}", file=sys.stderr)
    print(json.dumps({"regress_from": path, "checked": len(measured),
                      "regressions": regs}))
    return 3 if regs else 0


def _vs_baseline(baselines: dict, key: str, value: float,
                 base_path: str) -> float:
    _MEASURED[key] = value
    if key not in baselines:
        baselines[key] = value
        try:
            with open(base_path, "w") as f:
                json.dump(baselines, f, indent=1)
        except OSError:
            pass
    return value / baselines[key] if baselines[key] else 1.0


def main() -> int:
    _enable_compile_cache()
    section_secs: dict[str, float] = {}

    class _section_timer:
        """Record a section's wall time (stderr + the JSON line) so a
        timed-out attempt leaves a diagnosis, not a mystery (the round-5
        window was lost to exactly that)."""

        def __init__(self, name: str) -> None:
            self.name = name

        def __enter__(self):
            self.t0 = time.perf_counter()

        def __exit__(self, *exc):
            section_secs[self.name] = round(time.perf_counter() - self.t0, 1)
            print(f"bench: section {self.name} took "
                  f"{section_secs[self.name]}s", file=sys.stderr)

    if os.environ.get("BENCH_CPU_FALLBACK") == "1":
        # env vars alone don't unpin a site-registered platform; the
        # jax.config route works pre-backend-init (tests/conftest.py)
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.models.resnet import resnet50
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    from __graft_entry__ import _flagship

    devices = _devices_or_cpu_fallback()
    platform = devices[0].platform
    device_kind = devices[0].device_kind
    n_chips = len(devices)
    on_tpu = platform == "tpu"
    mesh = build_mesh({"data": n_chips})
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # --- headline: ResNet-50, ImageNet geometry (224x224, 1000 classes) ----
    # one attempt per process; the batch-backoff ladder lives in
    # orchestrate(), which retries smaller sizes in fresh watchdogged
    # workers (a single policy, and failed attempts can't pin HBM)
    batch_env = os.environ.get("BENCH_BATCH")
    per_chip = os.environ.get("BENCH_BATCH_PER_CHIP")
    if batch_env:
        batch = int(batch_env)
    elif per_chip:
        batch = int(per_chip) * n_chips
    else:
        batch = 256 * n_chips if on_tpu else 8
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 2))
    # space-to-depth stem (mathematically-equivalent 4x4-s1 packed conv,
    # models/resnet.py) is the TPU default; BENCH_S2D=0 reverts
    s2d = on_tpu and os.environ.get("BENCH_S2D", "1") != "0"
    with _section_timer("headline"):
        ips, flops_per_step = _train_throughput(
            resnet50(dtype=dtype, stem_s2d=s2d), image_size=224,
            num_classes=1000, batch=batch, steps=steps, mesh=mesh)

    mfu = flops_per_image = None
    peak = chip_peak_flops(device_kind) if on_tpu else None
    if flops_per_step:
        flops_per_image = flops_per_step / batch
        if peak:
            mfu = ips * flops_per_image / peak

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")
    baselines = {}
    if os.path.exists(base_path):
        with open(base_path) as f:
            baselines = json.load(f)
    vs = _vs_baseline(baselines, f"{platform}:resnet50_224_train_v1", ips,
                      base_path)

    # MFU bookkeeping: a TPU run that beats the recorded best updates it;
    # a CPU fallback carries the recorded best forward (labelled) so the
    # driver's parsed block never loses the hardware datum to a dead
    # transport round.
    mfu_source = "measured" if mfu else None
    if on_tpu and mfu and mfu > (_recorded_mfu(baselines) or 0.0):
        baselines[RECORDED_MFU_KEY] = round(mfu, 4)
        try:
            with open(base_path, "w") as f:
                json.dump(baselines, f, indent=1)
        except OSError:
            pass
    if mfu is None and not on_tpu:
        recorded = _recorded_mfu(baselines)
        if recorded is not None:
            mfu, mfu_source = recorded, "recorded_tpu"

    # Optional sections each guard themselves: the headline ResNet number
    # must print even if a secondary model OOMs, hits a compile bug, or a
    # degraded transport slows it down (their absence reads as null).
    # --- secondary: the reference's flagship (DenseNet-BC, PCB 64x64) ------
    # Shed thresholds are MEASURED cold-compile worst cases from the
    # round-5 hardware window (validation log timestamps: ResNet compile
    # ~90s over the tunnel, LM section ~200s, input ~250s with JPEG
    # tree).  They gate on on_tpu: CPU sections compile in seconds, and
    # the guaranteed CPU fallback attempt (240-300 s budget) must not
    # shed data it can easily afford.
    t_secondary, t_lm, t_input = (150, 300, 250) if on_tpu else (60, 120, 60)
    secondary = None
    if os.environ.get("BENCH_SECONDARY", "1") != "0" and \
            _time_left() < t_secondary:
        print(f"bench: shedding densenet section ({_time_left():.0f}s left)",
              file=sys.stderr)
    elif os.environ.get("BENCH_SECONDARY", "1") != "0":
        try:
            dbatch = int(os.environ.get("BENCH_DENSENET_BATCH",
                                        1024 * n_chips if on_tpu else 16))
            dsteps = int(os.environ.get("BENCH_DENSENET_STEPS",
                                        30 if on_tpu else 2))
            with _section_timer("densenet"):
                dips, _ = _train_throughput(
                    _flagship(dtype=dtype), image_size=64, num_classes=6,
                    batch=dbatch, steps=dsteps, mesh=mesh)
            dvs = _vs_baseline(baselines,
                               f"{platform}:densenet_bc_train_v2",
                               dips, base_path)
            secondary = {"metric": "densenet_bc64 train images/sec/chip",
                         "value": round(dips, 2),
                         "vs_baseline": round(dvs, 4)}
        except Exception as exc:
            print(f"bench: densenet secondary failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- LM: decoder-only transformer, flash attention + fused CE head -----
    lm = None
    if os.environ.get("BENCH_LM", "1" if on_tpu else "0") != "0" and \
            _time_left() < t_lm:
        print(f"bench: shedding lm section ({_time_left():.0f}s left)",
              file=sys.stderr)
    elif os.environ.get("BENCH_LM", "1" if on_tpu else "0") != "0":
        try:
            lbatch = int(os.environ.get("BENCH_LM_BATCH",
                                        8 * n_chips if on_tpu else 2))
            lseq = int(os.environ.get("BENCH_LM_SEQ",
                                      2048 if on_tpu else 128))
            lsteps = int(os.environ.get("BENCH_LM_STEPS",
                                        10 if on_tpu else 2))
            with _section_timer("lm"):
                ltps, lflops = _lm_throughput(batch=lbatch, seq_len=lseq,
                                              steps=lsteps, mesh=mesh,
                                              dtype=dtype)
            lvs = _vs_baseline(baselines,
                               f"{platform}:causal_lm_2048_train_v1",
                               ltps, base_path)
            lmfu = None
            if lflops and peak:
                lmfu = ltps * (lflops / (lbatch * lseq)) / peak
            lm = {"metric": "causal_lm_768x12 T2048 train tokens/sec/chip",
                  "value": round(ltps, 2), "vs_baseline": round(lvs, 4),
                  "mfu": round(lmfu, 4) if lmfu else None}
        except Exception as exc:
            print(f"bench: lm section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- host input pipeline on the measured path --------------------------
    input_pipe = None
    if os.environ.get("BENCH_INPUT", "1") != "0" and _time_left() < t_input:
        print(f"bench: shedding input-pipeline section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_INPUT", "1") != "0":
        try:
            with _section_timer("input_pipeline"):
                input_pipe = _input_pipeline(mesh=mesh, dtype=dtype)
        except Exception as exc:
            print(f"bench: input-pipeline section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- serving: continuous-batching engine vs naive generate() -----------
    serving = None
    t_serving = 120 if on_tpu else 60
    if os.environ.get("BENCH_SERVE", "1") != "0" and \
            _time_left() < t_serving:
        print(f"bench: shedding serving section ({_time_left():.0f}s left)",
              file=sys.stderr)
    elif os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            with _section_timer("serving"):
                serving = _serving()
            svs = _vs_baseline(baselines,
                               f"{platform}:serving_tokens_per_sec_v1",
                               serving["engine_tokens_per_sec"], base_path)
            serving["vs_baseline"] = round(svs, 4)
            # paged-generation headline numbers (ISSUE 9): hit rate and
            # SLO attainment regress toward 0, so a ratio < 1 flags them
            # the same way a throughput drop would
            for bkey, val in (
                    ("serving_prefix_hit_rate_v1",
                     serving.get("prefix_hit_rate")),
                    ("serving_slo_attainment_v1",
                     serving.get("slo_attainment")),
                    ("serving_spec_acceptance_v1",
                     serving.get("spec_acceptance"))):
                if val is not None:
                    serving[bkey.replace("_v1", "_vs_baseline")] = round(
                        _vs_baseline(baselines, f"{platform}:{bkey}",
                                     val, base_path), 4)
        except Exception as exc:
            print(f"bench: serving section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- serving quantization: int8 KV + int8 weights A/B ------------------
    serving_quant = None
    t_squant = 120 if on_tpu else 60
    if os.environ.get("BENCH_SERVE_QUANT", "1") != "0" and \
            _time_left() < t_squant:
        print(f"bench: shedding serving-quant section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_SERVE_QUANT", "1") != "0":
        try:
            with _section_timer("serving_quant"):
                serving_quant = _serving_quant()
            for bkey, val in (
                    ("serving_quant_kv_shrink_v1",
                     serving_quant.get("kv_shrink_x")),
                    ("serving_quant_tokens_per_sec_v1",
                     serving_quant.get("tokens_per_sec")),
                    ("serving_quant_logprob_drift_v1",
                     serving_quant.get("logprob_drift"))):
                if val is not None:
                    serving_quant[bkey.replace("_v1", "_vs_baseline")] = \
                        round(_vs_baseline(baselines, f"{platform}:{bkey}",
                                           float(val), base_path), 4)
        except Exception as exc:
            print(f"bench: serving-quant section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- serving disaggregation: prefill/decode pools + KV migration -------
    serving_disagg = None
    t_disagg = 150 if on_tpu else 120
    if os.environ.get("BENCH_SERVE_DISAGG", "1") != "0" and \
            _time_left() < t_disagg:
        print(f"bench: shedding serving-disagg section "
              f"({_time_left():.0f}s left)", file=sys.stderr)
    elif os.environ.get("BENCH_SERVE_DISAGG", "1") != "0":
        try:
            with _section_timer("serving_disagg"):
                serving_disagg = _serving_disagg()
            for bkey, val in (
                    ("serving_disagg_speedup_v1",
                     serving_disagg.get("speedup")),
                    ("serving_disagg_tokens_per_sec_v1",
                     serving_disagg.get("tokens_per_sec")),
                    ("serving_disagg_migration_gbps_v1",
                     serving_disagg.get("migration_gbps")),
                    ("serving_disagg_itl_p99_ratio_v1",
                     serving_disagg.get("itl_p99_ratio"))):
                if val is not None:
                    serving_disagg[bkey.replace("_v1", "_vs_baseline")] = \
                        round(_vs_baseline(baselines, f"{platform}:{bkey}",
                                           float(val), base_path), 4)
        except Exception as exc:
            print(f"bench: serving-disagg section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- resilience: the self-healing chain under injected faults ----------
    resilience = None
    t_res = 90 if on_tpu else 60
    if os.environ.get("BENCH_RESILIENCE", "1") != "0" and \
            _time_left() < t_res:
        print(f"bench: shedding resilience section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_RESILIENCE", "1") != "0":
        try:
            with _section_timer("resilience"):
                resilience = _resilience()
        except Exception as exc:
            print(f"bench: resilience section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- serve resilience: supervisor + hot swap under injected faults -----
    serve_resilience = None
    t_sres = 150 if on_tpu else 120
    if os.environ.get("BENCH_SERVE_RESILIENCE", "1") != "0" and \
            _time_left() < t_sres:
        print(f"bench: shedding serve-resilience section "
              f"({_time_left():.0f}s left)", file=sys.stderr)
    elif os.environ.get("BENCH_SERVE_RESILIENCE", "1") != "0":
        try:
            with _section_timer("serve_resilience"):
                serve_resilience = _serve_resilience()
            for bkey, val in (
                    ("serve_resilience_detection_ticks_v1",
                     serve_resilience.get("detection_ticks_max")),
                    ("serve_resilience_recovery_s_v1",
                     serve_resilience.get("recovery_seconds_max")),
                    ("serve_resilience_requests_lost_v1",
                     serve_resilience.get("requests_lost_total")),
                    ("serve_resilience_slo_attainment_v1",
                     serve_resilience.get("slo_attainment_clean"))):
                if val is not None:
                    serve_resilience[bkey.replace("_v1", "_vs_baseline")] = \
                        round(_vs_baseline(baselines, f"{platform}:{bkey}",
                                           float(val), base_path), 4)
        except Exception as exc:
            print(f"bench: serve-resilience section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- fleet resilience: router failover + preemption under faults --------
    fleet_resilience = None
    t_fleet = 180 if on_tpu else 150
    if os.environ.get("BENCH_FLEET_RESILIENCE", "1") != "0" and \
            _time_left() < t_fleet:
        print(f"bench: shedding fleet-resilience section "
              f"({_time_left():.0f}s left)", file=sys.stderr)
    elif os.environ.get("BENCH_FLEET_RESILIENCE", "1") != "0":
        try:
            with _section_timer("fleet_resilience"):
                fleet_resilience = _fleet_resilience()
            for bkey, val in (
                    ("fleet_detection_ticks_v1",
                     fleet_resilience.get("detection_ticks_max")),
                    ("fleet_recovery_s_v1",
                     fleet_resilience.get("recovery_seconds_max")),
                    ("fleet_requests_lost_v1",
                     fleet_resilience.get("requests_lost_total")),
                    ("fleet_slo_attainment_v1",
                     fleet_resilience.get("slo_attainment"))):
                if val is not None:
                    fleet_resilience[bkey.replace("_v1", "_vs_baseline")] = \
                        round(_vs_baseline(baselines, f"{platform}:{bkey}",
                                           float(val), base_path), 4)
        except Exception as exc:
            print(f"bench: fleet-resilience section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- fleet rebalance: live evacuation + elastic autoscaling -------------
    fleet_rebalance = None
    t_rebal = 220 if on_tpu else 180
    if os.environ.get("BENCH_FLEET_REBALANCE", "1") != "0" and \
            _time_left() < t_rebal:
        print(f"bench: shedding fleet-rebalance section "
              f"({_time_left():.0f}s left)", file=sys.stderr)
    elif os.environ.get("BENCH_FLEET_REBALANCE", "1") != "0":
        try:
            with _section_timer("fleet_rebalance"):
                fleet_rebalance = _fleet_rebalance()
            for bkey, val in (
                    ("rebalance_requests_lost_v1",
                     fleet_rebalance.get("requests_lost_total")),
                    ("rebalance_evac_ms_v1",
                     fleet_rebalance.get("evac_ms_mean")),
                    ("rebalance_scale_events_v1",
                     fleet_rebalance.get("scale_events_total")),
                    ("rebalance_scenarios_passed_v1",
                     fleet_rebalance.get("scenarios_passed_frac"))):
                if val is not None:
                    fleet_rebalance[bkey.replace("_v1", "_vs_baseline")] = \
                        round(_vs_baseline(baselines, f"{platform}:{bkey}",
                                           float(val), base_path), 4)
        except Exception as exc:
            print(f"bench: fleet-rebalance section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- autotune: planner search vs hand default ---------------------------
    autotune = None
    t_tune = 120 if on_tpu else 60
    if os.environ.get("BENCH_AUTOTUNE", "1") != "0" and \
            _time_left() < t_tune:
        print(f"bench: shedding autotune section ({_time_left():.0f}s left)",
              file=sys.stderr)
    elif os.environ.get("BENCH_AUTOTUNE", "1") != "0":
        try:
            with _section_timer("autotune"):
                autotune = _autotune()
            avs = _vs_baseline(baselines,
                               f"{platform}:autotune_mlp_steps_per_sec_v1",
                               autotune["best_steps_per_sec"], base_path)
            autotune["vs_baseline"] = round(avs, 4)
        except Exception as exc:
            print(f"bench: autotune section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- reshard: cross-topology redistribution + shrink drill --------------
    reshard = None
    t_reshard = 90 if on_tpu else 60
    if os.environ.get("BENCH_RESHARD", "1") != "0" and \
            _time_left() < t_reshard:
        print(f"bench: shedding reshard section ({_time_left():.0f}s left)",
              file=sys.stderr)
    elif os.environ.get("BENCH_RESHARD", "1") != "0":
        try:
            with _section_timer("reshard"):
                reshard = _reshard()
            rvs = _vs_baseline(baselines,
                               f"{platform}:reshard_chunked_gb_per_sec_v1",
                               reshard["chunked_gb_per_sec"], base_path)
            reshard["vs_baseline"] = round(rvs, 4)
        except Exception as exc:
            print(f"bench: reshard section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- observability: telemetry overhead on the train loop ---------------
    observability = None
    t_obs = 60 if on_tpu else 45
    if os.environ.get("BENCH_OBS", "1") != "0" and _time_left() < t_obs:
        print(f"bench: shedding observability section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_OBS", "1") != "0":
        try:
            with _section_timer("observability"):
                observability = _observability()
            # lower is better, but _vs_baseline just ratios against the
            # first recorded value — drift either way shows up
            ovs = _vs_baseline(baselines,
                               f"{platform}:obs_overhead_fraction_v1",
                               observability["obs_overhead_fraction"],
                               base_path)
            observability["vs_baseline"] = round(ovs, 4)
            tvs = _vs_baseline(
                baselines, f"{platform}:obs_trace_overhead_fraction_v1",
                observability["trace"]["obs_trace_overhead_fraction"],
                base_path)
            observability["trace"]["vs_baseline"] = round(tvs, 4)
        except Exception as exc:
            print(f"bench: observability section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- memory model: calibrated vs analytic HBM prediction error ---------
    memory_model = None
    t_mem = 90 if on_tpu else 60
    if os.environ.get("BENCH_MEMORY", "1") != "0" and _time_left() < t_mem:
        print(f"bench: shedding memory-model section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_MEMORY", "1") != "0":
        try:
            with _section_timer("memory_model"):
                memory_model = _memory_model()
            merr = memory_model["calibrated_error_mean"]
            if merr is not None:
                mvs = _vs_baseline(baselines,
                                   f"{platform}:mem_model_error_v1",
                                   merr, base_path)
                memory_model["vs_baseline"] = round(mvs, 4)
        except Exception as exc:
            print(f"bench: memory-model section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    # --- collectives: quantized + ring-overlapped FSDP comm layer ----------
    collectives = None
    t_comm = 90 if on_tpu else 60
    if os.environ.get("BENCH_COMM", "1") != "0" and _time_left() < t_comm:
        print(f"bench: shedding collectives section ({_time_left():.0f}s "
              "left)", file=sys.stderr)
    elif os.environ.get("BENCH_COMM", "1") != "0":
        try:
            with _section_timer("collectives"):
                collectives = _collectives()
            cvs = _vs_baseline(baselines,
                               f"{platform}:comm_int8_bytes_reduction_v1",
                               collectives["bytes"]["int8_reduction_x"],
                               base_path)
            collectives["vs_baseline"] = round(cvs, 4)
            ofrac = collectives["overlap"]["overlap_fraction"]
            if ofrac:
                # only a nonzero fraction seeds/ratios the baseline: a
                # loaded-box zero must not pin the record at 0 forever
                collectives["overlap_vs_baseline"] = round(
                    _vs_baseline(baselines,
                                 f"{platform}:comm_overlap_fraction_v1",
                                 ofrac, base_path), 4)
        except Exception as exc:
            print(f"bench: collectives section failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)

    attn_speedup = None
    if on_tpu and os.environ.get("BENCH_ATTENTION", "1") != "0":
        if _time_left() < 90:
            print(f"bench: shedding attention micro ({_time_left():.0f}s "
                  "left)", file=sys.stderr)
        else:
            with _section_timer("attention"):
                attn_speedup = _attention_speedup()
    if attn_speedup is not None:
        # latest-wins decision datum: workloads' `--attention auto` gates
        # the TPU flash default on this recorded ratio (northstar.py)
        from distributed_deep_learning_tpu.utils.bench_records import (
            record_flash_speedup)

        record_flash_speedup(attn_speedup)

    line = {
        "metric": f"resnet50_224 bf16 train images/sec/chip ({platform})",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 4),
        "mfu": round(mfu, 4) if mfu else None,
        "mfu_source": mfu_source,
        "flops_per_image": round(flops_per_image) if flops_per_image else None,
        "device_kind": device_kind,
        "secondary": secondary,
        "lm": lm,
        "input_pipeline": input_pipe,
        "serving": serving,
        "serving_quant": serving_quant,
        "serving_disagg": serving_disagg,
        "resilience": resilience,
        "serve_resilience": serve_resilience,
        "fleet_resilience": fleet_resilience,
        "fleet_rebalance": fleet_rebalance,
        "autotune": autotune,
        "reshard": reshard,
        "observability": observability,
        "memory_model": memory_model,
        "collectives": collectives,
        "flash_attention_speedup":
            round(attn_speedup, 3) if attn_speedup else None,
        "section_secs": section_secs,
    }
    # --- perf-regression sentry (ISSUE 11) --------------------------------
    # Every measured value is judged against its recorded baseline with a
    # noise-aware band; breaches always WARN loudly on stderr and ride
    # the JSON line.  BENCH_REGRESS=1 turns breaches into exit code 3
    # (the CI gate) — run it worker-direct (BENCH_REGRESS=1 python
    # bench.py), optionally shedding sections with the BENCH_* toggles.
    regressions = regression_sentry(baselines)
    line["regressions"] = regressions
    # every datum this run measured, flat — what BENCH_REGRESS_FROM
    # re-judges later without re-running the benches
    line["measured"] = {k: _MEASURED[k] for k in sorted(_MEASURED)}
    for r in regressions:
        print(f"bench: REGRESSION {r['key']}: {r}", file=sys.stderr)
    if not on_tpu:
        # CPU fallback: carry the RECORDED hardware history (labelled as
        # such — these are prior measured baselines from
        # bench_baseline.json, not this run) so a dead-transport round
        # still reports the chip numbers it has already earned.
        recorded = {k: v for k, v in baselines.items()
                    if k.startswith("tpu:")}
        if recorded:
            line["recorded_tpu"] = recorded
    print(json.dumps(line))
    if regressions and os.environ.get("BENCH_REGRESS") == "1":
        print(f"bench: {len(regressions)} regression(s) vs baseline; "
              "failing (BENCH_REGRESS=1)", file=sys.stderr)
        return 3
    return 0


def orchestrate() -> int:
    """Deadline-proof driver entry (round-3 postmortem, VERDICT.md).

    Round 3 lost its only hardware datum because the orchestrator treated
    fast *errors* differently from hangs: a TPU transport erroring
    UNAVAILABLE in ~1 min per attempt walked the whole 5-attempt ladder
    and the driver's outer timeout (rc 124) killed the process before the
    guaranteed-CPU attempt ran.  Three rules now make "one JSON line
    always prints" hold against a real outer budget:

    1. GLOBAL wall-clock deadline (``BENCH_TIMEOUT``, default 1200 s —
       deliberately far under any plausible driver window).  Per-attempt
       timeouts are carved from what remains, always reserving enough for
       the CPU attempt.
    2. ANY failed attempt — nonzero rc or timeout — counts as transport
       evidence; after 2 failures of any kind, go straight to CPU.
    3. A ~75 s watchdogged trivial-matmul probe precedes the first heavy
       attempt; a hung or erroring backend is detected for the price of
       one import instead of one ResNet compile.

    Workers receive the absolute deadline (``BENCH_DEADLINE``) and shed
    optional sections (DenseNet / LM / attention micro) to get the
    headline out inside it.
    """
    import subprocess
    import time as _time

    t0 = _time.monotonic()
    total = float(os.environ.get("BENCH_TIMEOUT", 1200))
    deadline = t0 + total
    cpu_reserve = min(300.0, total * 0.5)

    def remaining() -> float:
        return deadline - _time.monotonic()

    def run_attempt(extra: dict, timeout: float) -> str | None:
        env = dict(os.environ, BENCH_WORKER="1", **extra)
        if extra.get("BENCH_CPU_FALLBACK") == "1":
            # the guaranteed-to-print attempt must not inherit a TPU-sized
            # user batch pin
            env.pop("BENCH_BATCH", None)
            env.pop("BENCH_BATCH_PER_CHIP", None)
        # absolute soft deadline, with margin for the final print/flush
        env["BENCH_DEADLINE"] = repr(_time.time() + timeout - 10.0)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            print(f"bench: attempt {extra} timed out after {timeout:.0f}s",
                  file=sys.stderr)
            return None
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout
        print(f"bench: attempt {extra} failed rc={proc.returncode}",
              file=sys.stderr)
        return None

    def cpu_attempt() -> int:
        # floor of 240 s even if the budget is spent: printing late still
        # beats printing nothing, and the global default leaves this floor
        # far inside any driver window
        out = run_attempt({"JAX_PLATFORMS": "cpu", "BENCH_CPU_FALLBACK": "1"},
                          max(remaining(), 240.0))
        if out is None:  # pragma: no cover - CPU backend catastrophe
            return 1
        sys.stdout.write(out)
        return 0

    # --- probe: is the default backend alive at all? -----------------------
    probe_budget = min(75.0, max(remaining() - cpu_reserve, 30.0))
    probe_env = dict(os.environ, BENCH_WORKER="1", BENCH_PROBE="1")
    try:
        probe = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=probe_env,
            stdout=subprocess.PIPE, text=True, timeout=probe_budget)
        probe_ok = probe.returncode == 0 and "probe-ok" in probe.stdout
    except subprocess.TimeoutExpired:
        probe_ok = False
    if not probe_ok:
        print(f"bench: backend probe failed within {probe_budget:.0f}s; "
              "straight to CPU", file=sys.stderr)
        return cpu_attempt()

    # --- accelerator attempts, batch backing off on failure ----------------
    pinned = "BENCH_BATCH" in os.environ or \
        "BENCH_BATCH_PER_CHIP" in os.environ
    # Retries shed the optional sections up front (round-5 lesson: after a
    # 720 s first-attempt timeout only ~170 s remained — a full section
    # set can never fit, but headline-only with a warm compile cache can).
    shed = {"BENCH_SECONDARY": "0", "BENCH_LM": "0", "BENCH_INPUT": "0",
            "BENCH_ATTENTION": "0", "BENCH_SERVE": "0",
            "BENCH_RESILIENCE": "0", "BENCH_SERVE_RESILIENCE": "0",
            "BENCH_FLEET_REBALANCE": "0", "BENCH_RESHARD": "0",
            "BENCH_OBS": "0", "BENCH_COMM": "0", "BENCH_MEMORY": "0"}
    plan: list[dict] = [{}] if pinned else [
        {"BENCH_BATCH_PER_CHIP": "256"},
        {"BENCH_BATCH_PER_CHIP": "128", **shed},
        # insurance against a TPU-specific s2d-stem compile failure: one
        # attempt with the plain 7x7 stem before giving up the chip
        {"BENCH_BATCH_PER_CHIP": "128", "BENCH_S2D": "0", **shed},
    ]
    failures = 0
    for extra in plan:
        budget = remaining() - cpu_reserve
        if failures >= 2 or budget < 60:
            break  # transport is sick or time is short: take the CPU line
        out = run_attempt(extra, budget if pinned else min(budget, total * 0.6))
        if out is not None:
            sys.stdout.write(out)
            return 0
        failures += 1
    return cpu_attempt()


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE") == "1":
        # minimal end-to-end device proof: init backend, one matmul, one
        # host fetch — everything a heavy attempt needs, in miniature
        import jax
        import jax.numpy as jnp

        x = jnp.ones((128, 128))
        float(jnp.sum(x @ x))
        print("probe-ok")
        sys.exit(0)
    if os.environ.get("BENCH_REGRESS_FROM"):
        # judge an existing record against the baselines — no benches run
        sys.exit(regress_from(os.environ["BENCH_REGRESS_FROM"]))
    if os.environ.get("BENCH_WORKER") == "1" or \
            os.environ.get("BENCH_NO_WATCHDOG") == "1" or \
            os.environ.get("BENCH_REGRESS") == "1":
        # BENCH_REGRESS runs worker-direct: the orchestrator would treat
        # the sentry's exit 3 as a transport failure and retry on CPU,
        # swallowing the very signal the gate exists to surface
        sys.exit(main())
    sys.exit(orchestrate())
