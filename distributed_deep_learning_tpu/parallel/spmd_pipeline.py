"""SPMD pipeline parallelism: GPipe fill-drain inside one XLA program.

This is the TPU-native pipeline the reference's hand-rolled Python scheduler
(``MLP/model.py:81-130`` and byte-identical copies) maps onto: all stages
run the *same* compiled program over a ``stage`` mesh axis (`shard_map`),
stage parameters are stacked along a leading axis and sharded so each device
holds its own stage's weights, and activations rotate between neighbouring
devices with ``lax.ppermute`` over ICI inside a ``lax.scan`` over schedule
ticks.  Forward AND backward pipeline (the scan/ppermute transpose replays
the schedule in reverse) — unlike the reference, whose scheduler only
overlapped forward (SURVEY.md §3.3).

Constraint (inherent to SPMD pipelining): all stages share one
``stage_fn(params, x) -> y`` with ``y.shape == x.shape`` — i.e. a
homogeneous stack (transformer blocks, LSTM layers, residual trunks).
Heterogeneous models use :class:`..mpmd.MPMDPipeline` instead; the usual
composition for real models is embed (outside) → homogeneous trunk
(this pipeline) → head (outside).

Schedule: ``T = M + S - 1`` ticks for M microbatches over S stages.  At tick
``t`` stage ``s`` processes microbatch ``t - s`` (bubble ticks compute on
garbage and are masked at collection — uniform control flow, nothing
data-dependent, exactly what XLA wants).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.7 exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def stack_stage_params(params_list: list[Any]) -> Any:
    """Stack per-stage param pytrees along a new leading `stage` axis.

    Requires homogeneous stages (identical pytree structure and leaf shapes).
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def spmd_pipeline(stage_fn: StageFn, stacked_params: Any, x: jnp.ndarray, *,
                  mesh: Mesh, microbatch_size: int | None = None,
                  axis: str = "stage", batch_axes: tuple[str, ...] = ("data", "fsdp")
                  ) -> jnp.ndarray:
    """Run `x` through S pipelined applications of `stage_fn`.

    Args:
      stage_fn: one stage's computation, shape-preserving.
      stacked_params: pytree with leading dim S on every leaf, sharded over
        `axis` (see :func:`stack_stage_params`).
      x: global batch ``(B, ...)``; also sharded over `batch_axes` if the
        mesh has data parallelism — pipeline and data parallelism compose
        inside the same program.
      microbatch_size: reference ``-p`` semantics (microbatch SIZE); default
        one microbatch per stage.
    """
    S = mesh.shape[axis]
    B = x.shape[0]
    if microbatch_size is None:
        # divisor-safe default: the largest microbatch count <= S that
        # divides B (M == S when possible, M == 1 in the worst case)
        M = max(m for m in range(1, S + 1) if B % m == 0)
        mb = B // M
    else:
        mb = microbatch_size
        if B % mb:
            raise ValueError(f"batch {B} not divisible by microbatch size {mb}")
        M = B // mb
    dp = mesh.shape.get(batch_axes[0], 1) if len(batch_axes) else 1
    for ax in batch_axes[1:]:
        dp *= mesh.shape.get(ax, 1)
    if mb % dp:
        raise ValueError(
            f"microbatch size {mb} not divisible by data-parallel size {dp} "
            f"(mesh axes {batch_axes} = {[mesh.shape.get(a, 1) for a in batch_axes]})")
    xs = x.reshape(M, mb, *x.shape[1:])

    batch_spec = P(None, batch_axes)  # (M, mb, ...): shard the mb dim
    param_spec = P(axis)

    @partial(shard_map, mesh=mesh, in_specs=(param_spec, batch_spec),
             out_specs=batch_spec, check_vma=False)
    def run(params, xs):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index(axis)

        def tick(carry, t):
            # stage 0 feeds from the microbatch queue; others from their
            # left neighbour's previous output (the carry).
            inp0 = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), keepdims=False)
            inp = jnp.where(stage == 0, inp0, carry)
            out = stage_fn(params, inp)
            nxt = lax.ppermute(out, axis,
                               [(i, (i + 1) % S) for i in range(S)])
            return nxt, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(M + S - 1))
        # Microbatch m finishes on the last stage at tick m + S - 1; mask
        # everyone else and broadcast with a psum (valid rows are unique).
        res = lax.slice_in_dim(outs, S - 1, S - 1 + M, axis=0)
        res = jnp.where(stage == S - 1, res, jnp.zeros_like(res))
        return lax.psum(res, axis)

    out = run(stacked_params, xs)
    return out.reshape(B, *out.shape[2:])
