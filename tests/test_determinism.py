"""Executable determinism contract (the reference's seed-42 substitute for
race detection, checked rather than assumed)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.loader import DeviceLoader
from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import place_state
from distributed_deep_learning_tpu.utils.determinism import (
    NondeterminismError, check_step_determinism, diff_trees)


def test_diff_trees_equal():
    t = {"a": np.ones(3), "b": [np.zeros(2)]}
    assert diff_trees(t, t) == []


def test_diff_trees_detects_difference():
    a = {"x": np.ones(3), "y": np.zeros(2)}
    b = {"x": np.ones(3), "y": np.array([0.0, 1e-12])}
    assert diff_trees(a, b) == ["y"]


def test_train_step_is_deterministic(mesh8):
    """The DP train step (psum included) must be bit-deterministic."""
    model = MLP(hidden_size=16)
    state = create_train_state(model, jax.random.key(0), jnp.zeros((1, 48)),
                               optax.sgd(0.1))
    state = place_state(state, mesh8)
    ds = synthetic_mqtt(128, seed=2)
    x, y = next(iter(DeviceLoader(ds, np.arange(64), 64, mesh8)))

    # non-donating step: determinism checks reuse the same state object
    def step(state, x, y):
        def loss(p):
            pred, _, _ = state.apply_fn(p, state.model_state, x, train=True)
            return cross_entropy_loss(pred, y)

        return jax.jit(jax.value_and_grad(loss))(state.params)

    check_step_determinism(step, state, x, y, runs=3)


def test_nondeterminism_detected():
    calls = []

    def flaky(state, x):
        calls.append(1)
        return {"out": np.asarray(x) + len(calls)}

    with pytest.raises(NondeterminismError) as e:
        check_step_determinism(flaky, None, np.zeros(4))
    assert e.value.paths == ["out"]
