"""Unified run telemetry: goodput/MFU accounting, stall attribution,
and latency histograms.

One :class:`RunTelemetry` object per run threads through the train loop,
elastic recovery, and the workload runner; the serve engine builds its
own :class:`~.metrics.MetricsRegistry` per ``run()`` (serving latency is
meaningful even without a run-level stream).  Everything is pure host
Python — nothing here touches jax until/unless ``measure_flops`` is
asked to lower a step.

Layout:

* :mod:`obs.metrics`  — counters / gauges / log-bucketed histograms.
* :mod:`obs.timeline` — per-step spans → goodput breakdown.
* :mod:`obs.mfu`      — model-FLOP accounting + chip peak table.
* :mod:`obs.export`   — JSONL event stream + Prometheus exposition.
* :mod:`obs.bench`    — instrumentation-overhead harness (bench.py).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from .export import EventWriter
from .memory import MemoryTracker
from .metrics import MetricsRegistry
from .mfu import chip_peak_flops, measure_step_flops, mfu_record
from .recorder import FlightRecorder
from .timeline import Timeline
from .trace import Tracer

__all__ = ["RunTelemetry", "MetricsRegistry", "Timeline", "EventWriter",
           "Tracer", "FlightRecorder", "MemoryTracker", "chip_peak_flops"]


class RunTelemetry:
    """The per-run telemetry hub every layer reports into.

    ``path=None`` keeps the full accounting in memory without a sidecar
    (tests, the overhead harness); instruments stay live either way.

    Generation 2 (ISSUE 11): ``trace_path`` turns on the per-request /
    per-step span :class:`~.trace.Tracer` (exported as a Chrome/Perfetto
    trace on :meth:`close`); ``recorder`` attaches a
    :class:`~.recorder.FlightRecorder` the train loop and serve engines
    feed (sentinel anomalies, SLO breaches) so a dying run leaves a
    black box.  ``rotate_mb`` size-caps the JSONL sidecar (see
    :class:`~.export.EventWriter`).
    """

    def __init__(self, path: str | None = None,
                 clock=time.perf_counter, *,
                 trace_path: str | None = None,
                 tracer: "Tracer | None" = None,
                 recorder: "FlightRecorder | None" = None,
                 rotate_mb: float | None = None,
                 fsync_on_rollover: bool = False) -> None:
        self.registry = MetricsRegistry()
        self.tracer = tracer if tracer is not None else (
            Tracer(clock=clock) if trace_path else None)
        self.trace_path = trace_path
        self.recorder = recorder
        self.timeline = Timeline(clock=clock, tracer=self.tracer)
        self.writer = EventWriter(
            path, clock=clock,
            max_bytes=int(rotate_mb * 1e6) if rotate_mb else None,
            fsync_on_rollover=fsync_on_rollover)
        self.clock = clock
        # live memory gauges; resolves its device lazily on first sample,
        # so constructing it here keeps the "no jax until asked" contract
        self.memory = MemoryTracker(self.registry)
        # model-FLOP state (filled by measure_flops / note_train)
        self.step_flops: float | None = None
        self.n_devices: int | None = None
        self.train_steps = 0.0
        self.train_seconds = 0.0
        self.train_examples = 0.0
        self._dispatched_fns: set[int] = set()
        self._closed = False

    # -- compile attribution ------------------------------------------
    def dispatch_kind(self, fn: Any) -> str:
        """First dispatch of a given jitted fn is trace+XLA-build time:
        attribute it to "compile"; every later one is "dispatch"."""
        key = id(fn)
        if key in self._dispatched_fns:
            return "dispatch"
        self._dispatched_fns.add(key)
        return "compile"

    # -- model-FLOP accounting ----------------------------------------
    def measure_flops(self, step_fn: Callable, *args,
                      n_devices: int | None = None, **kwargs) -> None:
        """Record the global per-step FLOPs of the run's train step
        (costs one extra compile, charged to the compile span).
        ``n_devices`` is the device count the step's mesh spans (MFU
        denominator too); default: every visible device.  Failure
        degrades to step_flops=None rather than killing the run."""
        self.n_devices = n_devices
        with self.timeline.span("compile"):
            try:
                self.step_flops = measure_step_flops(
                    step_fn, *args, n_devices=n_devices, **kwargs)
            except Exception:
                self.step_flops = None

    def note_train(self, steps: float, seconds: float,
                   examples: float = 0.0) -> None:
        """Accumulate productive-phase totals for the run MFU number."""
        self.train_steps += steps
        self.train_seconds += seconds
        self.train_examples += examples

    def mfu(self) -> dict:
        import jax

        devs = jax.devices()
        return mfu_record(self.step_flops, self.train_steps,
                          self.train_seconds,
                          self.n_devices or len(devs),
                          devs[0].device_kind)

    # -- rollups -------------------------------------------------------
    def phase_rollup(self, scope: str, since: dict | None = None) -> dict:
        """Emit (and return) a goodput breakdown for a phase delta."""
        gp = self.timeline.goodput(since=since)
        self.writer.emit("obs_goodput", scope=scope, **gp)
        return gp

    def close(self) -> dict:
        """Run-level rollup: whole-timeline goodput, MFU, and the full
        metrics snapshot, then close the sidecar.  Idempotent; returns
        the summary dict (also what obs_report renders)."""
        if self._closed:
            return {}
        self._closed = True
        gp = self.timeline.goodput()
        rec = self.mfu()
        snap = self.registry.snapshot()
        self.writer.emit("obs_goodput", scope="run", **gp)
        self.writer.emit("obs_mfu", **rec)
        self.writer.emit("obs_snapshot", snapshot=snap)
        summary = {"goodput": gp, "mfu": rec, "snapshot": snap}
        if self.memory.samples or self.memory.steps:
            mem = self.memory.summary()
            self.writer.emit("obs_memory", **mem)
            summary["memory"] = mem
        if self.tracer is not None and self.trace_path:
            n = self.tracer.export(self.trace_path)
            self.writer.emit("obs_trace", path=self.trace_path, spans=n,
                             dropped=self.tracer.dropped)
            summary["trace"] = {"path": self.trace_path, "spans": n}
        self.writer.close()
        return summary
