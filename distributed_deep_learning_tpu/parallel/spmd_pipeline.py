"""SPMD pipeline parallelism: GPipe fill-drain inside one XLA program.

This is the TPU-native pipeline the reference's hand-rolled Python scheduler
(``MLP/model.py:81-130`` and byte-identical copies) maps onto: all stages
run the *same* compiled program over a ``stage`` mesh axis (`shard_map`),
stage parameters are stacked along a leading axis and sharded so each device
holds its own stage's weights, and activations rotate between neighbouring
devices with ``lax.ppermute`` over ICI inside a ``lax.scan`` over schedule
ticks.  Forward AND backward pipeline (the scan/ppermute transpose replays
the schedule in reverse) — unlike the reference, whose scheduler only
overlapped forward (SURVEY.md §3.3).

Constraint (inherent to SPMD pipelining): all stages share one
``stage_fn(params, x) -> y`` with ``y.shape == x.shape`` — i.e. a
homogeneous stack (transformer blocks, LSTM layers, residual trunks).
Heterogeneous models use :class:`..mpmd.MPMDPipeline` instead; the usual
composition for real models is embed (outside) → homogeneous trunk
(this pipeline) → head (outside).

Schedule: ``T = M + S - 1`` ticks for M microbatches over S stages.  At tick
``t`` stage ``s`` processes microbatch ``t - s`` (bubble ticks compute on
garbage and are masked at collection — uniform control flow, nothing
data-dependent, exactly what XLA wants).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.7 exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def stack_stage_params(params_list: list[Any]) -> Any:
    """Stack per-stage param pytrees along a new leading `stage` axis.

    Requires homogeneous stages (identical pytree structure and leaf shapes).
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def spmd_pipeline(stage_fn: StageFn, stacked_params: Any, x: jnp.ndarray, *,
                  mesh: Mesh, microbatch_size: int | None = None,
                  axis: str = "stage", batch_axes: tuple[str, ...] = ("data", "fsdp"),
                  rng: jnp.ndarray | None = None
                  ) -> jnp.ndarray:
    """Run `x` through S pipelined applications of `stage_fn`.

    Args:
      stage_fn: one stage's computation, shape-preserving.
      stacked_params: pytree with leading dim S on every leaf, sharded over
        `axis` (see :func:`stack_stage_params`).
      x: global batch ``(B, ...)``; also sharded over `batch_axes` if the
        mesh has data parallelism — pipeline and data parallelism compose
        inside the same program.
      microbatch_size: reference ``-p`` semantics (microbatch SIZE); default
        one microbatch per stage.
      rng: optional PRNG key enabling train-time stochasticity: each tick
        calls ``stage_fn(params, x, key)`` with a key derived from
        (stage, microbatch) — deterministic given ``rng``, distinct per
        stage and microbatch, and stable under the scan transpose (the
        backward replays the same keys).
    """
    S = mesh.shape[axis]
    B = x.shape[0]
    if microbatch_size is None:
        # divisor-safe default: the largest microbatch count <= S that
        # divides B (M == S when possible, M == 1 in the worst case)
        M = max(m for m in range(1, S + 1) if B % m == 0)
        mb = B // M
    else:
        mb = microbatch_size
        if B % mb:
            raise ValueError(f"batch {B} not divisible by microbatch size {mb}")
        M = B // mb
    dp = mesh.shape.get(batch_axes[0], 1) if len(batch_axes) else 1
    for ax in batch_axes[1:]:
        dp *= mesh.shape.get(ax, 1)
    if mb % dp:
        raise ValueError(
            f"microbatch size {mb} not divisible by data-parallel size {dp} "
            f"(mesh axes {batch_axes} = {[mesh.shape.get(a, 1) for a in batch_axes]})")
    xs = x.reshape(M, mb, *x.shape[1:])

    batch_spec = P(None, batch_axes)  # (M, mb, ...): shard the mb dim
    param_spec = P(axis)

    @partial(shard_map, mesh=mesh, in_specs=(param_spec, batch_spec),
             out_specs=batch_spec, check_vma=False)
    def run(params, xs):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index(axis)

        def tick(carry, t):
            # stage 0 feeds from the microbatch queue; others from their
            # left neighbour's previous output (the carry).
            inp0 = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), keepdims=False)
            inp = jnp.where(stage == 0, inp0, carry)
            if rng is not None:
                m_idx = jnp.clip(t - stage, 0, M - 1)
                key = jax.random.fold_in(jax.random.fold_in(rng, stage),
                                         m_idx)
                # distinct masks per data shard too, not just per stage/mb
                for a in batch_axes:
                    if mesh.shape.get(a, 1) > 1:
                        key = jax.random.fold_in(key, lax.axis_index(a))
                out = stage_fn(params, inp, key)
            else:
                out = stage_fn(params, inp)
            nxt = lax.ppermute(out, axis,
                               [(i, (i + 1) % S) for i in range(S)])
            return nxt, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(M + S - 1))
        # Microbatch m finishes on the last stage at tick m + S - 1; mask
        # everyone else and broadcast with a psum (valid rows are unique).
        res = lax.slice_in_dim(outs, S - 1, S - 1 + M, axis=0)
        res = jnp.where(stage == S - 1, res, jnp.zeros_like(res))
        return lax.psum(res, axis)

    out = run(stacked_params, xs)
    return out.reshape(B, *out.shape[2:])


def one_f_one_b_schedule(n_microbatches: int, n_stages: int
                         ) -> list[tuple[int, int, str, int]]:
    """The 1F1B tick table: ``(tick, stage, 'F'|'B', microbatch)`` entries.

    Stage ``s`` forwards microbatch ``m`` at tick ``m + s`` and backwards it
    at tick ``2(S-1) - s + m`` — the backward of microbatch m starts on the
    last stage in the SAME tick as its forward there, then walks left.  Key
    property vs GPipe-with-scan-transpose: microbatch m's residuals on
    stage s live for only ``2(S-1-s)`` ticks, so peak activation residency
    is O(S) instead of O(M) — which is what lets M grow (and the bubble
    fraction (S-1)/(M+S-1) shrink) without running out of HBM.
    Used by :func:`spmd_pipeline_1f1b` and analysed in tests.
    """
    M, S = n_microbatches, n_stages
    ops = []
    for t in range(M + 2 * S - 2):
        for s in range(S):
            if 0 <= t - s < M:
                ops.append((t, s, "F", t - s))
            if 0 <= t - (2 * S - 2 - s) < M:
                ops.append((t, s, "B", t - (2 * S - 2 - s)))
    return ops


def spmd_pipeline_1f1b(stage_fn: StageFn, head_loss_fn, stacked_params: Any,
                       head_params: Any, x: jnp.ndarray, targets: Any, *,
                       mesh: Mesh, microbatch_size: int | None = None,
                       axis: str = "stage",
                       batch_axes: tuple[str, ...] = ("data", "fsdp"),
                       has_aux: bool = False):
    """One-forward-one-backward pipelined TRAIN pass in a single scan.

    The GPipe path (:func:`spmd_pipeline` under ``jax.grad``) lets the scan
    transpose replay the schedule in reverse, which stores every tick's
    residuals — O(M) activations per stage.  Here forward AND backward are
    hand-scheduled in one ``lax.scan`` (:func:`one_f_one_b_schedule`):
    each tick a stage forwards one microbatch and backwards another, with a
    ring buffer of just ``2S-1`` stage inputs and rematerialised block
    backward (recompute-fwd + vjp, the standard TPU trade).

    Because backward of microbatch m must start as soon as its forward
    leaves the last stage, the loss must be computable there:
    ``head_loss_fn(head_params, y_mb, target_mb) -> scalar`` (mean over the
    microbatch rows) runs on the last stage inside the pipeline.

    Returns ``(loss, trunk_grads, head_grads, dx)`` where ``loss`` is the
    global mean, grads are already psum-reduced over the data axes (this
    function hand-rolls its backward inside ``shard_map``, so the outer
    autodiff/partitioner cannot insert those collectives), ``trunk_grads``
    keeps the stacked stage-leading layout of ``stacked_params``, and
    ``dx`` is the loss cotangent w.r.t. ``x`` (feeds the embedding's
    backward in the caller).

    With ``has_aux=True``, ``head_loss_fn`` returns ``(scalar, aux_tree)``
    (e.g. correct/count metric counters); aux leaves are SUMMED over
    microbatches and all mesh axes and appended as a fifth return value.
    """
    S = mesh.shape[axis]
    B = x.shape[0]
    if microbatch_size is None:
        M = max(m for m in range(1, S + 1) if B % m == 0)
        mb = B // M
    else:
        mb = microbatch_size
        if B % mb:
            raise ValueError(f"batch {B} not divisible by microbatch {mb}")
        M = B // mb
    dp_axes = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1)
    dp = 1
    for a in dp_axes:
        dp *= mesh.shape[a]
    if mb % dp:
        raise ValueError(f"microbatch size {mb} not divisible by "
                         f"data-parallel size {dp}")
    xs = x.reshape(M, mb, *x.shape[1:])
    ts = jax.tree.map(lambda a: a.reshape(M, mb, *a.shape[1:]), targets)

    R = 2 * S - 1           # residual ring slots (peak in-flight + 1)
    T = M + 2 * S - 2       # total schedule ticks
    scale = 1.0 / (M * dp)  # Σ microbatch-means → global mean

    batch_spec = P(None, batch_axes)
    param_spec = P(axis)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P(), batch_spec, batch_spec),
             out_specs=(P(), param_spec, P(), batch_spec, P()),
             check_vma=False)
    def run(params, head_params, xs, ts):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        s = lax.axis_index(axis)
        fperm = [(i, (i + 1) % S) for i in range(S)]
        bperm = [(i, (i - 1) % S) for i in range(S)]
        zeros_g = lambda tree: jax.tree.map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        def masked_add(acc, upd, flag):
            return jax.tree.map(
                lambda a, u: a + jnp.where(flag, u.astype(a.dtype), 0), acc,
                upd)

        def tick(carry, t):
            fwd_in, bwd_ct, resid, tg, hg, loss, aux = carry
            # ---- forward: microbatch f = t - s ----
            f = t - s
            do_f = jnp.logical_and(f >= 0, f < M)
            inp = jnp.where(s == 0,
                            lax.dynamic_index_in_dim(
                                xs, jnp.clip(f, 0, M - 1), keepdims=False),
                            fwd_in)
            out = stage_fn(params, inp)
            # park the stage input in its ring slot (keep the old value on
            # non-forward ticks so a live slot is never clobbered)
            slot_f = jnp.clip(f, 0, M - 1) % R
            old = lax.dynamic_index_in_dim(resid, slot_f, keepdims=False)
            resid = lax.dynamic_update_index_in_dim(
                resid, jnp.where(do_f, inp, old), slot_f, axis=0)
            # ---- backward: microbatch b = t - (2S-2-s) ----
            b = t - (2 * S - 2 - s)
            do_b = jnp.logical_and(b >= 0, b < M)
            bc = jnp.clip(b, 0, M - 1)
            rin = lax.dynamic_index_in_dim(resid, bc % R, keepdims=False)
            y2, stage_vjp = jax.vjp(lambda p, a: stage_fn(p, a), params, rin)
            tgt = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, bc, keepdims=False),
                ts)
            if has_aux:
                lval, head_vjp, aux_mb = jax.vjp(
                    lambda hp, y: head_loss_fn(hp, y, tgt), head_params, y2,
                    has_aux=True)
            else:
                lval, head_vjp = jax.vjp(
                    lambda hp, y: head_loss_fn(hp, y, tgt), head_params, y2)
                aux_mb = {}
            dhp, dy = head_vjp(jnp.ones((), lval.dtype))
            seed = jnp.where(s == S - 1, dy.astype(y2.dtype), bwd_ct)
            dparams, dinp = stage_vjp(seed)
            last = s == S - 1
            tg = masked_add(tg, dparams, do_b)
            hg = masked_add(hg, dhp, jnp.logical_and(do_b, last))
            loss = loss + jnp.where(jnp.logical_and(do_b, last),
                                    lval.astype(jnp.float32), 0.0)
            aux = masked_add(aux, aux_mb, jnp.logical_and(do_b, last))
            # ---- rotate carries; emit stage-0 input cotangents ----
            fwd_next = lax.ppermute(out, axis, fperm)
            bwd_next = lax.ppermute(dinp, axis, bperm)
            dx_emit = jnp.where(jnp.logical_and(s == 0, do_b), dinp, 0)
            return (fwd_next, bwd_next, resid, tg, hg, loss, aux), dx_emit

        z = jnp.zeros_like(xs[0])
        if has_aux:
            y_s = jax.eval_shape(stage_fn, params, xs[0])
            aux_shape = jax.eval_shape(
                head_loss_fn, head_params, y_s,
                jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:],
                                                            a.dtype), ts))[1]
            aux0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                                aux_shape)
        else:
            aux0 = {}
        carry0 = (z, z, jnp.zeros((R,) + xs.shape[1:], xs.dtype),
                  zeros_g(params), zeros_g(head_params),
                  jnp.zeros((), jnp.float32), aux0)
        (_, _, _, tg, hg, loss, aux), dxs = lax.scan(tick, carry0,
                                                     jnp.arange(T))

        # stage 0 emits microbatch b's dx at tick 2S-2+b; other stages 0
        dxs = lax.slice_in_dim(dxs, 2 * S - 2, 2 * S - 2 + M, axis=0)
        dxs = jnp.where(s == 0, dxs, jnp.zeros_like(dxs))
        dx = lax.psum(dxs, axis) * scale
        loss = lax.psum(loss, axis)                  # only last stage added
        hg = jax.tree.map(lambda a: lax.psum(a, axis), hg)
        if dp_axes:
            tg = jax.tree.map(lambda a: lax.psum(a, dp_axes), tg)
            hg = jax.tree.map(lambda a: lax.psum(a, dp_axes), hg)
            loss = lax.psum(loss, dp_axes)
        aux = jax.tree.map(lambda a: lax.psum(a, axis), aux)
        if dp_axes:
            aux = jax.tree.map(lambda a: lax.psum(a, dp_axes), aux)
        loss = loss * scale                          # Σ shard/mb sums → mean
        hg = jax.tree.map(lambda a: a * scale, hg)
        tg = jax.tree.map(lambda a: (a * scale)[None], tg)  # restack stage dim
        return loss, tg, hg, dx, aux

    loss, tg, hg, dx, aux = run(stacked_params, head_params, xs, ts)
    dx = dx.reshape(B, *dx.shape[2:])
    if has_aux:
        return loss, tg, hg, dx, aux
    return loss, tg, hg, dx
