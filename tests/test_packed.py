"""Packed sample cache: round-trip parity, resume determinism, error
paths, and the pack/feed-bench script surfaces.

The contract under test (``data/packed.py``): packing a dataset and
reading it back through the mmap'd ``PackedDataset`` is invisible to
training — same batches, same order, same bits — while batch formation
drops the per-epoch decode entirely.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from distributed_deep_learning_tpu.data.datasets import synthetic_pcb
from distributed_deep_learning_tpu.data.loader import DeviceLoader
from distributed_deep_learning_tpu.data.packed import (PackedDataset,
                                                       PackedFormatError,
                                                       pack_dataset,
                                                       read_header)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("images")
    rng = np.random.default_rng(0)
    for cls, shade in (("cat", 60), ("dog", 180)):
        d = root / cls
        d.mkdir()
        for i in range(6):
            arr = np.full((20 + i, 24, 3), shade, np.uint8)
            arr += rng.integers(0, 20, arr.shape, dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.png")
    return str(root)


@pytest.fixture(scope="module")
def eager_ds(image_root):
    from distributed_deep_learning_tpu.data.imagefolder import (
        ImageFolderDataset)

    return ImageFolderDataset(image_root, image_size=8)


@pytest.fixture(scope="module")
def packed_path(eager_ds, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("cache") / "imgs.ddlpack")
    pack_dataset(eager_ds, path, chunk_size=5)  # chunk ∤ n: tail exercised
    return path


# --- round-trip parity ------------------------------------------------------

def test_imagefolder_roundtrip_bit_identical(eager_ds, packed_path):
    packed = PackedDataset(packed_path)
    assert len(packed) == len(eager_ds)
    assert packed.classes == eager_ds.classes
    idx = np.array([0, 11, 3, 7, 3])  # unordered + repeated
    xe, ye = eager_ds.batch(idx)
    xp, yp = packed.batch(idx)
    assert xp.dtype == xe.dtype
    np.testing.assert_array_equal(xp, xe)
    np.testing.assert_array_equal(yp, ye)


def test_array_dataset_roundtrip_bit_identical(tmp_path):
    ds = synthetic_pcb(n=40, seed=3)  # tabular/one-hot family
    path = str(tmp_path / "pcb.ddlpack")
    pack_dataset(ds, path)
    packed = PackedDataset(path)
    xe, ye = ds.batch(np.arange(40))
    xp, yp = packed.batch(np.arange(40))
    np.testing.assert_array_equal(xp, xe)
    np.testing.assert_array_equal(yp, ye)


def test_token_rows_keep_int_dtype(tmp_path):
    from distributed_deep_learning_tpu.data.datasets import ArrayDataset

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.integers(0, 999, (30, 16)).astype(np.int32),
                      rng.integers(0, 999, (30, 16)).astype(np.int32))
    path = str(tmp_path / "tok.ddlpack")
    header = pack_dataset(ds, path)
    assert header["feature_dtype"] == "int32"  # ints never quantise to u8
    xp, yp = PackedDataset(path).batch(np.array([5, 2]))
    assert xp.dtype == np.int32 and yp.dtype == np.int32
    np.testing.assert_array_equal(xp, ds.features[[5, 2]])


def test_uint8_auto_storage_lossless(image_root, tmp_path):
    """Images decoded at native size are integral floats → stored uint8
    (4x smaller) yet read back bit-identical as float32."""
    from PIL import Image

    from distributed_deep_learning_tpu.data.imagefolder import (
        ImageFolderDataset)

    root = tmp_path / "native"
    for cls in ("a", "b"):
        (root / cls).mkdir(parents=True)
        rng = np.random.default_rng(7)
        for i in range(3):
            Image.fromarray(rng.integers(0, 255, (16, 16, 3),
                                         dtype=np.uint8)).save(
                root / cls / f"{i}.png")
    ds = ImageFolderDataset(str(root), image_size=16)  # identity resize
    path = str(tmp_path / "u8.ddlpack")
    header = pack_dataset(ds, path)
    assert header["feature_dtype"] == "uint8"
    assert header["feature_out_dtype"] == "float32"
    xe, _ = ds.batch(np.arange(6))
    xp, _ = PackedDataset(path).batch(np.arange(6))
    assert xp.dtype == np.float32
    np.testing.assert_array_equal(xp, xe)


def test_forced_uint8_rejects_lossy_samples(eager_ds, tmp_path):
    # 8px bilinear resize of 20-24px images produces fractional values
    with pytest.raises(ValueError, match="uint8-representable"):
        pack_dataset(eager_ds, str(tmp_path / "x.ddlpack"), dtype="uint8")


def test_pack_subset_indices(eager_ds, tmp_path):
    path = str(tmp_path / "sub.ddlpack")
    keep = np.array([2, 9, 4])
    pack_dataset(eager_ds, path, indices=keep)
    packed = PackedDataset(path)
    assert len(packed) == 3
    xe, _ = eager_ds.batch(keep)
    xp, _ = packed.batch(np.arange(3))
    np.testing.assert_array_equal(xp, xe)


# --- loader determinism / resume --------------------------------------------

def test_loader_batches_match_eager_path(eager_ds, packed_path, mesh8):
    """The full seeded DeviceLoader pipeline (epoch permutation + shard
    assembly + device_put) is bit-identical packed vs eager."""
    packed = PackedDataset(packed_path)
    n = (len(eager_ds) // 8) * 8
    le = DeviceLoader(eager_ds, np.arange(n), 8, mesh8, shuffle=True, seed=5)
    lp = DeviceLoader(packed, np.arange(n), 8, mesh8, shuffle=True, seed=5)
    le.set_epoch(2)
    lp.set_epoch(2)
    ae, ap = list(le), list(lp)
    assert len(ae) == len(ap) > 0
    for (xe, ye), (xp, yp) in zip(ae, ap):
        np.testing.assert_array_equal(np.asarray(xe), np.asarray(xp))
        np.testing.assert_array_equal(np.asarray(ye), np.asarray(yp))


def test_mid_epoch_skip_replays_exact_suffix(packed_path):
    """iter_batches(skip) — the loader-position-sidecar resume path — must
    replay the identical batch suffix on the packed loader."""
    import jax

    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    mesh2 = build_mesh({"data": 2}, jax.devices()[:2])
    packed = PackedDataset(packed_path)
    n = (len(packed) // 4) * 4
    loader = DeviceLoader(packed, np.arange(n), 4, mesh2, shuffle=True,
                          seed=11)
    loader.set_epoch(1)
    full = [(np.asarray(x), np.asarray(y)) for x, y in loader.iter_batches()]
    resumed = [(np.asarray(x), np.asarray(y))
               for x, y in loader.iter_batches(skip=1)]
    assert len(resumed) == len(full) - 1
    for (xf, yf), (xr, yr) in zip(full[1:], resumed):
        np.testing.assert_array_equal(xf, xr)
        np.testing.assert_array_equal(yf, yr)


def test_checkpoint_resume_through_packed_loader(tmp_path, monkeypatch):
    """Mid-epoch checkpoint resume (`--checkpoint-every` + the
    loader-position sidecar) stays deterministic with --packed-cache: the
    interrupted-and-resumed run's final params equal the uninterrupted
    run's, bit for bit.  (mlp keeps the e2e cheap; the loader mechanics
    are workload-independent.)"""
    import jax

    from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
    from distributed_deep_learning_tpu.utils.config import Config, Mode
    from distributed_deep_learning_tpu.workloads import get_spec, run_workload

    monkeypatch.delenv("DDL_INJECT_STEP_FAILURE", raising=False)
    cache = str(tmp_path / "mqtt.ddlpack")
    pack_dataset(synthetic_mqtt(n=64, seed=2), cache)

    def run(ckpt_dir=None, resume=False, every=0):
        config = Config(mode=Mode.SEQUENTIAL, packed_cache=cache,
                        batch_size=4, epochs=2, seed=9,
                        checkpoint_dir=ckpt_dir, resume=resume,
                        checkpoint_every=every)
        state, _ = run_workload(get_spec("mlp"), config)
        return state

    straight = run()
    ckpt = str(tmp_path / "ckpt")
    # save every step, then resume from a TRUNCATED copy of the directory
    run(ckpt_dir=ckpt, every=3)
    import glob

    steps = sorted(int(os.path.basename(p)) for p in glob.glob(
        os.path.join(ckpt, "[0-9]*")) if os.path.basename(p).isdigit())
    mid = [s for s in steps if s != max(steps)]
    assert mid, "need a mid-run checkpoint to resume from"
    cut = str(tmp_path / "cut")
    shutil.copytree(ckpt, cut)
    for s in steps:
        if s > mid[-1]:
            shutil.rmtree(os.path.join(cut, str(s)))
            extra = os.path.join(cut, f"extra-{s}.json")
            if os.path.exists(extra):
                os.remove(extra)
    resumed = run(ckpt_dir=cut, resume=True, every=3)
    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- error paths ------------------------------------------------------------

def test_bad_magic_rejected(tmp_path):
    path = tmp_path / "not.ddlpack"
    path.write_bytes(b"definitely not a packed cache, longer than header")
    with pytest.raises(PackedFormatError, match="magic"):
        PackedDataset(str(path))


def test_truncated_file_rejected(packed_path, tmp_path):
    cut = str(tmp_path / "trunc.ddlpack")
    shutil.copy(packed_path, cut)
    with open(cut, "r+b") as f:
        f.truncate(os.path.getsize(cut) - 64)
    with pytest.raises(PackedFormatError, match="truncated|bytes on disk"):
        PackedDataset(cut)


def test_version_mismatch_rejected(packed_path, tmp_path):
    fut = str(tmp_path / "v99.ddlpack")
    shutil.copy(packed_path, fut)
    with open(fut, "r+b") as f:
        f.seek(7)
        f.write(bytes([99]))
    with pytest.raises(PackedFormatError, match="version 99"):
        read_header(fut)


def test_empty_dataset_rejected(tmp_path):
    ds = synthetic_pcb(n=8)
    with pytest.raises(ValueError, match="empty"):
        pack_dataset(ds, str(tmp_path / "e.ddlpack"),
                     indices=np.array([], np.int64))


def test_missing_cache_flag_fails_loudly(tmp_path):
    from distributed_deep_learning_tpu.utils.config import Config
    from distributed_deep_learning_tpu.workloads import get_spec
    from distributed_deep_learning_tpu.workloads.base import _build_dataset

    config = Config(packed_cache=str(tmp_path / "missing.ddlpack"))
    with pytest.raises(FileNotFoundError):
        _build_dataset(get_spec("resnet"), config)


# --- config / workload wiring ----------------------------------------------

def test_cli_parses_packed_cache():
    from distributed_deep_learning_tpu.utils.config import parse_args

    c = parse_args(["--packed-cache", "/tmp/c.ddlpack"], workload="resnet")
    assert c.packed_cache == "/tmp/c.ddlpack"
    assert parse_args([], workload="resnet").packed_cache is None


def test_resnet_geometry_from_packed_cache(packed_path):
    """Head width and stem choice come from the cache's stored metadata,
    not from flags that described the original tree."""
    from distributed_deep_learning_tpu.utils.config import Config
    from distributed_deep_learning_tpu.workloads.northstar import (
        _resnet_model)

    packed = PackedDataset(packed_path)
    model = _resnet_model(Config(packed_cache=packed_path, size=18), packed)
    assert model.num_classes == 2
    assert model.small_inputs  # 8px samples → CIFAR stem


# --- script smokes (tier-1: the tools must not rot) -------------------------

def _run_script(name, *args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", name), *args],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)


def test_pack_dataset_script_smoke(image_root, tmp_path):
    out = str(tmp_path / "cli.ddlpack")
    proc = _run_script("pack_dataset.py", "--workload", "resnet",
                       "--data-dir", image_root, "--image-size", "8",
                       "--out", out, "--limit", "6")
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["num_samples"] == 6
    assert os.path.getsize(out) == line["bytes"]
    assert len(PackedDataset(out)) == 6


def test_feed_bench_script_smoke(image_root, tmp_path):
    report = str(tmp_path / "feed.json")
    proc = _run_script("feed_bench.py", "--data-dir", image_root,
                       "--image-size", "8", "--batch", "4",
                       "--epochs", "2", "--out", report)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(report) as f:
        line = json.load(f)
    assert line["packed_images_per_sec"] > 0
    assert line["eager_images_per_sec"] > 0
    # the tiny PNG fixture already shows a multiple; the 20x floor is
    # asserted on the JPEG bench fixture (bench.py / acceptance runs),
    # not here where 24 images make timing noisy
    assert line["speedup"] is not None


# --- bench satellite: recorded TPU MFU fallback -----------------------------

def test_bench_recorded_mfu_helper():
    sys.path.insert(0, REPO)
    import bench

    assert bench._recorded_mfu({}) is None
    assert bench._recorded_mfu({"tpu:resnet50_mfu_v1": 0.29}) == 0.29
    assert bench._recorded_mfu({"tpu:resnet50_mfu_v1": None}) is None
    # the shipped baseline file carries the r5 validation datum, so the
    # driver's CPU-fallback line gets a non-null mfu (VERDICT #5b)
    with open(os.path.join(REPO, "bench_baseline.json")) as f:
        assert bench._recorded_mfu(json.load(f)) is not None
