"""CLI-facing pipelined language models: embed → SPMD trunk → head.

This is the model the ``transformer``/``bert`` workloads build for
``-m pipeline``: the homogeneous transformer trunk runs through
:class:`..parallel.pipeline_transformer.PipelinedTrunk` (one XLA program,
``stage`` mesh axis, forward AND backward pipelined — unlike the
reference's forward-only scheduler, ``src/pytorch/MLP/model.py:81-130``),
while the heterogeneous ends (embedding, LM head) run outside the pipeline
with ordinary shardings.

Design notes (documented divergences, both TPU-first):

* SPMD pipelining requires a homogeneous stack, so the ``transformer``
  workload's pipeline mode trains a *decoder-only* causal LM over the
  concatenated source⊕target token stream, reading logits at the target
  positions — the modern pipeline-friendly formulation of seq2seq; the
  encoder-decoder form stays available in ``-m data``.
* The head is untied (no weight sharing with the embedding): a tied head
  would have to reference embedding parameters across the pipeline
  boundary, forcing an extra gather per step.
* Dropout: the pipeline derives a per-(stage, microbatch) PRNG key each
  tick, so ``--dropout`` works under the GPipe schedule (the hand-rolled
  1F1B backward replays forward with recompute and stays deterministic —
  it rejects dropout instead).

The object is not an ``nn.Module``: it owns three Flax sub-models and
exposes the package's ``TrainState`` calling convention directly
(``apply_fn(params, model_state, x, train, rngs)``), with a sharding-rule
table (``shard_rules``) that puts the stacked trunk parameters on the
``stage`` axis.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_deep_learning_tpu.parallel.pipeline_transformer import (
    PipelinedTrunk)


class LMEmbed(nn.Module):
    """Token + positional embedding (ignores ``train``).

    ``pos_embedding='rope'`` creates NO position table — the rotation is
    applied inside every attention block instead (mirroring
    :class:`..models.transformer.CausalLM`'s convention)."""

    vocab_size: int
    d_model: int
    max_len: int = 4096
    dtype: jnp.dtype = jnp.float32
    pos_embedding: str = "learned"      # "learned" | "rope"

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = nn.Embed(self.vocab_size, self.d_model,
                     embedding_init=nn.initializers.normal(0.02),
                     dtype=self.dtype, name="tok")(tokens)
        if self.pos_embedding == "rope":
            return x
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (self.max_len, self.d_model))
        return x + pos[None, :tokens.shape[1]].astype(self.dtype)


class LMHead(nn.Module):
    """Vocabulary projection, f32 logits; optionally reads only a static
    slice of positions (the target segment of a src⊕tgt stream)."""

    vocab_size: int
    take: Optional[tuple[int, int]] = None  # (start, length) or None = all
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.take is not None:
            start, length = self.take
            x = x[:, start:start + length]
        x = nn.Dense(self.vocab_size, dtype=self.dtype,
                     kernel_init=nn.initializers.xavier_uniform())(x)
        return x.astype(jnp.float32)


class PipelinedLM:
    """embed → pipelined trunk → head with ``TrainState`` conventions."""

    #: params whose leading (stacked-stage) axis lives on ``stage``
    shard_rules = ((r"^trunk/.*", P("stage")),)

    def __init__(self, *, vocab_size: int, num_layers: int, d_model: int,
                 num_heads: int, mlp_dim: int, mesh: Mesh,
                 causal: bool = False,
                 head_take: Optional[tuple[int, int]] = None,
                 microbatch_size: Optional[int] = None,
                 max_len: int = 4096, dtype: jnp.dtype = jnp.float32,
                 attention_fn=None, dropout_rate: float = 0.0,
                 n_chunks: int = 1, pos_embedding: str = "learned",
                 attention_window: Optional[int] = None,
                 num_kv_heads: Optional[int] = None):
        if pos_embedding not in ("learned", "rope"):
            raise ValueError(f"pos_embedding must be 'learned' or 'rope', "
                             f"got {pos_embedding!r}")
        if attention_window is not None and not causal:
            raise ValueError("attention_window (sliding window) requires "
                             "a causal trunk")
        self.embed = LMEmbed(vocab_size, d_model, max_len, dtype,
                             pos_embedding)
        self.trunk = PipelinedTrunk(num_layers, mesh, num_heads=num_heads,
                                    mlp_dim=mlp_dim, causal=causal,
                                    dtype=dtype,
                                    microbatch_size=microbatch_size,
                                    attention_fn=attention_fn,
                                    dropout_rate=dropout_rate,
                                    n_chunks=n_chunks,
                                    rope=pos_embedding == "rope",
                                    window=attention_window,
                                    num_kv_heads=num_kv_heads)
        if n_chunks > 1:
            # (V, S, ...) stacks: chunk dim replicated, stage dim sharded
            self.shard_rules = ((r"^trunk/.*", P(None, "stage")),)
        self.head = LMHead(vocab_size, head_take, dtype)

    def init(self, rng: jax.Array, tokens: jnp.ndarray) -> dict[str, Any]:
        r_embed, r_trunk, r_head = jax.random.split(rng, 3)
        e = self.embed.init(r_embed, tokens)["params"]
        x0 = self.embed.apply({"params": e}, tokens)
        t = self.trunk.init(r_trunk, x0)
        h = self.head.init(r_head, x0)["params"]
        return {"embed": e, "trunk": t, "head": h}

    def apply_fn(self, params, model_state, tokens, train: bool = False,
                 rngs=None):
        """→ (logits, model_state, aux) — the ``TrainState`` convention."""
        x = self.embed.apply({"params": params["embed"]}, tokens)
        rng = rngs.get("dropout") if (train and rngs) else None
        x = self.trunk.apply(params["trunk"], x, rng=rng)
        logits = self.head.apply({"params": params["head"]}, x)
        return logits, model_state, jnp.zeros((), jnp.float32)

    def apply_sequential(self, params, tokens, train: bool = False):
        """Same weights without the pipeline (equivalence testing)."""
        x = self.embed.apply({"params": params["embed"]}, tokens)
        x = self.trunk.apply_sequential(params["trunk"], x)
        return self.head.apply({"params": params["head"]}, x)
