"""Chaos drill: rehearse the detect→contain→recover chain, print one JSON
line.

Runs :func:`distributed_deep_learning_tpu.utils.chaos.run_resilience_drill`
— NaN'd batch contained by the anomaly sentinel (bit-identical params),
truncated latest checkpoint quarantined with fallback to the verified
save, injected worker failure recovered by elastic restart — and reports
detection latency, recovery wall time, restarts used and the sentinel's
step-time overhead.  CPU-runnable (the chain is host+XLA logic, not
accelerator-specific); ``bench.py`` embeds the same record as its
``resilience`` section.

Usage::

    python scripts/chaos_drill.py [--seed N]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--seed", type=int, default=0,
                   help="chaos plan seed (same seed = same faults, "
                        "bit-identical poison masks)")
    args = p.parse_args()

    from distributed_deep_learning_tpu.utils.chaos import run_resilience_drill

    record = run_resilience_drill(seed=args.seed)
    ok = record["containment_bit_identical"] and \
        record["corrupt_restore_fell_back"] and \
        record["recovered_bit_identical"]
    record["drill_passed"] = bool(ok)
    print(json.dumps({"metric": "resilience drill", **record}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
