"""Telemetry export: JSONL event stream + Prometheus text exposition.

The JSONL stream extends ``PhaseLogger``'s sidecar grammar — every line
is ``{"event": <name>, "t": <monotonic seconds>, **fields}`` — so a
run's obs stream and its phase log speak the same dialect and a single
reader (:func:`read_events`) serves both.  Obs-specific events:

* ``obs_goodput``  — a goodput breakdown (``scope``: phase label or
  ``"run"``), fields from ``Timeline.goodput()``.
* ``obs_mfu``      — an ``mfu.mfu_record`` dict.
* ``obs_snapshot`` — a full ``MetricsRegistry.snapshot()``.
* ``obs_serve``    — serve engine stats (latency percentiles included).

:func:`prometheus_text` renders a registry snapshot in the Prometheus
text exposition format (cumulative ``le`` buckets, ``_sum``/``_count``)
so a scrape endpoint or a file-based textfile collector can serve it
without any new dependency.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Iterator


class EventWriter:
    """Line-buffered JSONL appender in the PhaseLogger sidecar grammar.

    Safe to construct with ``path=None`` (all writes become no-ops), so
    call sites never need their own ``if telemetry`` guards.

    ``max_bytes`` caps the live file: when an emit pushes it past the
    cap the file ROTATES — ``path`` is renamed to ``path.1`` (older
    generations shifting to ``path.2`` … ``path.{keep}``, the oldest
    dropped) and a fresh ``path`` is opened, so a multi-hour run holds
    at most ``(keep + 1) * max_bytes`` of sidecar.  Rotation happens on
    line boundaries — every generation is a well-formed JSONL file in
    the unchanged grammar.  ``fsync_on_rollover`` additionally fsyncs
    the closing generation before the rename, so a power cut can only
    lose lines from the CURRENT generation.
    """

    def __init__(self, path: str | None, clock=time.perf_counter,
                 max_bytes: int | None = None, keep: int = 3,
                 fsync_on_rollover: bool = False) -> None:
        self.path = path
        self.clock = clock
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.keep = max(1, int(keep))
        self.fsync_on_rollover = fsync_on_rollover
        self.rollovers = 0
        self._fh = open(path, "a", buffering=1) if path else None
        self._bytes = os.path.getsize(path) if path else 0

    def emit(self, event: str, **fields: Any) -> None:
        if self._fh is None:
            return
        rec = {"event": event, "t": self.clock(), **fields}
        # allow_nan=False because json would otherwise emit the literal
        # ``NaN`` — valid to json.loads but poison to strict readers
        # (jq, browsers); _json_default cannot intercept floats (they
        # are natively serializable), so non-finite floats route through
        # the ValueError path and get scrubbed to None.
        try:
            line = json.dumps(rec, default=_json_default, allow_nan=False)
        except ValueError:
            line = json.dumps(_scrub(rec), default=_json_default,
                              allow_nan=False)
        self._fh.write(line + "\n")
        if self.max_bytes is not None:
            self._bytes += len(line) + 1
            if self._bytes >= self.max_bytes:
                self._rollover()

    def _rollover(self) -> None:
        self._fh.flush()
        if self.fsync_on_rollover:
            os.fsync(self._fh.fileno())
        self._fh.close()
        for gen in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{gen}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{gen + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", buffering=1)
        self._bytes = 0
        self.rollovers += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _scrub(o: Any):
    """Recursively replace non-finite floats with None (cold path: only
    runs when a record actually contains one)."""
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, dict):
        return {k: _scrub(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_scrub(v) for v in o]
    return o


def _json_default(o: Any):
    """Last-resort encoder: inf/nan → None (JSON has no inf), arrays and
    numpy scalars → python."""
    if isinstance(o, float):
        return None if not math.isfinite(o) else o
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        return tolist()
    item = getattr(o, "item", None)
    if item is not None:
        return item()
    return str(o)


def read_events(path: str, event: str | None = None) -> Iterator[dict]:
    """Yield event dicts from a JSONL sidecar (PhaseLogger or obs),
    optionally filtered by event name.  Tolerates a torn final line
    (a killed run mid-write) by skipping undecodable lines."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if event is None or rec.get("event") == event:
                yield rec


def read_rotated(path: str, event: str | None = None) -> Iterator[dict]:
    """Like :func:`read_events` but chaining rotated generations oldest
    first (``path.N`` … ``path.1``, then the live ``path``), so a
    size-capped run's whole retained history reads as one stream."""
    gen = 1
    older: list[str] = []
    while os.path.exists(f"{path}.{gen}"):
        older.append(f"{path}.{gen}")
        gen += 1
    for p in reversed(older):
        yield from read_events(p, event)
    if os.path.exists(path):
        yield from read_events(path, event)


def _prom_name(key: str) -> tuple[str, str]:
    """Split a registry key ``name{a=b}`` into (metric name, label part
    incl. braces or empty), quoting label values per the exposition
    format."""
    if "{" not in key:
        return key, ""
    name, _, rest = key.partition("{")
    inner = rest.rstrip("}")
    quoted = ",".join(
        f'{k}="{v}"' for k, _, v in
        (pair.partition("=") for pair in inner.split(","))
    )
    return name, "{" + quoted + "}"


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(snapshot: dict) -> str:
    """Render a ``MetricsRegistry.snapshot()`` in Prometheus text
    format.  Histogram buckets are emitted cumulatively with ``le``
    upper bounds plus the ``+Inf`` bucket, ``_sum`` and ``_count``."""
    lines: list[str] = []
    for key, v in sorted(snapshot.get("counters", {}).items()):
        name, labels = _prom_name(key)
        # classic text format: the TYPE line names the sample family
        # (name_total), not the bare metric — a mismatch reads as
        # untyped to strict parsers
        lines.append(f"# TYPE {name}_total counter")
        lines.append(f"{name}_total{labels} {_fmt(v)}")
    for key, v in sorted(snapshot.get("gauges", {}).items()):
        name, labels = _prom_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {_fmt(v)}")
    for key, h in sorted(snapshot.get("histograms", {}).items()):
        name, labels = _prom_name(key)
        base = labels[1:-1] if labels else ""
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lab = f'{base},le="{_fmt(float(bound))}"' if base \
                else f'le="{_fmt(float(bound))}"'
            lines.append(f"{name}_bucket{{{lab}}} {cum}")
        lab = f'{base},le="+Inf"' if base else 'le="+Inf"'
        lines.append(f"{name}_bucket{{{lab}}} {h['count']}")
        lines.append(f"{name}_sum{labels} {_fmt(h['sum'])}")
        lines.append(f"{name}_count{labels} {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")
