"""MPMD staged execution: the reference's `model` and `pipeline` modes.

The reference moves activations between per-device ``nn.Sequential`` stages
with ``.to(device)`` (``MLP/model.py:77-80``) and pipelines them with a
hand-rolled 3-phase load/process/flush microbatch scheduler, byte-identical
in all three models (``MLP/model.py:81-130``, quirk: forward-only overlap).

The TPU-native translation keeps the *placement* idea — each stage's
parameters committed to its own device, activations transferred at stage
boundaries via ``jax.device_put`` — but gets overlap for free from JAX's
async dispatch: stage programs are independently-jitted computations on
different devices, so once microbatch *k* has been dispatched on stage *s*,
microbatch *k+1*'s stage *s-1* program runs concurrently.  No explicit
load/process/flush phases are needed; the dependency graph *is* the
schedule, for backward as well as forward (the reference's scheduler was
forward-only).

For homogeneous layer stacks prefer :func:`..spmd_pipeline.spmd_pipeline`,
which runs the whole pipeline inside one XLA program over a ``stage`` mesh
axis.  MPMD staging is the general mechanism that works for arbitrarily
heterogeneous models (conv → pool → lstm → dense), exactly like the
reference's.

`microbatch_size` follows the reference's ``-p`` semantics: the SIZE of
each microbatch, not the count (``CNN/model.py:212`` splits by size).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from distributed_deep_learning_tpu.parallel.staging import StagedModel


class MPMDPipeline:
    """Stage-placed execution of a :class:`StagedModel` over explicit devices."""

    def __init__(self, staged: StagedModel, devices: Sequence[jax.Device],
                 microbatch_size: int | None = None):
        if len(devices) != len(staged.stages):
            raise ValueError(f"{len(staged.stages)} stages need "
                             f"{len(staged.stages)} devices, got {len(devices)}")
        self.staged = staged
        self.devices = list(devices)
        self.microbatch_size = microbatch_size
        # One jitted program per stage; committed inputs pin execution to the
        # stage's device.
        self._stage_fns = [jax.jit(stage.apply) for stage in staged.stages]

    # -- parameter placement -------------------------------------------------
    def init(self, rng: jax.Array, example: Any) -> list[Any]:
        params = self.staged.init(rng, example)
        return self.place(params)

    def place(self, params: Sequence[Any]) -> list[Any]:
        return [jax.device_put(p, d) for p, d in zip(params, self.devices)]

    # -- forwards ------------------------------------------------------------
    def _stage_walk(self, params: Sequence[Any], x: jnp.ndarray) -> jnp.ndarray:
        for fn, p, d in zip(self._stage_fns, params, self.devices):
            x = fn(p, jax.device_put(x, d))
        return x

    def forward(self, params: Sequence[Any], x: jnp.ndarray) -> jnp.ndarray:
        """`model` mode: one chunk walks the stages (reference
        ``modelParallelismForward``)."""
        return self._stage_walk(params, x)

    def pipelined_forward(self, params: Sequence[Any], x: jnp.ndarray) -> jnp.ndarray:
        """`pipeline` mode: microbatch the input (reference ``-p`` = chunk
        size), dispatch each chunk through the stage walk, concatenate.

        JAX's async dispatch overlaps chunk *k* on stage *s* with chunk
        *k+1* on stage *s-1* — the fill/process/flush staircase emerges from
        data dependencies instead of being scheduled by hand.
        """
        mb = self.microbatch_size or len(x)
        chunks = [x[i:i + mb] for i in range(0, len(x), mb)]
        outs = [self._stage_walk(params, c) for c in chunks]
        return jnp.concatenate(outs, axis=0)

    def __call__(self, params: Sequence[Any], x: jnp.ndarray,
                 pipelined: bool | None = None) -> jnp.ndarray:
        if pipelined or (pipelined is None and self.microbatch_size):
            return self.pipelined_forward(params, x)
        return self.forward(params, x)
