"""Step-granular checkpointing + mid-epoch resume (VERDICT r4 item 5):
--checkpoint-every N saves the loader position and partial-phase totals in
the checkpoint sidecar, so a preemption costs at most N steps and the
resumed run is BIT-IDENTICAL to an uninterrupted one."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.loader import DeviceLoader, make_loaders
from distributed_deep_learning_tpu.data.splits import train_val_test_split
from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.train.elastic import (fit_with_recovery,
                                                         resume_point)
from distributed_deep_learning_tpu.train.loop import fit
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                      place_state)
from distributed_deep_learning_tpu.utils.checkpoint import Checkpointer

SPE = 11  # 1024 rows -> 716 train -> 11 steps of 64


def _setup(mesh):
    ds = synthetic_mqtt(1024, seed=33)
    splits = train_val_test_split(len(ds), seed=42)
    loaders = make_loaders(ds, splits, 64, mesh)
    assert len(loaders[0]) == SPE
    model = MLP(hidden_size=16)

    def make_state():
        state = create_train_state(model, jax.random.key(7),
                                   jnp.zeros((1, 48)), optax.sgd(0.05))
        return place_state(state, mesh)

    return make_state, make_step_fns(mesh, cross_entropy_loss), loaders


def test_mid_epoch_resume_bit_identical(tmp_path, mesh8):
    """Kill at epoch-2 step 4 (after the step-3 checkpoint), resume from the
    sidecar: final params are EXACTLY the uninterrupted run's, and the
    resumed epoch's logged totals match (partial totals restored)."""
    make_state, (train_step, eval_step), loaders = _setup(mesh8)

    ref_state, ref_hist = fit(make_state(), train_step, eval_step, *loaders,
                              epochs=2)

    calls = {"n": 0}

    def flaky_step(state, x, y):
        calls["n"] += 1
        if calls["n"] == SPE + 4:  # epoch 2, batch 4
            raise RuntimeError("simulated preemption")
        return train_step(state, x, y)

    with Checkpointer(tmp_path / "ck") as ckpt:
        with pytest.raises(RuntimeError, match="preemption"):
            fit(make_state(), flaky_step, eval_step, *loaders, epochs=2,
                checkpointer=ckpt, checkpoint_every=3)
        ckpt_step, start_epoch, resume_batch, resume_totals = \
            resume_point(ckpt)
        assert (start_epoch, resume_batch) == (2, 3)  # last step boundary
        assert ckpt_step == SPE + 3  # global-step id
        state = ckpt.restore(make_state(), step=ckpt_step)
        state, hist = fit(state, train_step, eval_step, *loaders, epochs=2,
                          checkpointer=ckpt, checkpoint_every=3,
                          start_epoch=start_epoch, resume_batch=resume_batch,
                          resume_totals=resume_totals)

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), ref_state.params, state.params)
    # resumed epoch-2 train totals == uninterrupted (partials restored)
    ref2 = next(h for h in ref_hist if h.phase == "train" and h.epoch == 2)
    got2 = next(h for h in hist if h.phase == "train" and h.epoch == 2)
    assert got2.examples == ref2.examples
    assert got2.accuracy == pytest.approx(ref2.accuracy, abs=1e-9)
    assert got2.loss == pytest.approx(ref2.loss, rel=1e-6)


def test_fit_with_recovery_resumes_at_step_not_epoch(tmp_path, mesh8):
    """The elastic loop recovers from the last STEP boundary: total
    executed train steps == uninterrupted count (an epoch-level redo would
    re-run the epoch's earlier steps)."""
    make_state, (train_step, eval_step), loaders = _setup(mesh8)

    ref_state, _ = fit(make_state(), train_step, eval_step, *loaders,
                       epochs=2)

    calls = {"n": 0, "armed": True}

    def flaky_step(state, x, y):
        calls["n"] += 1
        if calls["armed"] and calls["n"] == SPE + 4:
            calls["armed"] = False
            raise RuntimeError("simulated preemption")
        return train_step(state, x, y)

    with Checkpointer(tmp_path / "ck") as ckpt:
        state, hist = fit_with_recovery(
            make_state, flaky_step, eval_step, loaders, epochs=2,
            checkpointer=ckpt, checkpoint_every=3)

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), ref_state.params, state.params)
    # attempt 1: 14 trained + 1 raising call; attempt 2 resumes at batch 4:
    # 8 more.  Epoch-level redo would re-run epoch 2's batches 1-3 too.
    assert calls["n"] == SPE + 4 + (SPE - 3)


def test_legacy_epoch_checkpoints_still_resume(tmp_path, mesh8):
    """Sidecar-less run dirs (pre-round-5) keep the step==epoch
    convention."""
    make_state, (train_step, eval_step), loaders = _setup(mesh8)
    with Checkpointer(tmp_path / "ck") as ckpt:
        state = make_state()
        ckpt.save(1, state, wait=True)  # legacy: no extra sidecar
        assert resume_point(ckpt)[:3] == (1, 2, 0)


def test_loader_iter_batches_skip_matches_tail(mesh8):
    """iter_batches(skip) yields exactly the epoch's batches [skip:] —
    the replayed order a mid-epoch resume depends on."""
    ds = synthetic_mqtt(512, seed=9)
    loader = DeviceLoader(ds, np.arange(448), 64, mesh8, shuffle=True)
    loader.set_epoch(3)
    full = [(np.asarray(x), np.asarray(y)) for x, y in loader]
    tail = [(np.asarray(x), np.asarray(y))
            for x, y in loader.iter_batches(skip=4)]
    assert len(tail) == len(full) - 4
    for (fx, fy), (tx, ty) in zip(full[4:], tail):
        np.testing.assert_array_equal(fx, tx)
        np.testing.assert_array_equal(fy, ty)


def test_id_scheme_mismatch_rejected(tmp_path, mesh8):
    """Resuming a gstep-id run dir without --checkpoint-every (or vice
    versa) must be a clear error, not an infinite repeat of stale work
    (review finding: latest_step would never advance)."""
    make_state, (train_step, eval_step), loaders = _setup(mesh8)
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(SPE * 2, make_state(), wait=True,
                  extra={"epoch": 2, "batch": SPE, "epoch_complete": True})
        # same dir, cadence dropped: epoch ids (1, 2, ...) < existing 22
        with pytest.raises(ValueError, match="never advance"):
            fit(make_state(), train_step, eval_step, *loaders, epochs=3,
                checkpointer=ckpt, start_epoch=3)
        # a resume point past this run's epochs trains nothing further but
        # completes gracefully (dir trained longer than the rerun asks)
        state, hist = fit(make_state(), train_step, eval_step, *loaders,
                          epochs=2, checkpointer=ckpt,
                          start_epoch=SPE * 2 + 1)
        assert [h.phase for h in hist] == ["test"]


def test_save_skips_already_finalised_step(tmp_path, mesh8):
    """An elastic retry replaying a boundary it already persisted is a
    no-op, not an orbax StepAlreadyExistsError — and force=True really
    overwrites."""
    import jax.numpy as jnp

    make_state, _, _ = _setup(mesh8)
    with Checkpointer(tmp_path / "ck") as ckpt:
        s0 = make_state()
        assert ckpt.save(3, s0, wait=True, extra={"epoch": 1})
        assert ckpt.save(3, s0, wait=True, extra={"epoch": 1}) is False
        bumped = s0.replace(params=jax.tree.map(lambda a: a + 1.0, s0.params))
        assert ckpt.save(3, bumped, wait=True, force=True)
        # force without extra removed the stale sidecar too (review
        # finding: old resume metadata must not describe the new state)
        assert ckpt.read_extra(3) is None
        back = ckpt.restore(make_state(), step=3)
        leaf = jax.tree_util.tree_leaves(back.params)[0]
        ref = jax.tree_util.tree_leaves(bumped.params)[0]
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(ref))


def test_dirty_dir_without_resume_rejected(tmp_path, mesh8):
    """A fresh (non-resume, non-elastic) run over a dir holding another
    run's checkpoints must refuse, not silently skip its own saves in
    favour of the old steps (review finding)."""
    from distributed_deep_learning_tpu.utils.config import Config
    from distributed_deep_learning_tpu.workloads.base import (
        _maybe_checkpointer)

    make_state, _, _ = _setup(mesh8)
    d = str(tmp_path / "ck")
    with Checkpointer(d) as ckpt:
        ckpt.save(1, make_state(), wait=True)
    with pytest.raises(ValueError, match="already holds"):
        _maybe_checkpointer(Config(checkpoint_dir=d))
    # --resume and --elastic both legitimately reuse the dir
    ck2, step, *_ = _maybe_checkpointer(Config(checkpoint_dir=d,
                                               resume=True))
    ck2.close()
    assert step == 1
    ck3, *_ = _maybe_checkpointer(Config(checkpoint_dir=d, elastic=True))
    ck3.close()


def test_sidecar_gc_follows_orbax_pruning(tmp_path, mesh8):
    """extra-*.json sidecars of pruned checkpoints are collected; the
    newest (possibly in-flight) step keeps its sidecar."""
    import glob
    import os

    make_state, _, _ = _setup(mesh8)
    state = make_state()
    with Checkpointer(tmp_path / "ck", keep=2) as ckpt:
        for s in (1, 2, 3, 4, 5):
            ckpt.save(s, state, wait=True, extra={"epoch": s})
        steps = set(int(os.path.basename(p)[len("extra-"):-len(".json")])
                    for p in glob.glob(str(tmp_path / "ck" / "extra-*.json")))
    assert 5 in steps            # newest always kept
    assert steps <= {3, 4, 5}    # pruned steps' sidecars are gone


def test_step_checkpoint_elastic_under_pipeline(tmp_path, monkeypatch):
    """--checkpoint-every composes with the SPMD pipeline mode: the chaos
    hook kills epoch 2 mid-flight (gstep 8 = batch 3 of 5), recovery
    resumes from the step-7 boundary and the run completes through
    run_workload."""
    from distributed_deep_learning_tpu.utils import failures
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec
    from distributed_deep_learning_tpu.workloads.base import run_workload

    monkeypatch.setenv("DDL_DATA_LIMIT", "128")  # 89 train -> 5 steps of 16
    monkeypatch.setenv("DDL_INJECT_STEP_FAILURE", "0:8")
    failures._step_injected = False
    try:
        config = parse_args(
            ["-m", "pipeline", "-e", "2", "-b", "16", "-l", "4", "-s", "32",
             "--nstages", "4", "--elastic",
             "--checkpoint-dir", str(tmp_path / "ck"),
             "--checkpoint-every", "2"], workload="bert")
        _, history = run_workload(get_spec("bert"), config)
    finally:
        failures._step_injected = False
    phases = [h.phase for h in history]
    assert phases.count("train") == 2 and "test" in phases
    assert np.isfinite(history[-1].loss)


def test_step_failure_injection_validation(monkeypatch):
    from distributed_deep_learning_tpu.utils import failures

    for bad in ("5", "all:x", "1:2:3"):
        monkeypatch.setenv("DDL_INJECT_STEP_FAILURE", bad)
        with pytest.raises(ValueError, match="DDL_INJECT_STEP_FAILURE"):
            failures.maybe_inject_step_failure(1)

    monkeypatch.setenv("DDL_INJECT_STEP_FAILURE", "0:3")
    failures.maybe_inject_step_failure(2)  # wrong step: no-op
    with pytest.raises(RuntimeError, match="at step 3"):
        failures.maybe_inject_step_failure(3)
    failures.maybe_inject_step_failure(3)  # fires at most once per process
    failures._step_injected = False        # reset for other tests
