"""Ring attention: exact attention over sequences sharded across devices.

Context parallelism for long sequences — the capability the reference lacks
entirely (its only sequence model consumes 10-step windows,
``LSTM/dataset.py:25``; SURVEY.md §2.5 lists SP/CP as absent) but which a
TPU framework must treat as first-class: sequence length is the axis that
outgrows a single chip's HBM first.

Mechanism (Ring Attention with blockwise softmax): queries stay put, K/V
blocks rotate around the ``seq`` mesh axis with ``lax.ppermute`` over ICI;
each hop every device contracts its local queries against the visiting K/V
block and folds the result into an online-softmax accumulator
(running max ``m``, denominator ``l``, numerator ``acc`` — the
flash-attention recurrence), so the full (T×T) score matrix never
materialises and per-device memory is O(T/S · T/S) per hop.  After S hops
every query has seen every key exactly once and the result equals full
attention bit-for-near-bit.

Communication and compute overlap naturally: the ppermute for hop r+1 is
independent of hop r's contraction, so XLA can pipeline them over ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_deep_learning_tpu.runtime.shmap import shard_map

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() well-defined


def _block_attention(q, k, v, m, l, acc, q_start, k_start, causal,
                     window=None, key_valid=None):
    """Fold one visiting K/V block into the online-softmax accumulator.

    Shapes: q (B,H,Tq,D); k,v (B,H,Tk,D); m,l (B,H,Tq); acc (B,H,Tq,D);
    ``key_valid`` (B,Tk) bools for the VISITING key block (padding mask).
    ``q_start``/``k_start`` are the blocks' global sequence offsets (for the
    causal / sliding-window mask across blocks).
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1] * 1.0)
    mask = None
    if causal:
        q_pos = q_start + jnp.arange(q.shape[2])
        k_pos = k_start + jnp.arange(k.shape[2])
        mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        if window is not None:
            mask = jnp.logical_and(
                mask, (q_pos[:, None] - k_pos[None, :] < window)[None, None])
    if key_valid is not None:
        kvm = key_valid[:, None, None, :]  # (B,1,1,Tk)
        mask = kvm if mask is None else jnp.logical_and(mask, kvm)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])
    if key_valid is not None:
        # explicit zeroing: for a query row whose every key so far is
        # invalid, new_m == NEG_INF and exp(scores - new_m) == exp(0) == 1
        # — the exp trick alone would count masked keys.  Only key_valid
        # can produce such rows (hop 0's diagonal block makes new_m finite
        # on the pure-causal path, where exp already underflows to 0.0),
        # so the causal fast path skips this multiply.
        p = p * mask
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return new_m, new_l, new_acc


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, axis: str = "seq", causal: bool = False,
                   window: int | None = None,
                   key_valid: jnp.ndarray | None = None,
                   batch_axes: tuple[str, ...] = ("data", "fsdp")
                   ) -> jnp.ndarray:
    """Exact multi-head attention with the sequence sharded over ``axis``.

    Args:
      q, k, v: global ``(B, T, H, D)`` arrays (sharded or not — the
        shard_map partitions them: T over `axis`, B over `batch_axes`).
      mesh: mesh containing `axis`; composes with data parallelism.
      causal: standard autoregressive mask, applied across blocks via
        global positions.
      window: optional causal sliding-window size (each query attends to
        its last ``window`` global positions).  Masked via the same
        global-position arithmetic as the causal mask; the hop-0 diagonal
        block guarantees every query row folds at least its own position
        first, so later fully-masked blocks contribute exp(-inf)=0.
      key_valid: optional ``(B, T)`` boolean padding mask (True = key may
        be attended), sharded over ``axis`` like K.  Each device's
        validity block RIDES THE RING with its K/V block (one extra
        ppermute of B·T/S bools per hop) so every hop masks the visiting
        keys exactly as the dense path would.  A query row with no valid
        key anywhere (a pad query under causal+padding) returns zeros —
        finite, so downstream layers and grads stay NaN-free; the loss
        masks such rows anyway.

    Returns ``(B, T, H, D)`` attention output, sharded like ``q``.
    """
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    S = mesh.shape[axis]
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if T % S or Tk % S:
        raise ValueError(f"sequence lengths q={T}, k={Tk} must divide "
                         f"{axis}={S}")
    has_kv = key_valid is not None
    if has_kv and key_valid.shape != (B, Tk):
        raise ValueError(f"key_valid shape {key_valid.shape} != ({B}, {Tk})")

    spec = P(batch_axes, axis, None, None)
    kv_spec = P(batch_axes, axis)
    in_specs = (spec, spec, spec) + ((kv_spec,) if has_kv else ())

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=spec, check_vma=False)
    def run(q, k, v, *maybe_kv):
        # local blocks: (B', Tl, H, D) → (B', H, Tl, D)
        q_ = jnp.swapaxes(q, 1, 2)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
        Tl = q_.shape[2]
        my = lax.axis_index(axis)
        q_start = my * Tl

        m0 = jnp.full(q_.shape[:3], NEG_INF, q_.dtype)
        l0 = jnp.zeros(q_.shape[:3], q_.dtype)
        acc0 = jnp.zeros_like(q_)
        perm = [(i, (i + 1) % S) for i in range(S)]
        kv0 = maybe_kv[0] if has_kv else jnp.zeros((), q_.dtype)  # carry stub

        Tkl = k_.shape[2]  # cross-attention: K's block length, not Q's

        def hop(carry, r):
            k_blk, v_blk, kv_blk, m, l, acc = carry
            # the block visiting at hop r originated on device (my - r) mod S
            k_start = ((my - r) % S) * Tkl
            m, l, acc = _block_attention(
                q_, k_blk, v_blk, m, l, acc, q_start, k_start, causal,
                window, key_valid=kv_blk if has_kv else None)
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            if has_kv:
                kv_blk = lax.ppermute(kv_blk, axis, perm)
            return (k_blk, v_blk, kv_blk, m, l, acc), None

        (_, _, _, m, l, acc), _ = lax.scan(
            hop, (k_, v_, kv0, m0, l0, acc0), jnp.arange(S))
        if has_kv:
            # guarded division: all-keys-invalid rows have l == 0 → 0 out
            out = jnp.where(l[..., None] > 0,
                            acc / jnp.maximum(l, 1e-30)[..., None], 0.0)
        else:
            out = acc / l[..., None]
        return jnp.swapaxes(out, 1, 2)

    return run(q, k, v, *((key_valid,) if has_kv else ()))


def make_attention_fn(mesh: Mesh, axis: str = "seq", causal: bool = False,
                      batch_axes: tuple[str, ...] = ("data", "fsdp")):
    """Adapter: ring attention as a ``MultiHeadAttention.attention_fn``.

    The causal mask is computed internally from global block positions (the
    (T×T) mask tensor the dense path builds would defeat the whole point),
    so pass ``causal=True`` HERE and leave the layer's ``causal=False``.
    ``key_valid`` padding masks are supported (they ride the ring, VERDICT
    r4 item 4); arbitrary pre-built dense ``mask`` tensors are not — a
    global (T×T) mask is exactly what sequence sharding avoids.
    """

    forced_causal = causal

    def attn(q, k, v, *, mask=None, key_valid=None, causal=False,
             window=None, dtype=jnp.float32):
        if mask is not None:
            raise NotImplementedError(
                "ring attention computes masks internally from global "
                "positions (causal=...) and per-key validity "
                "(key_valid=...); arbitrary dense mask tensors are "
                "unsupported — a global (T, T) mask defeats sequence "
                "sharding")
        out = ring_attention(q, k, v, mesh=mesh, axis=axis,
                             causal=causal or forced_causal, window=window,
                             key_valid=key_valid, batch_axes=batch_axes)
        return out.astype(dtype)

    return attn


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   causal: bool = False) -> jnp.ndarray:
    """Single-device reference: softmax(qkᵀ/√d)v on ``(B, T, H, D)``."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1] * 1.0)
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
