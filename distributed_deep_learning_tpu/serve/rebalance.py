"""Live slot evacuation: move a mid-request decode slot between replicas.

PR 15 made the fleet *crash-tolerant* — a dead replica's requests
replay from the fleet :class:`..serve.supervisor.RequestLedger` onto
survivors, recomputing the committed prefix from scratch.  This module
makes it *proactive*: a hot or degrading replica hands its decoding
slots to a healthy one BEFORE it crashes, and the handoff moves the
committed KV blocks instead of recomputing them.

The mechanism composes three landed primitives:

* the **ledger** knows every open request's committed-token tail, so
  ``prompt + committed`` is the exact token stream whose KV the source
  replica holds;
* the source's **prefix index** (fed per tick by
  :meth:`..serve.paged.BlockManager.register_committed`) maps that
  stream to the physical blocks, and the destination's
  :meth:`..serve.paged.BlockManager.adopt_prefix` registers the same
  chain locally with fresh blocks;
* the **migrator** (:class:`..serve.migrate.BlockMigrator`) moves the
  payload digest-verified and at-rest bit-exact (fp32, bf16 and
  int8+scales pools all round-trip exactly).

Failure is first-class: a corrupted payload (the ``evac_drop`` chaos
kind) trips the end-to-end digest BEFORE anything scatters, and
:func:`evacuate_slot` rolls the destination back with
:meth:`..serve.paged.BlockManager.unadopt` — the source keeps its
blocks, the request stays open in the ledger, and the normal replay
path recovers bit-identically.  Zero loss either way, by construction.

:class:`EvacuationSignal` is the control-plane half: the router's tick
observer raises it on a healthy→degraded transition (or a hot-spot
detection — :class:`HotspotDetector`), the replica's supervisor treats
it as FATAL (escalates without containing, exactly like
:class:`..serve.fleet.ReplicaCrash`), and the router drains the
replica's open slots onto its peers.
"""

from __future__ import annotations

import numpy as np

from distributed_deep_learning_tpu.serve.migrate import (BlockMigrator,
                                                         MigrationError)


class EvacuationSignal(RuntimeError):
    """Raised from the router's per-tick observer to pull a replica out
    of its serving loop for a proactive drain.  Fleet supervisors run
    with this in their ``fatal`` tuple, so it escalates to the router —
    which, unlike a crash, migrates the replica's committed KV to its
    peers instead of discarding it.

    ``rid``/``reason`` identify the replica and the trigger
    (``"degraded"`` or ``"hotspot"``)."""

    def __init__(self, rid: int, reason: str):
        super().__init__(f"evacuating replica {rid}: {reason}")
        self.rid = int(rid)
        self.reason = str(reason)


def evacuate_slot(src_engine, dst_engine, stream,
                  migrator: BlockMigrator, *, device=None, chaos=None,
                  sync: bool = False) -> dict:
    """Move the committed full-block KV prefix of ``stream`` (prompt +
    committed tokens, from the ledger) from ``src_engine``'s pools into
    ``dst_engine``'s, digest-verified, rolling back on failure.

    Returns a record dict: ``ok`` (the destination now holds every
    block it adopted), ``blocks``/``tokens`` moved, ``rolled_back``
    (a :class:`..serve.migrate.MigrationError` tripped and the adopted
    blocks were released), and ``error``.  ``ok`` with ``blocks == 0``
    means there was nothing to move (no committed full blocks on the
    source, or the destination already held the chain) — the request
    simply replays with a cold cache; correctness never depends on the
    move landing."""
    stream = np.asarray(stream)
    bs = int(src_engine.block_size)
    sp = src_engine.manager.match_prefix(stream)
    if not sp.full_blocks:
        return {"ok": True, "blocks": 0, "tokens": 0,
                "rolled_back": False, "error": None}
    adopted = dst_engine.manager.adopt_prefix(stream, len(sp.full_blocks))
    if adopted is None:
        return {"ok": False, "blocks": 0, "tokens": 0,
                "rolled_back": False,
                "error": "destination cannot adopt the chain "
                         "(pool full or hash collision)"}
    start, dst_ids = adopted
    if not dst_ids:
        # destination already holds the whole chain — nothing to carry
        return {"ok": True, "blocks": 0, "tokens": start * bs,
                "rolled_back": False, "error": None}
    src_ids = list(sp.full_blocks[start:start + len(dst_ids)])
    try:
        for i in range(0, len(dst_ids), migrator.width):
            dst_engine.pools = migrator.migrate(
                src_engine.pools, dst_engine.pools,
                src_ids[i:i + migrator.width],
                dst_ids[i:i + migrator.width],
                device=device, verify=True, chaos=chaos, sync=sync,
                trace_id="evacuate")
    except MigrationError as exc:
        # nothing from the failed chunk was scattered; chunks that DID
        # land sit in blocks we are about to free — unreachable once
        # the index entries go, so the destination is clean either way
        dst_engine.manager.unadopt(dst_ids)
        return {"ok": False, "blocks": 0, "tokens": 0,
                "rolled_back": True, "error": str(exc)}
    return {"ok": True, "blocks": len(dst_ids),
            "tokens": (start + len(dst_ids)) * bs,
            "rolled_back": False, "error": None}


class HotspotDetector:
    """Per-replica ITL-skew detector over the router's live tick feed.

    Each replica's decode-tick wall times land in a bounded trailing
    sample (:meth:`observe`); a replica is HOT when its p99 exceeds
    ``ratio`` × the fleet-wide median of per-replica p50s for
    ``patience`` consecutive observations — the queue-depth/ITL-p99
    skew signal the ROADMAP names, computed without wall-clock
    dependence so drills stay deterministic.  A single replica has no
    fleet to skew against and is never hot."""

    def __init__(self, *, ratio: float = 3.0, patience: int = 3,
                 min_ticks: int = 4, window: int = 64):
        if ratio <= 1.0:
            raise ValueError(f"hotspot ratio must be > 1, got {ratio}")
        if patience < 1:
            raise ValueError(f"hotspot patience must be >= 1, got "
                             f"{patience}")
        self.ratio = float(ratio)
        self.patience = int(patience)
        self.min_ticks = int(min_ticks)
        self.window = int(window)
        self._samples: dict[int, list] = {}
        self._streak: dict[int, int] = {}
        self.detections: list[tuple[int, float]] = []

    def observe(self, rid: int, elapsed_s: float) -> bool:
        """Feed one decode tick; True when ``rid`` crosses into hot."""
        s = self._samples.setdefault(int(rid), [])
        s.append(float(elapsed_s))
        del s[:-self.window]
        if len(s) < self.min_ticks:
            return False
        others = [np.percentile(v, 50)
                  for r, v in self._samples.items()
                  if r != rid and len(v) >= self.min_ticks]
        if not others:
            return False
        floor = float(np.median(others))
        p99 = float(np.percentile(s, 99))
        if p99 > self.ratio * max(floor, 1e-9):
            self._streak[rid] = self._streak.get(rid, 0) + 1
        else:
            self._streak[rid] = 0
        if self._streak[rid] >= self.patience:
            self._streak[rid] = 0
            self.detections.append((int(rid), p99))
            return True
        return False
