"""The examples/ scripts must stay runnable — they are the documented
on-ramp (each asserts its own learning/parity condition internally)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py") and not f.startswith("_"))


def test_examples_inventory_complete():
    assert len(EXAMPLES) >= 3


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_green(script):
    # examples force the emulated-CPU mesh themselves (no --tpu here);
    # a fresh env keeps the suite's XLA_FLAGS from leaking in
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
