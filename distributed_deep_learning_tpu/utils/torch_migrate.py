"""Import trained PyTorch weights from the reference's model families.

The reference (`/root/reference/src/pytorch/{MLP,CNN,LSTM}/model.py`) is
torch; a user switching to this framework brings `state_dict()` files.
These importers convert them into this package's Flax variables with
exact forward-pass parity (tested against torch twins in
`tests/test_torch_migrate.py`):

* layout: torch `Linear` stores `(out, in)` -> Flax kernel `(in, out)`;
  `Conv1d` `(O, I, K)` -> `(K, I, O)`; `Conv2d` `(O, I, H, W)` ->
  NHWC-native `(H, W, I, O)`.
* BatchNorm: `weight/bias` -> `scale/bias` params; `running_mean/var` ->
  the `batch_stats` collection (`num_batches_tracked` is dropped); the
  torch-vs-flax momentum-complement is a MODEL concern, already handled
  at `models/densenet.py:44` — stats import unchanged.
* LSTM: torch packs the four gates row-wise as (i, f, g, o) in
  `weight_ih_l{k}`/`weight_hh_l{k}`; Flax `OptimizedLSTMCell` keeps
  per-gate kernels (`ii/if/ig/io`, `hi/hf/hg/ho`) and a SINGLE bias per
  gate on the hidden branch — torch's two biases sum into it.

Matching is POSITIONAL BY TYPE: `state_dict()` preserves registration
order, which for the reference models (plain sequential construction) is
forward order — so importers consume typed parameter groups in order
instead of depending on the reference's attribute names.  Every import
is validated leaf-by-leaf (structure + shapes) against `model.init`.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

__all__ = ["mlp_params_from_torch", "cnn_lstm_params_from_torch",
           "densenet_params_from_torch", "causal_lm_params_from_hf_gpt2"]


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor, without importing torch
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _typed_groups(state_dict) -> list[tuple[str, dict]]:
    """Insertion-ordered (kind, tensors) groups from a torch state_dict.

    Kinds: ``linear`` (2-D weight [+bias]), ``conv1d``/``conv2d``,
    ``bn`` (weight/bias/running_mean/running_var), ``lstm`` (one group
    PER stacked layer: weight_ih/weight_hh/bias_ih/bias_hh).

    ALIASED registrations are dropped: a module registered under two
    names (the reference's ``WrapperTriton`` does ``self.layer = ...``
    then ``add_module('DenseLayer', self.layer)``, `CNN/model.py:72`)
    appears twice in ``state_dict()`` with tensors sharing storage —
    torch serialisation preserves the sharing, so the duplicate group's
    data pointers match the first occurrence and it is skipped.

    A numpy/safetensors ROUND-TRIP loses that storage sharing (every
    entry materialises as its own array), so when no tensor in the
    state_dict carries a ``data_ptr`` the detector falls back to VALUE
    equality: a prefix group whose full leaf set (names, shapes, dtypes,
    bytes) exactly duplicates an earlier group's is treated as the same
    aliased registration.  The fallback never engages for torch-saved
    checkpoints (pointers stay authoritative there), and an exact
    whole-group duplicate among TRAINED weights is, in practice, only
    ever the double registration.
    """
    def _ptr(val):
        if hasattr(val, "data_ptr"):      # torch tensor (incl. loaded)
            return val.data_ptr()
        return None                       # numpy/safetensors round-trip

    # single pass: prefix -> leaves and pointer sets (insertion-ordered)
    raw: dict[str, dict] = {}
    ptrs: dict[str, set] = {}
    for key, val in state_dict.items():
        prefix, _, leaf = key.rpartition(".")
        raw.setdefault(prefix, {})[leaf] = val
        ptrs.setdefault(prefix, set()).add(_ptr(val))

    have_ptrs = all(None not in s for s in ptrs.values())

    def _fingerprint(leaves: dict) -> tuple:
        import hashlib

        out = []
        for name in sorted(leaves):
            arr = np.ascontiguousarray(leaves[name])
            out.append((name, arr.shape, str(arr.dtype),
                        hashlib.sha256(arr.tobytes()).hexdigest()))
        return tuple(out)

    order: list[str] = []
    by_prefix: dict[str, dict] = {}
    seen_ptrs: set = set()
    seen_values: set = set()
    for prefix, leaves in raw.items():
        if have_ptrs and ptrs[prefix] <= seen_ptrs:
            continue  # every tensor aliases an earlier registration
        seen_ptrs |= ptrs[prefix]
        group = {k: _to_np(v) for k, v in leaves.items()}
        if not have_ptrs:
            fp = _fingerprint(group)
            if fp in seen_values:
                continue  # exact whole-group duplicate: aliased
            seen_values.add(fp)
        by_prefix[prefix] = group
        order.append(prefix)

    groups: list[tuple[str, dict]] = []
    for prefix in order:
        g = by_prefix[prefix]
        if "running_mean" in g:
            groups.append(("bn", g))
        elif "weight_ih_l0" in g:
            consumed = set()
            layer = 0
            while f"weight_ih_l{layer}" in g:
                names = [f"{n}_l{layer}" for n in
                         ("weight_ih", "weight_hh", "bias_ih", "bias_hh")]
                groups.append(("lstm", dict(zip(
                    ("weight_ih", "weight_hh", "bias_ih", "bias_hh"),
                    (g[n] for n in names)))))
                consumed.update(names)
                layer += 1
            extra = set(g) - consumed
            if extra:  # _reverse (bidirectional) / _hr (proj_size) leaves
                raise ValueError(
                    f"LSTM group has unsupported leaves {sorted(extra)} "
                    "(bidirectional/proj_size checkpoints have no "
                    "equivalent in this package's LSTM)")
        elif g.get("weight") is not None and g["weight"].ndim == 2:
            groups.append(("linear", g))
        elif g.get("weight") is not None and g["weight"].ndim == 3:
            groups.append(("conv1d", g))
        elif g.get("weight") is not None and g["weight"].ndim == 4:
            groups.append(("conv2d", g))
        # anything else (e.g. a bare num_batches_tracked prefix) is ignored
    return groups


class _Consumer:
    """Pop typed groups in order, failing loudly on a kind mismatch."""

    def __init__(self, state_dict):
        self._groups = _typed_groups(state_dict)
        self._pos = 0

    def take(self, kind: str) -> dict:
        if self._pos >= len(self._groups):
            raise ValueError(f"state_dict exhausted wanting a {kind!r} "
                             f"group at position {self._pos}")
        got, tensors = self._groups[self._pos]
        if got != kind:
            raise ValueError(f"state_dict group {self._pos} is {got!r}, "
                             f"expected {kind!r} — is this checkpoint from "
                             "the matching reference model family?")
        self._pos += 1
        return tensors

    def finish(self) -> None:
        if self._pos != len(self._groups):
            raise ValueError(f"{len(self._groups) - self._pos} unconsumed "
                             "parameter groups — model config (layers/"
                             "blocks) smaller than the checkpoint's")


def _linear(g: dict) -> dict:
    out = {"kernel": g["weight"].T}
    if "bias" in g:
        out["bias"] = g["bias"]
    return out


def _conv2d(g: dict) -> dict:
    out = {"kernel": g["weight"].transpose(2, 3, 1, 0)}  # OIHW -> HWIO
    if "bias" in g:
        out["bias"] = g["bias"]
    return out


def _bn(g: dict) -> tuple[dict, dict]:
    return ({"scale": g["weight"], "bias": g["bias"]},
            {"mean": g["running_mean"], "var": g["running_var"]})


def _validated(model, example, variables: dict) -> dict:
    """Leaf-by-leaf structure+shape check against ``model.init``; returns
    the imported tree with each leaf cast to the init leaf's dtype."""
    ref = model.init(jax.random.key(0), example)
    ref_flat = jax.tree_util.tree_flatten_with_path(ref)
    got_flat = jax.tree_util.tree_flatten_with_path(variables)
    if ref_flat[1] != got_flat[1]:
        ref_paths = {jax.tree_util.keystr(p) for p, _ in ref_flat[0]}
        got_paths = {jax.tree_util.keystr(p) for p, _ in got_flat[0]}
        raise ValueError(
            "imported tree structure mismatch; "
            f"missing={sorted(ref_paths - got_paths)} "
            f"extra={sorted(got_paths - ref_paths)}")
    leaves = []
    for (path, r), (_, g) in zip(ref_flat[0], got_flat[0]):
        if tuple(r.shape) != tuple(np.shape(g)):
            raise ValueError(f"shape mismatch at {jax.tree_util.keystr(path)}"
                             f": checkpoint {np.shape(g)} vs model {r.shape}")
        leaves.append(np.asarray(g, dtype=r.dtype))
    return jax.tree_util.tree_unflatten(ref_flat[1], leaves)


# --------------------------------------------------------------------------
# family importers
# --------------------------------------------------------------------------

def mlp_params_from_torch(state_dict, model, example) -> dict:
    """Reference MLP (`MLP/model.py:23-76`): Linear stack -> `models.mlp.MLP`
    variables (`{"params": ...}`)."""
    c = _Consumer(state_dict)
    params: dict[str, Any] = {}
    for i in range(model.num_hidden_layers + 1):
        params[f"DenseReLU_{i}"] = {"Dense_0": _linear(c.take("linear"))}
    params["DenseHead_0"] = {"Dense_0": _linear(c.take("linear"))}
    c.finish()
    return _validated(model, example, {"params": params})


def cnn_lstm_params_from_torch(state_dict, model, example) -> dict:
    """Reference CNN-LSTM (`LSTM/model.py:38-96`): Conv1d stem + stacked
    LSTM + head -> `models.cnn_lstm.CNNLSTM` variables."""
    c = _Consumer(state_dict)
    conv = c.take("conv1d")
    params: dict[str, Any] = {"PdMConvStem_0": {"Conv_0": {
        # torch Conv1d (O, I, K) -> flax (K, I, O)
        "kernel": conv["weight"].transpose(2, 1, 0),
        **({"bias": conv["bias"]} if "bias" in conv else {}),
    }}}
    for i in range(model.hidden_layers):
        g = c.take("lstm")
        hidden = g["weight_hh"].shape[1]
        cell: dict[str, Any] = {}
        for j, gate in enumerate(("i", "f", "g", "o")):
            rows = slice(j * hidden, (j + 1) * hidden)
            cell[f"i{gate}"] = {"kernel": g["weight_ih"][rows].T}
            cell[f"h{gate}"] = {"kernel": g["weight_hh"][rows].T,
                                # flax keeps ONE bias per gate (hidden
                                # branch); torch's pair sums into it
                                "bias": g["bias_ih"][rows] +
                                        g["bias_hh"][rows]}
        params[f"LSTMLayer_{i}"] = {"OptimizedLSTMCell_0": cell}
    params["RegressionHead_0"] = {"Dense_0": _linear(c.take("linear"))}
    c.finish()
    return _validated(model, example, {"params": params})


def causal_lm_params_from_hf_gpt2(state_dict, model, example) -> dict:
    """HuggingFace GPT-2 weights -> `models.transformer.CausalLM`.

    Beyond-reference interop: the architectures align exactly (pre-LN
    blocks, tanh-approximate gelu, learned positions, weight-tied head),
    so pretrained GPT-2 checkpoints load into the TPU-native LM.  Build
    the target as ``CausalLM(vocab_size=50257, num_layers=12,
    d_model=768, num_heads=12, mlp_dim=3072, max_len=1024,
    ln_eps=1e-5, pad_id=None)`` for gpt2-small — ``ln_eps=1e-5``
    matches HF's LayerNorm epsilon and ``pad_id=None`` disables this
    package's id-0-is-padding convention (GPT-2's id 0 is the real
    token ``"!"``), making the import numerically exact (tested to
    2e-5 logits parity, including id-0 tokens).  Mapping is NAME-based (HF's key names are a stable
    public contract, unlike the reference's): ``wte/wpe`` -> the embed
    table/positions, packed ``c_attn`` (d, 3d) splits into per-head
    q/k/v DenseGeneral kernels (HF's head split is H-major like Flax's,
    and Conv1D already stores (in, out) — no transposes anywhere),
    ``c_proj`` reshapes to the (H, Dh, d) out kernel, ``ln_1/ln_2/ln_f``
    -> the pre-LNs and final norm.  ``lm_head.weight`` (tied) and the
    causal-mask buffers are ignored; any other leftover key is an error.
    """
    sd = {}
    for key, val in state_dict.items():
        key = key.removeprefix("transformer.")
        if key == "lm_head.weight" or key.endswith(
                (".attn.bias", ".attn.masked_bias")):
            continue  # tied duplicate / causal-mask buffers
        sd[key] = _to_np(val)

    d, H = model.d_model, model.num_heads
    dh = d // H
    used = set()

    def take(key: str) -> np.ndarray:
        if key not in sd:
            raise ValueError(f"GPT-2 key {key!r} missing from the "
                             "checkpoint — model config (num_layers?) "
                             "larger than the checkpoint's")
        used.add(key)
        return sd[key]

    def ln(prefix: str) -> dict:
        return {"scale": take(f"{prefix}.weight"),
                "bias": take(f"{prefix}.bias")}

    params: dict[str, Any] = {
        "embed": {"tok": {"embedding": take("wte.weight")},
                  "pos": take("wpe.weight")},
        "final_norm": ln("ln_f"),
    }
    for i in range(model.num_layers):
        pre = f"h.{i}"
        qw, kw, vw = np.split(take(f"{pre}.attn.c_attn.weight"), 3, axis=1)
        qb, kb, vb = np.split(take(f"{pre}.attn.c_attn.bias"), 3)
        params[f"layer_{i}"] = {
            "LayerNorm_0": ln(f"{pre}.ln_1"),
            "self_attn": {
                "q": {"kernel": qw.reshape(d, H, dh),
                      "bias": qb.reshape(H, dh)},
                "k": {"kernel": kw.reshape(d, H, dh),
                      "bias": kb.reshape(H, dh)},
                "v": {"kernel": vw.reshape(d, H, dh),
                      "bias": vb.reshape(H, dh)},
                "out": {"kernel":
                        take(f"{pre}.attn.c_proj.weight").reshape(H, dh, d),
                        "bias": take(f"{pre}.attn.c_proj.bias")},
            },
            "LayerNorm_1": ln(f"{pre}.ln_2"),
            "Dense_0": {"kernel": take(f"{pre}.mlp.c_fc.weight"),
                        "bias": take(f"{pre}.mlp.c_fc.bias")},
            "Dense_1": {"kernel": take(f"{pre}.mlp.c_proj.weight"),
                        "bias": take(f"{pre}.mlp.c_proj.bias")},
        }
    leftover = set(sd) - used
    if leftover:
        raise ValueError(f"unconsumed GPT-2 keys {sorted(leftover)[:5]}... — "
                         "model config (num_layers?) smaller than the "
                         "checkpoint's")
    return _validated(model, example, {"params": params})


def densenet_params_from_torch(state_dict, model, example) -> dict:
    """Reference DenseNet-BC (`CNN/model.py:104-193`): stem / dense blocks /
    transitions / classifier -> `models.densenet.DenseNet` variables
    (`{"params": ..., "batch_stats": ...}`)."""
    c = _Consumer(state_dict)
    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}

    params["Stem_0"] = {"Conv_0": _conv2d(c.take("conv2d"))}
    p, s = _bn(c.take("bn"))
    params["StemNorm_0"] = {"BatchNorm_0": p}
    stats["StemNorm_0"] = {"BatchNorm_0": s}

    for b in range(model.dense_blocks):
        block_p: dict[str, Any] = {}
        block_s: dict[str, Any] = {}
        for l in range(model.dense_layers):
            p0, s0 = _bn(c.take("bn"))
            conv0 = _conv2d(c.take("conv2d"))
            p1, s1 = _bn(c.take("bn"))
            conv1 = _conv2d(c.take("conv2d"))
            block_p[f"DenseLayer_{l}"] = {"BatchNorm_0": p0, "Conv_0": conv0,
                                          "BatchNorm_1": p1, "Conv_1": conv1}
            block_s[f"DenseLayer_{l}"] = {"BatchNorm_0": s0,
                                          "BatchNorm_1": s1}
        params[f"DenseBlock_{b}"] = block_p
        stats[f"DenseBlock_{b}"] = block_s
        if b < model.dense_blocks - 1:
            p, s = _bn(c.take("bn"))
            params[f"Transition_{b}"] = {"BatchNorm_0": p,
                                         "Conv_0": _conv2d(c.take("conv2d"))}
            stats[f"Transition_{b}"] = {"BatchNorm_0": s}

    params["Classifier_0"] = {"Dense_0": _linear(c.take("linear"))}
    c.finish()
    return _validated(model, example,
                      {"params": params, "batch_stats": stats})
