"""distributed_deep_learning_tpu — a TPU-native distributed deep-learning framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
``Belegkarnil/distributed-deep-learning`` benchmark harness (multi-framework
distributed-training workloads: MLP / DenseNet-BC CNN / CNN-LSTM under
sequential, model-parallel, pipelined and data-parallel execution), built
TPU-first:

* one compiled program per training step (``jax.jit``), not an eager loop;
* parallelism expressed as shardings over a named ``jax.sharding.Mesh``
  (axes: ``data``, ``stage``, ``model``, ``seq``, ``expert``) with XLA
  collectives over ICI/DCN — not NCCL/MPI process groups;
* pipeline parallelism as an SPMD ``shard_map`` + ``lax.ppermute`` schedule,
  not a Python microbatch loop;
* host-side batched input pipelines feeding device-sharded arrays, not
  per-item ``.to(device)`` copies.

Subpackages
-----------
``utils``     config/CLI, logging, PRNG discipline
``runtime``   mesh construction, multi-host bootstrap, device placement
``data``      dataset semantics of the three reference workloads + loaders
``models``    Flax model zoo (MLP, DenseNet-BC, CNN-LSTM, ResNet, Transformer…)
``parallel``  partitioners, DP/MP/PP/TP/SP strategies, collectives
``ops``       Pallas TPU kernels for the hot ops
``train``     jitted train/eval steps, the epoch loop, metrics, checkpointing
"""

__version__ = "0.1.0"

# Keep the top-level import cheap: subpackages import jax lazily enough that
# `import distributed_deep_learning_tpu` never triggers device initialisation.
from distributed_deep_learning_tpu.utils.config import Config, Mode  # noqa: F401
