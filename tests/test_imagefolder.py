"""Generic ImageFolder dataset: class discovery, decode+resize, batching."""

import numpy as np
import pytest

from distributed_deep_learning_tpu.data.imagefolder import (ImageFolderDataset,
                                                            find_classes)


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("images")
    rng = np.random.default_rng(0)
    for cls, shade in (("cat", 60), ("dog", 180)):
        d = root / cls
        d.mkdir()
        for i in range(3):
            arr = np.full((20 + i, 24, 3), shade, np.uint8)
            arr += rng.integers(0, 20, arr.shape, dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.png")
    return str(root)


def test_class_discovery_sorted(image_root):
    classes, mapping = find_classes(image_root)
    assert classes == ["cat", "dog"]
    assert mapping == {"cat": 0, "dog": 1}


def test_batch_shapes_and_labels(image_root):
    ds = ImageFolderDataset(image_root, image_size=16)
    assert len(ds) == 6
    x, y = ds.batch(np.array([0, 3, 5]))
    assert x.shape == (3, 16, 16, 3) and x.dtype == np.float32
    assert y.shape == (3, 2)
    # items 0-2 are cats, 3-5 dogs (sorted walk)
    np.testing.assert_array_equal(y.argmax(-1), [0, 1, 1])
    # the class shades survive resize: cats darker than dogs
    assert x[0].mean() < x[1].mean()


def test_batch_is_deterministic(image_root):
    ds = ImageFolderDataset(image_root, image_size=8, num_workers=4)
    x1, y1 = ds.batch(np.arange(6))
    x2, y2 = ds.batch(np.arange(6))
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)


def test_serial_matches_threaded(image_root):
    ds_threaded = ImageFolderDataset(image_root, image_size=8, num_workers=4)
    ds_serial = ImageFolderDataset(image_root, image_size=8, num_workers=1)
    xt, _ = ds_threaded.batch(np.arange(6))
    xs, _ = ds_serial.batch(np.arange(6))
    np.testing.assert_array_equal(xt, xs)


def test_empty_root_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ImageFolderDataset(str(tmp_path))


def test_loader_rejects_indivisible_batch(image_root, mesh8):
    from distributed_deep_learning_tpu.data.loader import DeviceLoader

    ds = ImageFolderDataset(image_root, image_size=8)
    # batch 2 doesn't divide the 8-way mesh: rejected at construction
    with pytest.raises(ValueError):
        DeviceLoader(ds, np.arange(6), 2, mesh8, shuffle=False)


def test_feeds_device_loader_divisible(image_root):
    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    import jax

    mesh2 = build_mesh({"data": 2}, jax.devices()[:2])
    ds = ImageFolderDataset(image_root, image_size=8)
    loader = DeviceLoader(ds, np.arange(6), 2, mesh2, shuffle=False)
    x, y = next(iter(loader))
    assert x.shape == (2, 8, 8, 3)
    assert y.shape == (2, 2)
