"""Elastic fleet sizing: a hysteresis control loop over live signals.

The :class:`..serve.admission.AdmissionController` answers "the fleet
is overloaded, shed work"; the autoscaler answers the next question —
"the fleet is the wrong SIZE, change it".  :class:`FleetAutoscaler` is
the decision half: a pure, clock-free control loop over the windowed
queue-depth / occupancy / ITL gauges (:class:`..obs.window.LiveSignals`
shapes them; the router summarises them per round), mirroring the
admission ladder's patience/cool hysteresis so a transient spike never
births a replica and a momentary lull never kills one.  The actuation
half lives in :class:`..serve.fleet.FleetRouter` (grow = warm a new
replica from the published weights + ``clone_prefix`` of the hottest
shared prefixes; shrink = drain protocol: stop placement → evacuate
open slots → retire) — keeping ``observe`` pure makes the hysteresis
unit-testable with injected signal dicts.

:class:`PoolRebalancer` is the disaggregated cousin: under ``--disagg``
the replica set is fixed but the prefill/decode ROLE of each device is
not (MPMD pipeline scaling, arxiv 2412.14374) — sustained
``prefill_util`` skew moves one idle worker between pools through
:meth:`..serve.disagg.DisaggEngine.reassign`.
"""

from __future__ import annotations

from typing import Optional


class FleetAutoscaler:
    """Grow/shrink decisions for a supervised replica set.

    ``observe(signals, n_replicas)`` consumes one round's fleet summary
    — ``queue_depth`` (open requests), ``occupancy`` (live-slot
    fraction), optional ``itl_p99_s`` — and returns ``"grow"``,
    ``"shrink"`` or ``None``.  A round is HOT when the queue holds more
    than ``grow_queue_per_replica`` open requests per live replica (or
    occupancy crosses ``grow_occupancy``, or ITL p99 crosses
    ``grow_itl_p99_s`` when given); COLD when occupancy sits below
    ``shrink_occupancy`` with an empty queue.  ``patience`` consecutive
    hot rounds trigger a grow, ``cool`` consecutive cold rounds a
    shrink — the admission ladder's hysteresis shape, so load between
    the two bands parks the fleet where it is.  ``min_replicas`` /
    ``max_replicas`` clamp the actuation; ``events`` records every
    decision for the drill record."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 patience: int = 2, cool: int = 2,
                 grow_queue_per_replica: float = 4.0,
                 grow_occupancy: float = 0.9,
                 grow_itl_p99_s: Optional[float] = None,
                 shrink_occupancy: float = 0.25):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < "
                             f"min_replicas {min_replicas}")
        if patience < 1 or cool < 1:
            raise ValueError(f"patience/cool must be >= 1, got "
                             f"patience={patience} cool={cool}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.patience = int(patience)
        self.cool = int(cool)
        self.grow_queue_per_replica = float(grow_queue_per_replica)
        self.grow_occupancy = float(grow_occupancy)
        self.grow_itl_p99_s = grow_itl_p99_s
        self.shrink_occupancy = float(shrink_occupancy)
        self._hot = 0
        self._cold = 0
        self.events: list[dict] = []

    def _is_hot(self, signals: dict, n: int) -> bool:
        q = float(signals.get("queue_depth", 0.0))
        if q > self.grow_queue_per_replica * max(n, 1):
            return True
        if float(signals.get("occupancy", 0.0)) >= self.grow_occupancy:
            return True
        itl = signals.get("itl_p99_s")
        return (self.grow_itl_p99_s is not None and itl is not None
                and float(itl) > self.grow_itl_p99_s)

    def _is_cold(self, signals: dict) -> bool:
        return (float(signals.get("queue_depth", 0.0)) == 0.0
                and float(signals.get("occupancy", 1.0))
                < self.shrink_occupancy)

    def observe(self, signals: dict, n_replicas: int):
        """One control-loop step; returns ``"grow"``/``"shrink"``/None.

        Counters are mutually exclusive (a hot round zeroes the cold
        streak and vice versa) and reset after every decision, so an
        oscillating load (the ``scale_thrash`` drill) pays full
        patience/cool for EVERY action — bounded thrash by
        construction."""
        n = int(n_replicas)
        if self._is_hot(signals, n):
            self._hot += 1
            self._cold = 0
        elif self._is_cold(signals):
            self._cold += 1
            self._hot = 0
        else:
            self._hot = self._cold = 0
        if self._hot >= self.patience and n < self.max_replicas:
            self._hot = self._cold = 0
            self.events.append({"action": "grow", "replicas": n,
                                "signals": dict(signals)})
            return "grow"
        if self._cold >= self.cool and n > self.min_replicas:
            self._hot = self._cold = 0
            self.events.append({"action": "shrink", "replicas": n,
                                "signals": dict(signals)})
            return "shrink"
        return None

    def stats(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "patience": self.patience,
            "cool": self.cool,
            "scale_events": len(self.events),
            "grows": sum(1 for e in self.events
                         if e["action"] == "grow"),
            "shrinks": sum(1 for e in self.events
                           if e["action"] == "shrink"),
        }


class PoolRebalancer:
    """Role elasticity for disaggregated serving: decide when a device
    should change sides between the prefill and decode pools.

    Feed :meth:`observe` the run's ``prefill_util`` (useful rows per
    dispatched row-slot of the batched chunk program).  Sustained
    utilisation above ``hi`` means prefill is the bottleneck (every
    row-slot full, prompts queueing) — move a decode worker over
    (``"to_prefill"``); sustained utilisation below ``lo`` means the
    prefill pool is overprovisioned — hand a worker to decode
    (``"to_decode"``).  Same patience hysteresis as the autoscaler; the
    caller actuates via :meth:`..serve.disagg.DisaggEngine.reassign`,
    which keeps >= 1 worker per role and only moves idle workers."""

    def __init__(self, *, hi: float = 0.9, lo: float = 0.25,
                 patience: int = 2):
        if not 0.0 <= lo < hi <= 1.0:
            raise ValueError(f"need 0 <= lo < hi <= 1, got lo={lo} "
                             f"hi={hi}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.hi, self.lo = float(hi), float(lo)
        self.patience = int(patience)
        self._high = 0
        self._low = 0
        self.events: list[dict] = []

    def observe(self, prefill_util: float):
        """Returns ``"to_prefill"``/``"to_decode"``/None."""
        u = float(prefill_util)
        if u >= self.hi:
            self._high += 1
            self._low = 0
        elif u <= self.lo:
            self._low += 1
            self._high = 0
        else:
            self._high = self._low = 0
        if self._high >= self.patience:
            self._high = 0
            self.events.append({"action": "to_prefill", "util": u})
            return "to_prefill"
        if self._low >= self.patience:
            self._low = 0
            self.events.append({"action": "to_decode", "util": u})
            return "to_decode"
        return None
