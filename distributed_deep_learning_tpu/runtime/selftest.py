"""Distributed smoke test: verify the multi-process runtime end to end.

Run one copy per rank (usually via :func:`.launch.launch_local` or
``python -m distributed_deep_learning_tpu.runtime.selftest`` under an MPI/
SLURM launcher): each rank initialises :func:`.bootstrap.initialize_runtime`,
builds a global ``data`` mesh over every process's devices, trains a few
fused-psum steps on a deterministic dataset, and prints one line::

    SELFTEST rank=R world=W loss=<f> checksum=<f>

``loss`` and ``checksum`` (sum of |param|) must be IDENTICAL across ranks —
if gradient synchronisation were broken (the reference's quirk Q1: per-rank
models silently diverging) the checksums differ, which is exactly what the
reference could never detect (its only liveness coupling is one trailing
barrier, CNN/main.py:183-184).
"""

from __future__ import annotations


def main(steps: int = 3) -> str:
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.models.mlp import MLP
    from distributed_deep_learning_tpu.runtime.bootstrap import (
        initialize_runtime)
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    from distributed_deep_learning_tpu.train.objectives import (
        cross_entropy_loss)
    from distributed_deep_learning_tpu.train.state import create_train_state
    from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                          place_state)

    initialize_runtime()
    import numpy as np

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)}, devices)
    ds = synthetic_mqtt(256, seed=1)
    loader = DeviceLoader(ds, np.arange(len(ds)), 64, mesh, shuffle=True,
                          seed=7)
    state = create_train_state(MLP(hidden_size=16), jax.random.key(3),
                               jnp.zeros((1, 48)), optax.sgd(0.05))
    state = place_state(state, mesh)
    train_step, _ = make_step_fns(mesh, cross_entropy_loss)
    loss = 0.0
    done = 0
    while done < steps:
        for x, y in loader:
            state, m = train_step(state, x, y)
            loss = float(m["loss"])
            done += 1
            if done >= steps:
                break
    checksum = float(sum(jnp.sum(jnp.abs(l))
                         for l in jax.tree.leaves(state.params)))
    line = (f"SELFTEST rank={jax.process_index()} "
            f"world={jax.process_count()} loss={loss:.6f} "
            f"checksum={checksum:.6f}")
    print(line, flush=True)
    return line


if __name__ == "__main__":
    main()
