"""Model staging: express a model as partitionable layer stages.

The reference's models subclass ``nn.Sequential`` and their constructors
split the layer list into per-device ``nn.Sequential`` stages
(``MLP/model.py:41-45``).  Here staging is separated from modelling: a model
exposes a *layer sequence* (a list of Flax modules), a partitioner assigns
layers to stages, and :class:`StagedModel` packages the per-stage submodules
with shape-threaded initialisation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import numpy as np

from distributed_deep_learning_tpu.parallel.partition import stage_slices


class Stage(nn.Module):
    """A contiguous run of layers executed in order (one pipeline stage)."""

    layers: tuple[nn.Module, ...]

    @nn.compact
    def __call__(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


@dataclasses.dataclass(frozen=True)
class StagedModel:
    """A model split into per-stage Flax modules.

    ``params[i]`` inits/applies with ``stages[i]`` only — so each stage's
    parameters can live on its own device (MPMD) or mesh shard (SPMD).
    """

    stages: tuple[Stage, ...]

    @staticmethod
    def from_layers(layers: Sequence[nn.Module], assignment: np.ndarray,
                    n_stages: int) -> "StagedModel":
        slices = stage_slices(np.asarray(assignment), n_stages)
        stages = tuple(Stage(layers=tuple(layers[a:b])) for a, b in slices)
        return StagedModel(stages=stages)

    def init(self, rng: jax.Array, example: Any) -> list[Any]:
        """Initialise per-stage params, threading activation shapes through
        stages with ``eval_shape`` (no real compute on the example)."""
        import jax.numpy as jnp

        params = []
        x = example
        for stage in self.stages:
            rng, sub = jax.random.split(rng)
            params.append(stage.init(sub, x))
            shape = jax.eval_shape(lambda p, v, s=stage: s.apply(p, v),
                                   params[-1], x)
            x = jnp.zeros(shape.shape, shape.dtype)
        return params

    def apply(self, params: Sequence[Any], x: Any) -> Any:
        """Plain sequential forward (the reference's `sequential` mode)."""
        for stage, p in zip(self.stages, params):
            x = stage.apply(p, x)
        return x
