"""Analytic HBM model: reject plans that cannot fit BEFORE any compile.

Per-device footprint of one train step under a plan, from first principles:

* params — fp32 master copy, divided by the fsdp shard under ``--zero fsdp``
* gradients — same dtype/shape as params, sharded with them under fsdp
* optimizer — ``opt_slots`` fp32 moments per param (Adam 2, momentum 1,
  adafactor ~sublinear ≈ 1); ZeRO-1 shards them over the shard axis, fsdp
  shards them with the params
* activations — one *microbatch*'s worth (batch / (dp x grad_accum)) of
  per-layer activations, scaled by the fraction each remat policy keeps
  live for the backward

The fractions are a ranking model, not a byte-exact one — their job is a
correct ORDER (no remat > dots > dots_no_batch > full recompute), which the
monotonicity tests pin and each measured trial cross-checks against XLA's
``compiled.memory_analysis()`` (see :mod:`.trial`).  Everything here is
jax-free arithmetic; the HBM budget comes from ``device.memory_stats()``
where the backend reports one (TPU) and is ``None`` elsewhere (CPU test
meshes), in which case pruning only happens under an explicit override.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Mapping, Sequence

from distributed_deep_learning_tpu.tune.space import Plan

#: fraction of a layer's forward activations the backward keeps live under
#: each (remat, policy) combo.  No remat keeps everything; 'dots' keeps
#: matmul outputs; 'dots_no_batch' keeps only batch-free matmuls (weights'
#: contractions); policy 'nothing' under remat recomputes all but the layer
#: boundaries.
ACT_FRACTION: dict[tuple[bool, str], float] = {
    (False, "nothing"): 1.00,
    (True, "dots"): 0.60,
    (True, "dots_no_batch"): 0.45,
    (True, "nothing"): 0.15,
}

#: fp32 moment slots per parameter for each optimizer family; the analytic
#: model only needs the right order of magnitude
OPT_SLOTS = {"sgd": 0, "momentum": 1, "adam": 2, "adamw": 2, "lamb": 2,
             "adafactor": 1, "auto": 2}


@dataclasses.dataclass(frozen=True)
class ModelGeometry:
    """What the memory model needs to know about a workload's model."""

    param_count: int                     # trainable parameter count
    num_layers: int                      # repeated-block depth
    layer_act_elems_per_example: int     # activation elems / layer / example
    extra_act_elems_per_example: int = 0  # embeddings / head / input staging
    opt_slots: int = 2                   # fp32 moments per param


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """Per-device byte estimate for one train step under a plan."""

    params_bytes: int
    gradients_bytes: int
    optimizer_bytes: int
    activations_bytes: int

    @property
    def total_bytes(self) -> int:
        return (self.params_bytes + self.gradients_bytes
                + self.optimizer_bytes + self.activations_bytes)

    def to_dict(self) -> dict[str, int]:
        return {**dataclasses.asdict(self), "total_bytes": self.total_bytes}


def _shard_axis_size(plan: Plan) -> int:
    """The axis ZeRO shards over — fsdp when the mesh has one, else data
    (the same rule :mod:`..workloads.base` uses to pick the spec axis)."""
    md = plan.mesh_dict()
    fsdp = md.get("fsdp", 1)
    return fsdp if fsdp > 1 else md.get("data", 1)


def resolve_act_fraction(plan: Plan,
                         act_fraction: Mapping[tuple[bool, str], float]
                         | None = None) -> float:
    """The activation fraction for a plan's remat corner: the measured
    (calibrated) value when one is supplied, the static analytic table
    otherwise.  A calibration that lacks this corner falls back
    per-corner — partial calibrations never lose the analytic model."""
    key = (plan.remat, plan.remat_policy)
    if act_fraction is not None and key in act_fraction:
        return float(act_fraction[key])
    return ACT_FRACTION[key]


def estimate_memory(plan: Plan, geom: ModelGeometry, batch_size: int,
                    *, act_fraction: Mapping[tuple[bool, str], float]
                    | None = None) -> MemoryEstimate:
    """Analytic per-device HBM footprint of one train step.

    ``act_fraction`` optionally replaces the static :data:`ACT_FRACTION`
    table with measured per-corner constants (a
    :class:`~.calibrate.MemoryCalibration`'s ``act_fraction`` map);
    corners it doesn't cover keep the analytic value."""
    dtype_bytes = 2 if plan.dtype == "bfloat16" else 4
    shard = max(1, _shard_axis_size(plan))
    params = geom.param_count * 4          # fp32 master copy
    grads = geom.param_count * 4
    opt = geom.opt_slots * geom.param_count * 4
    if plan.zero == "1":
        opt = -(-opt // shard)             # moments sharded, params whole
    elif plan.zero == "fsdp":
        params = -(-params // shard)
        grads = -(-grads // shard)
        opt = -(-opt // shard)
    micro = max(1, batch_size // (plan.dp * plan.grad_accum))
    frac = resolve_act_fraction(plan, act_fraction)
    act = int(micro * (geom.num_layers * geom.layer_act_elems_per_example
                       * frac + geom.extra_act_elems_per_example)
              * dtype_bytes)
    return MemoryEstimate(params_bytes=params, gradients_bytes=grads,
                          optimizer_bytes=opt, activations_bytes=act)


def hbm_budget(devices: Sequence[Any] | None = None,
               override: int | None = None) -> int | None:
    """Per-device memory budget in bytes, or None when unknown.

    TPU runtimes report ``bytes_limit`` via ``device.memory_stats()``; the
    CPU test backend reports nothing, so CPU searches only prune under an
    explicit ``override`` (tests inject tiny/huge budgets this way)."""
    if override is not None:
        return override
    if not devices:
        return None
    try:
        stats = devices[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def prune_plans(plans: Iterable[Plan], geom: ModelGeometry, batch_size: int,
                budget_bytes: int | None, *, safety: float = 0.9,
                act_fraction: Mapping[tuple[bool, str], float] | None = None,
                ) -> tuple[list[Plan], list[tuple[Plan, MemoryEstimate]]]:
    """Split plans into (feasible, rejected-with-estimates).

    ``safety`` reserves headroom for XLA temporaries the analytic model
    cannot see (fusion scratch, collective buffers).  With no budget the
    model cannot reject anything — every plan is feasible and the measured
    trials' OOM containment is the backstop.  ``act_fraction`` threads a
    calibration's measured constants into every estimate."""
    feasible: list[Plan] = []
    rejected: list[tuple[Plan, MemoryEstimate]] = []
    for plan in plans:
        est = estimate_memory(plan, geom, batch_size,
                              act_fraction=act_fraction)
        if budget_bytes is not None and est.total_bytes > safety * budget_bytes:
            rejected.append((plan, est))
        else:
            feasible.append(plan)
    return feasible, rejected
