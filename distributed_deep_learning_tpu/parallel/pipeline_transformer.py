"""Pipeline-parallel transformer: embed → SPMD-pipelined trunk → head.

The composition rule for real models on the SPMD pipeline
(:mod:`.spmd_pipeline`): the *homogeneous* part — a stack of identical
transformer blocks — runs inside the pipeline over the ``stage`` mesh axis,
while the heterogeneous ends (embedding, norm, LM head) run outside it with
ordinary shardings.  Each stage holds ``num_layers / num_stages``
consecutive blocks; stage parameters stack along a leading axis sharded
over ``stage``, so every device stores and runs only its own blocks —
pipeline parallelism for the transformer trunk in one XLA program, forward
AND backward (scan/ppermute transpose).

Composes with data parallelism: the microbatch dimension stays sharded
over ``data``/``fsdp`` inside the pipeline.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_deep_learning_tpu.models.transformer import TransformerLayer
from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
    spmd_pipeline, stack_stage_params)


class TrunkStage(nn.Module):
    """``layers_per_stage`` consecutive pre-LN blocks — one pipeline stage.

    Train-time stochasticity: the pipeline derives a per-(stage,
    microbatch) PRNG key (``spmd_pipeline``'s ``rng``), handed to
    ``apply`` as the ``dropout`` stream — Flax then folds it per Dropout
    site, so masks are distinct across stages, blocks and microbatches yet
    deterministic per seed.  ``attention_fn`` plugs the Pallas flash
    kernel into every block (padding masks are not threaded through the
    pipeline — pad to microbatch boundaries instead).
    """

    layers_per_stage: int
    num_heads: int = 8
    mlp_dim: int = 2048
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_fn: object = None
    dropout_rate: float = 0.0
    rope: bool = False                  # rotary positions, applied in-block
    window: int | None = None           # causal sliding-window size
    num_kv_heads: int | None = None     # < num_heads = grouped-query attn

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i in range(self.layers_per_stage):
            x = TransformerLayer(self.num_heads, self.mlp_dim,
                                 dropout_rate=self.dropout_rate,
                                 causal=self.causal,
                                 dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 rope=self.rope, window=self.window,
                                 num_kv_heads=self.num_kv_heads,
                                 name=f"block_{i}")(x, train=train)
        return x


class PipelinedTrunk:
    """A transformer trunk split over the mesh's ``stage`` axis.

    ``n_chunks > 1`` (interleaved pipelining) gives each device ``V``
    non-contiguous model chunks: virtual stage ``v·S + s`` lives on device
    ``s``, params stack as ``(V, S, ...)``, and the interleaved-1F1B
    schedule (:func:`.spmd_pipeline.spmd_pipeline_interleaved`) fills the
    pipeline bubble with the extra chunks.
    """

    def __init__(self, num_layers: int, mesh: Mesh, *, num_heads: int = 8,
                 mlp_dim: int = 2048, causal: bool = False,
                 dtype: jnp.dtype = jnp.float32,
                 microbatch_size: Optional[int] = None,
                 attention_fn=None, dropout_rate: float = 0.0,
                 n_chunks: int = 1, rope: bool = False,
                 window: Optional[int] = None,
                 num_kv_heads: Optional[int] = None):
        self.mesh = mesh
        self.n_stages = mesh.shape["stage"]
        if n_chunks < 1:
            raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
        self.n_chunks = n_chunks
        n_virtual = self.n_stages * n_chunks
        if num_layers % n_virtual:
            raise ValueError(f"{num_layers} layers not divisible into "
                             f"{self.n_stages} stages x {n_chunks} chunks")
        self.microbatch_size = microbatch_size
        self.stage = TrunkStage(num_layers // n_virtual, num_heads,
                                mlp_dim, causal, dtype, attention_fn,
                                dropout_rate, rope, window, num_kv_heads)

    def init(self, rng: jax.Array, example: jnp.ndarray) -> Any:
        """Stacked per-stage params: ``(S, ...)`` leaves, or ``(V, S, ...)``
        when interleaving (virtual stage ``v·S + s`` at index [v, s])."""
        params = [
            self.stage.init(jax.random.fold_in(rng, i), example)["params"]
            for i in range(self.n_stages * self.n_chunks)]
        stacked = stack_stage_params(params)
        if self.n_chunks == 1:
            return stacked
        return jax.tree.map(
            lambda l: l.reshape(self.n_chunks, self.n_stages, *l.shape[1:]),
            stacked)

    def stage_fn(self):
        """One stage's pure ``(params, x) -> y`` — the unit both pipeline
        schedules (GPipe scan and 1F1B) apply per tick."""
        return lambda p, a: self.stage.apply({"params": p}, a)

    def stage_fn_train(self):
        """Stochastic variant ``(params, x, key) -> y`` for runs with
        dropout (the pipeline derives ``key`` per stage+microbatch)."""
        return lambda p, a, key: self.stage.apply(
            {"params": p}, a, train=True, rngs={"dropout": key})

    def apply(self, stacked_params: Any, x: jnp.ndarray,
              rng: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """(B, T, d) → (B, T, d) through all stages, pipelined; pass
        ``rng`` to activate dropout.  With ``n_chunks > 1`` the forward
        laps the S-stage GPipe pipeline V times (chunk ``v`` of every
        device = lap ``v``) — correct for eval and for the
        scan-transpose backward; the train step swaps in the interleaved
        1F1B schedule instead."""
        laps = ([jax.tree.map(lambda l, v=v: l[v], stacked_params)
                 for v in range(self.n_chunks)]
                if self.n_chunks > 1 else [stacked_params])
        for v, lap in enumerate(laps):
            if rng is not None:
                x = spmd_pipeline(
                    self.stage_fn_train(), lap, x, mesh=self.mesh,
                    microbatch_size=self.microbatch_size,
                    rng=jax.random.fold_in(rng, v))
            else:
                x = spmd_pipeline(
                    self.stage_fn(), lap, x, mesh=self.mesh,
                    microbatch_size=self.microbatch_size)
        return x

    def apply_sequential(self, stacked_params: Any, x: jnp.ndarray
                         ) -> jnp.ndarray:
        """Reference semantics: the same stages applied one after another
        without the pipeline (for equivalence tests; deterministic)."""
        for i in range(self.n_stages * self.n_chunks):
            if self.n_chunks == 1:
                p = jax.tree.map(lambda l, i=i: l[i], stacked_params)
            else:
                p = jax.tree.map(
                    lambda l, i=i: l[i // self.n_stages, i % self.n_stages],
                    stacked_params)
            x = self.stage.apply({"params": p}, x)
        return x
