"""Serving throughput harness: continuous batching vs run-to-completion.

Drives the SAME seeded mixed-length request trace through both decode
paths and reports one JSON-able record:

* **engine** — :class:`..serve.engine.ServeEngine`: slot-based static KV
  cache, bucketed compile-once prefill, one compiled decode program;
  rows retire individually and freed slots refill immediately.
* **naive**  — the batch-synchronous :func:`..models.transformer.generate`
  baseline a framework without a serving layer would use: requests
  grouped into fixed-size batches, prompts right-padded to the batch
  max, every row decoded to the batch's LONGEST budget, and every new
  ``(B, P, max_new)`` shape triple a fresh XLA compile.  (Padded rows
  additionally sample their first token from a pad position — the naive
  path is only CORRECT when all prompts in a batch share one length;
  the engine's true-length prefill fixes that too.)

Tokens/sec counts USEFUL tokens only — the ``max_new_tokens`` each
request asked for — so the naive path's overshoot (decoding finished
rows to the batch max) is wasted time, not credited throughput.  That
asymmetry, plus per-shape recompiles, is precisely what continuous
batching exists to eliminate; the record carries compile counts and
mean slot occupancy so the mechanism is visible, not just the ratio.

Shared by ``scripts/serve_bench.py`` (CLI), ``bench.py`` (the
``serving`` sub-record) and ``scripts/tpu_validation.py`` (the TPU
harvest section).

:func:`paged_serving_bench` is the second-generation bench: a
trace-driven SLO load (:mod:`..serve.load` — Poisson/bursty arrivals,
shared system prompts, per-request deadlines) through
:class:`..serve.engine.PagedEngine`, A/B'd against the v1 engine on the
same trace.  Load shapes live HERE (``DEFAULT_LOAD``) so every caller
benches the same story.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from distributed_deep_learning_tpu.serve.engine import (CountingJit,
                                                        PagedEngine,
                                                        ServeEngine)
from distributed_deep_learning_tpu.serve.load import LoadSpec, make_load
from distributed_deep_learning_tpu.serve.scheduler import Request

#: CPU-CI-sized default model geometry (big enough that a decode tick is
#: real compute, small enough that the whole A/B fits a bench section)
DEFAULT_MODEL = dict(vocab_size=512, num_layers=2, d_model=128,
                     num_heads=4, mlp_dim=256, max_len=160)

#: default trace for the PAGED bench — the serving story in one load
#: shape: Poisson arrivals, bimodal prompt lengths, 60% of requests
#: opening with one shared 32-token system prompt (the prefix cache's
#: target), per-request TTFT/e2e SLOs.  ONE place defines it; the CLI
#: (scripts/serve_bench.py), bench.py and tpu_validation.py all override
#: fields of this dict rather than re-rolling their own traces.
DEFAULT_LOAD = dict(n_requests=24, arrival="poisson", rate=2.0,
                    prompt_short=(4, 16), prompt_long=(40, 72),
                    long_frac=0.3, shared_prefix_len=32, shared_frac=0.6,
                    new_tokens=(4, 32), slo_ttft_ms=2000.0,
                    slo_e2e_ms=15000.0)


def build_model(seed: int = 0, **overrides):
    """A randomly-initialised :class:`CausalLM` + params for serving
    benches (throughput does not care that the weights are untrained)."""
    import jax
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.models.transformer import CausalLM

    model = CausalLM(**{**DEFAULT_MODEL, **overrides})
    toks = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(seed), toks)["params"]
    return model, params


def make_trace(n_requests: int, *, vocab_size: int, seed: int = 0,
               prompt_lens: tuple[int, int] = (4, 48),
               new_tokens: tuple[int, int] = (4, 64),
               stagger: int = 0) -> list[Request]:
    """Seeded mixed-length trace.  ``prompt_lens``/``new_tokens`` are
    inclusive uniform ranges; ``stagger`` is the mean inter-arrival gap
    in decode ticks (0 = every request queued at tick 0)."""
    rng = np.random.default_rng(seed)
    reqs, tick = [], 0
    for uid in range(n_requests):
        p = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
        n = int(rng.integers(new_tokens[0], new_tokens[1] + 1))
        prompt = rng.integers(1, vocab_size, p).astype(np.int32)
        reqs.append(Request(uid, prompt, n, arrival_tick=tick))
        if stagger:
            tick += int(rng.integers(0, 2 * stagger + 1))
    return reqs


def run_engine(model, params, requests: Sequence[Request], telemetry=None,
               **engine_kw):
    """One engine lifetime over the trace; returns the engine's record.
    ``telemetry`` (a :class:`..obs.RunTelemetry`) routes the engine's
    latency histograms into the run's shared registry + event stream."""
    eng = ServeEngine(model, params, **engine_kw)
    return eng.run(requests, telemetry=telemetry)


def run_naive(model, params, requests: Sequence[Request],
              batch_size: int) -> dict:
    """The run-to-completion baseline at the same concurrency.

    Batches of ``batch_size`` in submission order (arrival ticks are
    ignored — generous to the baseline), padded to the batch max prompt
    length, decoded to the batch max budget through a jitted
    ``generate``.  Wall time includes the per-shape compiles: that IS
    the naive path's serving cost.
    """
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.models.transformer import generate

    pad_fill = model.pad_id if model.pad_id is not None else 0
    gen = CountingJit(
        lambda p, prompts, n: generate(model, p, prompts,
                                       max_new_tokens=n),
        static_argnums=(2,))

    results: dict[int, np.ndarray] = {}
    useful = decoded = 0
    t0 = time.perf_counter()
    for i in range(0, len(requests), batch_size):
        batch = requests[i:i + batch_size]
        pmax = max(len(r.prompt) for r in batch)
        nmax = max(r.max_new_tokens for r in batch)
        prompts = np.full((len(batch), pmax), pad_fill, np.int32)
        for j, r in enumerate(batch):
            prompts[j, :len(r.prompt)] = r.prompt
        out = np.asarray(gen(params, jnp.asarray(prompts), nmax))
        for j, r in enumerate(batch):
            results[r.uid] = out[j, :r.max_new_tokens]
            useful += r.max_new_tokens
        decoded += len(batch) * nmax
    total = time.perf_counter() - t0
    return {"results": results, "stats": {
        "requests": len(requests),
        "generated_tokens": useful,
        "decoded_tokens": decoded,
        "wasted_fraction": round(1 - useful / decoded, 4) if decoded else 0,
        "tokens_per_sec": useful / total if total else None,
        "total_seconds": total,
        "batch_size": batch_size,
        "compiles": gen.traces,
    }}


def serving_bench(*, seed: int = 0, n_requests: int = 32,
                  model_kw: Optional[dict] = None,
                  prompt_lens: tuple[int, int] = (4, 48),
                  new_tokens: tuple[int, int] = (4, 64),
                  max_slots: int = 8,
                  prefill_buckets: Optional[Sequence[int]] = None,
                  stagger: int = 0, skip_naive: bool = False,
                  kv_dtype: Optional[str] = None,
                  weight_dtype: Optional[str] = None,
                  telemetry=None) -> dict:
    """The full A/B at one configuration; returns the ``serving``
    record ``bench.py`` embeds and ``scripts/serve_bench.py`` prints."""
    model, params = build_model(seed, **(model_kw or {}))
    if prompt_lens[1] + new_tokens[1] > model.max_len:
        raise ValueError(
            f"trace upper bounds {prompt_lens[1]}+{new_tokens[1]} exceed "
            f"max_len {model.max_len}")
    trace = make_trace(n_requests, vocab_size=model.vocab_size, seed=seed,
                       prompt_lens=prompt_lens, new_tokens=new_tokens,
                       stagger=stagger)

    eng = run_engine(model, params, trace, telemetry=telemetry,
                     max_slots=max_slots, prefill_buckets=prefill_buckets,
                     kv_dtype=kv_dtype, weight_dtype=weight_dtype)
    es = eng["stats"]
    record = {
        "metric": "serving throughput tokens/sec (mixed-length trace)",
        "model": {**DEFAULT_MODEL, **(model_kw or {})},
        "kv_dtype": kv_dtype,
        "weight_dtype": weight_dtype,
        "requests": n_requests,
        "prompt_lens": list(prompt_lens),
        "new_tokens": list(new_tokens),
        "max_slots": max_slots,
        "engine": {
            "tokens_per_sec": round(es["tokens_per_sec"], 2),
            "kv_cache_bytes": es["kv_cache_bytes"],
            "prefill_seconds": round(es["prefill_seconds"], 3),
            "decode_seconds": round(es["decode_seconds"], 3),
            "mean_slot_occupancy": round(es["mean_slot_occupancy"], 3),
            "decode_ticks": es["decode_ticks"],
            "prefill_compiles": es["prefill_compiles"],
            "decode_compiles": es["decode_compiles"],
            "buckets": es["buckets"],
            # per-request latency percentiles from the engine's
            # log-bucketed histograms (obs/metrics.py) — TTFT anchors at
            # the wall time the arrival tick was reached, so queue wait
            # under load is counted
            "latency": {k: (round(v, 5) if isinstance(v, float) else v)
                        for k, v in es["latency"].items()},
        },
    }
    if not skip_naive:
        naive = run_naive(model, params, trace, batch_size=max_slots)
        ns = naive["stats"]
        record["naive"] = {
            "tokens_per_sec": round(ns["tokens_per_sec"], 2),
            "total_seconds": round(ns["total_seconds"], 3),
            "wasted_fraction": ns["wasted_fraction"],
            "compiles": ns["compiles"],
        }
        record["speedup"] = round(
            es["tokens_per_sec"] / ns["tokens_per_sec"], 3) \
            if ns["tokens_per_sec"] else None
    return record


def run_paged(model, params, requests: Sequence[Request], telemetry=None,
              keep_timeline: bool = False, **engine_kw):
    """One :class:`PagedEngine` lifetime over the trace (same contract
    as :func:`run_engine`)."""
    eng = PagedEngine(model, params, **engine_kw)
    return eng.run(requests, telemetry=telemetry,
                   keep_timeline=keep_timeline)


def run_supervised(model, params, requests: Sequence[Request], *,
                   paged: bool = False, telemetry=None,
                   deadline_ms: Optional[float] = None, retries: int = 2,
                   stall_timeout_s: Optional[float] = None,
                   reload_watch: Optional[str] = None,
                   canary_slots: int = 2,
                   admission: Optional[dict] = None,
                   **engine_kw) -> dict:
    """One SUPERVISED engine lifetime over the trace: same
    ``{"results", "errors", "stats"}`` contract as :func:`run_engine` /
    :func:`run_paged`, with the engine run under
    :class:`..serve.supervisor.ServeSupervisor` — tick watchdog, crash
    containment with zero-loss replay, per-request deadlines and bounded
    retries.  ``reload_watch`` additionally wires hot weight reload
    (:class:`..serve.reload.ReloadManager` watching that directory, with
    ``canary_slots`` of canary before promote); ``admission`` is a
    kwargs dict for :class:`..serve.admission.AdmissionController`
    (``utils/config.parse_admission_arg`` produces it from the CLI).
    The engine-level stats land under ``stats["engine"]``."""
    from distributed_deep_learning_tpu.serve.supervisor import ServeSupervisor

    eng = (PagedEngine if paged else ServeEngine)(model, params,
                                                  **engine_kw)
    rm = None
    if reload_watch is not None:
        from distributed_deep_learning_tpu.serve.reload import ReloadManager

        rm = ReloadManager(reload_watch, canary_slots=canary_slots)
    adm = None
    if admission is not None:
        from distributed_deep_learning_tpu.serve.admission import (
            AdmissionController)

        adm = AdmissionController(**admission)
    sup = ServeSupervisor(eng, deadline_ms=deadline_ms, retries=retries,
                          stall_timeout_s=stall_timeout_s, reload=rm,
                          admission=adm)
    return sup.run(requests, telemetry=telemetry)


def paged_max_len(model_max_len: int, kv_block_size: int,
                  draft: bool, spec_k: int) -> int:
    """Largest engine ``max_len`` a model geometry supports: the paged
    cache rounds capacity up to whole blocks and, with speculation on,
    needs ``spec_k + 1`` positions of verify headroom — all of which
    must still fit the model's learned position range."""
    head = (spec_k + 1) if draft else 0
    cap = (model_max_len // kv_block_size) * kv_block_size - head
    if cap < kv_block_size:
        raise ValueError(
            f"model max_len {model_max_len} too small for block size "
            f"{kv_block_size} (+{head} speculative headroom)")
    return cap


def paged_serving_bench(*, seed: int = 0,
                        load_kw: Optional[dict] = None,
                        model_kw: Optional[dict] = None,
                        max_slots: int = 8,
                        kv_block_size: int = 16,
                        prefill_chunk: int = 32,
                        draft_layers: Optional[int] = None,
                        spec_k: int = 4,
                        compare_engine: bool = True,
                        kv_dtype: Optional[str] = None,
                        weight_dtype: Optional[str] = None,
                        telemetry=None) -> dict:
    """The paged-generation bench: one trace-driven load (``DEFAULT_LOAD``
    overridden by ``load_kw``) through :class:`PagedEngine`, optionally
    A/B'd against the v1 :class:`ServeEngine` on the SAME trace.

    The record carries the three fields the CI baseline tracks —
    ``prefix_hit_rate``, ``slo_attainment``, ``spec_acceptance`` — plus
    the mechanism counters (chunk/verify compiles, CoW copies,
    evictions, prefill tokens computed) that explain them.  The v1
    comparison reports ``prefill_tokens_saved_frac``: v1 prefills every
    prompt to its padded bucket; the paged path prefills only
    unshared tokens, in chunks.
    """
    model, params = build_model(seed, **(model_kw or {}))
    spec = LoadSpec(**{**DEFAULT_LOAD, **(load_kw or {})})
    cap = paged_max_len(model.max_len, kv_block_size,
                        draft_layers is not None, spec_k)
    need = spec.shared_prefix_len + spec.prompt_long[1] + spec.new_tokens[1]
    if need > cap:
        raise ValueError(
            f"trace upper bound {need} tokens exceeds paged capacity "
            f"{cap} (model max_len {model.max_len})")
    trace = make_load(spec, vocab_size=model.vocab_size, seed=seed)

    res = run_paged(model, params, trace, telemetry=telemetry,
                    max_slots=max_slots, max_len=cap,
                    kv_block_size=kv_block_size,
                    prefill_chunk=min(prefill_chunk, cap),
                    draft_layers=draft_layers, spec_k=spec_k,
                    kv_dtype=kv_dtype, weight_dtype=weight_dtype)
    ps = res["stats"]
    record = {
        "metric": "paged serving under trace-driven SLO load",
        "model": {**DEFAULT_MODEL, **(model_kw or {})},
        "load": {**DEFAULT_LOAD, **(load_kw or {})},
        "max_slots": max_slots,
        "kv_block_size": kv_block_size,
        "prefill_chunk": prefill_chunk,
        "kv_dtype": kv_dtype,
        "weight_dtype": weight_dtype,
        "errors": len(res["errors"]),
        "paged_engine": {
            "tokens_per_sec": round(ps["tokens_per_sec"], 2),
            "kv_cache_bytes": ps["kv_cache_bytes"],
            "prefill_seconds": round(ps["prefill_seconds"], 3),
            "decode_seconds": round(ps["decode_seconds"], 3),
            "mean_slot_occupancy": round(ps["mean_slot_occupancy"], 3),
            "prefill_chunks": ps["prefill_chunks"],
            "decode_ticks": ps["decode_ticks"],
            "chunk_compiles": ps["chunk_compiles"],
            "decode_compiles": ps["decode_compiles"],
            "verify_compiles": ps["verify_compiles"],
            "draft_compiles": ps["draft_compiles"],
            # the three baseline-tracked headline numbers
            "prefix_hit_rate": ps["paged"]["prefix_hit_rate"],
            "slo_attainment": ps["slo"]["slo_attainment"],
            "spec_acceptance": ps["spec"]["acceptance_rate"],
            "paged": ps["paged"],
            "spec": ps["spec"],
            "slo": ps["slo"],
            "latency": {k: (round(v, 5) if isinstance(v, float) else v)
                        for k, v in ps["latency"].items()},
        },
    }
    if compare_engine:
        v1 = run_engine(model, params, trace, max_slots=max_slots)
        vs = v1["stats"]
        # v1 prefills each admitted prompt to its padded compile bucket
        buckets = vs["buckets"]
        v1_prefill = sum(min(b for b in buckets if b >= len(r.prompt))
                         for r in trace if r.uid not in v1["errors"])
        record["engine_v1"] = {
            "tokens_per_sec": round(vs["tokens_per_sec"], 2),
            "prefill_seconds": round(vs["prefill_seconds"], 3),
            "prefill_compiles": vs["prefill_compiles"],
            "prefill_tokens_computed": v1_prefill,
            "latency": {k: (round(v, 5) if isinstance(v, float) else v)
                        for k, v in vs["latency"].items()},
        }
        if v1_prefill:
            record["prefill_tokens_saved_frac"] = round(
                1 - ps["paged"]["prefill_tokens_computed"] / v1_prefill, 4)
        if vs["tokens_per_sec"]:
            record["speedup_vs_v1"] = round(
                ps["tokens_per_sec"] / vs["tokens_per_sec"], 3)
    return record


def _token_agreement(a: dict, b: dict) -> float:
    """Fraction of greedy tokens identical between two result maps
    (uid -> token array) over their shared uids."""
    total = same = 0
    for uid, toks in a.items():
        if uid not in b:
            continue
        other = np.asarray(b[uid])
        toks = np.asarray(toks)
        n = min(len(toks), len(other))
        total += n
        same += int(np.sum(toks[:n] == other[:n]))
    return same / total if total else 1.0


def quantized_serving_bench(*, seed: int = 0,
                            load_kw: Optional[dict] = None,
                            model_kw: Optional[dict] = None,
                            max_slots: int = 8,
                            kv_block_size: int = 16,
                            prefill_chunk: int = 32,
                            kv_dtype: str = "int8",
                            weight_dtype: str = "int8",
                            telemetry=None) -> dict:
    """The quantized-serving A/B: the SAME trace through the paged
    engine at full precision and again with ``kv_dtype`` block pools +
    ``weight_dtype`` weights.

    The record carries the three numbers the CI baseline tracks:

    * ``kv_shrink_x`` — full-precision / quantized ``kv_cache_bytes``
      at identical slots x capacity (the gauge measures the REAL
      resident pools, scales included, so this is the honest at-rest
      shrink, not the 4x a bare dtype ratio would claim);
    * ``tokens_per_sec`` of the quantized arm (decode is memory-bound,
      so the shrink should never cost throughput — the band protects
      against a quantize/dequant regression in the hot loop);
    * ``logprob_drift`` — the CALIBRATED per-token greedy logprob
      drift of the quantized weights (:func:`..serve.quant.
      calibrate_weight_drift` over a probe batch drawn from the trace),
      which is also the declared bound the parity tests gate int8 on.

    Plus ``max_context_at_budget``: how many KV positions fit in the
    full-precision pools' byte footprint under each representation —
    the "max context before OOM" number, computed from measured bytes
    per position rather than an OOM hunt (deterministic on CPU, and
    exactly how the HBM memory model would plan it).
    """
    from distributed_deep_learning_tpu.serve import quant

    model, params = build_model(seed, **(model_kw or {}))
    spec = LoadSpec(**{**DEFAULT_LOAD, **(load_kw or {})})
    cap = paged_max_len(model.max_len, kv_block_size, False, 0)
    trace = make_load(spec, vocab_size=model.vocab_size, seed=seed)
    engine_kw = dict(max_slots=max_slots, max_len=cap,
                     kv_block_size=kv_block_size,
                     prefill_chunk=min(prefill_chunk, cap))

    base = run_paged(model, params, trace, **engine_kw)
    bs_ = base["stats"]
    q = run_paged(model, params, trace, telemetry=telemetry,
                  kv_dtype=kv_dtype, weight_dtype=weight_dtype,
                  **engine_kw)
    qs = q["stats"]

    # measured bytes per KV position (pool bytes / pool capacity) under
    # each representation -> max context inside the BASELINE's budget
    positions = bs_["paged"]["blocks_total"] * kv_block_size
    budget = bs_["kv_cache_bytes"]
    base_ctx = int(budget // (bs_["kv_cache_bytes"] / positions))
    quant_ctx = int(budget // (qs["kv_cache_bytes"] / positions))

    # the declared int8 weight-drift bound, measured on a probe batch of
    # real trace prompts (greedy trajectory logprobs, full forward)
    probe = np.concatenate([np.asarray(r.prompt) for r in trace[:4]])[:64]
    drift = quant.calibrate_weight_drift(
        model, params, quant.quantize_weights(params, weight_dtype),
        probe) if weight_dtype else {
            "measured_max_drift": 0.0, "declared_bound": 0.0,
            "probe_argmax_agreement": 1.0, "probe_tokens": 0}

    return {
        "metric": "quantized serving hot path A/B (paged engine)",
        "model": {**DEFAULT_MODEL, **(model_kw or {})},
        "load": {**DEFAULT_LOAD, **(load_kw or {})},
        "max_slots": max_slots,
        "kv_block_size": kv_block_size,
        "kv_dtype": kv_dtype,
        "weight_dtype": weight_dtype,
        "errors": len(base["errors"]) + len(q["errors"]),
        "baseline": {
            "tokens_per_sec": round(bs_["tokens_per_sec"], 2),
            "kv_cache_bytes": bs_["kv_cache_bytes"],
            "kv_bytes_per_slot": bs_["kv_cache_bytes"] // max_slots,
            "max_context_at_budget": base_ctx,
            "decode_compiles": bs_["decode_compiles"],
        },
        "quantized": {
            "tokens_per_sec": round(qs["tokens_per_sec"], 2),
            "kv_cache_bytes": qs["kv_cache_bytes"],
            "kv_bytes_per_slot": qs["kv_cache_bytes"] // max_slots,
            "max_context_at_budget": quant_ctx,
            "decode_compiles": qs["decode_compiles"],
            "chunk_compiles": qs["chunk_compiles"],
            "weight_bytes": quant.weight_bytes(
                quant.quantize_weights(params, weight_dtype))
            if weight_dtype else quant.weight_bytes(params),
        },
        "kv_shrink_x": round(
            bs_["kv_cache_bytes"] / qs["kv_cache_bytes"], 3),
        "token_agreement": round(
            _token_agreement(base["results"], q["results"]), 4),
        "logprob_drift": round(drift["measured_max_drift"], 5),
        "declared_drift_bound": round(drift["declared_bound"], 5),
        "probe_argmax_agreement": drift["probe_argmax_agreement"],
    }


#: the fleet bench's priority mix: a quarter interactive (priority 0,
#: never preempted or shed), half standard, a quarter batch — shared by
#: bench.py's fleet_resilience section and tpu_validation's
#: serving_fleet harvest so both tiers measure the same story
DEFAULT_PRIORITY_CLASSES = ((0, 0.25), (1, 0.5), (2, 0.25))


def fleet_serving_bench(*, seed: int = 0, replicas: int = 3,
                        load_kw: Optional[dict] = None,
                        model_kw: Optional[dict] = None,
                        max_slots: int = 4,
                        kv_block_size: int = 16,
                        prefill_chunk: int = 32,
                        telemetry=None) -> dict:
    """Throughput + routing quality of a :class:`..serve.fleet.
    FleetRouter` over ``replicas`` paged engines on the shared-prefix
    Poisson trace with priority classes.

    The record carries fleet tokens/sec, the router's predicted-hit
    placement total (the prefix-affinity signal actually paying off is
    visible as per-replica ``prefix_hit_rate`` in the engine stats),
    and the merged per-priority SLO report."""
    from distributed_deep_learning_tpu.serve.fleet import FleetRouter

    model, params = build_model(seed, **(model_kw or {}))
    lk = {**DEFAULT_LOAD,
          "priority_classes": DEFAULT_PRIORITY_CLASSES,
          **(load_kw or {})}
    spec = LoadSpec(**lk)
    trace = make_load(spec, vocab_size=model.vocab_size, seed=seed)
    cap = paged_max_len(model.max_len, kv_block_size, False, 0)
    engines = [PagedEngine(model, params, max_slots=max_slots,
                           max_len=cap, kv_block_size=kv_block_size,
                           prefill_chunk=prefill_chunk)
               for _ in range(replicas)]
    flt = FleetRouter(engines, telemetry=telemetry)
    t0 = time.perf_counter()
    out = flt.run(list(trace))
    total = time.perf_counter() - t0
    st = out["stats"]
    tokens = int(sum(len(v) for v in out["results"].values()))
    return {
        "metric": "fleet serving: routed throughput / SLO by priority",
        "replicas": replicas,
        "requests": st["requests"],
        "completed": st["completed"],
        "requests_lost": st["requests_lost"],
        "errors": len(out["errors"]),
        "generated_tokens": tokens,
        "tokens_per_sec": round(tokens / total, 2) if total else None,
        "rounds": st["rounds"],
        "routing": st["routing"],
        "health": st["health"],
        "decode_compiles_max": max(
            v["decode_compiles"] for v in st["per_replica"].values()),
        "slo_attainment": st["slo"]["slo_attainment"],
        "slo_by_priority": {
            p: s["slo_attainment"]
            for p, s in st["slo"].get("by_priority", {}).items()},
    }


def disagg_serving_bench(*, seed: int = 0,
                         load_kw: Optional[dict] = None,
                         model_kw: Optional[dict] = None,
                         prefill_workers: int = 1,
                         decode_workers: int = 1,
                         prefill_streams: int = 4,
                         max_slots: int = 8,
                         kv_block_size: int = 16,
                         prefill_chunk: int = 32,
                         kv_dtype: Optional[str] = None,
                         decode_passes: int = 2,
                         telemetry=None) -> dict:
    """The disaggregation A/B: the SAME shared-prefix Poisson trace
    through the unified :class:`PagedEngine` and through
    :class:`..serve.disagg.DisaggEngine` (prefill pool + decode pool on
    separate devices, joined by device-to-device KV-block migration).

    Both engines are WARMED on the trace and reset before the timed
    runs, so the A/B measures steady-state serving, not compiles (the
    compile counters still ride along and must read compile-once); the
    migrator's stats are re-zeroed after the warm run so the embedded
    migration record covers exactly the timed run.  The record carries
    the baseline-tracked numbers — ``speedup`` (disagg / unified
    tokens/sec), both ITL p99s, sync-measured ``migration_gbps`` — plus
    ``token_agreement`` (greedy outputs must be bit-identical: decode
    workers run the unified engine's own compiled program) and
    ``prefill_util`` (fraction of batched-chunk rows doing real work).

    Needs >= 2 visible devices; callers on a single-device host re-exec
    under ``--xla_force_host_platform_device_count`` (bench.py does).
    """
    from distributed_deep_learning_tpu.serve import migrate as migrate_mod
    from distributed_deep_learning_tpu.serve.disagg import DisaggEngine

    model, params = build_model(seed, **(model_kw or {}))
    spec = LoadSpec(**{**DEFAULT_LOAD, **(load_kw or {})})
    cap = paged_max_len(model.max_len, kv_block_size, False, 0)
    trace = make_load(spec, vocab_size=model.vocab_size, seed=seed)

    uni = PagedEngine(model, params, max_slots=max_slots, max_len=cap,
                      kv_block_size=kv_block_size,
                      prefill_chunk=min(prefill_chunk, cap),
                      kv_dtype=kv_dtype)
    dis = DisaggEngine(model, params, prefill_workers=prefill_workers,
                       decode_workers=decode_workers,
                       prefill_streams=prefill_streams,
                       max_slots=max_slots, max_len=cap,
                       kv_block_size=kv_block_size,
                       prefill_chunk=min(prefill_chunk, cap),
                       kv_dtype=kv_dtype, decode_passes=decode_passes,
                       telemetry=telemetry)

    # warm both arms (all compiles land here), then reset to a fresh
    # serving state; the timed runs below retrace NOTHING
    uni.run(list(trace))
    uni.reset()
    dis.run(list(trace))
    dis.reset()
    dis.migrator.stats = migrate_mod.MigrationStats()

    du = uni.run(list(trace))
    dd = dis.run(list(trace), telemetry=telemetry)
    us, ds = du["stats"], dd["stats"]

    # sync-measured migration bandwidth: move one slot's worth of
    # committed blocks prefill->decode a few times with a blocking wait,
    # so seconds are transfer time rather than dispatch time (the run
    # above overlaps migration with the next prefill chunk by design)
    pw, dw = dis.prefill[0], dis.decode[0]
    nb = pw.eng.blocks_per_slot
    ids = np.arange(nb)
    dis.migrator.stats = migrate_mod.MigrationStats()
    for _ in range(4):
        dw.eng.pools = dis.migrator.migrate(
            pw.eng.pools, dw.eng.pools, ids, ids, device=dw.device,
            sync=True, trace_id="bench")
    sync_stats = dis.migrator.stats
    at_rest_per_block = sync_stats.wire_bytes / sync_stats.blocks

    # the int8 wire's shrink on the same payload (skipped over int8
    # pools, where the at-rest wire already moves int8+scales)
    wire_shrink = None
    if kv_dtype != "int8":
        m8 = migrate_mod.BlockMigrator(nb, wire="int8")
        dw.eng.pools = m8.migrate(pw.eng.pools, dw.eng.pools, ids, ids,
                                  device=dw.device, trace_id="bench")
        wire_shrink = round(
            at_rest_per_block / (m8.stats.wire_bytes / m8.stats.blocks), 3)

    speedup = (round(ds["tokens_per_sec"] / us["tokens_per_sec"], 3)
               if us["tokens_per_sec"] else None)
    ul, dl = us["latency"], ds["latency"]
    return {
        "metric": "disaggregated prefill/decode vs unified paged engine",
        "model": {**DEFAULT_MODEL, **(model_kw or {})},
        "load": {**DEFAULT_LOAD, **(load_kw or {})},
        "prefill_workers": prefill_workers,
        "decode_workers": decode_workers,
        "prefill_streams": prefill_streams,
        "max_slots": max_slots,
        "kv_block_size": kv_block_size,
        "kv_dtype": kv_dtype,
        "decode_passes": decode_passes,
        "errors": len(du["errors"]) + len(dd["errors"]),
        "unified": {
            "tokens_per_sec": round(us["tokens_per_sec"], 2),
            "kv_cache_bytes": us["kv_cache_bytes"],
            "decode_compiles": us["decode_compiles"],
            "chunk_compiles": us["chunk_compiles"],
            "itl_p99_s": ul["itl_p99_s"],
            "ttft_p99_s": ul["ttft_p99_s"],
        },
        "disagg": {
            "tokens_per_sec": round(ds["tokens_per_sec"], 2),
            "kv_cache_bytes": ds["kv_cache_bytes"],
            "decode_compiles": ds["decode_compiles"],
            "chunk_compiles": ds["chunk_compiles"],
            "migrate_gather_compiles": ds["migrate_gather_compiles"],
            "migrate_scatter_compiles": ds["migrate_scatter_compiles"],
            "prefill_util": ds["prefill_util"],
            "prefill_chunk_calls": ds["prefill_chunk_calls"],
            "itl_p99_s": dl["itl_p99_s"],
            "ttft_p99_s": dl["ttft_p99_s"],
            "migration": ds["migration"],
        },
        "speedup": speedup,
        # > 1 means disagg's inter-token gaps are WORSE than unified's
        "itl_p99_ratio": (round(dl["itl_p99_s"] / ul["itl_p99_s"], 3)
                          if ul["itl_p99_s"] else None),
        "token_agreement": round(
            _token_agreement(du["results"], dd["results"]), 4),
        "migration_gbps": round(sync_stats.gb_per_s(), 3),
        "migration_ms_per_move": round(
            1e3 * sync_stats.seconds / sync_stats.moves, 3),
        "wire_bytes_per_block": int(at_rest_per_block),
        "int8_wire_shrink_x": wire_shrink,
    }
