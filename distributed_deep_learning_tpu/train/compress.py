"""Compressed gradient all-reduce for data-parallel training.

Gradient synchronisation traffic is the whole DP communication bill; the
reference pays it in fp32 per parameter per step (its per-param
``all_reduce``, reference ``CNN/main.py:84-89,137-139``).  This module
trades gradient precision for wire bytes (cf. EQuARX, PAPERS.md — XLA-level
quantized all-reduce; here is the framework-level rendition):

* ``bf16`` — gradients cross the wire as bfloat16: HALF the bytes, exponent
  range preserved; the reduction itself accumulates in f32 (psum upcasts on
  TPU), so the only loss is the pre-send mantissa rounding.  Safe default
  for bandwidth-bound DCN data parallelism.
* ``int8`` — common-scale symmetric int8 quantization: every replica scales
  by the GLOBAL max-|g| (one scalar pmax per leaf), rounds to int8, and the
  values reduce as int32 (overflow-free up to 2^24 replicas).  This is the
  EQuARX numerics at framework level — the wire-format win needs compiler
  support, so treat int8 here as the accuracy-emulation / research mode and
  ``bf16`` as the deployment mode.

Implementation note: the normal step (:mod:`.step`) never *sees* its
all-reduce — XLA's partitioner inserts it from shardings.  To compress the
reduction we must own it, so the gradient computation runs inside
``shard_map`` with explicit ``psum``/``pmax`` collectives; outputs (mean
gradients, summed metrics, averaged model state) are replicated exactly
like the standard path, and the optimizer update stays outside, bit-equal
in structure to :func:`.step.make_step_fns`.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_learning_tpu.data.loader import BATCH_AXES
from distributed_deep_learning_tpu.runtime.shmap import shard_map
from distributed_deep_learning_tpu.train.objectives import prediction_metrics
from distributed_deep_learning_tpu.train.state import TrainState


def _psum_bf16(leaf, axes, residual=None):
    """bf16 on the wire, f32 result.  No error feedback (the mantissa
    rounding is unbiased enough that a residual buys nothing)."""
    out = lax.psum(leaf.astype(jnp.bfloat16), axes).astype(leaf.dtype)
    return out if residual is None else (out, residual)


def _psum_int8(leaf, axes, residual=None):
    """Common-scale symmetric int8 values, int32 reduction.

    With ``residual`` (the per-device error-feedback buffer from
    :func:`..parallel.collectives.attach_residual`) last step's
    quantization error is added back before quantizing and the new error
    returned — the applied updates telescope to the true gradient sum,
    so the estimator is unbiased across steps instead of per step."""
    v = leaf if residual is None else leaf + residual
    amax = lax.pmax(jnp.max(jnp.abs(v)), axes)
    scale = jnp.maximum(amax / 127.0, jnp.asarray(1e-30, leaf.dtype))
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
    summed = lax.psum(q.astype(jnp.int32), axes)
    out = (summed.astype(leaf.dtype)) * scale
    if residual is None:
        return out
    return out, v - q.astype(leaf.dtype) * scale


_REDUCERS = {"bf16": _psum_bf16, "int8": _psum_int8}


def make_compressed_step_fns(mesh: Mesh, loss_fn: Callable, *,
                             method: str = "bf16", remat: bool = False,
                             remat_policy: str = "nothing",
                             batch_spec: P = P(BATCH_AXES)):
    """(train_step, eval_step) with a compressed gradient all-reduce.

    Data-parallel only (params/optimizer replicated): compressing a
    reduction only makes sense when there IS a pure gradient all-reduce;
    ZeRO/TP reshape the dataflow instead — the runner rejects those
    combinations.  ``remat``/``remat_policy`` rematerialise the forward
    in backward exactly like :func:`.step.make_step_fns`.
    """
    if method not in _REDUCERS:
        raise ValueError(f"unknown compression {method!r}; "
                         f"choose from {sorted(_REDUCERS)}")
    from distributed_deep_learning_tpu.train.step import _remat_policy

    policy = _remat_policy(remat_policy)  # eager: fail fast on typos
    reduce_leaf = _REDUCERS[method]
    axes = tuple(a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1)
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, batch_spec)

    n = 1
    for a in axes:
        n *= mesh.shape[a]

    def train_step(state: TrainState, x, y):
        # rng None-ness is static (pytree structure); pass the key as an
        # explicit shard_map operand — closures over traced values are not
        has_rng = state.rng is not None
        # error feedback (int8 only): the per-device residual rides in
        # TrainState with a leading per-shard axis, sharded over the
        # batch axes — each replica sees exactly its own buffer
        has_res = method == "int8" and state.comm_residual is not None \
            and bool(axes)
        res_spec = P(BATCH_AXES) if has_res else P()
        key = jax.random.fold_in(state.rng, state.step) if has_rng \
            else jax.random.key(0)

        def compute(params, ms, key, x, y):
            rngs = {"dropout": key} if has_rng else None
            fwd = state.apply_fn
            if remat:
                fwd = jax.checkpoint(lambda p, m, xx: state.apply_fn(
                    p, m, xx, train=True, rngs=rngs), policy=policy)
                pred, new_ms, aux = fwd(params, ms, x)
            else:
                pred, new_ms, aux = fwd(params, ms, x, train=True, rngs=rngs)
            loss = loss_fn(pred, y)
            return loss + aux, (prediction_metrics(pred, y, loss), new_ms)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P(), batch_spec, batch_spec, res_spec),
                 out_specs=(P(), P(), P(), res_spec), check_vma=False)
        def sync_grads(params, ms, key, x, y, res):
            if has_rng and axes:
                # each data shard must draw an INDEPENDENT dropout mask
                # (the GSPMD path masks the global batch in one draw)
                for a in axes:
                    key_local = jax.random.fold_in(key, lax.axis_index(a))
                    key = key_local
            (_, (metrics, new_ms)), g = jax.value_and_grad(
                compute, has_aux=True)(params, ms, key, x, y)
            if axes:
                # local grads are means over the LOCAL shard; compressed
                # psum of those means / n == the global-batch mean
                if has_res:
                    res_local = jax.tree.map(lambda r: jnp.squeeze(r, 0),
                                             res)
                    pairs = jax.tree.map(
                        lambda l, r: reduce_leaf(l, axes, residual=r),
                        g, res_local)
                    is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
                    g = jax.tree.map(lambda t: t[0] / n, pairs,
                                     is_leaf=is_pair)
                    res = jax.tree.map(lambda t: t[1][None], pairs,
                                       is_leaf=is_pair)
                else:
                    g = jax.tree.map(lambda l: reduce_leaf(l, axes) / n, g)
                metrics = {  # loss is a shard mean → average; counts sum
                    "loss": lax.psum(metrics["loss"], axes) / n,
                    "correct": lax.psum(metrics["correct"], axes),
                    "count": lax.psum(metrics["count"], axes),
                }
                new_ms = jax.tree.map(
                    lambda s: lax.psum(s.astype(jnp.float32), axes) / n
                    if jnp.issubdtype(s.dtype, jnp.floating) else s, new_ms)
            return g, metrics, new_ms, res

        res_in = state.comm_residual if has_res else jnp.zeros(())
        grads, metrics, new_ms, new_res = sync_grads(
            state.params, state.model_state, key, x, y, res_in)
        state = state.apply_gradients(grads, model_state=new_ms)
        if has_res:
            state = state.replace(comm_residual=new_res)
        return state, metrics

    def eval_step(state: TrainState, x, y):
        pred, _, _ = state.apply_fn(state.params, state.model_state, x,
                                    train=False)
        return prediction_metrics(pred, y, loss_fn(pred, y))

    # state shardings are inferred (None), not pinned replicated: the
    # error-feedback residual is per-device state that must stay sharded
    # over the batch axes while everything else stays replicated
    train_step = jax.jit(train_step,
                         in_shardings=(None, batch_sh, batch_sh),
                         out_shardings=(None, repl),
                         donate_argnums=(0,))
    eval_step = jax.jit(eval_step,
                        in_shardings=(None, batch_sh, batch_sh),
                        out_shardings=repl)
    return train_step, eval_step
