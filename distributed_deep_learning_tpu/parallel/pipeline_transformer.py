"""Pipeline-parallel transformer: embed → SPMD-pipelined trunk → head.

The composition rule for real models on the SPMD pipeline
(:mod:`.spmd_pipeline`): the *homogeneous* part — a stack of identical
transformer blocks — runs inside the pipeline over the ``stage`` mesh axis,
while the heterogeneous ends (embedding, norm, LM head) run outside it with
ordinary shardings.  Each stage holds ``num_layers / num_stages``
consecutive blocks; stage parameters stack along a leading axis sharded
over ``stage``, so every device stores and runs only its own blocks —
pipeline parallelism for the transformer trunk in one XLA program, forward
AND backward (scan/ppermute transpose).

Composes with data parallelism: the microbatch dimension stays sharded
over ``data``/``fsdp`` inside the pipeline.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_deep_learning_tpu.models.transformer import TransformerLayer
from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
    spmd_pipeline, stack_stage_params)


class TrunkStage(nn.Module):
    """``layers_per_stage`` consecutive pre-LN blocks — one pipeline stage.

    Train-time stochasticity: the pipeline derives a per-(stage,
    microbatch) PRNG key (``spmd_pipeline``'s ``rng``), handed to
    ``apply`` as the ``dropout`` stream — Flax then folds it per Dropout
    site, so masks are distinct across stages, blocks and microbatches yet
    deterministic per seed.  ``attention_fn`` plugs the Pallas flash
    kernel into every block (padding masks are not threaded through the
    pipeline — pad to microbatch boundaries instead).
    """

    layers_per_stage: int
    num_heads: int = 8
    mlp_dim: int = 2048
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_fn: object = None
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i in range(self.layers_per_stage):
            x = TransformerLayer(self.num_heads, self.mlp_dim,
                                 dropout_rate=self.dropout_rate,
                                 causal=self.causal,
                                 dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 name=f"block_{i}")(x, train=train)
        return x


class PipelinedTrunk:
    """A transformer trunk split over the mesh's ``stage`` axis."""

    def __init__(self, num_layers: int, mesh: Mesh, *, num_heads: int = 8,
                 mlp_dim: int = 2048, causal: bool = False,
                 dtype: jnp.dtype = jnp.float32,
                 microbatch_size: Optional[int] = None,
                 attention_fn=None, dropout_rate: float = 0.0):
        self.mesh = mesh
        self.n_stages = mesh.shape["stage"]
        if num_layers % self.n_stages:
            raise ValueError(f"{num_layers} layers not divisible into "
                             f"{self.n_stages} stages")
        self.microbatch_size = microbatch_size
        self.stage = TrunkStage(num_layers // self.n_stages, num_heads,
                                mlp_dim, causal, dtype, attention_fn,
                                dropout_rate)

    def init(self, rng: jax.Array, example: jnp.ndarray) -> Any:
        """Stacked per-stage params (leading dim = stage; shard it)."""
        params = [
            self.stage.init(jax.random.fold_in(rng, i), example)["params"]
            for i in range(self.n_stages)]
        return stack_stage_params(params)

    def stage_fn(self):
        """One stage's pure ``(params, x) -> y`` — the unit both pipeline
        schedules (GPipe scan and 1F1B) apply per tick."""
        return lambda p, a: self.stage.apply({"params": p}, a)

    def stage_fn_train(self):
        """Stochastic variant ``(params, x, key) -> y`` for runs with
        dropout (the pipeline derives ``key`` per stage+microbatch)."""
        return lambda p, a, key: self.stage.apply(
            {"params": p}, a, train=True, rngs={"dropout": key})

    def apply(self, stacked_params: Any, x: jnp.ndarray,
              rng: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """(B, T, d) → (B, T, d) through all stages, pipelined; pass
        ``rng`` to activate dropout."""
        if rng is not None:
            return spmd_pipeline(
                self.stage_fn_train(), stacked_params, x, mesh=self.mesh,
                microbatch_size=self.microbatch_size, rng=rng)
        return spmd_pipeline(
            self.stage_fn(), stacked_params, x, mesh=self.mesh,
            microbatch_size=self.microbatch_size)

    def apply_sequential(self, stacked_params: Any, x: jnp.ndarray
                         ) -> jnp.ndarray:
        """Reference semantics: the same stages applied one after another
        without the pipeline (for equivalence tests; deterministic)."""
        for s in range(self.n_stages):
            p = jax.tree.map(lambda l, s=s: l[s], stacked_params)
            x = self.stage.apply({"params": p}, x)
        return x
