"""Offline packer: any workload dataset → one mmap-able binary artifact.

One-off preprocessing (the ``tokens.npy`` pattern, generalised): build a
workload's dataset exactly as training would — ImageFolder / PCB decode
through the threaded decoder, PdM/MQTT CSV windows, token rows — stream
it through ``batch()`` in chunks, and write a ``data/packed.py`` cache.
Training then runs with ``--packed-cache`` and assembles batches from the
memory-mapped file with zero per-sample Python work (~2 orders of
magnitude faster than per-epoch JPEG decode; ``scripts/feed_bench.py``
measures it).

    JAX_PLATFORMS=cpu python scripts/pack_dataset.py \\
        --workload resnet --data-dir /data/imagenet --image-size 224 \\
        -w 16 --out /data/imagenet.ddlpack

Prints one JSON line describing the artifact (samples, shapes, dtypes,
bytes, pack rate).  Packing is atomic — a crash leaves no partial file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _script_env() -> None:
    """Repo import path + CPU jax (packing is host work; never grab a
    TPU).  main()-only, so importing this module (the tests reuse
    build_source) has no side effects on the importer's jax state."""
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_source(args):
    """The SAME dataset object the workload would train on (so the packed
    batches are bit-identical to the eager run's)."""
    from distributed_deep_learning_tpu.utils.config import Config
    from distributed_deep_learning_tpu.workloads import get_spec

    config = Config(data_dir=args.data_dir, image_size=args.image_size,
                    num_workers=args.workers, seed=args.seed)
    return get_spec(args.workload).build_dataset(config)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="pack a workload dataset into an mmap-able sample "
                    "cache (train with --packed-cache)")
    p.add_argument("--workload", default="resnet",
                   help="whose dataset builder to pack (resnet, cnn, "
                        "lstm, mlp, ... — must match the training run)")
    p.add_argument("--data-dir", default=None,
                   help="real-data root (ImageFolder tree, PCB tree, CSV "
                        "dir); omitted = the workload's synthetic twin")
    p.add_argument("--image-size", type=int, default=224,
                   help="square decode size for image sources")
    p.add_argument("-w", "--workers", type=int, default=0,
                   help="decode threads while packing (0 = workload "
                        "default)")
    p.add_argument("--out", required=True,
                   help="artifact path (convention: *.ddlpack)")
    p.add_argument("--dtype", choices=["auto", "uint8", "source"],
                   default="auto",
                   help="feature storage: auto stores uint8 when lossless "
                        "(4x smaller), source keeps the decode dtype, "
                        "uint8 forces it (errors if lossy)")
    p.add_argument("--chunk", type=int, default=256,
                   help="samples decoded/written per chunk")
    p.add_argument("--limit", type=int, default=0,
                   help="pack only the first N samples (CI smoke)")
    p.add_argument("--seed", type=int, default=42)
    args = p.parse_args(argv)

    from distributed_deep_learning_tpu.data.packed import pack_dataset

    t0 = time.perf_counter()
    dataset = build_source(args)
    t_build = time.perf_counter() - t0

    import numpy as np

    indices = None
    if args.limit:
        indices = np.arange(min(args.limit, len(dataset)))
    t0 = time.perf_counter()
    header = pack_dataset(
        dataset, args.out, dtype=args.dtype, chunk_size=args.chunk,
        indices=indices,
        meta={"workload": args.workload, "data_dir": args.data_dir,
              "image_size": args.image_size, "seed": args.seed,
              "limit": args.limit or None})
    t_pack = time.perf_counter() - t0
    n = header["num_samples"]
    print(json.dumps({
        "out": os.path.abspath(args.out),
        "num_samples": n,
        "feature_shape": header["feature_shape"],
        "feature_dtype": header["feature_dtype"],
        "target_shape": header["target_shape"],
        "target_dtype": header["target_dtype"],
        "bytes": header["total_bytes"],
        "build_seconds": round(t_build, 2),
        "pack_seconds": round(t_pack, 2),
        "samples_per_sec": round(n / t_pack, 1) if t_pack else None,
    }))
    return 0


if __name__ == "__main__":
    _script_env()
    sys.exit(main())
