"""Pipeline-parallel GPT training through the CLI — one command.

The `-m pipeline` mode runs the decoder trunk as an SPMD pipeline:
`--nstages` sets the mesh's `stage` axis, layers stack into per-stage
parameter shards, and microbatches flow through a 1F1B schedule inside
ONE compiled XLA program (`shard_map` + `ppermute` stage rotation +
`lax.scan` over schedule ticks).  Swap `--pipeline-schedule interleaved
--virtual-stages 2` for virtual-stage interleaving; `gpipe` for plain
fill-drain.

    python examples/04_pipelined_gpt_cli.py          # 8 emulated devices
    python examples/04_pipelined_gpt_cli.py --tpu    # the machine's chips

Equivalent shell command (on a real multi-chip host):

    python -m distributed_deep_learning_tpu gpt -l 4 -s 64 -e 2 -b 16 \
        -m pipeline --nstages 4 --pipeline-schedule 1f1b
"""

import os
import runpy
import sys
import tempfile

import _bootstrap  # noqa: F401  (must precede jax import)

metrics = os.path.join(tempfile.mkdtemp(), "metrics.jsonl")
os.environ.setdefault("DDL_DATA_LIMIT", "256")  # keep the demo quick
sys.argv = ["ddl", "gpt", "-l", "4", "-s", "64", "-e", "2", "-b", "16",
            "-m", "pipeline", "--nstages", "4",
            "--pipeline-schedule", "1f1b", "--metrics-file", metrics]
runpy.run_module("distributed_deep_learning_tpu", run_name="__main__")

trains = _bootstrap.train_phase_ends(metrics)
assert trains[-1]["loss"] < trains[0]["loss"], "pipeline run did not learn"
print(f"pipelined train loss: {trains[0]['loss']:.4f} -> "
      f"{trains[-1]['loss']:.4f}")
