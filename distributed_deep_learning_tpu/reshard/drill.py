"""The shrink drill: kill K of N workers, re-plan, reshard, continue.

The proof the ISSUE demands, runnable on the 8-device CPU test mesh:

1. train one epoch on mesh A (``data=8``) with ZeRO-1 sharded optimizer
   state, checkpointing at the epoch boundary (topology manifest
   included);
2. :meth:`~..utils.chaos.ChaosPlan.shrink_topology` seed-kills ``kill``
   workers;
3. :func:`~.replan.choose_plan` re-plans for the survivors (6 of 8 — a
   non-power-of-2 mesh — exercising exactly the splits a power-of-2-only
   implementation gets wrong);
4. :func:`~.restore.restore_resharded` restores the verified checkpoint
   onto the new mesh/spec;
5. gates: restored params AND resharded optimizer state allclose against
   a same-topology restore, and the elastic continuation
   (``fit_with_recovery`` + ``make_restore_fn`` — the real wiring, not a
   shortcut) reaches an epoch-2 loss allclose to the uninterrupted
   topology's.

The global batch is 96, not the repo-default 64: every full-mesh plan
has batch-parallel degree == device count, and 64 does not divide over 6
survivors — 96 divides over 8, 6 and 4, so the drill exercises a *true*
8→6 re-plan rather than silently stepping down to 4.
"""

from __future__ import annotations

import time


def _zero_axis(mesh) -> str:
    return "fsdp" if dict(mesh.shape).get("fsdp", 1) > 1 else "data"


def _epoch_loss(history, epoch: int, phase: str = "train") -> float:
    for h in history:
        if h.phase == phase and h.epoch == epoch:
            return float(h.loss)
    raise LookupError(f"no {phase} record for epoch {epoch}")


def run_shrink_drill(seed: int = 0, kill: int = 2, *, n_devices: int = 8,
                     batch: int = 96, hidden: int = 512, rows: int = 1024,
                     min_leaf_size: int = 2 ** 14, method: str = "auto",
                     ) -> dict:
    """Run the full kill→re-plan→reshard→continue chain; return the
    ``reshard`` drill record (all gates as booleans, wall times in
    seconds).  Deterministic under ``seed``."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
    from distributed_deep_learning_tpu.data.loader import make_loaders
    from distributed_deep_learning_tpu.data.splits import train_val_test_split
    from distributed_deep_learning_tpu.models.mlp import MLP
    from distributed_deep_learning_tpu.parallel.zero import zero1_state_spec
    from distributed_deep_learning_tpu.reshard.replan import choose_plan
    from distributed_deep_learning_tpu.reshard.restore import (
        make_restore_fn, restore_resharded)
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    from distributed_deep_learning_tpu.train.elastic import fit_with_recovery
    from distributed_deep_learning_tpu.train.loop import fit
    from distributed_deep_learning_tpu.train.objectives import (
        cross_entropy_loss)
    from distributed_deep_learning_tpu.train.state import create_train_state
    from distributed_deep_learning_tpu.train.step import make_step_fns
    from distributed_deep_learning_tpu.tune.artifact import plan_hash
    from distributed_deep_learning_tpu.tune.memory import (ModelGeometry,
                                                           hbm_budget)
    from distributed_deep_learning_tpu.utils.chaos import ChaosPlan
    from distributed_deep_learning_tpu.utils.checkpoint import Checkpointer

    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise RuntimeError(f"shrink drill needs {n_devices} devices, "
                           f"have {len(devices)}")
    ds = synthetic_mqtt(rows, seed=21)
    splits = train_val_test_split(len(ds), seed=42)
    model = MLP(hidden_size=hidden)

    def setup(mesh):
        """Per-mesh training kit.  One pristine host-side state per mesh:
        the ZeRO spec pytree carries the state's static fields
        (apply_fn/tx), so spec, step fns and every placed copy must share
        one state instance; ``make_state`` re-places fresh device copies
        of it (the pristine leaves are never donated)."""
        from distributed_deep_learning_tpu.train.step import place_state

        pristine = create_train_state(model, jax.random.key(7),
                                      jnp.zeros((1, 48)), optax.adam(1e-3))
        # host-side leaves: device_put then always copies, so a donated
        # training step can never delete the pristine buffers
        pristine = jax.device_get(pristine)
        spec = zero1_state_spec(pristine, mesh, axis=_zero_axis(mesh),
                                min_leaf_size=min_leaf_size)
        train_step, eval_step = make_step_fns(mesh, cross_entropy_loss,
                                              state_spec=spec)
        loaders = make_loaders(ds, splits, batch, mesh)
        return spec, train_step, eval_step, loaders, \
            lambda: place_state(pristine, mesh, spec)

    record: dict = {"metric": "shrink drill", "seed": seed,
                    "n_devices": n_devices, "batch": batch}

    # --- mesh A: train epoch 1, checkpoint with topology manifest ----------
    mesh_a = build_mesh({"data": n_devices}, devices)
    spec_a, train_a, eval_a, loaders_a, state_a_fn = setup(mesh_a)
    with tempfile.TemporaryDirectory() as d:
        ck = Checkpointer(d)
        state_a, _ = fit(state_a_fn(), train_a, eval_a,
                         *loaders_a, epochs=1, checkpointer=ck)
        ck.wait_until_finished()

        # --- kill K of N (seeded, replayable) ------------------------------
        survivors, dead = ChaosPlan.shrink_topology(devices, kill=kill,
                                                    seed=seed)
        record["killed"] = dead
        record["survivors"] = len(survivors)

        # --- re-plan for the survivors via tune/ ---------------------------
        params = jax.device_get(state_a.params)
        geom = ModelGeometry(
            param_count=sum(int(np.prod(np.shape(p)))
                            for p in jax.tree.leaves(params)),
            num_layers=1, layer_act_elems_per_example=hidden * 4,
            extra_act_elems_per_example=48)
        plan = choose_plan(
            len(survivors), batch, geom=geom,
            budget_bytes=hbm_budget(survivors),
            space_options={"dtypes": ("float32",),
                           "grad_accum_options": (1,),
                           "attention_options": ("auto",),
                           "zero_options": ("1",),
                           "compress_options": ("none",)})
        record["plan"] = plan.describe()
        record["plan_hash"] = plan_hash(plan)
        record["plan_devices"] = plan.n_devices
        record["non_power_of_two"] = any(
            s & (s - 1) for _, s in plan.mesh)

        # --- mesh B on the survivors; reshard-restore ----------------------
        mesh_b = build_mesh(plan.mesh_dict(), survivors[:plan.n_devices])
        spec_b, train_b, eval_b, loaders_b, state_b_fn = setup(mesh_b)
        start = time.perf_counter()
        restored_b, step_b, info = restore_resharded(
            ck, state_b_fn(), mesh=mesh_b, state_spec=spec_b, method=method)
        record["restore_seconds"] = round(time.perf_counter() - start, 4)
        record["restore_mode"] = info.get("mode")
        record["restored_step"] = step_b

        # --- gate: allclose vs a same-topology restore ---------------------
        restored_a, _ = ck.restore_verified(state_a_fn())

        def tree_allclose(x, y, rtol=1e-6, atol=1e-8):
            xs = jax.tree.leaves(jax.device_get(x))
            ys = jax.tree.leaves(jax.device_get(y))
            return len(xs) == len(ys) and all(
                np.allclose(np.asarray(a), np.asarray(b),
                            rtol=rtol, atol=atol)
                for a, b in zip(xs, ys))

        record["params_allclose"] = bool(
            restored_b is not None and
            tree_allclose(restored_a.params, restored_b.params))
        record["opt_state_allclose"] = bool(
            restored_b is not None and
            tree_allclose(restored_a.opt_state, restored_b.opt_state))

        # --- gate: continued loss matches the unshrunk topology ------------
        _, hist_a = fit(restored_a, train_a, eval_a, *loaders_a,
                        epochs=2, start_epoch=2)
        loss_a = _epoch_loss(hist_a, 2)

        # the REAL elastic wiring: fit_with_recovery restores through the
        # resharding restore_fn, then continues on the surviving mesh
        _, hist_b = fit_with_recovery(
            state_b_fn, train_b, eval_b, loaders_b, epochs=2,
            checkpointer=ck,
            restore_fn=make_restore_fn(ck, mesh_b, spec_b, method=method))
        loss_b = _epoch_loss(hist_b, 2)
        record["loss_epoch2_same_topology"] = round(loss_a, 6)
        record["loss_epoch2_resharded"] = round(loss_b, 6)
        record["loss_allclose"] = bool(np.allclose(loss_b, loss_a,
                                                   rtol=5e-3, atol=1e-5))
        ck.close()

    record["drill_passed"] = bool(
        record["params_allclose"] and record["opt_state_allclose"]
        and record["loss_allclose"] and record["restored_step"] == 1
        and record["restore_mode"] in ("chunked", "gather"))
    return record
