"""Re-plan for the surviving topology before a resharding restore.

When an elastic restart comes up on fewer (or differently-arranged)
devices than the checkpoint was written on, *something* has to pick the
new mesh.  This module delegates that to the existing ``tune/`` machinery
instead of inventing a second planner: :func:`choose_plan` enumerates the
legal lattice for the surviving device count (same legality rules as
``--tune``), prunes with the analytic memory model when a geometry is
available, and ranks by the analytic cost score; :func:`replan_config` can
optionally confirm the analytic pick with a couple of measured trial steps
(``run_search``) before committing.

The global batch size is held fixed across the re-plan — convergence
math (LR schedule, steps/epoch, accumulation) must not silently change
because hardware died.  If the surviving count cannot divide the batch
(e.g. batch 64 on 6 devices), the planner steps down to the largest
device subset that can, which is exactly what a human operator would do.
"""

from __future__ import annotations

import glob
import json
import os
import re

from distributed_deep_learning_tpu.reshard.manifest import Topology
from distributed_deep_learning_tpu.tune.artifact import plan_hash
from distributed_deep_learning_tpu.tune.memory import hbm_budget, prune_plans
from distributed_deep_learning_tpu.tune.search import (analytic_score,
                                                       model_geometry,
                                                       run_search)
from distributed_deep_learning_tpu.tune.space import (Plan, apply_plan,
                                                      enumerate_plans)
from distributed_deep_learning_tpu.utils.config import Config


def _pinned_options(config: Config) -> dict:
    """Restrict the lattice to the knobs the run was already using — a
    restart should change the mesh, not the numerics."""
    return {
        "dtypes": (config.dtype,),
        "grad_accum_options": (config.grad_accum,),
        "attention_options": (config.attention,),
        "zero_options": (config.zero,),
        "compress_options": (config.grad_compress,),
    }


def choose_plan(n_devices: int, batch_size: int, *, geom=None,
                budget_bytes: int | None = None, allow_fewer: bool = True,
                space_options: dict | None = None) -> Plan:
    """Best legal plan for at most ``n_devices`` devices at ``batch_size``.

    Walks device counts downward (``allow_fewer``) so a batch that cannot
    divide over the survivors still finds a home on the largest usable
    subset — 6 survivors at batch 64 re-plan onto 4.  Raises ``ValueError``
    when no subset admits a legal plan.
    """
    opts = dict(space_options or {})
    counts = range(n_devices, 0, -1) if allow_fewer else (n_devices,)
    for m in counts:
        plans = enumerate_plans(m, batch_size, **opts)
        if plans and geom is not None:
            plans, _ = prune_plans(plans, geom, batch_size, budget_bytes)
        if not plans:
            continue
        # Rank: analytic cost, then widest data axis, then stable hash.
        return min(plans, key=lambda p: (analytic_score(p),
                                         -p.mesh_dict().get("data", 1),
                                         plan_hash(p)))
    raise ValueError(
        f"no legal plan for <= {n_devices} device(s) at batch {batch_size}"
        f" under {opts or 'default lattice options'}")


def replan_config(spec, config: Config, devices, *, dataset=None,
                  logger=None, measure_trials: bool = False,
                  ) -> tuple[Config, Plan]:
    """Pick a plan for ``devices`` and realise it on ``config``.

    Analytic by default (restart latency matters more than the last few
    percent of throughput); ``measure_trials=True`` runs a tiny
    ``run_search`` (2 steps, <=4 trials, knobs pinned) and falls back to
    the analytic pick if measurement fails for any reason — a re-plan
    must never strand the restart it exists to save.
    """
    geom = None
    try:
        if spec is not None:
            if dataset is None:
                dataset = spec.build_dataset(config)
            geom = model_geometry(spec, config, dataset)
    except Exception:
        geom = None  # analytic model is an optimisation, never a blocker
    budget = hbm_budget(list(devices))

    if measure_trials and spec is not None:
        try:
            result = run_search(spec, config, devices=list(devices),
                                dataset=dataset, logger=logger,
                                trial_steps=2, max_trials=4,
                                space_options=_pinned_options(config))
            plan = result.best
            if logger:
                logger.info(f"reshard: measured re-plan picked "
                            f"{plan.describe()} ({plan_hash(plan)})")
            return apply_plan(config, plan), plan
        except Exception as exc:
            if logger:
                logger.info(f"reshard: measured re-plan failed "
                            f"({type(exc).__name__}: {exc}); "
                            "using the analytic planner")

    try:
        plan = choose_plan(len(list(devices)), config.batch_size, geom=geom,
                           budget_bytes=budget,
                           space_options=_pinned_options(config))
    except ValueError:
        # Pinned knobs admitted nothing (e.g. zero=fsdp on a 1-wide shard
        # axis): relax to the default lattice rather than refuse to restart.
        plan = choose_plan(len(list(devices)), config.batch_size, geom=geom,
                           budget_bytes=budget)
    if logger:
        logger.info(f"reshard: re-planned for {len(list(devices))} "
                    f"device(s): {plan.describe()} ({plan_hash(plan)})")
    return apply_plan(config, plan), plan


_MANIFEST_RE = re.compile(r"manifest-(\d+)\.json$")


def latest_topology(checkpoint_dir: str) -> tuple[int | None,
                                                  Topology | None]:
    """Newest saved step's topology, read straight from the sidecar files —
    no orbax manager, safe to call before any mesh exists.

    Returns ``(step, Topology)``; ``(step, None)`` when the newest sidecar
    predates topology manifests (legacy); ``(None, None)`` when nothing
    readable is saved."""
    candidates = []
    for path in glob.glob(os.path.join(checkpoint_dir, "manifest-*.json")):
        m = _MANIFEST_RE.search(os.path.basename(path))
        if m:
            candidates.append((int(m.group(1)), path))
    for step, path in sorted(candidates, reverse=True):
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        return step, Topology.from_json(payload.get("topology"))
    return None, None


def resolve_restart_topology(spec, config: Config, devices, logger, *,
                             dataset=None) -> Config:
    """The ``--reshard`` startup hook: decide this restart's mesh *before*
    the trainer builds it.

    * ``--target-mesh`` wins outright (operator knows best).
    * Nothing saved yet, or a legacy checkpoint with no topology manifest:
      leave the config alone (warn for legacy — the restore will treat it
      as same-topology).
    * Saved topology matches what this run would build anyway: no-op.
    * Otherwise: re-plan for the surviving devices via ``tune/``.
    """
    if config.target_mesh:
        if logger:
            logger.info("reshard: explicit --target-mesh "
                        f"{config.target_mesh}; skipping re-plan")
        return config.replace(mesh_shape=dict(config.target_mesh))
    if not config.checkpoint_dir:
        return config
    step, topo = latest_topology(config.checkpoint_dir)
    if step is None:
        return config  # fresh run: nothing to reshard from
    if topo is None:
        if logger:
            logger.info(f"reshard: checkpoint step {step} predates topology "
                        "manifests; assuming same topology (legacy)")
        return config
    if config.mesh_shape:
        # Operator pinned a mesh with --mesh; the resharding restore
        # handles any mismatch against the saved topology.
        return config
    n = len(list(devices))
    saved = dict(topo.normalized_mesh())
    if topo.n_devices == n and saved == {"data": n}:
        return config  # the default data=N mesh — same topology, no re-plan
    if logger:
        logger.info(f"reshard: saved topology {topo.describe()} != "
                    f"{n} surviving device(s); re-planning via tune/")
    new_config, _plan = replan_config(spec, config, list(devices),
                                     dataset=dataset, logger=logger)
    return new_config
