"""--elastic / --heartbeat-dir behind the CLI: checkpointed restart wired
into run_workload (the reference's failure model is 'any rank failure hangs
the job', reference CNN/main.py:183-184; this is the recover path)."""

import numpy as np
import pytest

import distributed_deep_learning_tpu.train.elastic as elastic_mod
from distributed_deep_learning_tpu.utils.config import (Config,
                                                        DistributedEnv, Mode,
                                                        parse_args)
from distributed_deep_learning_tpu.utils.failures import WorkerFailure
from distributed_deep_learning_tpu.workloads.base import run_workload
from distributed_deep_learning_tpu.workloads.mlp import SPEC as MLP_SPEC


def test_cli_parses_elastic_flags():
    c = parse_args(["--elastic", "--checkpoint-dir", "/tmp/ck",
                    "--heartbeat-dir", "/tmp/hb",
                    "--heartbeat-timeout", "7.5"], workload="mlp")
    assert c.elastic and c.checkpoint_dir == "/tmp/ck"
    assert c.heartbeat_dir == "/tmp/hb" and c.heartbeat_timeout == 7.5


def test_elastic_requires_checkpoint_dir(monkeypatch):
    monkeypatch.setenv("DDL_DATA_LIMIT", "128")
    config = Config(mode=Mode.DATA, epochs=1, batch_size=32, elastic=True)
    with pytest.raises(ValueError, match="checkpoint-dir"):
        run_workload(MLP_SPEC, config)


def test_elastic_recovers_through_cli(tmp_path, monkeypatch):
    """A runtime error on the first attempt restarts from the checkpoint
    and the run completes — all through run_workload."""
    monkeypatch.setenv("DDL_DATA_LIMIT", "256")
    real_fit = elastic_mod.fit
    calls = {"n": 0}

    def flaky_fit(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected device failure")
        return real_fit(*args, **kwargs)

    monkeypatch.setattr(elastic_mod, "fit", flaky_fit)
    config = Config(mode=Mode.DATA, epochs=2, batch_size=32, elastic=True,
                    checkpoint_dir=str(tmp_path / "ck"))
    _, history = run_workload(MLP_SPEC, config)
    assert calls["n"] == 2  # failed once, recovered, finished
    phases = [h.phase for h in history]
    assert phases.count("train") == 2 and "test" in phases
    assert np.isfinite(history[0].loss)


def test_elastic_detects_dead_peer_via_heartbeats(tmp_path, monkeypatch):
    """World size 2 with a never-beating rank 1: the CLI-wired monitor
    raises WorkerFailure instead of hanging; the peer STAYING dead makes
    the retry die identically at the same resume point, which fails fast
    as a restart loop (ISSUE 3) with the WorkerFailure chained."""
    monkeypatch.setenv("DDL_DATA_LIMIT", "128")
    # a 2-process env would trigger jax.distributed.initialize, which the
    # already-initialised test process cannot do — the monitor wiring under
    # test only needs the declared world size
    import distributed_deep_learning_tpu.workloads.base as base_mod

    monkeypatch.setattr(base_mod, "initialize_runtime", lambda c: None)
    config = Config(
        mode=Mode.DATA, epochs=1, batch_size=32, elastic=True,
        checkpoint_dir=str(tmp_path / "ck"),
        heartbeat_dir=str(tmp_path / "hb"), heartbeat_timeout=0.2,
        distributed=DistributedEnv(process_id=0, num_processes=2))
    from distributed_deep_learning_tpu.train.elastic import RestartLoopError

    with pytest.raises(RestartLoopError) as e:
        run_workload(MLP_SPEC, config)
    assert isinstance(e.value.__cause__, WorkerFailure)
