"""Disaggregated serving (ISSUE 16): KV-block migration + prefill/decode
split.

The load-bearing guarantees this PR adds on top of the paged serving
stack:

* device-to-device block migration is LOSSLESS at rest — fp32, bf16 and
  int8+scales pools all round-trip bit-exactly through the gather /
  (chunked device_put) / scatter chain, and a payload corrupted in
  flight trips the end-to-end digest (``MigrationError``), never a
  silent wrong answer;
* the disaggregated engine (prefill worker pool + decode worker pool on
  separate devices, handoff via migration) produces greedy outputs
  BIT-IDENTICAL to the unified :class:`PagedEngine` on the same trace —
  the decode workers literally run the unified engine's own compiled
  decode program;
* preemption's ``migrate='device'`` spill path resumes bit-identically
  to the host-npz path it upgrades (and to the uncontended reference);
* all of it compile-once: batched prefill, decode, migration gather and
  scatter each trace exactly once per worker;
* the new CLI knobs (``--disagg``, ``--prefill-workers``, ``--migrate``)
  reject bad combinations at parse time with actionable messages.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.models.transformer import CausalLM
from distributed_deep_learning_tpu.parallel.collectives import wire_bytes
from distributed_deep_learning_tpu.serve import migrate as migrate_mod
from distributed_deep_learning_tpu.serve.disagg import DisaggEngine
from distributed_deep_learning_tpu.serve.engine import PagedEngine
from distributed_deep_learning_tpu.serve.migrate import (BlockMigrator,
                                                         MigrationError,
                                                         clone_prefix,
                                                         tree_digest)
from distributed_deep_learning_tpu.serve.scheduler import Request
from distributed_deep_learning_tpu.utils.config import parse_args

MODEL = dict(vocab_size=61, num_layers=1, d_model=32, num_heads=4,
             mlp_dim=64, max_len=48)


@functools.lru_cache(maxsize=None)
def _shared():
    model = CausalLM(**MODEL)
    toks = jnp.ones((1, 4), jnp.int32)
    return model, model.init(jax.random.key(1), toks)["params"]


def _req(uid, prompt_len=6, new=8, tick=0, prio=1, seed=None):
    rng = np.random.default_rng(uid if seed is None else seed)
    return Request(uid=uid,
                   prompt=rng.integers(1, MODEL["vocab_size"],
                                       size=prompt_len).astype(np.int64),
                   max_new_tokens=new, arrival_tick=tick, priority=prio)


def _mixed_trace(n=10, shared_len=9):
    """Mixed lengths incl. a shared-prefix cluster (the migration and
    prefix-index paths all get exercised)."""
    rng = np.random.default_rng(7)
    shared = rng.integers(1, MODEL["vocab_size"], shared_len)
    reqs = []
    for uid in range(n):
        plen = int(rng.integers(4, 20))
        prompt = rng.integers(1, MODEL["vocab_size"], plen)
        if uid % 2:
            prompt = np.concatenate([shared, prompt])
        reqs.append(Request(uid=uid, prompt=prompt.astype(np.int64),
                            max_new_tokens=int(rng.integers(3, 10)),
                            arrival_tick=uid // 3))
    return reqs


def _engine_with_committed(kv_dtype=None, n=3):
    """A unified engine that has served a few requests, so its pools
    hold real committed KV — the migration payload fixture."""
    model, params = _shared()
    eng = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, kv_dtype=kv_dtype)
    eng.run([_req(u, prompt_len=12, new=4) for u in range(n)])
    return eng


# --- migration bit-exactness ------------------------------------------


@pytest.mark.parametrize("kv_dtype", [None, "bf16", "int8"])
def test_migration_round_trip_bit_exact(kv_dtype):
    eng = _engine_with_committed(kv_dtype)
    dst = PagedEngine(*_shared(), max_slots=2, kv_block_size=8,
                      prefill_chunk=8, kv_dtype=kv_dtype)
    mig = BlockMigrator(eng.blocks_per_slot)
    ids = np.arange(2)  # two committed blocks

    def rows(pools):  # block-major leaves only (pools also carry 0-dim
        return [np.asarray(leaf[:2])  # cache-index scalars)
                for leaf in jax.tree.leaves(pools)
                if getattr(leaf, "ndim", 0) >= 1]

    before = rows(eng.pools)
    dst.pools = mig.migrate(eng.pools, dst.pools, ids, ids,
                            device=jax.local_devices()[1], verify=True)
    for b, a in zip(before, rows(dst.pools)):
        assert b.dtype == a.dtype
        np.testing.assert_array_equal(b, a)
    assert mig.stats.moves == 1 and mig.stats.hops == 1
    assert mig.stats.verified == 1 and mig.stats.failed == 0


def test_migration_digest_catches_in_flight_corruption():
    eng = _engine_with_committed()
    dst = PagedEngine(*_shared(), max_slots=2, kv_block_size=8,
                      prefill_chunk=8)
    mig = BlockMigrator(eng.blocks_per_slot)

    def flip(payload):
        leaves, treedef = jax.tree.flatten(payload)
        leaves[0] = leaves[0].at[0].add(1.0)
        return jax.tree.unflatten(treedef, leaves)

    with pytest.raises(MigrationError, match="digest"):
        mig.migrate(eng.pools, dst.pools, np.arange(2), np.arange(2),
                    device=jax.local_devices()[1], verify=True,
                    chaos=flip)
    assert mig.stats.failed == 1


def test_migration_compile_once_across_moves_and_id_sets():
    eng = _engine_with_committed()
    dst = PagedEngine(*_shared(), max_slots=2, kv_block_size=8,
                      prefill_chunk=8)
    mig = BlockMigrator(eng.blocks_per_slot)
    for src in ([0, 1], [2, 3], [1, 2]):  # same width, new ids
        dst.pools = mig.migrate(eng.pools, dst.pools,
                                np.asarray(src), np.arange(2),
                                device=jax.local_devices()[1])
    assert mig.compiles == 2  # one gather trace + one scatter trace
    assert mig._gather.traces == 1 and mig._scatter.traces == 1
    assert mig.stats.moves == 3


def test_int8_wire_shrinks_bytes_on_fp32_pools():
    eng = _engine_with_committed()
    dst = PagedEngine(*_shared(), max_slots=2, kv_block_size=8,
                      prefill_chunk=8)
    at_rest = BlockMigrator(eng.blocks_per_slot)
    dst.pools = at_rest.migrate(eng.pools, dst.pools, np.arange(2),
                                np.arange(2))
    i8 = BlockMigrator(eng.blocks_per_slot, wire="int8")
    dst.pools = i8.migrate(eng.pools, dst.pools, np.arange(2),
                           np.arange(2))
    assert i8.stats.wire_bytes < at_rest.stats.wire_bytes / 3


def test_kv_migrate_wire_bytes_point_to_point():
    # one sender, one receiver: no (S-1)/S collective schedule factor
    assert wire_bytes("kv_migrate", "none", (8, 32), 8) == 8 * 32 * 4
    assert wire_bytes("kv_migrate", "int8", (8, 32), 8) == 8 * 32 + 4
    # and bf16 halves the fp32 payload
    assert wire_bytes("kv_migrate", "bf16", (8, 32), 8) == 8 * 32 * 2


def test_tree_digest_sees_every_leaf():
    eng = _engine_with_committed()
    d0 = tree_digest(eng.pools)
    assert d0 == tree_digest(eng.pools)
    leaves, treedef = jax.tree.flatten(eng.pools)
    leaves[-1] = leaves[-1].at[0].add(1.0)
    assert d0 != tree_digest(jax.tree.unflatten(treedef, leaves))


# --- warm-prefix sharing across engines (clone_prefix) -----------------


def _predicted_hit(eng, prompt):
    from distributed_deep_learning_tpu.serve import paged

    return paged.predict_shared_len(eng.manager.prefix_summary(),
                                    prompt, eng.block_size)


def test_clone_prefix_moves_shared_blocks_and_target_hits():
    model, params = _shared()
    prompt = _req(0, prompt_len=20).prompt
    donor = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                        prefill_chunk=8)
    donor.run([Request(uid=0, prompt=prompt, max_new_tokens=4)])
    target = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                         prefill_chunk=8)
    assert _predicted_hit(target, prompt) == 0
    mig = BlockMigrator(donor.blocks_per_slot)
    moved = clone_prefix(donor, target, prompt, mig,
                         device=jax.local_devices()[1])
    assert moved == _predicted_hit(donor, prompt) > 0
    assert _predicted_hit(target, prompt) == moved
    # the adopted blocks serve a real request bit-identically
    out = target.run([Request(uid=1, prompt=prompt, max_new_tokens=6)])
    ref = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8).run(
        [Request(uid=1, prompt=prompt, max_new_tokens=6)])
    np.testing.assert_array_equal(out["results"][1], ref["results"][1])
    assert out["stats"]["paged"]["shared_tokens"] >= moved


# --- disaggregated engine: parity, compile-once, migration overlap -----


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_disagg_bit_identical_to_unified(kv_dtype):
    model, params = _shared()
    reqs = _mixed_trace()
    uni = PagedEngine(model, params, max_slots=4, kv_block_size=8,
                      prefill_chunk=8, kv_dtype=kv_dtype)
    ref = uni.run([Request(uid=r.uid, prompt=r.prompt,
                           max_new_tokens=r.max_new_tokens,
                           arrival_tick=r.arrival_tick) for r in reqs])
    dis = DisaggEngine(model, params, prefill_streams=2, max_slots=4,
                       kv_block_size=8, prefill_chunk=8,
                       kv_dtype=kv_dtype)
    out = dis.run(reqs)
    assert not out["errors"] and not ref["errors"]
    for uid in ref["results"]:
        np.testing.assert_array_equal(
            out["results"][uid], ref["results"][uid],
            err_msg=f"request {uid} diverged from the unified engine")
    st = out["stats"]
    assert st["migration"]["moves"] == len(reqs)
    assert st["migration"]["hops"] == len(reqs)


def test_disagg_multi_worker_parity_and_compile_once():
    model, params = _shared()
    reqs = _mixed_trace(n=12)
    ref = PagedEngine(model, params, max_slots=4, kv_block_size=8,
                      prefill_chunk=8).run(
        [Request(uid=r.uid, prompt=r.prompt,
                 max_new_tokens=r.max_new_tokens,
                 arrival_tick=r.arrival_tick) for r in reqs])
    dis = DisaggEngine(model, params, prefill_workers=2, decode_workers=2,
                       prefill_streams=2, max_slots=2, kv_block_size=8,
                       prefill_chunk=8)
    out = dis.run(reqs)
    assert not out["errors"]
    for uid in ref["results"]:
        np.testing.assert_array_equal(out["results"][uid],
                                      ref["results"][uid])
    st = out["stats"]
    # compile-once PER WORKER: one batched-chunk trace per prefill
    # worker (the counter sums workers), one decode trace per decode
    # worker, one gather + one scatter for every migration in between
    assert st["chunk_compiles"] == 2
    assert st["decode_compiles"] == 1
    assert all(v == 1 for v in st["decode_compiles_per_worker"])
    assert st["migrate_gather_compiles"] == 1
    assert st["migrate_scatter_compiles"] == 1


def test_disagg_reset_reserves_without_retracing():
    model, params = _shared()
    reqs = _mixed_trace(n=6)
    dis = DisaggEngine(model, params, prefill_streams=2, max_slots=2,
                       kv_block_size=8, prefill_chunk=8)
    first = dis.run(reqs)
    dis.reset()
    second = dis.run(reqs)
    for uid in first["results"]:
        np.testing.assert_array_equal(first["results"][uid],
                                      second["results"][uid])
    st = second["stats"]
    assert st["chunk_compiles"] == 1 and st["decode_compiles"] == 1
    assert st["restarts"] == 1


def test_disagg_rejects_bad_topology():
    model, params = _shared()
    with pytest.raises(ValueError, match=">= 2 local devices"):
        DisaggEngine(model, params, devices=jax.local_devices()[:1])
    with pytest.raises(ValueError, match="need"):
        DisaggEngine(model, params, prefill_workers=5, decode_workers=5,
                     devices=jax.local_devices())
    with pytest.raises(ValueError, match="at_rest"):
        DisaggEngine(model, params, wire="int8", kv_dtype="int8")


# --- preemption spill: device path == host path ------------------------


def _contended_requests():
    return [_req(0, prio=2, new=10), _req(1, prio=2, new=10),
            _req(2, prio=0, tick=2, new=8), _req(3, prio=1, tick=2, new=8)]


def test_device_spill_bit_identical_to_host_spill():
    model, params = _shared()
    reqs = _contended_requests()
    host = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                       prefill_chunk=8, preempt=True, migrate="host")
    h = host.run([Request(uid=r.uid, prompt=r.prompt,
                          max_new_tokens=r.max_new_tokens,
                          arrival_tick=r.arrival_tick,
                          priority=r.priority) for r in reqs])
    dev = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, preempt=True, migrate="device")
    d = dev.run(list(reqs))
    hs, ds = h["stats"]["preempt"], d["stats"]["preempt"]
    assert hs["spill_path"] == "host" and ds["spill_path"] == "device"
    assert ds["preemptions"] > 0 and ds["still_spilled"] == 0
    assert ds["migration_moves"] == ds["preemptions"] + ds["resumes"]
    assert ds["migration_bytes"] > 0
    for uid in h["results"]:
        np.testing.assert_array_equal(
            d["results"][uid], h["results"][uid],
            err_msg=f"device-spill diverged from host-spill on {uid}")
    # compile-once holds on the device path too
    assert d["stats"]["decode_compiles"] == 1
    assert ds["spill_compiles"] == 1 and ds["unspill_compiles"] == 1


def test_device_spill_with_mesh_replicated_pools():
    # regression: engines born under a training mesh hold pools
    # committed across EVERY device; the resume hop lands the payload
    # on the home device only, and the scatter jit rejects the mixed
    # commitment unless resume re-places it to the pools' sharding
    model, params = _shared()
    reqs = _contended_requests()
    ref = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, preempt=True, migrate="host")
    r = ref.run([Request(uid=q.uid, prompt=q.prompt,
                         max_new_tokens=q.max_new_tokens,
                         arrival_tick=q.arrival_tick,
                         priority=q.priority) for q in reqs])
    eng = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, preempt=True, migrate="device")
    mesh = jax.sharding.Mesh(np.asarray(jax.local_devices()), ("d",))
    rep = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    eng.pools = jax.device_put(eng.pools, rep)
    d = eng.run(list(reqs))
    assert d["stats"]["preempt"]["preemptions"] > 0
    for uid in r["results"]:
        np.testing.assert_array_equal(d["results"][uid],
                                      r["results"][uid])


def test_migrate_drop_recovered_by_supervisor_replay():
    from distributed_deep_learning_tpu.serve.supervisor import (
        ServeSupervisor)

    model, params = _shared()
    reqs = _contended_requests()
    ref = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, preempt=True, migrate="device")
    clean = ref.run([Request(uid=r.uid, prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             arrival_tick=r.arrival_tick,
                             priority=r.priority) for r in reqs])
    eng = PagedEngine(model, params, max_slots=2, kv_block_size=8,
                      prefill_chunk=8, preempt=True, migrate="device")
    calls = {"n": 0}

    def corrupt_first(payload):
        calls["n"] += 1
        if calls["n"] > 1:
            return payload
        leaves, treedef = jax.tree.flatten(payload)
        i = max(range(len(leaves)), key=lambda j: leaves[j].size)
        leaves[i] = leaves[i].at[(0,) * leaves[i].ndim].add(1.0)
        return jax.tree.unflatten(treedef, leaves)

    eng._migrate_chaos = corrupt_first
    out = ServeSupervisor(eng, retries=2).run(list(reqs))
    st = out["stats"]
    assert st["requests_lost"] == 0 and not out["errors"]
    assert any(f["kind"] == "MigrationError" for f in st["faults"])
    assert st["restarts"] >= 1
    for uid in clean["results"]:
        np.testing.assert_array_equal(
            out["results"][uid], clean["results"][uid],
            err_msg=f"post-replay output diverged on {uid}")
    assert st["engine"]["decode_compiles"] == 1


# --- offload helper ----------------------------------------------------


def test_offload_commits_tree_to_device_bit_exact():
    eng = _engine_with_committed()
    target = jax.local_devices()[1]
    moved = migrate_mod.offload(eng.pools, target, chunk_bytes=4096)
    for a, b in zip(jax.tree.leaves(eng.pools), jax.tree.leaves(moved)):
        assert list(b.devices()) == [target]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- CLI surface -------------------------------------------------------


def test_cli_disagg_requires_paged():
    with pytest.raises(SystemExit, match="requires --paged"):
        parse_args(["--serve", "--disagg"])


def test_cli_prefill_workers_validated():
    with pytest.raises(SystemExit, match=">= 1"):
        parse_args(["--serve", "--paged", "--prefill-workers", "0"])
    with pytest.raises(SystemExit, match="requires --disagg"):
        parse_args(["--serve", "--paged", "--prefill-workers", "2"])
    # all 8 emulated devices on prefill would leave no decode pool
    with pytest.raises(SystemExit, match="at least one decode"):
        parse_args(["--serve", "--paged", "--disagg",
                    "--prefill-workers", "8"])


def test_cli_migrate_choices_and_accepts():
    with pytest.raises(SystemExit):
        parse_args(["--serve", "--paged", "--migrate", "npz"])
    cfg = parse_args(["--serve", "--paged", "--disagg",
                      "--prefill-workers", "2", "--migrate", "device"])
    assert cfg.disagg and cfg.prefill_workers == 2
    assert cfg.migrate == "device"
    cfg = parse_args(["--serve", "--paged"])
    assert not cfg.disagg and cfg.migrate == "host"
