"""ctypes bindings for the native host-data library, with NumPy fallbacks.

Build-on-first-import: compiles ``ddl_native.cpp`` with g++ into this
directory the first time it's needed (a few hundred ms, cached thereafter).
Every binding has a NumPy fallback with identical semantics, selected when
compilation is impossible or ``DDL_DISABLE_NATIVE=1`` — the test suite runs
both paths against each other.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ddl_native.cpp")
_LIB = os.path.join(_DIR, "libddl_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_i64 = ctypes.c_int64
_i32 = ctypes.c_int32
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def get_lib() -> ctypes.CDLL | None:
    """The loaded library, building it if necessary; None ⇒ use fallbacks."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DDL_DISABLE_NATIVE") == "1":
            return None
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.ddl_gather_rows.argtypes = [_f32p, _i64, _i64p, _i64, _f32p]
        lib.ddl_gather_rows.restype = None
        lib.ddl_window_gather.argtypes = [_f32p, _i64, _i64p, _i64, _i64,
                                          _f32p]
        lib.ddl_window_gather.restype = None
        lib.ddl_csv_dims.argtypes = [ctypes.c_char_p, _i32,
                                     ctypes.POINTER(_i64),
                                     ctypes.POINTER(_i64)]
        lib.ddl_csv_dims.restype = _i64
        lib.ddl_csv_parse.argtypes = [ctypes.c_char_p, _i32, _i32, _f32p,
                                      _i64, _i64]
        lib.ddl_csv_parse.restype = _i64
        lib.ddl_crop_resize_bilinear.argtypes = [
            _f32p, _i64, _i64, _i64, _i64, _i64, _i64, _i64, _i64, _i64,
            _f32p]
        lib.ddl_crop_resize_bilinear.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# Bindings (native fast path + NumPy fallback, identical semantics)
# ---------------------------------------------------------------------------

def gather_rows(data: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``data[idx]`` for 2D float32 `data` — the loader's hot op."""
    lib = get_lib()
    if lib is None or data.dtype != np.float32 or data.ndim != 2 \
            or not data.flags.c_contiguous:
        return data[idx]
    idx = np.ascontiguousarray(idx, np.int64)
    out = np.empty((len(idx), data.shape[1]), np.float32)
    lib.ddl_gather_rows(data, data.shape[1], idx, len(idx), out)
    return out


def take(arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``arr[idx]`` along axis 0 for ND arrays (images etc.): trailing dims
    are flattened into the native 2D row gather, then restored."""
    if arr.ndim == 2:
        return gather_rows(arr, idx)
    if arr.ndim < 2 or arr.dtype != np.float32 or not arr.flags.c_contiguous:
        return arr[idx]
    flat = arr.reshape(arr.shape[0], -1)
    return gather_rows(flat, idx).reshape((len(idx),) + arr.shape[1:])


def window_gather(data: np.ndarray, pos: np.ndarray, history: int
                  ) -> np.ndarray:
    """Windows ending at ``pos`` (inclusive): (B, history, d)."""
    lib = get_lib()
    if lib is None or data.dtype != np.float32 or data.ndim != 2 \
            or not data.flags.c_contiguous:
        offsets = np.arange(-(history - 1), 1)
        return data[np.asarray(pos)[:, None] + offsets]
    pos = np.ascontiguousarray(pos, np.int64)
    out = np.empty((len(pos), history, data.shape[1]), np.float32)
    lib.ddl_window_gather(data, data.shape[1], pos, len(pos), history, out)
    return out


def read_csv(path: str, *, skip_header: bool = True,
             drop_first_col: bool = False) -> np.ndarray:
    """Float CSV → (rows, cols) float32 array (pandas-free fast path)."""
    lib = get_lib()
    if lib is None:
        data = np.genfromtxt(path, delimiter=",",
                             skip_header=1 if skip_header else 0,
                             dtype=np.float32)
        data = np.atleast_2d(data)
        if drop_first_col:
            data = data[:, 1:]
        return np.ascontiguousarray(np.nan_to_num(data, nan=0.0))
    rows, cols = _i64(), _i64()
    rc = lib.ddl_csv_dims(path.encode(), 1 if skip_header else 0,
                          ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise FileNotFoundError(f"cannot read CSV {path!r} (rc={rc})")
    keep = cols.value - (1 if drop_first_col else 0)
    out = np.empty((rows.value, keep), np.float32)
    n = lib.ddl_csv_parse(path.encode(), 1 if skip_header else 0,
                          1 if drop_first_col else 0, out, rows.value,
                          cols.value)
    return out[:n]


def crop_resize_bilinear(img: np.ndarray, top: int, left: int, h: int,
                         w: int, out_h: int, out_w: int) -> np.ndarray:
    """torchvision ``resized_crop`` semantics on an (H, W, C) float32 image
    (align_corners=False bilinear)."""
    lib = get_lib()
    if lib is None or img.dtype != np.float32 or not img.flags.c_contiguous:
        return _crop_resize_numpy(np.asarray(img, np.float32), top, left, h,
                                  w, out_h, out_w)
    H, W, C = img.shape
    out = np.empty((out_h, out_w, C), np.float32)
    lib.ddl_crop_resize_bilinear(img, H, W, C, top, left, h, w, out_h,
                                 out_w, out)
    return out


def _crop_resize_numpy(img, top, left, h, w, out_h, out_w):
    fy = np.clip((np.arange(out_h) + 0.5) * (h / out_h) - 0.5, 0, h - 1)
    fx = np.clip((np.arange(out_w) + 0.5) * (w / out_w) - 0.5, 0, w - 1)
    y0 = fy.astype(np.int64)
    x0 = fx.astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0)[:, None, None]
    wx = (fx - x0)[None, :, None]
    crop = img[top:top + h, left:left + w]
    v0 = crop[y0][:, x0] * (1 - wx) + crop[y0][:, x1] * wx
    v1 = crop[y1][:, x0] * (1 - wx) + crop[y1][:, x1] * wx
    return (v0 * (1 - wy) + v1 * wy).astype(np.float32)
