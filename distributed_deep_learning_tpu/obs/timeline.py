"""Per-step span recording rolled up into a goodput breakdown.

A training run's wall-clock decomposes into a handful of span kinds the
trainer can actually attribute:

=============  ====================================================
kind           where it comes from
=============  ====================================================
``data_wait``  host blocked in ``next(loader)`` (input stall)
``h2d``        explicit host→device transfer outside the loader
``dispatch``   host time handing the jitted step to the runtime
``compile``    first dispatch of a given step fn (trace + XLA build)
``device_sync``host blocked fetching device results (the one
               sync-per-phase barrier — device compute hides here)
``checkpoint`` save + integrity manifest time
``recovery``   elastic restart: restore_verified / failure handling
``reshard``    cross-topology redistribution during restore
=============  ====================================================

:meth:`Timeline.goodput` maps those onto the categories large-scale TPU
fleet reports use: **productive** (dispatch + device_sync — the time the
device is doing model math, given the loop's async-dispatch design),
**input_stall** (data_wait + h2d), **checkpoint**, **recovery**
(recovery + reshard), **compile**, and **other** (unattributed wall).
Fractions are of elapsed wall-clock and sum to ≤ 1.0 by construction.

Hot-path contract: ``add(kind, dt)`` is two dict writes on interned
keys.  The ``span`` contextmanager is for cold paths (checkpoint,
recovery); hot loops should do their own ``perf_counter`` arithmetic and
call ``add``.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

# span kind -> goodput category; anything unlisted lands in "other"
CATEGORY = {
    "dispatch": "productive",
    "device_sync": "productive",
    "data_wait": "input_stall",
    "h2d": "input_stall",
    "checkpoint": "checkpoint",
    "recovery": "recovery",
    "reshard": "recovery",
    "compile": "compile",
}

CATEGORIES = ("productive", "input_stall", "checkpoint", "recovery",
              "compile", "other")


class Timeline:
    """Accumulates (seconds, count) per span kind against a wall-clock
    origin.  ``clock`` is injectable for deterministic tests.

    ``tracer`` (:class:`..obs.trace.Tracer`, optional) additionally
    records every ``add`` as a causal span on the ``train`` track —
    the step/compile/checkpoint spans of the exported trace.  The end
    time is read from the shared clock at add time (``add`` receives a
    duration, not endpoints), costing one extra clock read per span —
    only when tracing is on; the tracer-less path is unchanged."""

    def __init__(self, clock=time.perf_counter, tracer=None,
                 trace_id: str = "train") -> None:
        self.clock = clock
        self.tracer = tracer
        self.trace_id = trace_id
        self.seconds: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.steps = 0
        self._origin = clock()

    def add(self, kind: str, dt: float, n: int = 1) -> None:
        self.seconds[kind] = self.seconds.get(kind, 0.0) + dt
        self.counts[kind] = self.counts.get(kind, 0) + n
        if self.tracer is not None:
            t1 = self.clock()
            self.tracer.add(kind, t1 - dt, t1, self.trace_id,
                            track="train")

    @contextmanager
    def span(self, kind: str):
        t0 = self.clock()
        try:
            yield
        finally:
            self.add(kind, self.clock() - t0)

    def step(self, n: int = 1) -> None:
        self.steps += n

    def elapsed(self) -> float:
        return self.clock() - self._origin

    def snapshot(self) -> dict:
        """Cheap copy for delta-based rollups (phase goodput = snapshot
        at phase end minus snapshot at phase start)."""
        return {"seconds": dict(self.seconds), "counts": dict(self.counts),
                "steps": self.steps, "elapsed": self.elapsed()}

    def goodput(self, since: dict | None = None) -> dict:
        """Roll spans up into the goodput breakdown.

        With ``since`` (an earlier :meth:`snapshot`), the breakdown
        covers only the delta — used for per-phase rollups while the
        run-level report spans the whole timeline.
        """
        now = self.snapshot()
        base_sec = since["seconds"] if since else {}
        wall = now["elapsed"] - (since["elapsed"] if since else 0.0)
        steps = now["steps"] - (since["steps"] if since else 0)

        cat_seconds = {c: 0.0 for c in CATEGORIES}
        for kind, sec in now["seconds"].items():
            d = sec - base_sec.get(kind, 0.0)
            cat_seconds[CATEGORY.get(kind, "other")] += d
        attributed = sum(cat_seconds.values())
        # Unattributed wall (python glue between spans) is "other".
        cat_seconds["other"] += max(0.0, wall - attributed)

        # Spans can very slightly over-cover wall on coarse clocks;
        # normalize against the larger of the two so fractions sum ≤ 1.
        denom = max(wall, sum(cat_seconds.values()), 1e-12)
        fractions = {c: s / denom for c, s in cat_seconds.items()}
        return {
            "wall_seconds": wall,
            "steps": steps,
            "seconds": cat_seconds,
            "fractions": fractions,
            "goodput_fraction": fractions["productive"],
        }
