"""Host-level failure detection: heartbeats + liveness monitor.

The reference's only liveness coupling is a single trailing ``barrier()``
(``CNN/main.py:183-184``) — any rank failure hangs the job with no
diagnosis (SURVEY.md §5).  Within a jitted step, TPU collectives share that
all-or-nothing fate; what a framework CAN do is detect the dead host fast,
name it, and trigger checkpoint-resume instead of hanging a pod for hours.

Mechanism: each process runs a :class:`Heartbeat` thread touching
``<dir>/hb-<rank>`` every ``interval`` seconds (``dir`` on a filesystem all
hosts see — the standard TPU-pod setup has shared GCS/NFS scratch).  Any
process may call :func:`detect_failures` to list ranks whose beat is stale,
or wrap a training loop in :class:`FailureMonitor` to raise
:class:`WorkerFailure` promptly instead of waiting on a dead collective
forever.  Recovery = restart the job and resume from the last orbax
checkpoint (:mod:`.checkpoint`).
"""

from __future__ import annotations

import os
import threading
import time


class WorkerFailure(RuntimeError):
    """Raised by FailureMonitor when peers stop heartbeating."""

    def __init__(self, dead_ranks: list[int]):
        self.dead_ranks = dead_ranks
        super().__init__(f"worker(s) {dead_ranks} missed heartbeat deadline")


def _hb_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"hb-{rank}")


class Heartbeat:
    """Daemon thread stamping this process's liveness file."""

    def __init__(self, directory: str, rank: int, interval: float = 5.0):
        self.directory = os.fspath(directory)
        self.rank = rank
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat_once(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = _hb_path(self.directory, self.rank)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{time.time():f}\n")
        os.replace(tmp, path)  # atomic on POSIX

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat_once()

    def start(self) -> "Heartbeat":
        self.beat_once()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat-{self.rank}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def last_beat(directory: str, rank: int) -> float | None:
    """The timestamp WRITTEN INSIDE `rank`'s beat file (its own clock).

    Debug info only: cross-host clock skew makes it useless for staleness
    decisions — a writer whose clock runs minutes behind would look dead,
    one running ahead would look alive long after it hung.  Staleness uses
    :func:`beat_mtime` (the shared filesystem's clock) instead."""
    try:
        with open(_hb_path(directory, rank)) as f:
            return float(f.read().strip())
    except (FileNotFoundError, ValueError):
        return None


def beat_mtime(directory: str, rank: int) -> float | None:
    """mtime of `rank`'s beat file — stamped by the SHARED filesystem at
    each beat, so every reader compares against one clock."""
    try:
        return os.stat(_hb_path(directory, rank)).st_mtime
    except FileNotFoundError:
        return None


def fs_now(directory: str) -> float:
    """The shared filesystem's current clock, read by touching a probe
    file and statting its mtime — the same clock that stamps the beats,
    so staleness arithmetic never mixes two hosts' clocks.  Falls back to
    the local clock if the directory is unwritable (the monitor's I/O
    tolerance handles persistent failures)."""
    path = os.path.join(directory, f".clock-probe-{os.getpid()}")
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "w"):
            pass
        os.utime(path)
        return os.stat(path).st_mtime
    except OSError:
        return time.time()


def detect_failures(directory: str, world_size: int, timeout: float,
                    now: float | None = None,
                    grace_ranks: tuple[int, ...] = ()) -> list[int]:
    """Ranks whose heartbeat is older than `timeout` (or absent).

    Age = shared-FS "now" (:func:`fs_now`) minus the beat file's mtime —
    one clock on both sides.  Comparing the reader's ``time.time()``
    against a timestamp another host WROTE (the old scheme) let cross-host
    clock skew fake deaths or hide real ones.  ``now`` overrides the probe
    for tests."""
    now = fs_now(directory) if now is None else now
    dead = []
    for rank in range(world_size):
        if rank in grace_ranks:
            continue
        beat = beat_mtime(directory, rank)
        if beat is None or now - beat > timeout:
            dead.append(rank)
    return dead


class MonitorUnhealthy(RuntimeError):
    """The failure monitor itself stopped working (persistent I/O errors
    against the heartbeat directory) — distinct from "a peer died" so the
    loop can react to BOTH instead of training blind."""


class FlakyIOPolicy:
    """Consecutive-I/O-error tolerance, shared by every flaky-IO watcher
    (the heartbeat monitor here, the checkpoint-watch path in
    ``serve/reload``).

    A transient ``OSError`` says nothing about the thing being watched —
    tolerate up to ``tolerance`` CONSECUTIVE failures, then declare the
    WATCHER unhealthy (:class:`MonitorUnhealthy`) instead of silently
    retrying forever or dying quietly.  One policy object per watcher;
    one set of semantics for all of them."""

    def __init__(self, tolerance: int = 3, what: str = "scan"):
        if tolerance < 1:
            raise ValueError(f"tolerance must be >= 1, got {tolerance}")
        self.tolerance = int(tolerance)
        self.what = what
        self.consecutive = 0

    def note_success(self) -> None:
        self.consecutive = 0

    def note_error(self, exc: BaseException) -> MonitorUnhealthy | None:
        """Record one failure; returns the :class:`MonitorUnhealthy` to
        latch once the tolerance is exhausted (None while tolerating)."""
        self.consecutive += 1
        if self.consecutive >= self.tolerance:
            return MonitorUnhealthy(
                f"{self.what} failed {self.consecutive} consecutive "
                f"times ({type(exc).__name__}: {exc}); monitoring "
                "stopped")
        return None

    def reset(self) -> None:
        self.consecutive = 0


class FailureMonitor:
    """Background watcher raising :class:`WorkerFailure` via a callback (or
    recording it for polling) when any peer goes stale.

    A transient shared-FS hiccup (an ``OSError`` from the heartbeat scan)
    is tolerated up to ``io_error_tolerance`` CONSECUTIVE polls; beyond
    that a :class:`MonitorUnhealthy` is recorded — previously the thread
    died silently and monitoring stopped with no signal.  ``healthy``
    distinguishes "monitor alive, no failures" from "monitor dead"."""

    def __init__(self, directory: str, world_size: int, *,
                 timeout: float = 30.0, poll_interval: float = 5.0,
                 self_rank: int | None = None,
                 io_error_tolerance: int = 3):
        self.directory = os.fspath(directory)
        self.world_size = world_size
        self.timeout = timeout
        self.poll_interval = poll_interval
        self.grace = (self_rank,) if self_rank is not None else ()
        self.io_error_tolerance = io_error_tolerance
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._io = FlakyIOPolicy(io_error_tolerance,
                                 what="heartbeat scan")
        self.failure: Exception | None = None

    def check(self) -> None:
        """Raise immediately if any peer is stale (poll-style use)."""
        dead = detect_failures(self.directory, self.world_size, self.timeout,
                               grace_ranks=self.grace)
        if dead:
            raise WorkerFailure(dead)

    @property
    def healthy(self) -> bool:
        """True while monitoring is actually happening.

        False once a failure is recorded OR the background thread stopped
        without being asked to (crash, I/O give-up) — the loop can then
        tell "monitor dead" from "no failures so far"."""
        if self.failure is not None:
            return False
        if self._thread is None:  # poll-style use: check() does the work
            return True
        return self._thread.is_alive() or self._stop.is_set()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.check()
                self._io.note_success()
            except WorkerFailure as e:  # record; training thread polls
                self.failure = e
                return
            except OSError as e:
                # shared-FS hiccup: the scan failed, which says nothing
                # about the PEERS — retry, but never silently forever
                unhealthy = self._io.note_error(e)
                if unhealthy is not None:
                    self.failure = unhealthy
                    return

    def start(self) -> "FailureMonitor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="failure-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_interval)

    def reset(self) -> None:
        """Clear a recorded failure and resume monitoring — the elastic
        retry path: the replacement worker is expected to heartbeat again,
        and a latched failure from the dead attempt must not condemn every
        subsequent one.  Restarts the background thread only if it had
        been started (and died) before."""
        self.failure = None
        self._io.reset()
        if self._thread is not None and not self._thread.is_alive() \
                and not self._stop.is_set():
            self.start()

    def raise_if_failed(self) -> None:
        if self.failure is not None:
            raise self.failure

    def __enter__(self) -> "FailureMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_injected = False
_step_injected = False


def maybe_inject_step_failure(global_step: int) -> None:
    """Step-granular chaos hook: ``DDL_INJECT_STEP_FAILURE="<rank>:<step>"``
    raises ONE ``RuntimeError`` right after that global train step on that
    rank (or ``all``) — the mid-epoch preemption drill for
    ``--checkpoint-every`` (VERDICT r4 item 5: recovery must cost at most
    N steps, not an epoch)."""
    global _step_injected
    spec = os.environ.get("DDL_INJECT_STEP_FAILURE")
    if not spec or _step_injected:
        return
    parts = spec.split(":")
    if len(parts) != 2 or (parts[0] != "all" and not parts[0].isdigit()) \
            or not parts[1].isdigit():
        raise ValueError(
            f"DDL_INJECT_STEP_FAILURE={spec!r}: expected '<rank>:<step>' "
            "with rank a process index or 'all', e.g. '1:5' or 'all:5'")
    rank_s, step_s = parts
    import jax

    hit = rank_s == "all" or jax.process_index() == int(rank_s)
    if hit and global_step == int(step_s):
        _step_injected = True
        import sys

        print(f"CHAOS: injected failure on rank {jax.process_index()} "
              f"at step {step_s}", file=sys.stderr, flush=True)
        raise RuntimeError(
            f"injected failure (DDL_INJECT_STEP_FAILURE={spec}) on rank "
            f"{jax.process_index()} at step {step_s}")


def maybe_inject_failure(epoch: int) -> None:
    """Chaos/fault-injection hook: ``DDL_INJECT_FAILURE="<rank>:<epoch>"``
    raises ONE ``RuntimeError`` at the start of that epoch on that rank.

    Validates the elastic-recovery loop end to end — the failing rank's
    :func:`..train.elastic.fit_with_recovery` catches the error, restores
    the last epoch checkpoint, and rejoins its peers (who block briefly at
    the next collective, exactly as on a real pod).  The reference has no
    failure-drill mechanism at all (its only coupling is one trailing
    barrier, ``CNN/main.py:183-184``); this is the operational answer:
    a recovery path you can rehearse is one you can trust.
    """
    global _injected
    spec = os.environ.get("DDL_INJECT_FAILURE")
    if not spec or _injected:
        return
    # "<rank>:<epoch>" with rank a number or "all" (pod preemption drill);
    # validate eagerly — a malformed spec must be one clear error, not a
    # cryptic crash at the start of every epoch (and recovery churn under
    # --elastic, which would catch-and-retry into the same parse failure)
    parts = spec.split(":")
    if len(parts) != 2 or (parts[0] != "all" and not parts[0].isdigit()) \
            or not parts[1].isdigit():
        raise ValueError(
            f"DDL_INJECT_FAILURE={spec!r}: expected '<rank>:<epoch>' with "
            "rank a process index or 'all', e.g. '1:2' or 'all:2'")
    rank_s, epoch_s = parts
    import jax

    hit = rank_s == "all" or jax.process_index() == int(rank_s)
    if hit and epoch == int(epoch_s):
        _injected = True
        import sys

        # stderr, not the PhaseLogger: non-coordinator ranks log nothing,
        # but the drill must be visible in every rank's output
        print(f"CHAOS: injected failure on rank {jax.process_index()} "
              f"at epoch {epoch_s}", file=sys.stderr, flush=True)
        raise RuntimeError(
            f"injected failure (DDL_INJECT_FAILURE={spec}) on rank "
            f"{jax.process_index()} at epoch {epoch_s}")
