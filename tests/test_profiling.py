"""Profiling/diagnostics utilities."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_deep_learning_tpu.utils.profiling import (
    StepTimer, annotate, compiled_text, cost_analysis, hlo_text, trace)


def _fn(x):
    return jnp.sum(x @ x.T)


def test_hlo_text_contains_module():
    text = hlo_text(_fn, jnp.zeros((8, 8)))
    assert "module" in text.lower()
    assert "dot" in text.lower()  # the matmul is visible


def test_compiled_text_is_optimised_hlo():
    text = compiled_text(_fn, jnp.zeros((8, 8)))
    assert "HloModule" in text or "module" in text.lower()


def test_cost_analysis_reports_flops():
    stats = cost_analysis(_fn, jnp.zeros((64, 64)))
    # 64x64x64 matmul ≈ 524k flops; XLA reports at least the matmul
    assert stats.get("flops", 0) > 1e5


def test_trace_writes_files(tmp_path):
    d = str(tmp_path / "trace")
    with trace(d):
        jax.block_until_ready(_fn(jnp.ones((16, 16))))
    found = [f for _, _, files in os.walk(d) for f in files]
    assert found, "trace produced no files"


def test_trace_none_is_noop():
    with trace(None):
        pass


def test_annotate_nests():
    with annotate("outer"), annotate("inner"):
        jax.block_until_ready(_fn(jnp.ones((8, 8))))


def test_step_timer_rates():
    times = iter(np.arange(0.0, 100.0, 1.0))
    t = StepTimer(warmup=1, clock=lambda: next(times))
    for _ in range(5):
        t.tick(examples=32)
    s = t.summary()
    assert t.measured_steps == 4
    np.testing.assert_allclose(s["steps_per_sec"], 1.0)
    np.testing.assert_allclose(s["examples_per_sec"], 32.0)


def test_step_timer_warmup_excluded():
    # compile step completes at t=100 (the warmup tick); the measurement
    # window starts there, so the 100s compile never pollutes the rate
    times = iter([100.0, 101.0, 102.0, 103.0])
    t = StepTimer(warmup=1, clock=lambda: next(times))
    for _ in range(4):
        t.tick(examples=10)
    s = t.summary()
    np.testing.assert_allclose(s["steps_per_sec"], 1.0)  # 3 steps / 3s
    np.testing.assert_allclose(s["examples_per_sec"], 10.0)


def test_workload_cli_profile_dir(tmp_path, monkeypatch):
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec, run_workload

    monkeypatch.setenv("DDL_DATA_LIMIT", "512")
    d = str(tmp_path / "prof")
    argv = ["-e", "1", "-b", "64", "-m", "data", "--profile-dir", d]
    run_workload(get_spec("mlp"), parse_args(argv, workload="mlp"))
    found = [f for _, _, files in os.walk(d) for f in files]
    assert found, "profile dir empty after profiled run"
