"""Timestamped phase logging, format-compatible with the reference.

The reference's only observability is quote-delimited, UTC-timestamped phase
lines printed on rank 0 (``CNN/main.py:80,96,111,127``; ``verbose=rank==0``
at ``:181``), e.g.::

    "train epoch 3 begins at 1714056912.123456"
    "train epoch 3 ends at 1714056999.456 with accuracy 87.250 and loss 0.013digits"

We reproduce that exact stream (so downstream log scrapers keep working) and
add structured counters (steps/sec, examples/sec) the reference lacked.
"""

from __future__ import annotations

import json
import sys
import time
from typing import TextIO


class PhaseLogger:
    """Rank-0-gated phase logger emitting the reference's log grammar.

    ``jsonl_path`` additionally appends one machine-readable JSON object
    per event (``{"event", "t", ...fields}``) — the structured sibling of
    the reference's scrape-with-regex stream, written as the run progresses
    so a crashed run still leaves its history on disk.  JSONL recording is
    independent of ``verbose``: only the console stream is rank-0-gated,
    every process keeps its structured history.
    """

    def __init__(self, verbose: bool = True, stream: TextIO | None = None,
                 clock=time.time, jsonl_path: str | None = None):
        self.verbose = verbose
        self.stream = stream if stream is not None else sys.stdout
        self.clock = clock
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None

    def _emit(self, line: str) -> None:
        if self.verbose:
            # Reference prints quote-delimited lines for downstream scraping.
            print(f'"{line}"', file=self.stream, flush=True)

    def _record(self, event: str, **fields) -> None:
        if self._jsonl is not None:
            fields = {k: v for k, v in fields.items() if v is not None}
            self._jsonl.write(json.dumps(
                {"event": event, "t": self.clock(), **fields}) + "\n")
            self._jsonl.flush()

    # -- the reference grammar (CNN/main.py:80,96,111,127) -----------------
    def phase_begin(self, phase: str, epoch: int | None = None) -> float:
        t = self.clock()
        if epoch is None:
            self._emit(f"{phase} begins at {t:f}")
        else:
            self._emit(f"{phase} epoch {epoch} begins at {t:f}")
        self._record("phase_begin", phase=phase, epoch=epoch)
        return t

    def phase_end(self, phase: str, epoch: int | None = None, *,
                  accuracy: float | None = None, loss: float | None = None) -> float:
        t = self.clock()
        suffix = ""
        if accuracy is not None and loss is not None:
            suffix = f" with accuracy {accuracy:0.3f} and loss {loss:0.9f}"
        if epoch is None:
            self._emit(f"{phase} ends at {t:f}{suffix}")
        else:
            self._emit(f"{phase} epoch {epoch} ends at {t:f}{suffix}")
        self._record("phase_end", phase=phase, epoch=epoch,
                     accuracy=accuracy, loss=loss)
        return t

    # -- framework extensions ----------------------------------------------
    def metrics(self, **kv) -> None:
        parts = " ".join(f"{k}={v}" for k, v in kv.items())
        self._emit(f"metrics {parts}")
        self._record("metrics", **kv)

    def info(self, msg: str) -> None:
        self._emit(msg)

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
