"""Fleet tier: N supervised engines behind a health-checked router.

"Millions of users" means no single engine is ever the whole story —
the unit of serving becomes a FLEET of replicas, and the interesting
failure modes move up a layer: a replica crashing must not lose
requests, a straggling replica must stop receiving traffic before it
drags tail latency, and a router blind-spot must degrade placement
quality, not correctness.  :class:`FleetRouter` drives N
:class:`..serve.engine.PagedEngine` replicas, each under its own
:class:`..serve.supervisor.ServeSupervisor`, and owns the three
fleet-level behaviors:

* **Routing on predicted prefix hits.**  Each replica exports a cheap
  chain-hash summary of its prefix index
  (:meth:`..serve.paged.BlockManager.prefix_summary`); the router walks
  a prompt's block hashes against each summary
  (:func:`..serve.paged.predict_shared_len`) and places where the most
  prompt tokens are already cached, tiebreaking on least queue depth
  then replica id.  Placements feed back into the summary, so requests
  sharing a system prompt co-locate even before any of them finishes.
* **Zero-loss failover.**  Replica supervisors run with
  ``fatal=(ReplicaCrash,)``: a fleet-level crash escalates instead of
  being contained, the router quarantines the replica, warm-resets its
  engine (same compiled programs — ``decode_compiles`` stays 1), and
  replays the crashed replica's in-flight requests from the fleet
  :class:`..serve.supervisor.RequestLedger` onto healthy replicas.
  Greedy decode is deterministic and batch/replica-invariant, so the
  replayed continuations are bit-identical and ``requests_lost == 0``
  by construction.
* **Health tracking.**  Heartbeats (per-tick observations through the
  supervisor's ``fleet_hook``) and supervisor stats drive a three-state
  health machine — ``healthy`` / ``degraded`` (slow ticks beyond the
  budget, or deep in the admission ladder) / ``quarantined`` (crashed)
  — and the router prefers healthy replicas at placement time.

Execution is a ROUND-BASED SIMULATION on one box: per round the router
places every open request, runs each replica's supervisor to
completion, then harvests every supervisor ledger into the fleet
ledger.  That keeps the whole tier deterministic and drillable before
chips exist; the routing, failover, and health logic are exactly what a
concurrent deployment would run between ticks.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, Optional

import numpy as np

from distributed_deep_learning_tpu.obs.metrics import MetricsRegistry
from distributed_deep_learning_tpu.serve import migrate as migrate_mod
from distributed_deep_learning_tpu.serve import paged
from distributed_deep_learning_tpu.serve.load import merge_slo_reports
from distributed_deep_learning_tpu.serve.scheduler import Request
from distributed_deep_learning_tpu.serve.supervisor import (RequestLedger,
                                                            ServeSupervisor)

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"

_HEALTH_CODE = {HEALTHY: 0, DEGRADED: 1, QUARANTINED: 2}


class ReplicaCrash(RuntimeError):
    """A whole replica died (process gone, device wedged) — the fault
    class a single engine's supervisor cannot contain.  Supervisors in
    a fleet run with ``fatal=(ReplicaCrash,)`` so it escalates to the
    router, which owns quarantine + cross-replica replay."""


@dataclasses.dataclass
class _Replica:
    """Router-side record of one engine replica."""

    rid: int
    engine: object
    supervisor_kw: dict
    health: str = HEALTHY
    assigned: list = dataclasses.field(default_factory=list)
    summary: set = dataclasses.field(default_factory=set)
    ticks: int = 0
    slow_ticks: int = 0
    crashes: int = 0
    placements: int = 0
    stats: Optional[dict] = None      # last clean supervisor stats


def _prompt_hashes(prompt, block_size: int) -> list:
    """The chain hashes a prompt's full blocks will register under once
    prefilled — what a placement adds to the routed replica's PREDICTED
    summary (same ``len - 1`` cap as the real index)."""
    toks = np.asarray(prompt)
    L = len(toks)
    h = b""
    out = []
    i = 0
    while (i + 1) * block_size <= L - 1:
        h = paged.chain_hash(
            h, tuple(int(t) for t in toks[i * block_size:
                                          (i + 1) * block_size]))
        out.append(h)
        i += 1
    return out


class FleetRouter:
    """Health-checked router over N supervised engine replicas.

    ``engines`` share one model geometry (any mix of quantization /
    speculation settings with identical greedy outputs is fine — greedy
    continuations must be replica-invariant for failover bit-identity).
    ``chaos`` is a :class:`..utils.chaos.ChaosPlan` whose fleet kinds
    fire through the per-replica tick observer (``replica_crash``,
    ``replica_straggler``) and the placement path (``router_flake``).
    ``admissions`` optionally maps replica id -> its
    :class:`..serve.admission.AdmissionController` (each replica needs
    its own ladder state).

    ``run()`` returns the engines' ``{"results", "errors", "stats"}``
    contract; ``stats`` adds the fleet record — per-replica health,
    routing decisions, faults, and a merged per-priority SLO report.
    """

    def __init__(self, engines, *, chaos=None, deadline_ms=None,
                 retries: int = 2, max_restarts: int = 8,
                 stall_timeout_s=None, slow_tick_s: Optional[float] = None,
                 degrade_after: int = 2, degrade_pressure: float = 0.67,
                 admissions: Optional[dict] = None,
                 share_prefixes: bool = False, telemetry=None,
                 recorder=None, clock=time.monotonic):
        engines = list(engines)
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if degrade_after < 1:
            raise ValueError(f"degrade_after must be >= 1, got "
                             f"{degrade_after}")
        eos = {e.eos_id for e in engines}
        if len(eos) != 1:
            raise ValueError(f"replicas disagree on eos_id: {sorted(map(str, eos))}")
        self.chaos = chaos
        self.retries = int(retries)
        self.slow_tick_s = slow_tick_s
        self.degrade_after = int(degrade_after)
        self.degrade_pressure = float(degrade_pressure)
        self.admissions = dict(admissions or {})
        self.telemetry = telemetry
        self.recorder = recorder
        self._clock = clock
        sup_kw = dict(deadline_ms=deadline_ms, retries=retries,
                      max_restarts=max_restarts,
                      stall_timeout_s=stall_timeout_s)
        self.replicas = [_Replica(rid=i, engine=e, supervisor_kw=sup_kw)
                         for i, e in enumerate(engines)]
        self.ledger = RequestLedger(engines[0].eos_id)
        self.faults: list[dict] = []
        self.rounds = 0
        self.route_seq = 0
        self.flake_degraded = 0
        self.predicted_hit_tokens = 0
        self.shared_prefix_moves = 0
        self.shared_prefix_tokens = 0
        reg = telemetry.registry if telemetry is not None \
            else MetricsRegistry()
        # warm prefix sharing: when placement lands off the warm
        # replica (health outranks hits), migrate the donor's committed
        # prefix blocks to the chosen one instead of recomputing them
        self._migrator = migrate_mod.BlockMigrator(
            engines[0].blocks_per_slot, registry=reg) \
            if share_prefixes else None
        self._g_health = {r.rid: reg.gauge("fleet_replica_health",
                                           replica=str(r.rid))
                          for r in self.replicas}
        self._g_assigned = {r.rid: reg.gauge("fleet_replica_assigned",
                                             replica=str(r.rid))
                            for r in self.replicas}
        self._g_ticks = {r.rid: reg.gauge("fleet_replica_ticks",
                                          replica=str(r.rid))
                         for r in self.replicas}

    # --- health -----------------------------------------------------------
    def _observe_tick(self, rep: _Replica, report) -> None:
        """Per-tick heartbeat from a replica's supervisor (the
        ``fleet_hook`` seam): fires due fleet chaos, then folds the
        tick's wall time into the straggler detector."""
        rep.ticks += 1
        extra = 0.0
        if self.chaos is not None:
            extra = self.chaos.fleet_hook(rep.rid, report)
        if (self.slow_tick_s is not None
                and report.elapsed_s + extra > self.slow_tick_s):
            rep.slow_ticks += 1
            if (rep.slow_ticks >= self.degrade_after
                    and rep.health == HEALTHY):
                rep.health = DEGRADED
                if self.recorder is not None:
                    self.recorder.record("replica_degraded",
                                         replica=rep.rid,
                                         slow_ticks=rep.slow_ticks)

    def _export_gauges(self) -> None:
        for rep in self.replicas:
            self._g_health[rep.rid].set(_HEALTH_CODE[rep.health])
            self._g_assigned[rep.rid].set(len(rep.assigned))
            self._g_ticks[rep.rid].set(rep.ticks)

    # --- routing ----------------------------------------------------------
    def _route_one(self, req: Request, candidates: list) -> _Replica:
        """Place one request: most predicted prefix-hit tokens wins,
        healthy replicas outrank degraded ones, queue depth then
        replica id break ties.  A ``router_flake`` window blanks the
        hit signal (placement quality degrades; correctness never
        depends on it)."""
        flaky = (self.chaos is not None
                 and self.chaos.route_hook(self.route_seq))
        self.route_seq += 1
        if flaky:
            self.flake_degraded += 1
        hits = {}
        for rep in candidates:
            if flaky:
                hits[rep.rid] = 0
            else:
                hits[rep.rid] = paged.predict_shared_len(
                    rep.summary, req.prompt, rep.engine.block_size)
        best = sorted(
            candidates,
            key=lambda rep: (0 if rep.health == HEALTHY else 1,
                             -hits[rep.rid], len(rep.assigned),
                             rep.rid))[0]
        self.predicted_hit_tokens += hits[best.rid]
        if self._migrator is not None and not flaky:
            donor = max((r for r in candidates if r.rid != best.rid),
                        key=lambda r: hits[r.rid], default=None)
            if donor is not None and hits[donor.rid] > hits[best.rid]:
                # best-effort: moves only blocks the donor's REAL index
                # holds and the destination can adopt; 0 is fine
                moved = migrate_mod.clone_prefix(
                    donor.engine, best.engine, req.prompt,
                    self._migrator)
                if moved:
                    self.shared_prefix_moves += 1
                    self.shared_prefix_tokens += moved
                    if self.recorder is not None:
                        self.recorder.record(
                            "prefix_share", uid=req.uid,
                            donor=donor.rid, replica=best.rid,
                            tokens=moved)
        best.assigned.append(req)
        best.placements += 1
        # feed the placement back: the routed prompt's blocks will be
        # indexed there, so same-prefix followers co-locate immediately
        best.summary.update(_prompt_hashes(req.prompt,
                                           best.engine.block_size))
        if self.recorder is not None:
            self.recorder.record("route", uid=req.uid, replica=best.rid,
                                 predicted_hit=hits[best.rid],
                                 flaky=flaky)
        return best

    def _live_candidates(self) -> list:
        cands = [r for r in self.replicas if r.health != QUARANTINED]
        if not cands:
            # total-outage fallback: every replica crashed at least
            # once.  The engines were warm-reset at quarantine time, so
            # return them to service DEGRADED rather than losing work.
            for r in self.replicas:
                r.health = DEGRADED
            cands = list(self.replicas)
            if self.recorder is not None:
                self.recorder.record("fleet_unquarantine_all")
        return cands

    # --- replay (fleet ledger -> next round's requests) -------------------
    def _open_requests(self) -> list:
        out = []
        for e in self.ledger.open_entries():
            r = e.request
            if e.attempts > self.retries:
                e.error = (f"retries: request survived {e.attempts - 1} "
                           f"replica fault(s), exceeding the fleet "
                           f"retry budget {self.retries}")
                continue
            if e.committed:
                prompt = np.concatenate(
                    [np.asarray(r.prompt),
                     np.asarray(e.committed, dtype=r.prompt.dtype)])
                arrival = 0
            else:
                prompt = r.prompt
                arrival = r.arrival_tick
            out.append(Request(
                uid=r.uid, prompt=prompt,
                max_new_tokens=r.max_new_tokens - len(e.committed),
                arrival_tick=arrival, slo_ttft_ms=r.slo_ttft_ms,
                slo_e2e_ms=r.slo_e2e_ms, priority=r.priority))
        return out

    # --- main loop --------------------------------------------------------
    def run(self, requests: Iterable[Request]) -> dict:
        for req in requests:
            self.ledger.add(req)
        t_start = self._clock()
        slo_reports: list[dict] = []
        errors: dict = {}
        max_rounds = len(self.replicas) + 2 + self.retries

        while True:
            todo = self._open_requests()
            if not todo or self.rounds >= max_rounds:
                break
            self.rounds += 1
            for e in self.ledger.entries.values():
                if not e.retired and e.error is None:
                    e.attempts += 1
            # route this round's work over live replicas, freshest
            # REAL index summaries first (placement feedback stacks on
            # top for the requests routed within the round)
            cands = self._live_candidates()
            for rep in cands:
                rep.assigned = []
                rep.summary = set(rep.engine.manager.prefix_summary())
            for req in sorted(todo, key=lambda r: (r.arrival_tick,
                                                   r.uid)):
                self._route_one(req, cands)
            self._export_gauges()

            for rep in cands:
                if not rep.assigned:
                    continue
                sup = ServeSupervisor(
                    rep.engine, chaos=None,
                    admission=self.admissions.get(rep.rid),
                    recorder=self.recorder,
                    fleet_hook=(lambda report, _rep=rep:
                                self._observe_tick(_rep, report)),
                    fatal=(ReplicaCrash,), **rep.supervisor_kw)
                t0 = self._clock()
                try:
                    out = sup.run(list(rep.assigned),
                                  telemetry=self.telemetry)
                except ReplicaCrash as exc:
                    rep.crashes += 1
                    rep.health = QUARANTINED
                    fault_tick = (sup.faults[-1]["tick"]
                                  if sup.faults else None)
                    # warm reset NOW so the replica can return to
                    # service without retracing (the canary for that is
                    # decode_compiles staying 1)
                    rep.engine.reset()
                    self.faults.append({
                        "replica": rep.rid,
                        "kind": type(exc).__name__,
                        "message": str(exc),
                        "tick": fault_tick,
                        "round": self.rounds,
                        "recovery_s": None,   # filled when replays land
                        "_t_fault": t0,
                    })
                    if self.recorder is not None:
                        self.recorder.record("replica_quarantined",
                                             replica=rep.rid,
                                             tick=fault_tick)
                    out = None
                finally:
                    # EVERY supervisor ledger is harvested — crashed
                    # rounds contribute the tokens their ticks already
                    # committed, so replay resumes instead of restarting
                    for uid, entry in sup.ledger.entries.items():
                        for tok in entry.committed:
                            self.ledger.commit(uid, tok)
                if out is not None:
                    rep.stats = out["stats"]
                    slo_reports.append(out["stats"]["engine"]["slo"])
                    for uid, msg in out["errors"].items():
                        e = self.ledger.entries.get(uid)
                        if e is not None and not e.retired \
                                and e.error is None:
                            e.error = msg
                    # admission-ladder pressure marks a hot replica
                    adm = self.admissions.get(rep.rid)
                    if (adm is not None and rep.health == HEALTHY
                            and adm.pressure() >= self.degrade_pressure):
                        rep.health = DEGRADED
            # a completed round means every replayed request from prior
            # faults has landed — close their recovery clocks
            now = self._clock()
            for f in self.faults:
                if f["recovery_s"] is None:
                    f["recovery_s"] = now - f.pop("_t_fault")
            self._export_gauges()

        for uid, e in self.ledger.entries.items():
            if e.error is not None:
                errors[uid] = e.error
        results = self.ledger.results()
        lost = [uid for uid, e in self.ledger.entries.items()
                if not e.retired and e.error is None]
        for f in self.faults:                 # never leak the raw clock
            f.pop("_t_fault", None)
        stats = {
            "fleet": True,
            "replicas": len(self.replicas),
            "health": {r.rid: r.health for r in self.replicas},
            "rounds": self.rounds,
            "requests": len(self.ledger.entries),
            "completed": len(results),
            "errored": len(errors),
            "requests_lost": len(lost),
            "lost_uids": lost,
            "faults": self.faults,
            "total_seconds": self._clock() - t_start,
            "routing": {
                "decisions": self.route_seq,
                "assignments": {r.rid: r.placements
                                for r in self.replicas},
                "predicted_hit_tokens": self.predicted_hit_tokens,
                "flake_degraded": self.flake_degraded,
                "shared_prefix_moves": self.shared_prefix_moves,
                "shared_prefix_tokens": self.shared_prefix_tokens,
            },
            "per_replica": {
                r.rid: {
                    "health": r.health,
                    "ticks": r.ticks,
                    "slow_ticks": r.slow_ticks,
                    "crashes": r.crashes,
                    "placements": r.placements,
                    "decode_compiles": r.engine._decode.traces,
                    "restarts": r.engine.restarts,
                    "stats": r.stats,
                } for r in self.replicas},
            "slo": merge_slo_reports(slo_reports),
        }
        for rid, adm in sorted(self.admissions.items()):
            stats.setdefault("admission", {})[rid] = adm.stats()
        return {"results": results, "errors": errors, "stats": stats}
