from distributed_deep_learning_tpu.utils.config import (
    Config, DistributedEnv, Mode, parse_args, parse_mesh_arg,
)


def test_reference_flags_parse():
    cfg = parse_args(["-l", "3", "-s", "64", "-e", "2", "-b", "128",
                      "-d", "cpu", "-w", "2", "-m", "pipeline", "-p", "16",
                      "-r", "4"], env={})
    assert cfg.num_layers == 3
    assert cfg.size == 64
    assert cfg.epochs == 2
    assert cfg.batch_size == 128
    assert cfg.device.value == "cpu"
    assert cfg.num_workers == 2
    assert cfg.mode is Mode.PIPELINE
    assert cfg.microbatch == 16
    assert cfg.world_size == 4


def test_defaults_match_reference():
    cfg = parse_args([], env={})
    assert cfg.mode is Mode.SEQUENTIAL
    assert cfg.seed == 42  # reference pins manual_seed(42)
    # reference getConfiguration defaults (CNN/main.py:51-57)
    assert cfg.epochs == 10
    assert cfg.batch_size == 32
    assert cfg.microbatch == 2
    assert cfg.world_size == 1
    assert not cfg.distributed.is_distributed


def test_workload_defaults():
    assert parse_args([], workload="cnn", env={}).num_layers == 2
    assert parse_args([], workload="cnn", env={}).size == 4
    assert parse_args([], workload="lstm", env={}).size == 128
    assert parse_args([], workload="mlp", env={}).size == 38


def test_mpi_env_detection():
    env = {"OMPI_COMM_WORLD_RANK": "3", "OMPI_COMM_WORLD_SIZE": "8",
           "OMPI_COMM_WORLD_LOCAL_RANK": "1", "MASTER_ADDR": "head-node"}
    dist = DistributedEnv.from_environ(env)
    assert dist.process_id == 3
    assert dist.num_processes == 8
    assert dist.local_process_id == 1
    assert dist.coordinator == "head-node:29500"
    assert dist.is_distributed


def test_explicit_env_beats_mpi():
    env = {"DDL_NUM_PROCESSES": "2", "DDL_PROCESS_ID": "1",
           "OMPI_COMM_WORLD_SIZE": "8", "OMPI_COMM_WORLD_RANK": "5"}
    dist = DistributedEnv.from_environ(env)
    assert dist.num_processes == 2
    assert dist.process_id == 1


def test_mesh_arg():
    assert parse_mesh_arg("data=4,stage=2") == {"data": 4, "stage": 2}
    assert parse_mesh_arg(None) is None
    assert parse_mesh_arg("") is None
    assert parse_mesh_arg("data=-1,model=2") == {"data": -1, "model": 2}


def test_mesh_arg_rejects_bad_strings():
    import pytest

    # a bad --mesh is a parse-time argparse-style error naming the known
    # axes, not a MeshSpec ValueError from deep inside startup
    with pytest.raises(SystemExit, match="known axes.*data.*fsdp"):
        parse_mesh_arg("batch=4")
    with pytest.raises(SystemExit, match="expected axis=N"):
        parse_mesh_arg("data")
    with pytest.raises(SystemExit, match="given twice"):
        parse_mesh_arg("data=2,data=4")
    with pytest.raises(SystemExit, match="must be an integer"):
        parse_mesh_arg("data=two")
    with pytest.raises(SystemExit, match="must be >= 1"):
        parse_mesh_arg("data=0")
    with pytest.raises(SystemExit, match="at most one axis may be -1"):
        parse_mesh_arg("data=-1,fsdp=-1")


def test_mesh_stage_nstages_conflict():
    import pytest

    with pytest.raises(SystemExit, match="conflicts with --nstages"):
        parse_args(["--mesh", "stage=4", "--nstages", "2"], workload="mlp")
    # agreeing values are fine
    c = parse_args(["--mesh", "stage=2", "--nstages", "2"], workload="mlp")
    assert c.mesh_shape == {"stage": 2}


def test_autotune_plan_flags():
    import pytest

    c = parse_args(["--autotune"], workload="mlp")
    assert c.autotune and c.plan_file is None
    # --plan with --autotune is the OUTPUT path; it need not exist yet
    c = parse_args(["--autotune", "--plan", "/tmp/_no_such.plan.json"],
                   workload="mlp")
    assert c.autotune and c.plan_file == "/tmp/_no_such.plan.json"
    # --plan alone replays an artifact: a missing file fails at parse time
    with pytest.raises(SystemExit, match="no such file"):
        parse_args(["--plan", "/tmp/_no_such.plan.json"], workload="mlp")


def test_config_immutable_replace():
    cfg = Config()
    cfg2 = cfg.replace(epochs=9)
    assert cfg.epochs != 9 and cfg2.epochs == 9
