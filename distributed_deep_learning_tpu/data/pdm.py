"""Predictive-maintenance windowed dataset (reference ``LSTM/dataset.py``).

Semantics reproduced exactly (``LSTM/dataset.py:24-45``):

* CSV of ``machines × instances_per_machine`` rows (reference: 100 × 8759),
  last 5 columns are targets, the rest features;
* sliding windows of ``history`` rows that never cross a machine boundary:
  ``idx2pos`` maps the flat index to a window *end* ≥ row ``history-1``
  within its machine (``:36-39``);
* the item is ``(rows[pos-history+1 .. pos], targets_of_row[pos-history+1])``
  — note the target comes from the **first** (oldest) row of the window
  (``data[0,-5:]``, ``:45``), which we keep as the workload definition.

Unlike the reference (per-item pandas ``.iloc`` + ``.to(device)``), windows
are gathered for a whole batch at once with a single fancy-index — the
window tensor never materialises beyond the batch.
"""

from __future__ import annotations

import os

import numpy as np

NUM_TARGETS = 5


class PdMWindowedDataset:
    """Batch-gather windowed view over per-machine rows; ArrayDataset-API
    compatible (``__len__``/``batch``)."""

    def __init__(self, features: np.ndarray, targets: np.ndarray,
                 history: int = 10, instances_per_machine: int = 8759):
        if len(features) != len(targets):
            raise ValueError("features/targets length mismatch")
        if instances_per_machine < history:  # == history: one full window
            raise ValueError(
                f"instances_per_machine={instances_per_machine} is shorter "
                f"than history={history}: each machine needs at least one "
                "full window")
        if len(features) % instances_per_machine:
            raise ValueError(
                f"{len(features)} rows not divisible by instances_per_machine "
                f"{instances_per_machine}")
        self.features = features
        self.targets = targets
        self.history = history - 1          # reference keeps history-1
        self.instances_pm = instances_per_machine
        self.div = instances_per_machine - self.history
        self.machines = len(features) // instances_per_machine
        self._offsets = np.arange(-self.history, 1)  # window row offsets

    def __len__(self) -> int:
        return self.div * self.machines

    def idx2pos(self, idx: np.ndarray) -> np.ndarray:
        """Flat index → window-end row, skipping machine boundaries
        (reference ``LSTM/dataset.py:36-39``)."""
        idx = np.asarray(idx)
        machine = idx // self.div
        base = machine * self.instances_pm + self.history
        return base + (idx - machine * self.div)

    def batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        from distributed_deep_learning_tpu import native

        pos = self.idx2pos(np.asarray(indices))
        # windows ending at pos (inclusive), via the native C++ gather
        x = native.window_gather(self.features, pos, self.history + 1)
        y = native.take(self.targets, pos - self.history)  # first row (Q5)
        return x, y


def load_pdm(path: str = "/data/PredictiveMaintenance/dataset.csv",
             history: int = 10,
             instances_per_machine: int | None = 8759) -> PdMWindowedDataset:
    """Load the real CSV (all-float32, last 5 columns targets).

    ``instances_per_machine=None`` treats the whole file as ONE machine
    (fixture/arbitrary CSVs); the default 8759 is the reference dataset's
    per-machine row count (``LSTM/dataset.py``)."""
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found — use data.datasets.synthetic_pdm for the "
            "shape-compatible synthetic twin")
    from distributed_deep_learning_tpu import native

    data = native.read_csv(path, skip_header=True)
    ipm = len(data) if instances_per_machine is None \
        else instances_per_machine  # 0 is an error, not "one machine";
    # ipm-vs-history validation lives in PdMWindowedDataset.__init__
    return PdMWindowedDataset(
        np.ascontiguousarray(data[:, :-NUM_TARGETS]),
        np.ascontiguousarray(data[:, -NUM_TARGETS:]),
        history=history,
        instances_per_machine=ipm)
