"""Torch-checkpoint importers: forward-pass parity against torch twins.

Each test builds a torch module with the reference family's architecture
(standard torch layers, original construction — nothing copied), runs it
on a fixed input, imports its state_dict through
`utils.torch_migrate`, and asserts this framework's forward matches.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_deep_learning_tpu.utils.torch_migrate import (  # noqa: E402
    cnn_lstm_params_from_torch, densenet_params_from_torch,
    mlp_params_from_torch)

ATOL = 2e-5


def test_mlp_import_forward_parity():
    from distributed_deep_learning_tpu.models.mlp import MLP

    hidden, classes, features = 38, 5, 48
    # head compared at LOGITS: this package keeps softmax in the loss
    # (quirk Q4's explicit softmax is the opt-in --double-softmax)
    tm = torch.nn.Sequential(
        torch.nn.Linear(features, hidden), torch.nn.ReLU(),
        torch.nn.Linear(hidden, hidden), torch.nn.ReLU(),
        torch.nn.Linear(hidden, hidden), torch.nn.ReLU(),
        torch.nn.Linear(hidden, classes)).eval()

    x = np.random.default_rng(0).normal(size=(4, features)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()

    model = MLP(hidden_size=hidden, num_hidden_layers=2,
                num_classes=classes)
    variables = mlp_params_from_torch(tm.state_dict(), model, x[:1])
    got = model.apply(variables, x)
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_cnn_lstm_import_forward_parity():
    from distributed_deep_learning_tpu.models.cnn_lstm import CNNLSTM

    history, features, hidden, targets = 10, 32, 128, 5

    class Twin(torch.nn.Module):
        """The reference CNN-LSTM dataflow (LSTM/model.py:38-96): Conv1d
        over time-as-channels, LSTM over the conv channels as sequence,
        final hidden state -> Linear."""

        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv1d(history, 64, kernel_size=1)
            self.lstm = torch.nn.LSTM(features, hidden, num_layers=2,
                                      batch_first=True)
            self.head = torch.nn.Linear(hidden, targets)

        def forward(self, x):                  # x: (B, history, features)
            y = torch.relu(self.conv(x))       # (B, 64, features)
            out, (h, _) = self.lstm(y)         # seq axis = conv channels
            return self.head(h[-1])

    tm = Twin().eval()
    x = np.random.default_rng(1).normal(
        size=(4, history, features)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()

    model = CNNLSTM(hidden_layers=2, hidden_size=hidden,
                    num_targets=targets)
    variables = cnn_lstm_params_from_torch(tm.state_dict(), model, x[:1])
    got = model.apply(variables, x)
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_densenet_import_forward_parity():
    from distributed_deep_learning_tpu.models.densenet import DenseNet

    growth, bn_size, blocks, per_block, classes = 8, 4, 2, 2, 6
    init_features = 2 * growth
    eps = 1e-3   # the reference's BN eps (CNN/model.py), matched by _bn

    def bn(c):
        return torch.nn.BatchNorm2d(c, eps=eps)

    class TwinInner(torch.nn.Module):
        def __init__(self, in_c):
            super().__init__()
            self.norm1 = bn(in_c)
            self.conv1 = torch.nn.Conv2d(in_c, bn_size * growth, 1,
                                         bias=False)
            self.norm2 = bn(bn_size * growth)
            self.conv2 = torch.nn.Conv2d(bn_size * growth, growth, 3,
                                         padding=1, bias=False)

        def forward(self, x):
            y = self.conv1(torch.relu(self.norm1(x)))
            y = self.conv2(torch.relu(self.norm2(y)))
            return torch.cat([x, y], dim=1)

    class TwinLayer(torch.nn.Module):
        """Mimics the reference's WrapperTriton DOUBLE registration
        (`CNN/model.py:72`: attribute assignment + add_module of the
        same submodule), which duplicates every tensor in state_dict()
        under a second name — the importer must dedupe the aliases."""

        def __init__(self, in_c):
            super().__init__()
            self.layer = TwinInner(in_c)
            self.add_module("DenseLayer", self.layer)

        def forward(self, x):
            return self.layer(x)

    class Twin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.stem = torch.nn.Conv2d(3, init_features, 7, stride=2,
                                        padding=3, bias=False)
            self.stem_norm = bn(init_features)
            self.pool = torch.nn.MaxPool2d(3, stride=2, padding=1)
            mods, c = [], init_features
            for b in range(blocks):
                for _ in range(per_block):
                    mods.append(TwinLayer(c))
                    c += growth
                if b < blocks - 1:
                    mods.append(torch.nn.Sequential())  # placeholder
                    trans_norm = bn(c)
                    trans_conv = torch.nn.Conv2d(c, c // 2, 1, bias=False)
                    mods[-1].add_module("norm", trans_norm)
                    mods[-1].add_module("conv", trans_conv)
                    c //= 2
            self.features = torch.nn.ModuleList(mods)
            self.head = torch.nn.Linear(c, classes)

        def forward(self, x):
            x = self.pool(torch.relu(self.stem_norm(self.stem(x))))
            for m in self.features:
                if isinstance(m, TwinLayer):
                    x = m(x)
                else:  # transition: BN-ReLU-Conv1x1-AvgPool2
                    x = m.conv(torch.relu(m.norm(x)))
                    x = torch.nn.functional.avg_pool2d(x, 2, 2)
            k = min(7, x.shape[2], x.shape[3])
            x = torch.nn.functional.avg_pool2d(x, k, k)
            return self.head(torch.flatten(x, 1))

    tm = Twin().eval()
    # non-trivial running stats: one training-mode forward updates them
    tm.train()
    with torch.no_grad():
        tm(torch.randn(8, 3, 64, 64, generator=torch.Generator()
                       .manual_seed(3)))
    tm.eval()

    x = np.random.default_rng(2).normal(size=(2, 64, 64, 3)) \
        .astype(np.float32)
    with torch.no_grad():           # torch is NCHW; this package is NHWC
        want = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    model = DenseNet(dense_blocks=blocks, dense_layers=per_block,
                     growth_rate=growth, bn_size=bn_size,
                     num_classes=classes, double_softmax=False)
    variables = densenet_params_from_torch(tm.state_dict(), model, x[:1])
    got = model.apply(variables, x, train=False)
    np.testing.assert_allclose(got, want, atol=1e-4)

    # the user path is torch.save -> torch.load: serialisation must
    # preserve the storage sharing the alias dedupe keys on
    import io

    buf = io.BytesIO()
    torch.save(tm.state_dict(), buf)
    buf.seek(0)
    loaded = torch.load(buf, weights_only=True)
    v2 = densenet_params_from_torch(loaded, model, x[:1])
    np.testing.assert_allclose(model.apply(v2, x, train=False), want,
                               atol=1e-4)


def test_wrong_family_rejected():
    from distributed_deep_learning_tpu.models.mlp import MLP

    tm = torch.nn.Sequential(torch.nn.Conv1d(4, 8, 1))
    with pytest.raises(ValueError, match="expected 'linear'"):
        mlp_params_from_torch(tm.state_dict(), MLP(),
                              np.zeros((1, 48), np.float32))


def test_size_mismatch_rejected():
    from distributed_deep_learning_tpu.models.mlp import MLP

    tm = torch.nn.Sequential(torch.nn.Linear(48, 38),
                             torch.nn.Linear(38, 38),
                             torch.nn.Linear(38, 38),
                             torch.nn.Linear(38, 5))
    with pytest.raises(ValueError, match="unconsumed"):
        # model expects 1 hidden layer; checkpoint carries 2
        mlp_params_from_torch(tm.state_dict(), MLP(num_hidden_layers=1),
                              np.zeros((1, 48), np.float32))


def test_gpt2_import_logits_parity():
    """HF GPT-2 (random init, built offline from config) -> CausalLM:
    logits parity proves the full mapping — packed qkv split, head
    ordering, Conv1D orientation, tied head, final norm."""
    transformers = pytest.importorskip("transformers")

    from distributed_deep_learning_tpu.models.transformer import CausalLM

    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=32, n_embd=48, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()

    # include id 0 on purpose: GPT-2's id 0 is a real token, and the
    # import recipe disables this package's id-0-is-padding convention
    toks = np.random.default_rng(4).integers(0, 97, (2, 16))
    toks[0, 3] = 0
    with torch.no_grad():
        want = hf(torch.from_numpy(toks)).logits.numpy()

    model = CausalLM(vocab_size=97, num_layers=2, d_model=48, num_heads=4,
                     mlp_dim=4 * 48, max_len=32, with_logits=True,
                     ln_eps=1e-5, pad_id=None)  # HF eps; id 0 is a token
    from distributed_deep_learning_tpu.utils.torch_migrate import (
        causal_lm_params_from_hf_gpt2)

    variables = causal_lm_params_from_hf_gpt2(
        hf.state_dict(), model, jnp.asarray(toks[:1, :4], jnp.int32))
    got = model.apply(variables, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_gpt2_import_rejects_layer_mismatch():
    transformers = pytest.importorskip("transformers")

    from distributed_deep_learning_tpu.models.transformer import CausalLM
    from distributed_deep_learning_tpu.utils.torch_migrate import (
        causal_lm_params_from_hf_gpt2)

    cfg = transformers.GPT2Config(vocab_size=97, n_positions=32, n_embd=48,
                                  n_layer=3, n_head=4)
    hf = transformers.GPT2LMHeadModel(cfg)
    model = CausalLM(vocab_size=97, num_layers=2, d_model=48, num_heads=4,
                     mlp_dim=192, max_len=32, with_logits=True)
    with pytest.raises(ValueError, match="unconsumed GPT-2 keys"):
        causal_lm_params_from_hf_gpt2(
            hf.state_dict(), model, jnp.ones((1, 4), jnp.int32))


def test_bidirectional_lstm_rejected():
    from distributed_deep_learning_tpu.models.cnn_lstm import CNNLSTM

    class Twin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv1d(10, 64, 1)
            self.lstm = torch.nn.LSTM(32, 64, bidirectional=True,
                                      batch_first=True)
            self.head = torch.nn.Linear(128, 5)

    with pytest.raises(ValueError, match="unsupported leaves"):
        cnn_lstm_params_from_torch(
            Twin().state_dict(), CNNLSTM(hidden_size=64),
            np.zeros((1, 10, 32), np.float32))


def test_gpt2_rejects_model_larger_than_checkpoint():
    transformers = pytest.importorskip("transformers")

    from distributed_deep_learning_tpu.models.transformer import CausalLM
    from distributed_deep_learning_tpu.utils.torch_migrate import (
        causal_lm_params_from_hf_gpt2)

    cfg = transformers.GPT2Config(vocab_size=97, n_positions=32, n_embd=48,
                                  n_layer=1, n_head=4)
    hf = transformers.GPT2LMHeadModel(cfg)
    model = CausalLM(vocab_size=97, num_layers=2, d_model=48, num_heads=4,
                     mlp_dim=192, max_len=32, with_logits=True)
    with pytest.raises(ValueError, match="missing from the checkpoint"):
        causal_lm_params_from_hf_gpt2(
            hf.state_dict(), model, jnp.ones((1, 4), jnp.int32))


def test_aliased_dedupe_survives_numpy_roundtrip():
    """A numpy round-trip (e.g. via safetensors) loses the storage
    sharing the data_ptr dedupe keys on; the value-equality fallback
    must still drop the double-registered group."""
    from distributed_deep_learning_tpu.models.mlp import MLP

    hidden, classes, features = 38, 5, 48

    class Twin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.l_in = torch.nn.Linear(features, hidden)
            self.add_module("alias", self.l_in)   # WrapperTriton pattern
            self.l_h = torch.nn.Linear(hidden, hidden)
            self.head = torch.nn.Linear(hidden, classes)

        def forward(self, x):
            x = torch.relu(self.l_in(x))
            x = torch.relu(self.l_h(x))
            return self.head(x)

    tm = Twin().eval()
    x = np.random.default_rng(5).normal(size=(4, features)) \
        .astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()
    model = MLP(hidden_size=hidden, num_hidden_layers=1,
                num_classes=classes)

    # torch dict: pointer-based dedupe (the existing path)
    v1 = mlp_params_from_torch(tm.state_dict(), model, x[:1])
    np.testing.assert_allclose(model.apply(v1, x), want, atol=ATOL)

    # numpy round-trip: every tensor its own array, no data_ptr
    rt = {k: v.detach().cpu().numpy().copy()
          for k, v in tm.state_dict().items()}
    v2 = mlp_params_from_torch(rt, model, x[:1])
    np.testing.assert_allclose(model.apply(v2, x), want, atol=ATOL)


def test_numpy_roundtrip_without_aliases_not_overdeduped():
    """The value fallback must NOT merge distinct groups that merely
    share shapes (trained/random weights differ in value)."""
    from distributed_deep_learning_tpu.models.mlp import MLP

    tm = torch.nn.Sequential(
        torch.nn.Linear(48, 38), torch.nn.ReLU(),
        torch.nn.Linear(38, 38), torch.nn.ReLU(),
        torch.nn.Linear(38, 38), torch.nn.ReLU(),
        torch.nn.Linear(38, 5)).eval()
    x = np.random.default_rng(6).normal(size=(2, 48)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()
    rt = {k: v.detach().cpu().numpy().copy()
          for k, v in tm.state_dict().items()}
    model = MLP(hidden_size=38, num_hidden_layers=2, num_classes=5)
    variables = mlp_params_from_torch(rt, model, x[:1])
    np.testing.assert_allclose(model.apply(variables, x), want, atol=ATOL)
