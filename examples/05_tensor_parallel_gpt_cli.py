"""Hybrid data x tensor parallelism through the CLI — one `--mesh` flag.

`--mesh data=N,model=2` lays the devices out as an Nx2 mesh: the batch
shards over `data`, and the per-workload TP rules shard attention heads,
MLP hidden, and the embedding table over `model` (Megatron-style; XLA
inserts the all-reduces the sharding implies).  The training math is
unchanged — the suite asserts TP-vs-replicated loss parity to 1e-4.

    python examples/05_tensor_parallel_gpt_cli.py          # 8 emulated devices
    python examples/05_tensor_parallel_gpt_cli.py --tpu    # the machine's chips

Equivalent shell command (8 devices):

    python -m distributed_deep_learning_tpu gpt -l 2 -s 64 -e 2 -b 16 \
        -m data --mesh data=4,model=2
"""

import os
import runpy
import sys
import tempfile

import _bootstrap  # noqa: F401  (must precede jax import)
import jax

# TP degree 2 (the tiny demo model has 2 attention heads); `data` spans
# whatever devices the machine offers
n = len(jax.devices())
if n % 2:
    sys.exit(f"need an even device count for model=2, have {n}")
mesh = f"data={n // 2},model=2"

metrics = os.path.join(tempfile.mkdtemp(), "metrics.jsonl")
os.environ.setdefault("DDL_DATA_LIMIT", "256")  # keep the demo quick
sys.argv = ["ddl", "gpt", "-l", "2", "-s", "64", "-e", "2", "-b", "16",
            "-m", "data", "--mesh", mesh, "--metrics-file", metrics]
runpy.run_module("distributed_deep_learning_tpu", run_name="__main__")

trains = _bootstrap.train_phase_ends(metrics)
assert trains[-1]["loss"] < trains[0]["loss"], "TP run did not learn"
print(f"tensor-parallel ({mesh}) train loss: {trains[0]['loss']:.4f} -> "
      f"{trains[-1]['loss']:.4f}")
