"""Minimal library-API training run (no CLI): the five-call recipe.

    mesh -> loaders -> model -> step fns -> fit

This is what `python -m distributed_deep_learning_tpu mlp -m data` does
under the hood (workloads/base.py wires the same pieces plus checkpoints,
elastic restart, and the parallel modes).  Run anywhere:

    python examples/01_train_mlp_library_api.py          # 8 emulated devices
    python examples/01_train_mlp_library_api.py --tpu    # the machine's chips

The default emulates an 8-device mesh on CPU so the example always
demonstrates real sharding + the fused gradient psum; `--tpu` lets the
mesh span the machine's accelerators instead.
"""

import _bootstrap  # noqa: F401  (must precede jax import)
import jax
import optax

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.loader import make_loaders
from distributed_deep_learning_tpu.data.splits import train_val_test_split
from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.runtime.mesh import build_mesh
from distributed_deep_learning_tpu.train.loop import fit
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import make_step_fns, place_state
from distributed_deep_learning_tpu.utils.logging import PhaseLogger


def main():
    # 1. one mesh axis: pure data parallelism (DP).  Every parallel mode in
    #    this framework is "the same step fns, a different mesh/spec".
    mesh = build_mesh({"data": len(jax.devices())})

    # 2. dataset + seeded 70/10/20 split + sharded device loaders
    ds = synthetic_mqtt(n=4096)                 # MQTT-IDS shape twin
    splits = train_val_test_split(len(ds), seed=42)
    loaders = make_loaders(ds, splits, global_batch_size=64, mesh=mesh,
                           seed=42)

    # 3. model + optimizer -> TrainState
    model = MLP(num_classes=5)
    state = create_train_state(model, jax.random.key(42),
                               ds.features[:1], optax.sgd(0.05, momentum=0.9))
    state = place_state(state, mesh)

    # 4. jitted train/eval steps: ONE compiled program per step, gradient
    #    psum inserted by the partitioner (no per-parameter collectives)
    train_step, eval_step = make_step_fns(mesh, cross_entropy_loss)

    # 5. the reference-grammar training loop
    state, history = fit(state, train_step, eval_step, *loaders, epochs=3,
                         logger=PhaseLogger(verbose=True))
    final_train = [r for r in history if r.phase == "train"][-1]
    assert final_train.accuracy > 30.0, "did not learn"


if __name__ == "__main__":
    main()
