"""Test env: emulate an 8-device host platform before JAX initialises.

The JAX analogue of the reference's fake CPU device-list trick
(``LSTM/model.py:183`` builds a model over ``devices=[cpu]*4``): with
``--xla_force_host_platform_device_count=8`` every pjit/shard_map/collective
path runs for real on one machine (SURVEY.md §4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env may pin a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A site-installed TPU plugin may override the platform via jax.config at
# interpreter startup; force it back to CPU before any backend initialises.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests")


@pytest.fixture(scope="session")
def mesh8():
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    return build_mesh({"data": 8})


@pytest.fixture(scope="session")
def mesh_4x2():
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    return build_mesh({"data": 4, "stage": 2})


def padded_valid(T=32, lengths=(20, 32)):
    """(len(lengths), T) bool key_valid with ragged True prefixes — the
    shared padded-batch fixture for the SP/flash parity suites."""
    import jax.numpy as jnp

    return jnp.arange(T)[None, :] < jnp.array(lengths)[:, None]
