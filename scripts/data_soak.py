"""Reference-scale data soak (VERDICT r4 item 7).

Generates full-size synthetic corpora at the reference's documented scale
anchors (SURVEY.md §6):

* PdM:  100 machines x 8759 rows  (``LSTM/dataset.py:28-30``)
* PCB:  ~2953 images -> 5906 virtual samples (3597/1161/1148 split,
        ``CNN/dataset.py:114-117``)
* MQTT: a CSV big enough to anchor against the reference author's
        pandas full-load of ~1m41s (``MLP/dataset.py:43-45``)

then runs ONE full epoch of each through the REAL loaders (native C++ CSV
parser / window gather / crop-resize, PCB LRU image cache, sharded
DeviceLoader) and prints throughput + peak RSS as JSON lines.  Run:

    JAX_PLATFORMS=cpu python scripts/data_soak.py [--small]

(--small shrinks corpora ~10x for CI smoke; the recorded numbers in
PERFORMANCE.md come from the full run.)
"""

import argparse
import json
import os
import resource
import sys
import time

import numpy as np


def _script_env() -> None:
    """CPU 8-device setup — called from main() only, so importing this
    module as a library (the tests borrow the generators) has no side
    effects on the importer's jax state (review finding)."""
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def emit(**kv):
    print(json.dumps(kv), flush=True)


def gen_csv(path: str, rows: int, feat: int, targets: int = 5,
            chunk: int = 50_000) -> float:
    """Write a float CSV with header; returns file size in MB."""
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        f.write(",".join(f"c{i}" for i in range(feat + targets)) + "\n")
        for lo in range(0, rows, chunk):
            n = min(chunk, rows - lo)
            block = rng.normal(size=(n, feat + targets)).astype(np.float32)
            np.savetxt(f, block, fmt="%.5f", delimiter=",")
    return os.path.getsize(path) / 1e6


def soak_pdm(root: str, machines: int, ipm: int, batch: int = 512) -> None:
    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.data.pdm import load_pdm
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    path = os.path.join(root, "pdm.csv")
    t0 = time.monotonic()
    mb = gen_csv(path, machines * ipm, feat=32)
    gen_s = time.monotonic() - t0

    t0 = time.monotonic()
    ds = load_pdm(path, history=10, instances_per_machine=ipm)
    load_s = time.monotonic() - t0

    mesh = build_mesh({"data": 8})
    loader = DeviceLoader(ds, np.arange(len(ds)), batch, mesh, shuffle=True)
    loader.set_epoch(1)
    t0, n = time.monotonic(), 0
    for x, y in loader:
        n += x.shape[0]
    assert n, "corpus smaller than one batch — nothing soaked"
    epoch_s = time.monotonic() - t0
    emit(soak="pdm", rows=machines * ipm, csv_mb=round(mb, 1),
         gen_s=round(gen_s, 1), parse_s=round(load_s, 2),
         parse_mb_per_s=round(mb / load_s, 1), windows=len(ds),
         epoch_s=round(epoch_s, 2), windows_per_s=round(n / epoch_s),
         rss_mb=round(rss_mb()))


def soak_mqtt(root: str, rows: int, batch: int = 1024) -> None:
    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.data.mqtt import load_mqtt
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    path = os.path.join(root, "mqtt.csv")
    t0 = time.monotonic()
    mb = gen_csv(path, rows, feat=29)  # index col dropped + 28 features
    gen_s = time.monotonic() - t0

    t0 = time.monotonic()
    ds = load_mqtt(path)
    load_s = time.monotonic() - t0  # reference anchor: pandas ~101 s

    mesh = build_mesh({"data": 8})
    loader = DeviceLoader(ds, np.arange(len(ds)), batch, mesh, shuffle=True)
    loader.set_epoch(1)
    t0, n = time.monotonic(), 0
    for x, y in loader:
        n += x.shape[0]
    assert n, "corpus smaller than one batch — nothing soaked"
    epoch_s = time.monotonic() - t0
    emit(soak="mqtt", rows=rows, csv_mb=round(mb, 1), gen_s=round(gen_s, 1),
         parse_s=round(load_s, 2), parse_mb_per_s=round(mb / load_s, 1),
         epoch_s=round(epoch_s, 2), rows_per_s=round(n / epoch_s),
         rss_mb=round(rss_mb()))


def gen_pcb_tree(root: str, classes: int, per_class: int,
                 size: int = 600) -> int:
    """VOC-style tree with JPEG images + bbox XMLs; returns image count."""
    from PIL import Image

    rng = np.random.default_rng(1)
    n = 0
    for c in range(classes):
        cname = f"defect_{c}"
        img_dir = os.path.join(root, "images", cname)
        ann_dir = os.path.join(root, "Annotations", cname)
        os.makedirs(img_dir, exist_ok=True)
        os.makedirs(ann_dir, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(img_dir, f"{i:05d}.jpg"),
                                      quality=60)
            xmin, ymin = rng.integers(0, size - 120, size=2)
            w, h = rng.integers(40, 120, size=2)
            xml = ("<annotation><object><bndbox>"
                   f"<xmin>{xmin}</xmin><ymin>{ymin}</ymin>"
                   f"<xmax>{xmin + w}</xmax><ymax>{ymin + h}</ymax>"
                   "</bndbox></object></annotation>")
            with open(os.path.join(ann_dir, f"{i:05d}.xml"), "w") as f:
                f.write(xml)
            n += 1
    return n


def soak_pcb(root: str, classes: int, per_class: int,
             batch: int = 64) -> None:
    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.data.pcb import PCBDataset
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    tree = os.path.join(root, "pcb")
    t0 = time.monotonic()
    n_img = gen_pcb_tree(tree, classes, per_class)
    gen_s = time.monotonic() - t0

    t0 = time.monotonic()
    ds = PCBDataset(tree)
    scan_s = time.monotonic() - t0

    mesh = build_mesh({"data": 8})
    loader = DeviceLoader(ds, np.arange(len(ds)), batch, mesh, shuffle=True)
    loader.set_epoch(1)
    t0, n = time.monotonic(), 0
    for x, y in loader:
        n += x.shape[0]
    assert n, "corpus smaller than one batch — nothing soaked"
    epoch_s = time.monotonic() - t0
    emit(soak="pcb", images=n_img, virtual_samples=len(ds),
         gen_s=round(gen_s, 1), scan_s=round(scan_s, 2),
         epoch_s=round(epoch_s, 2), samples_per_s=round(n / epoch_s),
         rss_mb=round(rss_mb()))


def main():
    _script_env()
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="~10x smaller corpora (CI smoke)")
    ap.add_argument("--root", default="/tmp/ddl_soak")
    ap.add_argument("--only", choices=["pdm", "mqtt", "pcb"], default=None)
    args = ap.parse_args()
    os.makedirs(args.root, exist_ok=True)

    div = 10 if args.small else 1
    if args.only in (None, "pdm"):
        soak_pdm(args.root, machines=100 // div, ipm=8759)
    if args.only in (None, "mqtt"):
        soak_mqtt(args.root, rows=1_000_000 // div)
    if args.only in (None, "pcb"):
        soak_pcb(args.root, classes=6, per_class=492 // div)


if __name__ == "__main__":
    main()
