"""Local multi-process launcher — the ``torch.multiprocessing.spawn``
analogue.

The reference's ``-r N`` forks N local trainer processes over
``torch.multiprocessing.spawn`` (reference ``CNN/main.py:202``).  The JAX
equivalent launches N OS processes that rendezvous through
``jax.distributed.initialize`` (:mod:`.bootstrap`); each process owns its
local devices and the mesh spans all of them.  On a laptop/CI this runs the
REAL multi-process code paths — global device lists, the
``process_count() > 1`` loader branch, cross-process collectives over the
distributed service — on CPU (``force_cpu=True``), since a single TPU chip
cannot be shared by processes; on a pod the scheduler launches the
processes and this module is not involved.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Sequence


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(n_processes: int, argv: Sequence[str], *,
                 module: str = "distributed_deep_learning_tpu",
                 force_cpu: bool = True, devices_per_process: int = 1,
                 timeout: float | None = 600.0,
                 extra_env: dict[str, str] | None = None
                 ) -> list[subprocess.CompletedProcess]:
    """Run ``python -m <module> <argv>`` in ``n_processes`` rendezvousing
    processes; returns their CompletedProcess list (rank order).

    Raises ``RuntimeError`` if any rank exits nonzero (with its tail of
    output, stdout+stderr combined per rank).
    """
    import re

    port = free_port()
    procs: list[subprocess.Popen] = []
    for rank in range(n_processes):
        env = dict(os.environ)
        env.update(extra_env or {})
        env.update({
            "DDL_NUM_PROCESSES": str(n_processes),
            "DDL_PROCESS_ID": str(rank),
            "DDL_LOCAL_PROCESS_ID": str(rank),
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        })
        if force_cpu:
            # env var alone is not enough when a site plugin pins the
            # platform; bootstrap honours DDL_FORCE_CPU via jax.config
            env["JAX_PLATFORMS"] = "cpu"
            env["DDL_FORCE_CPU"] = "1"
            # pin the child's own device count (a pytest parent's forced
            # 8-device flag must not leak into every rank)
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                           "", env.get("XLA_FLAGS", ""))
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{devices_per_process}").strip()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", module, *argv], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    # drain every rank's pipe CONCURRENTLY: a crashing rank that fills its
    # 64KB pipe buffer would otherwise block, stall the collective its
    # peers wait on, and turn one rank's failure into a timeout that
    # discards the very log that explains it
    import threading
    import time as _time

    outputs = [""] * n_processes

    def drain(i: int, p: subprocess.Popen):
        outputs[i] = p.stdout.read()

    drainers = [threading.Thread(target=drain, args=(i, p), daemon=True)
                for i, p in enumerate(procs)]
    for t in drainers:
        t.start()
    deadline = None if timeout is None else _time.monotonic() + timeout
    results = []
    for rank, p in enumerate(procs):
        left = None if deadline is None else max(0.0,
                                                 deadline - _time.monotonic())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    for t in drainers:
        t.join(timeout=10)
    for rank, p in enumerate(procs):
        results.append(subprocess.CompletedProcess(p.args, p.returncode,
                                                   stdout=outputs[rank]))
    bad = [r for r in results if r.returncode != 0]
    if bad:
        tails = "\n---\n".join(r.stdout[-2000:] for r in bad)
        raise RuntimeError(f"{len(bad)}/{n_processes} ranks failed:\n{tails}")
    return results
