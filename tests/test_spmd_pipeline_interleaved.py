"""Interleaved 1F1B: schedule validity, bubble reduction vs plain 1F1B,
and numerical parity of the pipelined train pass against a sequential
reference (the same virtual stages applied in order, plain autodiff)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
    interleaved_1f1b_schedule, spmd_pipeline_interleaved)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh

S, V, D = 2, 2, 8


def _validate(ops, M, S, V, max_in_flight=2):
    """Assert deps, flow control, and capacity for a schedule."""
    L = V * S
    f_at = {(v, m): t for t, s, k, c, m in ops if k == "F"
            for v in [c * S + s]}
    b_at = {(v, m): t for t, s, k, c, m in ops if k == "B"
            for v in [c * S + s]}
    assert len(f_at) == L * M and len(b_at) == L * M
    per_tick: dict = {}
    for t, s, k, c, m in ops:
        v = c * S + s
        assert v % S == s, "chunk hosted on wrong device"
        key = (t, s, k)
        assert key not in per_tick, f"capacity violated at {key}"
        per_tick[key] = True
        if k == "F" and v > 0:
            assert f_at[(v - 1, m)] < t, f"F dep violated at {(v, m)}"
        if k == "B":
            assert f_at[(v, m)] <= t, f"B before F at {(v, m)}"
            if v < L - 1:
                # the cotangent from B(v+1, m) must ARRIVE (strictly
                # earlier tick) — only the last virtual stage seeds in-tick
                assert b_at[(v + 1, m)] < t, f"B dep violated at {(v, m)}"
    # FIFO + flow control per edge
    for v in range(1, L):
        for m in range(M):
            if m:
                assert f_at[(v, m)] > f_at[(v, m - 1)], "F not FIFO"
                assert b_at[(v, m)] > b_at[(v, m - 1)], "B not FIFO"
    for v in range(L - 1):
        for m in range(max_in_flight, M):
            # when F(v, m) runs, F(v+1, m-max_in_flight) must have consumed
            assert f_at[(v + 1, m - max_in_flight)] <= f_at[(v, m)], \
                f"activation flow control violated at v={v} m={m}"


@pytest.mark.parametrize("M,Sp,Vp", [(4, 2, 2), (8, 4, 2), (6, 3, 2),
                                     (16, 4, 4), (8, 2, 3)])
def test_schedule_valid(M, Sp, Vp):
    ops, T = interleaved_1f1b_schedule(M, Sp, Vp)
    _validate(ops, M, Sp, Vp)
    assert T == max(o[0] for o in ops) + 1


@pytest.mark.parametrize("M,Sp", [(8, 4), (16, 4), (16, 8)])
def test_interleaving_cuts_the_bubble(M, Sp):
    """Forward-slot utilisation (busy F ticks / total device-ticks) must
    strictly improve with V at equal per-device work."""
    utils = []
    for Vp in (1, 2, 4):
        ops, T = interleaved_1f1b_schedule(M, Sp, Vp)
        utils.append(sum(1 for o in ops if o[2] == "F") / (T * Sp))
    assert utils[0] < utils[1] < utils[2], utils


class Block(nn.Module):
    @nn.compact
    def __call__(self, h):
        return h + nn.Dense(D, kernel_init=nn.initializers.lecun_normal())(
            nn.relu(h))


@pytest.fixture(scope="module")
def setup():
    mesh = build_mesh({"stage": S, "data": 4})
    blk = Block()
    key = jax.random.key(0)
    h0 = jnp.zeros((1, D))
    # (V, S) stacked params: chunk v of device s = virtual stage v*S + s
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs).reshape(V, S, *xs[0].shape),
        *[blk.init(jax.random.fold_in(key, v * S + s), h0)["params"]
          for v in range(V) for s in range(S)])
    head = nn.Dense(6)
    x = jax.random.normal(jax.random.key(1), (16, D))
    y = jax.nn.one_hot(jax.random.randint(jax.random.key(2), (16,), 0, 6), 6)
    head_params = head.init(jax.random.key(3), x)["params"]
    stage_fn = lambda p, a: blk.apply({"params": p}, a)  # noqa: E731

    def head_loss(hp, h_mb, y_mb):
        logits = head.apply({"params": hp}, h_mb)
        return jnp.mean(
            -jnp.sum(y_mb * jax.nn.log_softmax(logits), axis=-1))

    return mesh, stage_fn, head_loss, stacked, head_params, x, y


def _sequential_reference(stage_fn, head_loss, stacked, head_params, x, y):
    """Same virtual stages applied in order; plain autodiff."""
    def loss_fn(stacked, hp):
        h = x
        for v in range(V * S):
            p = jax.tree.map(lambda l, v=v: l[v // S, v % S], stacked)
            h = stage_fn(p, h)
        return head_loss(hp, h, y)

    loss, (tg, hg) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        stacked, head_params)
    dx = jax.grad(lambda xx: head_loss(
        head_params, _walk(stage_fn, stacked, xx), y))(x)
    return loss, tg, hg, dx


def _walk(stage_fn, stacked, h):
    for v in range(V * S):
        p = jax.tree.map(lambda l, v=v: l[v // S, v % S], stacked)
        h = stage_fn(p, h)
    return h


def test_interleaved_matches_sequential(setup):
    mesh, stage_fn, head_loss, stacked, head_params, x, y = setup
    loss, tg, hg, dx = spmd_pipeline_interleaved(
        stage_fn, head_loss, stacked, head_params, x, y, mesh=mesh,
        microbatch_size=4)
    ref_loss, ref_tg, ref_hg, ref_dx = _sequential_reference(
        stage_fn, head_loss, stacked, head_params, x, y)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), tg, ref_tg)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), hg, ref_hg)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_single_microbatch_per_stage(setup):
    """Default microbatching (M = S) also works under interleaving."""
    mesh, stage_fn, head_loss, stacked, head_params, x, y = setup
    loss, tg, hg, dx = spmd_pipeline_interleaved(
        stage_fn, head_loss, stacked, head_params, x, y, mesh=mesh)
    ref_loss, *_ = _sequential_reference(
        stage_fn, head_loss, stacked, head_params, x, y)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_interleaved_has_aux(setup):
    mesh, stage_fn, head_loss, stacked, head_params, x, y = setup

    def head_loss_aux(hp, h_mb, y_mb):
        loss = head_loss(hp, h_mb, y_mb)
        return loss, {"count": jnp.float32(h_mb.shape[0])}

    loss, tg, hg, dx, aux = spmd_pipeline_interleaved(
        stage_fn, head_loss_aux, stacked, head_params, x, y, mesh=mesh,
        microbatch_size=4, has_aux=True)
    ref_loss, *_ = _sequential_reference(
        stage_fn, head_loss, stacked, head_params, x, y)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    # 4 microbatches x 1 local row, psummed over 4 dp shards = 16
    assert float(aux["count"]) == pytest.approx(16.0)


@pytest.mark.parametrize("M,Sp,Vp", [(4, 2, 2), (8, 4, 2), (16, 4, 4),
                                     (8, 2, 3), (3, 2, 2), (8, 4, 1)])
def test_residual_ring_never_clobbered(M, Sp, Vp):
    """Regression (review finding): the residual-ring depth must account
    for the executor's F-write-BEFORE-B-read order within a tick.  Replay
    the schedule against slot indices m % R and assert no live residual is
    overwritten before its backward consumes it."""
    from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
        _schedule_tables)

    tbl = _schedule_tables(M, Sp, Vp)
    R = tbl["resid_depth"]
    slots: dict = {}  # (v, slot) -> microbatch whose residual lives there
    for t in range(tbl["n_ticks"]):
        for s in range(Sp):
            # executor order: F write first...
            fc, fm = tbl["f_chunk"][t, s], tbl["f_mb"][t, s]
            if fc >= 0:
                v = fc * Sp + s
                key = (v, fm % R)
                assert key not in slots, \
                    f"slot {key} clobbered at t={t}: held mb {slots[key]}"
                slots[key] = fm
            # ...then B read+free
            bc, bm = tbl["b_chunk"][t, s], tbl["b_mb"][t, s]
            if bc >= 0:
                v = bc * Sp + s
                key = (v, bm % R)
                assert slots.get(key) == bm, \
                    f"B at t={t} read slot {key}: wanted {bm}, " \
                    f"held {slots.get(key)}"
                del slots[key]
    assert not slots


def test_interleaved_matches_sequential_many_microbatches(setup):
    """M = 8 (heavy residual-ring reuse) still matches the reference."""
    mesh, stage_fn, head_loss, stacked, head_params, x, y = setup
    x2 = jnp.concatenate([x, x * 0.5], axis=0)      # (32, D)
    y2 = jnp.concatenate([y, y], axis=0)
    loss, tg, hg, dx = spmd_pipeline_interleaved(
        stage_fn, head_loss, stacked, head_params, x2, y2, mesh=mesh,
        microbatch_size=4)

    def loss_fn(stacked, hp):
        return head_loss(hp, _walk(stage_fn, stacked, x2), y2)

    ref_loss, (ref_tg, ref_hg) = jax.value_and_grad(
        loss_fn, argnums=(0, 1))(stacked, head_params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), tg, ref_tg)


@pytest.mark.parametrize("M,Sp,Vp", [(2, 2, 2), (4, 2, 2), (8, 2, 2),
                                     (4, 2, 3), (6, 3, 2), (8, 4, 2),
                                     (8, 2, 4), (12, 4, 3)])
def test_comm_double_buffers_never_clobbered(M, Sp, Vp):
    """ADVICE r3: the executor parks ppermute arrivals in 2-deep
    microbatch-parity buffers BEFORE the tick's compute reads them.
    Replay every arrival/consume against (chunk, parity) slots and assert
    no unconsumed activation or cotangent is ever overwritten — the
    invariant the schedule's max_in_flight flow control (including the
    same-tick last-stage backward append) must guarantee."""
    from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
        _schedule_tables)

    tbl = _schedule_tables(M, Sp, Vp)
    L = Sp * Vp
    fbuf: dict = {}  # (v, parity) -> microbatch whose activation is parked
    bbuf: dict = {}
    for t in range(tbl["n_ticks"]):
        # arrivals land first (executor order: park, then compute)
        for s in range(Sp):
            c, m = tbl["fin_chunk"][t, s], tbl["fin_mb"][t, s]
            if c >= 0:
                key = (c * Sp + s, m % 2)
                assert key not in fbuf, \
                    f"fbuf slot {key} clobbered at t={t}: " \
                    f"held mb {fbuf[key]}, arriving mb {m}"
                fbuf[key] = m
            c, m = tbl["bin_chunk"][t, s], tbl["bin_mb"][t, s]
            if c >= 0:
                key = (c * Sp + s, m % 2)
                assert key not in bbuf, \
                    f"bbuf slot {key} clobbered at t={t}: " \
                    f"held mb {bbuf[key]}, arriving mb {m}"
                bbuf[key] = m
        # compute consumes
        for s in range(Sp):
            fc, fm = tbl["f_chunk"][t, s], tbl["f_mb"][t, s]
            if fc >= 0:
                v = fc * Sp + s
                if v > 0:  # virtual stage 0 microbatch reads xs directly
                    key = (v, fm % 2)
                    assert fbuf.get(key) == fm, \
                        f"F at t={t} read fbuf {key}: wanted {fm}, " \
                        f"held {fbuf.get(key)}"
                    del fbuf[key]
            bc, bm = tbl["b_chunk"][t, s], tbl["b_mb"][t, s]
            if bc >= 0:
                v = bc * Sp + s
                if v < L - 1:  # last virtual stage seeds from the head
                    key = (v, bm % 2)
                    assert bbuf.get(key) == bm, \
                        f"B at t={t} read bbuf {key}: wanted {bm}, " \
                        f"held {bbuf.get(key)}"
                    del bbuf[key]
    assert not fbuf and not bbuf


def test_interleaved_dropout_matches_sequential_replay():
    """VERDICT r3 item 5: --dropout under the interleaved schedule.  Keys
    are derived per GLOBAL virtual stage v = c*S + s and microbatch; a
    sequential replay with the same keys must agree exactly."""
    import flax.linen as nn
    import optax

    from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
        spmd_pipeline_interleaved, stack_stage_params)
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    Sp, Vp, D = 2, 2, 16
    L = Sp * Vp

    class DropBlock(nn.Module):
        @nn.compact
        def __call__(self, h, train: bool = False):
            h2 = nn.Dense(D, kernel_init=nn.initializers.lecun_normal())(
                nn.relu(h))
            h2 = nn.Dropout(0.5, deterministic=not train)(h2)
            return h + h2

    mesh = build_mesh({"stage": Sp}, jax.devices()[:Sp])
    blk = DropBlock()
    key = jax.random.key(0)
    h0 = jnp.zeros((1, D))
    flat = stack_stage_params(
        [blk.init(jax.random.fold_in(key, i), h0)["params"]
         for i in range(L)])   # index v = c*Sp + s
    stacked = jax.tree.map(
        lambda l: l.reshape(Vp, Sp, *l.shape[1:]), flat)
    head = nn.Dense(8)
    x = jax.random.normal(jax.random.key(1), (16, D))
    y = jax.nn.one_hot(jax.random.randint(jax.random.key(2), (16,), 0, 8),
                       8)
    head_params = head.init(jax.random.key(3), x)["params"]
    rng = jax.random.key(11)
    stage_fn = lambda p, a, k: blk.apply(  # noqa: E731
        {"params": p}, a, train=True, rngs={"dropout": k})

    def head_loss(hp, h, tgt):
        logits = head.apply({"params": hp}, h)
        return jnp.mean(optax.softmax_cross_entropy(logits, tgt))

    with mesh:
        loss, tg, hg, dx = jax.jit(
            lambda t, hp, x, y: spmd_pipeline_interleaved(
                stage_fn, head_loss, t, hp, x, y, mesh=mesh,
                microbatch_size=4, rng=rng))(stacked, head_params, x, y)

    M, mb = 4, 4

    def ref_loss(flat, hp, x):
        total = 0.0
        for m in range(M):
            h = x[m * mb:(m + 1) * mb]
            for v in range(L):
                p = jax.tree.map(lambda l, v=v: l[v], flat)
                h = stage_fn(p, h, jax.random.fold_in(
                    jax.random.fold_in(rng, v), m))
            total = total + head_loss(hp, h, y[m * mb:(m + 1) * mb])
        return total / M

    ref, (rtg_flat, rhg, rdx) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(flat, head_params, x)
    rtg = jax.tree.map(lambda l: l.reshape(Vp, Sp, *l.shape[1:]), rtg_flat)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), tg, rtg)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), hg, rhg)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rdx),
                               rtol=2e-4, atol=1e-6)
