"""Quantized + ring-overlapped FSDP collectives (parallel/collectives.py).

Covers the tentpole's three layers — wire formats (round-trip bounds,
error-feedback telescoping), the double-buffered ppermute rings
(bit-parity with the XLA primitives on exact data), and the explicit
FSDP step (loss parity with the zero.py annotation path, int8+EF
residual flow) — plus the --comm CLI validation and the runner's
dispatch rejections.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.parallel import collectives as coll
from distributed_deep_learning_tpu.parallel.zero import fsdp_state_spec
from distributed_deep_learning_tpu.runtime.mesh import build_mesh
from distributed_deep_learning_tpu.runtime.shmap import shard_map
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                      place_state)
from distributed_deep_learning_tpu.utils.config import parse_args


class TestWireFormats:
    def test_int8_round_trip_within_half_step(self):
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (64, 32)), jnp.float32)
        wire, scale = coll.quantize(x, "int8")
        assert wire.dtype == jnp.int8
        err = np.abs(np.asarray(
            coll.dequantize(wire, scale, "int8", x.dtype) - x))
        # symmetric rounding: error is at most half a quantization step
        assert err.max() <= float(scale) * 0.5 + 1e-7

    def test_bf16_round_trip_is_the_cast(self):
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (16, 8)), jnp.float32)
        wire, scale = coll.quantize(x, "bf16")
        assert wire.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(coll.dequantize(wire, scale, "bf16", x.dtype)),
            np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)))

    def test_none_is_identity(self):
        x = jnp.ones((4,))
        wire, scale = coll.quantize(x, "none")
        np.testing.assert_array_equal(
            np.asarray(coll.dequantize(wire, scale, "none", x.dtype)),
            np.asarray(x))

    def test_unknown_method_rejected(self):
        with pytest.raises(ValueError, match="unknown comm method"):
            coll.quantize(jnp.ones((4,)), "fp8")

    def test_error_feedback_telescopes(self):
        # the sum of T dequantized outputs must track the true sum of
        # inputs to within ONE quantization step (not T of them): the
        # residual carries each step's error into the next quantization
        x = jnp.asarray(np.random.default_rng(2).standard_normal(
            (256,)), jnp.float32)
        res = jnp.zeros_like(x)
        acc = np.zeros_like(np.asarray(x))
        steps = 20
        for _ in range(steps):
            wire, scale, res = coll.ef_quantize(x, res, "int8")
            acc += np.asarray(coll.dequantize(wire, scale, "int8", x.dtype))
        one_step = float(jnp.max(jnp.abs(x))) / 127.0
        assert np.abs(acc - steps * np.asarray(x)).max() <= one_step + 1e-6
        # without the residual the same bias compounds linearly
        wire, scale = coll.quantize(x, "int8")
        biased = steps * np.abs(
            np.asarray(coll.dequantize(wire, scale, "int8", x.dtype) - x))
        assert biased.max() > one_step

    def test_ef_quantize_degrades_without_residual(self):
        x = jnp.ones((8,))
        wire, scale, res = coll.ef_quantize(x, None, "int8")
        assert res is None


class TestRingParity:
    """Integer-valued operands: sums are exact in fp32, so the ring's
    different reduction/layout order must be BIT-equal to the XLA
    primitive, not merely close."""

    def _blocks(self, mesh):
        S = mesh.devices.size
        return jnp.asarray(np.random.default_rng(3).integers(
            -8, 9, (S * 4, 16)), jnp.float32)

    def test_ring_all_gather_bit_parity(self, mesh8):
        x = self._blocks(mesh8)
        S = mesh8.devices.size

        def run(overlap):
            @partial(shard_map, mesh=mesh8, in_specs=P("data"),
                     out_specs=P(), check_vma=False)
            def f(b):
                return coll.all_gather(b, "data", size=S, method="none",
                                       overlap=overlap)
            return np.asarray(f(x))

        np.testing.assert_array_equal(run(True), run(False))

    def test_ring_reduce_scatter_bit_parity(self, mesh8):
        x = self._blocks(mesh8)
        S = mesh8.devices.size

        def run(overlap):
            # reduce_scatter takes each shard's FULL-size contribution
            @partial(shard_map, mesh=mesh8, in_specs=P(),
                     out_specs=P("data"), check_vma=False)
            def f(b):
                c = b * (1.0 + jax.lax.axis_index("data"))
                return coll.reduce_scatter(c, "data", size=S,
                                           method="none", overlap=overlap)
            return np.asarray(f(x))

        np.testing.assert_array_equal(run(True), run(False))

    def test_quantized_gather_tracks_fp32(self, mesh8):
        x = jnp.asarray(np.random.default_rng(4).standard_normal(
            (8 * 4, 16)), jnp.float32)
        S = mesh8.devices.size

        def run(method):
            @partial(shard_map, mesh=mesh8, in_specs=P("data"),
                     out_specs=P(), check_vma=False)
            def f(b):
                return coll.all_gather(b, "data", size=S, method=method,
                                       overlap=True)
            return np.asarray(f(x))

        ref = run("none")
        scale = np.abs(ref).max()
        assert np.abs(run("int8") - ref).max() / scale < 0.01
        assert np.abs(run("bf16") - ref).max() / scale < 0.01

    def test_gather_matmul_matches_unfused(self, mesh8):
        S = mesh8.devices.size
        rng = np.random.default_rng(5)
        a = jnp.asarray(rng.standard_normal((S * 4, 8)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)

        def run(overlap):
            @partial(shard_map, mesh=mesh8, in_specs=(P("data"), P()),
                     out_specs=P(), check_vma=False)
            def f(x, y):
                return coll.gather_matmul(x, y, "data", size=S,
                                          method="none", overlap=overlap)
            return np.asarray(f(a, b))

        ref = np.asarray(a) @ np.asarray(b)
        np.testing.assert_allclose(run(False), ref, atol=1e-5)
        np.testing.assert_allclose(run(True), ref, atol=1e-5)


class TestWireAccounting:
    def test_int8_cuts_bytes_at_least_3x(self):
        fp32 = coll.wire_bytes("all_gather", "none", (256, 256), 8)
        int8 = coll.wire_bytes("all_gather", "int8", (256, 256), 8)
        assert fp32 / int8 >= 3.0
        assert coll.wire_bytes("all_gather", "bf16", (256, 256), 8) \
            == fp32 // 2

    def test_reduce_scatter_counts_the_scattered_share(self):
        # each shard sends (S-1)/S of ITS full contribution
        full = coll.wire_bytes("all_gather", "none", (8, 16), 8)
        rs = coll.wire_bytes("reduce_scatter", "none", (8, 16), 8)
        assert rs == full // 8

    def test_fsdp_wire_stats_reduction(self):
        state = create_train_state(
            MLP(hidden_size=64, num_hidden_layers=2, num_classes=8),
            jax.random.key(0), jnp.zeros((1, 32)), optax.sgd(0.1))
        mesh = build_mesh({"data": 8})
        spec = fsdp_state_spec(state, mesh, axis="data", min_leaf_size=16)
        dims = jax.tree.map(lambda s: coll._spec_dim(s, "data"),
                            spec.params)
        fp32 = coll.fsdp_wire_stats(state.params, dims, 8, "none")
        int8 = coll.fsdp_wire_stats(state.params, dims, 8, "int8")
        total = lambda st: (st["all_gather_bytes"]
                            + st["reduce_scatter_bytes"])  # noqa: E731
        assert total(fp32) / total(int8) >= 3.0


def _fsdp_setup(mesh, *, attach=False):
    model = MLP(hidden_size=64, num_hidden_layers=2, num_classes=8)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (16, 32), np.float32))
    y = jax.nn.one_hot(jnp.arange(16) % 8, 8)
    state = create_train_state(model, jax.random.key(0), x[:1],
                               optax.adam(1e-2))
    if attach:
        n = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
        state = coll.attach_residual(state, n)
    spec = fsdp_state_spec(state, mesh, axis="fsdp", min_leaf_size=16)
    return place_state(state, mesh, spec), spec, x, y


class TestExplicitFsdpStep:
    def test_none_is_loss_parity_with_annotation_path(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        s_ann, spec, x, y = _fsdp_setup(mesh)
        step_ann, _ = make_step_fns(mesh, cross_entropy_loss,
                                    state_spec=spec)
        s_exp, spec_e, _, _ = _fsdp_setup(mesh)
        step_exp, _ = coll.make_fsdp_step_fns(
            mesh, cross_entropy_loss, state_spec=spec_e, method="none",
            overlap=False, axis="fsdp")
        for _ in range(3):
            s_ann, m_ann = step_ann(s_ann, x, y)
            s_exp, m_exp = step_exp(s_exp, x, y)
            np.testing.assert_allclose(float(m_ann["loss"]),
                                       float(m_exp["loss"]), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(s_ann.params),
                        jax.tree.leaves(s_exp.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_ring_overlap_variant_same_numerics(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        losses = {}
        for overlap in (False, True):
            st, spec, x, y = _fsdp_setup(mesh)
            step, _ = coll.make_fsdp_step_fns(
                mesh, cross_entropy_loss, state_spec=spec, method="none",
                overlap=overlap, axis="fsdp")
            ls = []
            for _ in range(2):
                st, m = step(st, x, y)
                ls.append(float(m["loss"]))
            losses[overlap] = ls
        np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)

    def test_int8_ef_trains_and_updates_residual(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        st, spec, x, y = _fsdp_setup(mesh, attach=True)
        step, _ = coll.make_fsdp_step_fns(
            mesh, cross_entropy_loss, state_spec=spec, method="int8",
            overlap=True, axis="fsdp")
        first = None
        for _ in range(3):
            st, m = step(st, x, y)
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first      # it is actually learning
        res_l1 = sum(float(jnp.abs(l).sum())
                     for l in jax.tree.leaves(st.comm_residual))
        assert np.isfinite(res_l1) and res_l1 > 0.0   # EF is live

    def test_counts_wire_bytes_into_registry(self):
        from distributed_deep_learning_tpu.obs.metrics import (
            MetricsRegistry)

        mesh = build_mesh({"data": 2, "fsdp": 4})
        st, spec, x, y = _fsdp_setup(mesh, attach=True)
        reg = MetricsRegistry()
        step, _ = coll.make_fsdp_step_fns(
            mesh, cross_entropy_loss, state_spec=spec, method="int8",
            overlap=False, axis="fsdp", registry=reg)
        st, _ = step(st, x, y)
        st, _ = step(st, x, y)
        counters = reg.snapshot()["counters"]
        ag = counters["comm_bytes{method=int8,op=all_gather}"]
        rs = counters["comm_bytes{method=int8,op=reduce_scatter}"]
        assert ag > 0 and rs > 0
        # two steps → exactly twice the per-step accounting
        st2, spec2, _, _ = _fsdp_setup(mesh, attach=True)
        dims = jax.tree.map(lambda s: coll._spec_dim(s, "fsdp"),
                            spec2.params)
        per = coll.fsdp_wire_stats(st2.params, dims,
                                   mesh.shape["fsdp"], "int8")
        assert ag == 2 * per["all_gather_bytes"]
        assert rs == 2 * per["reduce_scatter_bytes"]

    def test_rejects_unknown_method_and_flat_axis(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        st, spec, x, y = _fsdp_setup(mesh)
        with pytest.raises(ValueError, match="unknown comm method"):
            coll.make_fsdp_step_fns(mesh, cross_entropy_loss,
                                    state_spec=spec, method="fp8")
        with pytest.raises(ValueError, match=">1"):
            coll.make_fsdp_step_fns(
                build_mesh({"data": 8}), cross_entropy_loss,
                state_spec=spec, method="none", axis="fsdp")


class TestCompressErrorFeedback:
    def test_int8_dp_allreduce_with_residual_tracks_bf16(self, mesh8):
        from distributed_deep_learning_tpu.train.compress import (
            make_compressed_step_fns)

        model = MLP(hidden_size=64, num_hidden_layers=2, num_classes=8)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (16, 32), np.float32))
        y = jax.nn.one_hot(jnp.arange(16) % 8, 8)

        from distributed_deep_learning_tpu.parallel.zero import (
            dp_state_spec)

        def run(method, attach):
            st = create_train_state(model, jax.random.key(0), x[:1],
                                    optax.adam(1e-2))
            if attach:
                st = coll.attach_residual(st, mesh8.devices.size)
            # the runner's derive_state_spec placement: replicated state,
            # batch-sharded residual (a bare P() breaks step donation)
            st = place_state(st, mesh8,
                             dp_state_spec(st) if attach else P())
            step, _ = make_compressed_step_fns(mesh8, cross_entropy_loss,
                                               method=method)
            ls = []
            for _ in range(3):
                st, m = step(st, x, y)
                ls.append(float(m["loss"]))
            return st, ls

        st8, l8 = run("int8", attach=True)
        _, lbf = run("bf16", attach=False)
        assert max(abs(a - b) for a, b in zip(l8, lbf)) < 5e-2
        res_l1 = sum(float(jnp.abs(l).sum())
                     for l in jax.tree.leaves(st8.comm_residual))
        assert res_l1 > 0.0


class TestCommCli:
    def test_valid_comm_flags_parse(self):
        cfg = parse_args(["--zero", "fsdp", "--comm", "int8",
                          "--comm-overlap"], workload="mlp", env={})
        assert cfg.comm == "int8" and cfg.comm_overlap

    def test_comm_requires_fsdp(self):
        with pytest.raises(SystemExit, match="requires.*--zero fsdp"):
            parse_args(["--comm", "int8"], workload="mlp", env={})

    def test_comm_excludes_grad_compress(self):
        with pytest.raises(SystemExit, match="mutually.*exclusive"):
            parse_args(["--zero", "fsdp", "--comm", "bf16",
                        "--grad-compress", "int8"],
                       workload="mlp", env={})

    def test_comm_excludes_grad_accum(self):
        with pytest.raises(SystemExit, match="--grad-accum"):
            parse_args(["--zero", "fsdp", "--comm", "bf16",
                        "--grad-accum", "4"], workload="mlp", env={})

    def test_comm_requires_data_fsdp_mesh(self):
        with pytest.raises(SystemExit, match="data/fsdp-only"):
            parse_args(["--zero", "fsdp", "--comm", "int8",
                        "--mesh", "data=2,model=4"],
                       workload="mlp", env={})

    def test_overlap_requires_comm(self):
        with pytest.raises(SystemExit, match="--comm-overlap requires"):
            parse_args(["--comm-overlap"], workload="mlp", env={})

    def test_unknown_method_is_an_argparse_error(self):
        with pytest.raises(SystemExit):
            parse_args(["--zero", "fsdp", "--comm", "fp8"],
                       workload="mlp", env={})


class TestRunnerDispatch:
    def test_comm_dispatch_rejects_bad_combo(self, mesh8):
        from distributed_deep_learning_tpu.workloads.base import (
            make_train_eval_steps)

        cfg = parse_args(["--zero", "fsdp", "--comm", "int8"],
                         workload="mlp", env={})
        bad = dataclasses.replace(cfg, zero="none")
        with pytest.raises(ValueError, match="--comm.*--zero fsdp"):
            make_train_eval_steps(bad, mesh8, cross_entropy_loss, P())

    def test_grad_compress_rejection_names_comm_path(self, mesh8):
        from distributed_deep_learning_tpu.workloads.base import (
            make_train_eval_steps)

        cfg = parse_args(["--grad-compress", "int8"],
                         workload="mlp", env={})
        bad = dataclasses.replace(cfg, zero="fsdp")
        with pytest.raises(ValueError, match="--comm bf16\\|int8"):
            make_train_eval_steps(bad, mesh8, cross_entropy_loss, P())


@pytest.mark.slow
class TestConvergenceGate:
    def test_int8_ef_fsdp_converges_like_fp32(self):
        """The quality gate: int8+EF explicit FSDP reaches the same loss
        neighbourhood as the uncompressed explicit path over a real
        (small) training run — the error feedback keeps compression from
        biasing Adam."""
        mesh = build_mesh({"data": 2, "fsdp": 4})
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.standard_normal((64, 32), np.float32))
        y = jax.nn.one_hot(jnp.arange(64) % 8, 8)

        def train(method, attach):
            model = MLP(hidden_size=64, num_hidden_layers=2,
                        num_classes=8)
            st = create_train_state(model, jax.random.key(0), x[:1],
                                    optax.adam(1e-2))
            if attach:
                st = coll.attach_residual(st, 8)
            spec = fsdp_state_spec(st, mesh, axis="fsdp",
                                   min_leaf_size=16)
            st = place_state(st, mesh, spec)
            step, _ = coll.make_fsdp_step_fns(
                mesh, cross_entropy_loss, state_spec=spec, method=method,
                overlap=True, axis="fsdp")
            loss = None
            for _ in range(60):
                st, m = step(st, x, y)
                loss = float(m["loss"])
            return loss

        ref = train("none", attach=False)
        q = train("int8", attach=True)
        assert q < 0.5 or abs(q - ref) < 0.1
