"""Fused (flash) attention as a Pallas TPU kernel.

The reference leans on cuDNN/Triton for its fused kernels
(``torch.compile``, ``WrapperTriton``, SURVEY.md §2.4); the TPU-native
counterpart is a Pallas kernel.  Attention is *the* op worth fusing: naive
attention materialises the (T×T) score matrix in HBM, while this kernel
streams K/V blocks through VMEM and keeps the online-softmax running
statistics (max ``m``, denominator ``l``, accumulator ``acc``) in
registers — O(T·D) memory, MXU-shaped contractions, no HBM round-trip for
the scores.

Grid: one program per (batch·head, query-block); each program loops over
key blocks with ``fori_loop`` (static trip count, causal handled by
masking — uniform control flow, nothing data-dependent).

Backward: ``jax.custom_vjp`` with a rematerialising dense backward (the
standard first rung of the flash-attention ladder — forward never pays the
O(T²) HBM cost; backward recomputes scores blockwise in plain XLA, which
fuses well).  On non-TPU platforms the kernel runs in interpreter mode so
the same code path is testable on the CPU mesh.

The same online-softmax recurrence drives :mod:`..parallel.ring_attention`
at the inter-chip level — this kernel is the intra-chip member of that
family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, kv_ref, o_ref, *, sm_scale: float,
                causal: bool, block_k: int, k_len: int):
    q = q_ref[0].astype(jnp.float32)                 # (bq, D)
    bq, d = q.shape
    q_off = pl.program_id(1) * bq

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if kv_ref is not None:
            valid = kv_ref[0, pl.ds(i * block_k, block_k)]  # (block_k,) f32
            s = jnp.where(valid[None, :] > 0, s, NEG_INF)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        new_acc = acc * corr + jnp.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return new_m, new_l, new_acc

    n_blocks = k_len // block_k
    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # all-keys-masked rows (fully-padded sequence) degrade to uniform
    # attention, matching the dense path's -1e9 semantics — never NaN
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _fit_block(length: int, requested: int) -> int:
    """Largest divisor of ``length`` not exceeding ``requested`` — block
    sizes adapt to the data's sequence length (user-controlled via real
    token files) instead of hard-failing on indivisible shapes."""
    return max(b for b in range(1, min(requested, length) + 1)
               if length % b == 0)


def _flash_fwd(q, k, v, kvalid, sm_scale, causal, block_q, block_k,
               interpret):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    block_q = _fit_block(Tq, block_q)
    block_k = _fit_block(Tk, block_k)
    kernel = functools.partial(
        _fwd_kernel if kvalid is not None else
        lambda qr, kr, vr, orf, **kw: _fwd_kernel(qr, kr, vr, None, orf, **kw),
        sm_scale=sm_scale, causal=causal, block_k=block_k, k_len=Tk)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, qi: (b, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tk, D), lambda b, qi: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tk, D), lambda b, qi: (b, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if kvalid is not None:
        in_specs.append(pl.BlockSpec((1, Tk), lambda b, qi: (b, 0),
                                     memory_space=pltpu.VMEM))
        args.append(kvalid)
    return pl.pallas_call(
        kernel,
        grid=(BH, Tq // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, qi: (b, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*args)


def _dense_attention_bhtd(q, k, v, kvalid, sm_scale, causal):
    """(BH, T, D) dense reference used for the rematerialised backward."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        # rectangular (Tq, Tk) mask on absolute positions — must match the
        # kernel's q_pos >= k_pos rule when Tq != Tk (cross-attention)
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    if kvalid is not None:
        s = jnp.where(kvalid[:, None, :] > 0, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", w, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_bhtd(q, k, v, kvalid, sm_scale, causal, block_q, block_k,
                interpret):
    return _flash_fwd(q, k, v, kvalid, sm_scale, causal, block_q, block_k,
                      interpret)


def _flash_vjp_fwd(q, k, v, kvalid, sm_scale, causal, block_q, block_k,
                   interpret):
    out = _flash_fwd(q, k, v, kvalid, sm_scale, causal, block_q, block_k,
                     interpret)
    return out, (q, k, v, kvalid)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, kvalid = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_attention_bhtd(q, k, v, kvalid, sm_scale,
                                              causal),
        q, k, v)
    dq, dk, dv = vjp(g)
    dkv = None if kvalid is None else jnp.zeros_like(kvalid)
    return dq, dk, dv, dkv


_flash_bhtd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False, key_valid: jnp.ndarray | None = None,
                    sm_scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jnp.ndarray:
    """Fused attention on ``(B, T, H, D)`` q/k/v (same layout as
    :func:`..models.transformer.dot_product_attention`).

    ``key_valid`` is an optional ``(B, Tk)`` boolean padding mask (True =
    attend); invalid keys are masked in-kernel with the same NEG_INF
    semantics as the dense path.  ``interpret=None`` auto-selects: compiled
    on TPU, interpreter elsewhere (so CPU tests exercise the identical
    kernel code).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    def to_bhtd(x):
        return jnp.swapaxes(x, 1, 2).reshape(B * x.shape[2], x.shape[1], D)

    kvalid = None
    if key_valid is not None:
        # per-batch mask, expanded over heads; float so the custom_vjp can
        # hand back an ordinary zero cotangent
        kvalid = jnp.repeat(key_valid.astype(jnp.float32), H, axis=0)
    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), kvalid, sm_scale,
                      causal, block_q, block_k, interpret)
    return jnp.swapaxes(out.reshape(B, H, Tq, D), 1, 2)


def make_attention_fn(causal: bool = False, **kw):
    """Adapter: flash attention as a ``MultiHeadAttention.attention_fn``
    (mirrors :func:`..parallel.ring_attention.make_attention_fn`).

    Supports the structured mask convention (``key_valid`` padding masks +
    a ``causal`` flag); pre-built dense ``mask`` tensors are rejected —
    materialising (T×T) masks is exactly what the kernel avoids.
    """

    forced_causal = causal

    def attn(q, k, v, *, mask=None, key_valid=None, causal=False,
             dtype=jnp.float32):
        if mask is not None:
            raise NotImplementedError(
                "flash_attention takes key_valid/causal, not dense mask "
                "tensors (pad-free batches or the dense path instead)")
        return flash_attention(q, k, v, causal=causal or forced_causal,
                               key_valid=key_valid, **kw).astype(dtype)

    return attn
