"""MLP workload model (reference ``src/pytorch/MLP/model.py:23-76``).

Reference architecture: ``Linear(input, hidden) → ReLU →
[Linear(hidden, hidden) → ReLU] × num_layers → Linear(hidden, classes) →
Softmax`` (Sigmoid head when ``classes < 2``).  Defaults hidden=38,
classes=5.  Differences by design:

* input width is data-driven (fixes quirk Q6's 52-vs-48 mismatch);
* the model emits **logits**; the softmax lives in the loss. The reference
  feeds Softmax output into CrossEntropyLoss (quirk Q4) — set
  ``double_softmax=True`` for bit-faithful replication of that behaviour.
* the layer list is exposed via :func:`mlp_layer_sequence` so the
  model-parallel partitioners (:mod:`..parallel.partition`) can stage it
  exactly like the reference's constructor-time partitioning
  (``MLP/model.py:41-45``); :class:`MLP` itself is built from that same
  sequence, so the sequential and staged paths cannot drift.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden_size: int = 38
    num_hidden_layers: int = 1
    num_classes: int = 5
    double_softmax: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        # single source of truth: the same layer sequence the staged
        # (model/pipeline-parallel) path partitions
        for layer in mlp_layer_sequence(self.hidden_size,
                                        self.num_hidden_layers,
                                        self.num_classes,
                                        self.double_softmax, self.dtype):
            x = layer(x, train=train)
        return x

    # --- stage partitioning support (model/pipeline modes) -----------------
    @property
    def num_partitionable_layers(self) -> int:
        """Layer count as the reference counts it: in + hidden + out
        (``MLP/model.py:62-76`` partitions ``hidden_layers + 2`` layers)."""
        return self.num_hidden_layers + 2


def mlp_layer_sequence(hidden_size: int = 38, num_hidden_layers: int = 1,
                       num_classes: int = 5, double_softmax: bool = False,
                       dtype: jnp.dtype = jnp.float32) -> list[nn.Module]:
    """The MLP as a partitionable layer list (same layer counting as the
    reference partitioner: in + hidden + out), for
    :class:`..parallel.staging.StagedModel`.

    A free function (not an ``MLP`` method): Flax wraps module methods in
    binding machinery that forbids creating child modules outside
    ``setup``/``compact``.
    """
    layers: list[nn.Module] = [DenseReLU(hidden_size, dtype=dtype)]
    layers += [DenseReLU(hidden_size, dtype=dtype)
               for _ in range(num_hidden_layers)]
    layers.append(DenseHead(num_classes, double_softmax=double_softmax,
                            dtype=dtype))
    return layers


class DenseReLU(nn.Module):
    """Dense + ReLU as one partitionable layer (reference pairs each Linear
    with its activation when partitioning)."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        return nn.relu(nn.Dense(self.features, dtype=self.dtype)(x))


class DenseHead(nn.Module):
    features: int
    double_softmax: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        x = nn.Dense(self.features, dtype=self.dtype)(x)
        if self.double_softmax:
            x = nn.sigmoid(x) if self.features < 2 else nn.softmax(x)
        return x.astype(jnp.float32)
