"""MPMD staged execution: the reference's `model`/`pipeline` modes on fake
multi-device CPU (the generalisation of the reference's ``devices=[cpu]*4``
trick, ``LSTM/model.py:183``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.models.mlp import mlp_layer_sequence
from distributed_deep_learning_tpu.parallel.mpmd import MPMDPipeline
from distributed_deep_learning_tpu.parallel.partition import balanced_partition
from distributed_deep_learning_tpu.parallel.staging import StagedModel


def _staged_mlp(n_stages, hidden_layers=2):
    layers = mlp_layer_sequence(hidden_size=16,
                                num_hidden_layers=hidden_layers, num_classes=5)
    assignment = balanced_partition(len(layers), n_stages)
    return StagedModel.from_layers(layers, assignment, n_stages)


def test_staged_apply_matches_shapes():
    staged = _staged_mlp(2)
    params = staged.init(jax.random.key(0), jnp.zeros((4, 8)))
    out = staged.apply(params, jnp.ones((4, 8)))
    assert out.shape == (4, 5)


def test_model_parallel_forward_matches_sequential():
    staged = _staged_mlp(4, hidden_layers=3)
    params = staged.init(jax.random.key(0), jnp.zeros((4, 8)))
    x = jax.random.normal(jax.random.key(1), (8, 8))
    expected = staged.apply(params, x)

    pipe = MPMDPipeline(staged, jax.devices()[:4])
    placed = pipe.place(params)
    got = pipe.forward(placed, x)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), rtol=1e-6)
    # stage params actually live on their devices
    for i, p in enumerate(placed):
        leaf = jax.tree.leaves(p)[0]
        assert leaf.devices() == {jax.devices()[i]}


def test_pipelined_forward_matches_model_parallel():
    staged = _staged_mlp(2)
    params = staged.init(jax.random.key(0), jnp.zeros((4, 8)))
    x = jax.random.normal(jax.random.key(2), (12, 8))
    pipe = MPMDPipeline(staged, jax.devices()[:2], microbatch_size=4)
    placed = pipe.place(params)
    np.testing.assert_allclose(np.asarray(pipe.forward(placed, x)),
                               np.asarray(pipe.pipelined_forward(placed, x)),
                               rtol=1e-6)
    # reference -p semantics: chunk SIZE, ragged tail allowed
    pipe_ragged = MPMDPipeline(staged, jax.devices()[:2], microbatch_size=5)
    out = pipe_ragged.pipelined_forward(placed, x)
    assert out.shape == (12, 5)


def test_gradients_flow_across_stage_devices():
    staged = _staged_mlp(2)
    pipe = MPMDPipeline(staged, jax.devices()[:2], microbatch_size=4)
    params = pipe.init(jax.random.key(0), jnp.zeros((4, 8)))
    x = jax.random.normal(jax.random.key(3), (8, 8))
    y = jax.nn.one_hot(jnp.arange(8) % 5, 5)

    def loss_fn(ps):
        import optax
        logits = pipe.pipelined_forward(ps, x)
        return optax.softmax_cross_entropy(logits, y).mean()

    grads = jax.grad(loss_fn)(params)
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    assert any(np.abs(np.asarray(g)).sum() > 0 for g in flat)


def test_device_count_mismatch_raises():
    staged = _staged_mlp(3)
    with pytest.raises(ValueError):
        MPMDPipeline(staged, jax.devices()[:2])
