"""CNN-LSTM predictive-maintenance model (reference ``src/pytorch/LSTM/model.py``).

Reference architecture (``LSTM/model.py:70-96``), faithfully including its
layout quirk: the input window is ``(batch, history=10, features=32)`` and
``Conv1d(10, 64, k=1)`` treats the **time axis as channels** — so the conv
mixes the 10 timesteps into 64 channels *per feature column*, and the LSTM
then runs over those 64 channels as its sequence axis with the 32 feature
columns as its input width (that is why the reference declares
``LSTM(32, hidden)``).  Sequence: ``Conv1d(k=1)+ReLU → MaxPool1d(1)+ReLU``
(the pool is a no-op and the second ReLU idempotent — kept as a layer for
partition-count parity) ``→ LSTM(32→H) → [LSTM(H→H)]×(n-1) → final hidden
state → Linear(H, 5)``.  No softmax: the workload regresses 5 raw targets
with L1 while logging argmax "accuracy" (quirk Q5).

TPU-native: the LSTM is a ``flax.linen.RNN`` over ``OptimizedLSTMCell`` —
an XLA ``lax.scan`` with static shapes.  The reference had to *disable*
``torch.compile`` for this model (``LSTM/main.py:162``) because cuDNN LSTM +
dynamo choke on it; under XLA the whole scan compiles like everything else.

Layer counting for the partitioners matches the reference (``hidden_layers
+ 3``: conv, pool, each LSTM, head — ``LSTM/model.py:50``), so
:func:`..parallel.partition.lstm_aware_partition` applies unchanged.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class PdMConvStem(nn.Module):
    """Conv1d(history→conv_features, k=1) + ReLU over the time-as-channels
    layout; emits ``(batch, conv_features, features)`` so downstream LSTM
    layers see channels as their sequence axis (the reference's implicit
    batch_first interpretation)."""

    conv_features: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        # x: (B, history, F).  Conv over the F axis with history as channels:
        # put channels last for flax, convolve, then channels (64) become the
        # sequence axis.
        x = x.astype(self.dtype)
        x = jnp.swapaxes(x, 1, 2)                      # (B, F, history)
        x = nn.Conv(self.conv_features, (1,), dtype=self.dtype)(x)  # (B, F, C)
        x = nn.relu(x)
        return jnp.swapaxes(x, 1, 2)                   # (B, C=seq, F=width)


class PoolReLU(nn.Module):
    """MaxPool1d(kernel=1) + ReLU — a no-op over non-negative inputs, kept
    as its own layer for partition-count parity (``LSTM/model.py:79-80``)."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        return nn.relu(x)


class LSTMLayer(nn.Module):
    """One LSTM layer via ``nn.RNN`` (lax.scan).  ``return_state`` selects
    the reference's ``ExtractFinalStateFromLSTM`` (final hidden state) vs
    ``ExtractOutputFromLSTM`` (full sequence) unwrapping."""

    hidden_size: int = 128
    return_state: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        rnn = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype),
                     return_carry=self.return_state)
        if self.return_state:
            (_, hidden), _ = rnn(x)
            return hidden          # (B, hidden): final hidden state
        return rnn(x)              # (B, seq, hidden)


class RegressionHead(nn.Module):
    num_targets: int = 5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        return nn.Dense(self.num_targets,
                        dtype=self.dtype)(x).astype(jnp.float32)


def cnn_lstm_layer_sequence(hidden_layers: int = 1, hidden_size: int = 128,
                            num_targets: int = 5, conv_features: int = 64,
                            dtype: jnp.dtype = jnp.float32) -> list[nn.Module]:
    """Partitionable layer list, ``hidden_layers + 3`` entries
    (``LSTM/model.py:50``)."""
    if hidden_layers < 1:
        raise ValueError("model requires at least one hidden layer")
    layers: list[nn.Module] = [PdMConvStem(conv_features, dtype), PoolReLU()]
    for i in range(hidden_layers):
        last = i == hidden_layers - 1
        layers.append(LSTMLayer(hidden_size, return_state=last, dtype=dtype))
    layers.append(RegressionHead(num_targets, dtype))
    return layers


class CNNLSTM(nn.Module):
    """Sequential CNN-LSTM, built from the same staged layer sequence."""

    hidden_layers: int = 1
    hidden_size: int = 128
    num_targets: int = 5
    conv_features: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        for layer in cnn_lstm_layer_sequence(
                self.hidden_layers, self.hidden_size, self.num_targets,
                self.conv_features, self.dtype):
            x = layer(x, train=train)
        return x
