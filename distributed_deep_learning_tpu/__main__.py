"""Package entry point: ``python -m distributed_deep_learning_tpu <workload>``.

The reference is launched per-workload (``python CNN/main.py -m data ...``);
the equivalent here is ``python -m distributed_deep_learning_tpu cnn -m data
...`` with the identical flag surface (``-l -s -e -b -d -w -m -p -r``).
"""

from __future__ import annotations

import sys


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else list(argv)
    from distributed_deep_learning_tpu.workloads import WORKLOADS

    if not argv or argv[0] in ("-h", "--help"):
        print(f"usage: python -m distributed_deep_learning_tpu "
              f"{{{'|'.join(WORKLOADS)}}} [flags]\n"
              f"Run '<workload> -h' for the per-workload flag reference.")
        return
    name, rest = argv[0], argv[1:]
    if "--spawn" in rest:
        # reference -r semantics, process edition: fork -r local ranks that
        # rendezvous via jax.distributed (CNN/main.py:202's
        # torch.multiprocessing.spawn analogue; CPU — one chip can't be
        # shared, pods launch ranks via the scheduler instead)
        rest = [a for a in rest if a != "--spawn"]
        from distributed_deep_learning_tpu.runtime.launch import launch_local
        from distributed_deep_learning_tpu.utils.config import parse_args

        n = parse_args(rest, workload=name).world_size
        if n < 2:
            raise SystemExit("--spawn needs -r N with N >= 2")
        for res in launch_local(n, [name, *rest]):
            sys.stdout.write(res.stdout)
        return
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec, run_workload

    spec = get_spec(name)
    run_workload(spec, parse_args(rest, workload=name))


if __name__ == "__main__":
    main()
