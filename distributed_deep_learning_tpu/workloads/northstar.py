"""North-star workloads behind the same CLI: resnet, transformer, bert.

These are the BASELINE.json configs (MNIST/CIFAR/ImageNet CNNs, WMT
seq2seq, C4 MLM) — scope beyond the reference, exposed exactly like its
workloads so one command line covers the whole model zoo::

    python -m distributed_deep_learning_tpu resnet -s 18 -e 5 -b 256 -m data
    python -m distributed_deep_learning_tpu transformer -l 6 -s 512 --zero 1
    python -m distributed_deep_learning_tpu bert -l 12 -s 768 --dtype bfloat16

Flag mapping: ``-l`` = layer count (transformer/bert), ``-s`` = ResNet
depth (18/34/50) or model width.  All run on synthetic shape-twins of the
real datasets (``data.datasets``) unless ``--data-dir`` points at real
files; the loaders' contract means pointing them at real data is a
dataset-constructor swap.

Parallel modes: ``-m data`` (+ ``--zero`` / ``--mesh model=K``) is the
primary path.  ``-m pipeline`` runs the SPMD pipeline for transformer/bert
(``build_pipelined`` → :mod:`..models.pipelined_lm`: ``stage`` mesh axis,
forward+backward in one XLA program) and MPMD staging for resnet;
``-m model`` stages the layer sequences over explicit devices.  moe rejects
staged modes (experts shard over the ``expert`` axis instead).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from distributed_deep_learning_tpu.data.datasets import (ArrayDataset,
                                                         synthetic_c4_mlm,
                                                         synthetic_cifar10,
                                                         synthetic_wmt)
from distributed_deep_learning_tpu.models.resnet import (BasicBlock,
                                                         BottleneckBlock,
                                                         ResNet)
from distributed_deep_learning_tpu.models.transformer import (BertEncoder,
                                                              TransformerSeq2Seq)
from distributed_deep_learning_tpu.parallel.partition import balanced_partition
from distributed_deep_learning_tpu.parallel.tensor_parallel import (
    transformer_tp_rules)
from distributed_deep_learning_tpu.train.objectives import (
    cross_entropy_loss, token_cross_entropy)
from distributed_deep_learning_tpu.utils.config import Config, parse_args
from distributed_deep_learning_tpu.workloads.base import (WorkloadSpec,
                                                          adamw,
                                                          config_dtype,
                                                          example_from_dataset,
                                                          resolve_lr,
                                                          run_workload)

_RESNET_LAYERS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3)}


# --- resnet ----------------------------------------------------------------

def _resnet_dataset(config: Config):
    """Real ImageFolder data when ``--data-dir`` is given (decode threads
    driven by ``-w``), the synthetic CIFAR twin otherwise."""
    if config.data_dir:
        from distributed_deep_learning_tpu.data.imagefolder import (
            ImageFolderDataset)

        return ImageFolderDataset(config.data_dir,
                                  image_size=config.image_size,
                                  num_workers=config.num_workers or 8)
    return synthetic_cifar10(seed=config.seed)


def _resnet_geometry(config: Config, dataset):
    depth = config.size if config.size in _RESNET_LAYERS else 18
    num_classes = len(getattr(dataset, "classes", ())) or 10
    # Decoded image size decides the stem: small inputs (<=64 px, the
    # CIFAR twin included) use the 3x3-s1 stem, ImageNet-size the 7x7-s2.
    # Materialised datasets (synthetic twins, --packed-cache) carry their
    # size in the feature array; the lazy ImageFolder path decodes at
    # --image-size.
    feats = getattr(dataset, "features", None)
    if feats is not None and feats.ndim == 4:
        small = feats.shape[1] <= 64
    else:
        small = config.image_size <= 64 if config.data_dir else True
    return depth, num_classes, small


def _resnet_model(config: Config, dataset):
    depth, num_classes, small = _resnet_geometry(config, dataset)
    return ResNet(stage_sizes=_RESNET_LAYERS[depth],
                  block_cls=BottleneckBlock if depth >= 50 else BasicBlock,
                  num_classes=num_classes, small_inputs=small,
                  stem_s2d=config.stem_s2d and not small,
                  dtype=config_dtype(config))


def _resnet_layers(config: Config, dataset):
    from distributed_deep_learning_tpu.models.resnet import (
        resnet_layer_sequence)

    depth, num_classes, small = _resnet_geometry(config, dataset)
    return resnet_layer_sequence(
        stage_sizes=_RESNET_LAYERS[depth],
        block_cls=BottleneckBlock if depth >= 50 else BasicBlock,
        num_classes=num_classes, width=64, small_inputs=small,
        dtype=config_dtype(config))


RESNET_SPEC = WorkloadSpec(
    name="resnet",
    build_dataset=_resnet_dataset,
    build_model=_resnet_model,
    build_layers=_resnet_layers,
    partitioner=balanced_partition,
    build_loss=lambda c: cross_entropy_loss,
    build_optimizer=lambda c, steps: optax.sgd(
        resolve_lr(c, steps,
                   c.learning_rate if c.learning_rate != 1e-3 else 0.1),
        momentum=0.9),
    example_input=example_from_dataset,
)




def _token_ce_loss(c: Config):
    """Per-config token cross-entropy (single definition for the four LM
    specs — --label-smoothing rides through here)."""
    from functools import partial

    return partial(token_cross_entropy, label_smoothing=c.label_smoothing)


def _n_chunks(config: Config) -> int:
    """Chunks per device for the interleaved pipeline schedule (1 = plain
    stacking for gpipe/1f1b)."""
    return (config.virtual_stages
            if config.pipeline_schedule == "interleaved" else 1)

# --- transformer (WMT seq2seq) --------------------------------------------

class Seq2SeqAdapter(nn.Module):
    """Adapts ``TransformerSeq2Seq``'s batch-dict interface to the runner's
    ``model(x, train)`` convention: ``x`` is source and target token ids
    concatenated along the sequence axis (``src_len`` is static)."""

    model: TransformerSeq2Seq
    src_len: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        batch = {"inputs": x[:, :self.src_len],
                 "targets": x[:, self.src_len:]}
        return self.model(batch, train=train)


def _wmt_dataset(config: Config, src_len: int = 32, tgt_len: int = 32,
                 vocab: int = 1024):
    if config.data_dir:
        from distributed_deep_learning_tpu.data.tokens import (load_tokens,
                                                               seq2seq_dataset)

        tokens = load_tokens(config.data_dir)
        if tokens is not None:
            return seq2seq_dataset(tokens)
    ds = synthetic_wmt(src_len=src_len, tgt_len=tgt_len, vocab_size=vocab,
                       seed=config.seed)
    feats = np.concatenate([ds.features, ds.targets], axis=1)
    return ArrayDataset(feats, ds.targets)


def _transformer_model(config: Config, dataset):
    d = config.size
    # --dropout seeds per-step PRNG streams through TrainState.rng;
    # the default 0.0 keeps steps deterministic (reference seed-42 contract)
    inner = TransformerSeq2Seq(
        vocab_size=_vocab(dataset), num_layers=config.num_layers, d_model=d,
        num_heads=max(2, d // 64), mlp_dim=4 * d,
        dropout_rate=config.dropout, dtype=config_dtype(config),
        attention_fn=_attention_fn(config))
    src_len = dataset.features.shape[1] - dataset.targets.shape[1]
    return Seq2SeqAdapter(inner, src_len)


def _measured_flash_speedup() -> float | None:
    """The last RECORDED flash-vs-dense ratio from the bench's attention
    micro; None when never measured (``utils.bench_records`` owns the key
    and file)."""
    from distributed_deep_learning_tpu.utils.bench_records import (
        read_flash_speedup)

    return read_flash_speedup()


def _attention_fn(config: Config):
    """Resolve ``--attention``: the Pallas flash kernel is the TPU default
    for the transformer family (in-kernel causal + padding masks, no (T×T)
    score materialisation); dense elsewhere, and either can be forced.

    ``auto`` is DATA-GATED (VERDICT r4 item 8): if the benchmark has
    recorded a flash-vs-dense ratio meaningfully below parity on this
    repo's own hardware history, auto resolves to dense even on TPU — the
    default must never be slower than what it replaced.  The cutoff is
    0.9, not 1.0 (ADVICE r4): the gate is latest-wins, so a single noisy
    run measuring e.g. 0.98 must not flip the fleet default over
    measurement jitter.  Forcing ``--attention flash`` bypasses the gate.
    """
    choice = config.attention
    if choice == "auto":
        import jax

        if jax.default_backend() == "tpu":
            speedup = _measured_flash_speedup()
            choice = "dense" if speedup is not None and speedup < 0.9 \
                else "flash"
        else:
            choice = "dense"
    if choice == "flash":
        from distributed_deep_learning_tpu.ops.attention_pallas import (
            make_attention_fn)

        return make_attention_fn()
    return None  # models fall back to dense dot_product_attention
    # (--window rides as a MODEL attribute — CausalLM.attention_window —
    # so the flash kernel, the dense fallback and the KV-cache decode all
    # apply the same band; see models/transformer.py)


def _vocab(dataset) -> int:
    """Vocabulary size: carried by file-based token datasets, 1024 for the
    synthetic twins."""
    return int(getattr(dataset, "vocab_size", 1024))


def _lm_geometry(config: Config, dataset):
    """(d_model, heads, mlp_dim, src_len, tgt_len) for the LM variants."""
    d = config.size
    tgt_len = dataset.targets.shape[1]
    src_len = dataset.features.shape[1] - tgt_len
    return d, max(2, d // 64), 4 * d, src_len, tgt_len


def _transformer_pipelined(config: Config, dataset, mesh):
    """``-m pipeline``: decoder-only causal LM over src⊕tgt tokens, logits
    read at the target positions (see :mod:`..models.pipelined_lm` for the
    divergence rationale — SPMD pipelining needs a homogeneous trunk)."""
    from distributed_deep_learning_tpu.models.pipelined_lm import PipelinedLM

    d, heads, mlp, src_len, tgt_len = _lm_geometry(config, dataset)
    return PipelinedLM(vocab_size=_vocab(dataset),
                       num_layers=config.num_layers,
                       d_model=d, num_heads=heads, mlp_dim=mlp, mesh=mesh,
                       causal=True, head_take=(src_len - 1, tgt_len),
                       microbatch_size=config.microbatch,
                       dtype=config_dtype(config),
                       attention_fn=_attention_fn(config),
                       dropout_rate=config.dropout,
                       n_chunks=_n_chunks(config))


def _transformer_layers(config: Config, dataset):
    """``-m model``: the same decoder-only LM as a partitionable layer list
    (embed / causal blocks / sliced head) for MPMD staging."""
    from distributed_deep_learning_tpu.models.pipelined_lm import (LMEmbed,
                                                                   LMHead)
    from distributed_deep_learning_tpu.models.transformer import (
        TransformerLayer)

    d, heads, mlp, src_len, tgt_len = _lm_geometry(config, dataset)
    dtype = config_dtype(config)
    vocab = _vocab(dataset)
    return [LMEmbed(vocab, d, dtype=dtype)] + [
        TransformerLayer(heads, mlp, dropout_rate=0.0, causal=True,
                         dtype=dtype)
        for _ in range(config.num_layers)
    ] + [LMHead(vocab, take=(src_len - 1, tgt_len), dtype=dtype)]


TRANSFORMER_SPEC = WorkloadSpec(
    name="transformer",
    build_dataset=_wmt_dataset,
    build_model=_transformer_model,
    build_layers=_transformer_layers,
    partitioner=balanced_partition,
    build_loss=_token_ce_loss,
    build_optimizer=lambda c, steps: adamw(
        resolve_lr(c, steps, c.learning_rate)),
    example_input=lambda c, ds: jnp.zeros((1, ds.features.shape[1]),
                                          jnp.int32),
    tp_rules=lambda c: transformer_tp_rules(),
    build_pipelined=_transformer_pipelined,
)


# --- bert (C4 MLM) ---------------------------------------------------------

def _mlm_dataset(config: Config, vocab: int = 1024, mask_id: int = 103):
    if config.data_dir:
        from distributed_deep_learning_tpu.data.tokens import (load_tokens,
                                                               mlm_dataset)

        tokens = load_tokens(config.data_dir)
        if tokens is not None:
            return mlm_dataset(tokens, mask_id=mask_id, seed=config.seed)
    ds = synthetic_c4_mlm(vocab_size=vocab, mask_id=mask_id, seed=config.seed)
    # loss/metric sites are exactly the masked positions: keep the original
    # id there and 0 (= ignore) everywhere else, matching the pad-exclusion
    # convention of token_cross_entropy / prediction_metrics
    targets = np.where(ds.features == mask_id, ds.targets, 0)
    return ArrayDataset(ds.features, targets.astype(np.int32))


def _bert_model(config: Config, dataset):
    d = config.size
    return BertEncoder(vocab_size=_vocab(dataset),
                       num_layers=config.num_layers,
                       d_model=d, num_heads=max(2, d // 64), mlp_dim=4 * d,
                       dropout_rate=config.dropout,
                       dtype=config_dtype(config),
                       attention_fn=_attention_fn(config))


def _bert_pipelined(config: Config, dataset, mesh):
    """``-m pipeline``: bidirectional trunk + untied MLM head over the
    ``stage`` axis (the full BertEncoder's tied head stays in ``-m data``)."""
    from distributed_deep_learning_tpu.models.pipelined_lm import PipelinedLM

    d = config.size
    return PipelinedLM(vocab_size=_vocab(dataset),
                       num_layers=config.num_layers,
                       d_model=d, num_heads=max(2, d // 64), mlp_dim=4 * d,
                       mesh=mesh, causal=False,
                       microbatch_size=config.microbatch,
                       dtype=config_dtype(config),
                       attention_fn=_attention_fn(config),
                       dropout_rate=config.dropout,
                       n_chunks=_n_chunks(config))


def _bert_layers(config: Config, dataset):
    from distributed_deep_learning_tpu.models.pipelined_lm import (LMEmbed,
                                                                   LMHead)
    from distributed_deep_learning_tpu.models.transformer import (
        TransformerLayer)

    d = config.size
    dtype = config_dtype(config)
    vocab = _vocab(dataset)
    return [LMEmbed(vocab, d, dtype=dtype)] + [
        TransformerLayer(max(2, d // 64), 4 * d, dropout_rate=0.0,
                         dtype=dtype)
        for _ in range(config.num_layers)
    ] + [LMHead(vocab, dtype=dtype)]


BERT_SPEC = WorkloadSpec(
    name="bert",
    build_dataset=_mlm_dataset,
    build_model=_bert_model,
    build_layers=_bert_layers,
    partitioner=balanced_partition,
    build_loss=_token_ce_loss,
    build_optimizer=lambda c, steps: adamw(
        resolve_lr(c, steps, c.learning_rate)),
    example_input=lambda c, ds: jnp.zeros((1, ds.features.shape[1]),
                                          jnp.int32),
    tp_rules=lambda c: transformer_tp_rules(),
    build_pipelined=_bert_pipelined,
)

# --- moe (sparse-expert MLM) -----------------------------------------------

def _moe_model(config: Config, dataset):
    from distributed_deep_learning_tpu.models.moe import MoELM

    d = config.size
    return MoELM(vocab_size=_vocab(dataset),
                 num_layers=config.num_layers, d_model=d,
                 num_heads=max(2, d // 64), mlp_dim=4 * d,
                 num_experts=8, dropout_rate=config.dropout,
                 dtype=config_dtype(config),
                 attention_fn=_attention_fn(config))


def _moe_rules(config: Config):
    """Expert weights over `expert`; everything else replicated (dense
    blocks could add the Megatron rules, kept replicated for clarity)."""
    from distributed_deep_learning_tpu.models.moe import moe_param_rules

    return moe_param_rules()


def _moe_no_staging(config, dataset):
    raise ValueError(
        "moe parallelises over experts, not stages: use -m data with "
        "--mesh expert=K (staged modes would drop the router's "
        "load-balance aux loss)")


MOE_SPEC = WorkloadSpec(
    name="moe",
    build_dataset=_mlm_dataset,
    build_model=_moe_model,
    build_layers=_moe_no_staging,
    partitioner=lambda n, s: np.zeros(n, np.int64),
    build_loss=_token_ce_loss,
    build_optimizer=lambda c, steps: adamw(
        resolve_lr(c, steps, c.learning_rate)),
    example_input=lambda c, ds: jnp.zeros((1, ds.features.shape[1]),
                                          jnp.int32),
    tp_rules=_moe_rules,
)

# --- gpt (decoder-only causal LM) ------------------------------------------

def _gpt_dataset(config: Config, seq_len: int = 64, vocab: int = 1024):
    if config.data_dir:
        from distributed_deep_learning_tpu.data.tokens import (lm_dataset,
                                                               load_tokens)

        tokens = load_tokens(config.data_dir)
        if tokens is not None:
            return lm_dataset(tokens)
    from distributed_deep_learning_tpu.data.datasets import synthetic_lm

    # vocab matches _vocab()'s synthetic default (1024)
    return synthetic_lm(seq_len=seq_len, vocab_size=vocab, seed=config.seed)


def _gpt_model(config: Config, dataset):
    from distributed_deep_learning_tpu.models.transformer import CausalLM

    d = config.size
    return CausalLM(vocab_size=_vocab(dataset),
                    num_layers=config.num_layers, d_model=d,
                    num_heads=max(2, d // 64), mlp_dim=4 * d,
                    dropout_rate=config.dropout, with_logits=True,
                    max_len=max(dataset.features.shape[1], 8),
                    pos_embedding=config.pos_embedding,
                    attention_window=config.attention_window,
                    num_kv_heads=config.num_kv_heads,
                    dtype=config_dtype(config),
                    attention_fn=_attention_fn(config))


def _gpt_layers(config: Config, dataset):
    """``-m model``: embed / causal blocks / full-sequence head."""
    from distributed_deep_learning_tpu.models.pipelined_lm import (LMEmbed,
                                                                   LMHead)
    from distributed_deep_learning_tpu.models.transformer import (
        TransformerLayer)

    d = config.size
    dtype = config_dtype(config)
    max_len = max(dataset.features.shape[1], 8)
    return [LMEmbed(_vocab(dataset), d, max_len=max_len, dtype=dtype,
                    pos_embedding=config.pos_embedding)] + [
        TransformerLayer(max(2, d // 64), 4 * d, dropout_rate=0.0,
                         causal=True, dtype=dtype,
                         rope=config.pos_embedding == "rope",
                         window=config.attention_window,
                         num_kv_heads=config.num_kv_heads)
        for _ in range(config.num_layers)
    ] + [LMHead(_vocab(dataset), dtype=dtype)]  # predict at every position


def _gpt_pipelined(config: Config, dataset, mesh):
    from distributed_deep_learning_tpu.models.pipelined_lm import PipelinedLM

    d = config.size
    return PipelinedLM(vocab_size=_vocab(dataset),
                       num_layers=config.num_layers, d_model=d,
                       num_heads=max(2, d // 64), mlp_dim=4 * d, mesh=mesh,
                       causal=True,  # head_take None: every position
                       microbatch_size=config.microbatch,
                       max_len=max(dataset.features.shape[1], 4096),
                       dtype=config_dtype(config),
                       attention_fn=_attention_fn(config),
                       dropout_rate=config.dropout,
                       n_chunks=_n_chunks(config),
                       pos_embedding=config.pos_embedding,
                       attention_window=config.attention_window,
                       num_kv_heads=config.num_kv_heads)


#: prompt length _gpt_generate slices from the dataset (rows 0-1)
_GENERATE_PROMPT_LEN = 8


def _gpt_pre_check(config: Config, dataset) -> None:
    """Reject an impossible ``--generate N`` BEFORE training: generate()
    checks prompt + N <= max_len itself, but only after the expensive part
    has finished (ADVICE r3).  Staged/pipelined modes are exempt —
    :func:`_gpt_generate` skips generation there with a notice, so the
    length can never be exercised and a pre-train error would reject runs
    that previously completed."""
    from distributed_deep_learning_tpu.utils.config import Mode

    if not config.generate_tokens or config.mode in (Mode.MODEL,
                                                     Mode.PIPELINE):
        return
    max_len = max(dataset.features.shape[1], 8)  # mirrors _gpt_model
    prompt = min(_GENERATE_PROMPT_LEN, dataset.features.shape[1])
    if prompt + config.generate_tokens > max_len:
        raise ValueError(
            f"--generate {config.generate_tokens}: prompt {prompt} + new "
            f"tokens exceeds the model's max_len {max_len} (the dataset "
            f"sequence length); at most {max_len - prompt} tokens fit")


def _gpt_generate(config: Config, state, logger, dataset) -> None:
    """``--generate N``: print KV-cached greedy continuations of two
    dataset prompts (rows 0-1 — typically TRAINING rows after the
    shuffled split, so treat the output as a smoke sample, not held-out
    evaluation) in the reference's quote-delimited log style."""
    from distributed_deep_learning_tpu.models.transformer import generate

    params = getattr(state, "params", None)
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    if not isinstance(params, dict) or "embed" not in params:
        # staged/pipelined states carry per-stage param lists, not the
        # CausalLM tree — a notice, not a crash, after a finished run
        logger.info("generate skipped: --generate needs the whole-model "
                    "parameter tree (-m data or sequential)")
        return
    model = _gpt_model(config, dataset)
    prompts = jnp.asarray(dataset.features[:2, :_GENERATE_PROMPT_LEN],
                          jnp.int32)
    out = generate(model, params, prompts,
                   max_new_tokens=config.generate_tokens)
    for row_p, row_o in zip(prompts.tolist(), out.tolist()):
        logger.info(f"generate prompt={row_p} continuation={row_o}")


def _serve_supervision_kw(config: Config) -> dict | None:
    """Supervisor kwargs when any serve-resilience knob is on the CLI
    (``--serve-deadline-ms`` / ``--reload-watch`` / ``--admission``);
    ``None`` means run the engine bare, exactly as before the
    supervisor existed.  ``--serve-retries`` and ``--canary-slots``
    only shape behaviour once one of the trigger knobs is set."""
    if (config.serve_deadline_ms is None and not config.reload_watch
            and config.admission is None):
        return None
    return dict(deadline_ms=config.serve_deadline_ms,
                retries=config.serve_retries,
                reload_watch=config.reload_watch,
                canary_slots=config.canary_slots,
                admission=config.admission)


def _log_supervision(logger, sv: dict) -> None:
    """One log line for the supervisor-level outcome (the engine-level
    tokens/sec line still follows from ``stats["engine"]``)."""
    line = (f"serve(supervised): restarts={sv['restarts']}, lost="
            f"{sv['requests_lost']}, deadline_misses="
            f"{sv['deadline_misses']}, ticks={sv['ticks']}")
    r = sv.get("reload")
    if r:
        line += (f", reload swaps={r['swaps']} rollbacks={r['rollbacks']}"
                 f" rejected={r['rejected']}")
    a = sv.get("admission")
    if a:
        line += f", admission level={a['level']} shed={a['shed_total']}"
    logger.info(line)


def _gpt_serve(config: Config, state, logger, dataset) -> None:
    """``--serve``: push a seeded mixed-length request trace (prompts
    drawn over the dataset's vocabulary) through the continuous-batching
    engine (serve/engine.py) on the just-trained weights and log
    tokens/sec, mean slot occupancy and compile counts — the
    serving-path sibling of ``--generate``'s batch-synchronous smoke
    sample.  With ``--paged`` the trace goes through the paged engine
    instead (block KV + prefix reuse + chunked prefill, ``--draft N``
    speculation) and the log line adds hit rate / acceptance / SLOs."""
    from distributed_deep_learning_tpu.serve.bench import (make_trace,
                                                           run_engine,
                                                           run_supervised)

    params = getattr(state, "params", None)
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    if not isinstance(params, dict) or "embed" not in params:
        logger.info("serve skipped: --serve needs the whole-model "
                    "parameter tree (-m data or sequential)")
        return
    model = _gpt_model(config, dataset)
    seq = dataset.features.shape[1]
    # prompt + budget must fit the slot capacity (the model's max_len,
    # dataset-derived and possibly tiny in smoke runs)
    p_hi = max(2, min(_GENERATE_PROMPT_LEN, seq, model.max_len - 1))
    new_hi = max(1, min(config.generate_tokens or 16,
                        model.max_len - p_hi))
    if config.paged:
        _gpt_serve_paged(config, model, params, logger, dataset,
                         p_hi, new_hi)
        return
    trace = make_trace(max(2 * config.max_slots, 8),
                       vocab_size=_vocab(dataset), seed=config.seed,
                       prompt_lens=(2, p_hi), new_tokens=(1, new_hi))
    sup_kw = _serve_supervision_kw(config)
    quant_kw = dict(kv_dtype=config.kv_dtype,
                    weight_dtype=config.weight_dtype)
    if sup_kw is None:
        out = run_engine(model, params, trace,
                         max_slots=config.max_slots,
                         prefill_buckets=config.prefill_buckets,
                         **quant_kw)
        s = out["stats"]
    else:
        out = run_supervised(model, params, trace,
                             max_slots=config.max_slots,
                             prefill_buckets=config.prefill_buckets,
                             **quant_kw, **sup_kw)
        _log_supervision(logger, out["stats"])
        s = out["stats"]["engine"]
        if s is None:
            return
    logger.info(
        f"serve: {s['requests']} requests, {s['generated_tokens']} tokens "
        f"at {s['tokens_per_sec']:.1f} tok/s, occupancy "
        f"{s['mean_slot_occupancy']:.2f}/{s['max_slots']}, compiles "
        f"prefill={s['prefill_compiles']} decode={s['decode_compiles']}")


def _gpt_serve_paged(config: Config, model, params, logger, dataset,
                     p_hi: int, new_hi: int) -> None:
    """``--serve --paged``: the same trace shape through the paged
    engine, with the config's block/chunk/draft/SLO knobs applied."""
    import dataclasses

    from distributed_deep_learning_tpu.serve.bench import (make_trace,
                                                           paged_max_len,
                                                           run_paged,
                                                           run_supervised)

    draft = config.draft or None
    if draft is not None and not 1 <= draft < model.num_layers:
        logger.info(f"serve: --draft {draft} needs 1 <= draft < "
                    f"{model.num_layers} (the model's layer count); "
                    "speculation disabled")
        draft = None
    block = min(config.kv_block_size, model.max_len)
    try:
        cap = paged_max_len(model.max_len, block, draft is not None,
                            config.spec_k)
    except ValueError as exc:
        logger.info(f"serve: paged engine skipped ({exc})")
        return
    p_hi = max(2, min(p_hi, cap - 1))
    new_hi = max(1, min(new_hi, cap - p_hi))
    trace = make_trace(max(2 * config.max_slots, 8),
                       vocab_size=_vocab(dataset), seed=config.seed,
                       prompt_lens=(2, p_hi), new_tokens=(1, new_hi))
    if config.slo_ttft_ms or config.slo_e2e_ms:
        trace = [dataclasses.replace(r, slo_ttft_ms=config.slo_ttft_ms,
                                     slo_e2e_ms=config.slo_e2e_ms)
                 for r in trace]
    engine_kw = dict(max_slots=config.max_slots, max_len=cap,
                     kv_block_size=block,
                     prefill_chunk=min(config.prefill_chunk, cap),
                     draft_layers=draft, spec_k=config.spec_k,
                     kv_dtype=config.kv_dtype,
                     weight_dtype=config.weight_dtype)
    if config.priority_classes:
        # seeded priority mix over the same trace (mirrors
        # LoadSpec.priority_classes) + engine-side preemption so the
        # mix has teeth: low-priority slots spill under pressure
        pcs = config.priority_classes
        rng = np.random.default_rng(config.seed)
        fr = np.asarray([f for _, f in pcs]) / sum(f for _, f in pcs)
        trace = [dataclasses.replace(r, priority=int(
            rng.choice([p for p, _ in pcs], p=fr))) for r in trace]
        engine_kw.update(preempt=True, spill_dir=config.spill_dir,
                         migrate=config.migrate)
    if config.disagg:
        _gpt_serve_disagg(config, model, params, logger, trace, engine_kw)
        return
    if config.replicas > 1:
        _gpt_serve_fleet(config, model, params, logger, trace, engine_kw)
        return
    sup_kw = _serve_supervision_kw(config)
    if sup_kw is None:
        out = run_paged(model, params, trace, **engine_kw)
        s = out["stats"]
    else:
        out = run_supervised(model, params, trace, paged=True,
                             **engine_kw, **sup_kw)
        _log_supervision(logger, out["stats"])
        s = out["stats"]["engine"]
        if s is None:
            return
    pg, sp, slo = s["paged"], s["spec"], s["slo"]
    line = (f"serve(paged): {s['requests']} requests, "
            f"{s['generated_tokens']} tokens at "
            f"{s['tokens_per_sec']:.1f} tok/s, prefix hit "
            f"{pg['prefix_hit_rate']:.3f}, cow {pg['cow_copies']}, "
            f"compiles chunk={s['chunk_compiles']} "
            f"decode={s['decode_compiles']} "
            f"verify={s['verify_compiles']}")
    if sp["enabled"] and sp["acceptance_rate"] is not None:
        line += f", spec acceptance {sp['acceptance_rate']:.3f}"
    if slo["slo_attainment"] is not None:
        line += f", slo attainment {slo['slo_attainment']:.2f}"
    logger.info(line)


def _gpt_serve_fleet(config: Config, model, params, logger, trace,
                     engine_kw: dict) -> None:
    """``--serve --paged --replicas N``: the same trace through N
    supervised paged replicas behind the prefix-affinity fleet router
    (serve/fleet.py) — crash quarantine, zero-loss replay, per-priority
    SLO rollup."""
    from distributed_deep_learning_tpu.serve.admission import (
        AdmissionController)
    from distributed_deep_learning_tpu.serve.engine import PagedEngine
    from distributed_deep_learning_tpu.serve.fleet import FleetRouter

    engines = [PagedEngine(model, params, **engine_kw)
               for _ in range(config.replicas)]
    admissions = None
    if config.admission is not None:
        admissions = {i: AdmissionController(**config.admission)
                      for i in range(config.replicas)}
    autoscaler = engine_factory = None
    if config.autoscale is not None:
        from distributed_deep_learning_tpu.serve.autoscaler import (
            FleetAutoscaler)

        autoscaler = FleetAutoscaler(**config.autoscale)
        # the published-weights seam: every grown replica serves the
        # same params the fleet was launched with
        engine_factory = lambda: PagedEngine(model, params, **engine_kw)  # noqa: E731
    flt = FleetRouter(engines, deadline_ms=config.serve_deadline_ms,
                      retries=config.serve_retries, admissions=admissions,
                      evacuate_on=config.evacuate_on,
                      autoscaler=autoscaler, engine_factory=engine_factory)
    out = flt.run(list(trace))
    st = out["stats"]
    tokens = sum(len(v) for v in out["results"].values())
    line = (f"serve(fleet): {st['requests']} requests over "
            f"{len(engines)} replicas, {tokens} tokens, rounds="
            f"{st['rounds']}, lost={st['requests_lost']}, predicted hit "
            f"tokens {st['routing']['predicted_hit_tokens']}, compiles "
            f"decode={max(v['decode_compiles'] for v in st['per_replica'].values())}")
    slo = st["slo"]
    if slo.get("slo_attainment") is not None:
        line += f", slo attainment {slo['slo_attainment']:.2f}"
        bp = slo.get("by_priority") or {}
        if bp:
            line += " (" + ", ".join(
                f"p{p}={s['slo_attainment']:.2f}" for p, s in
                sorted(bp.items()) if s["slo_attainment"] is not None) + ")"
    rb = st.get("rebalance")
    if rb and rb["evacuate_on"] != "off":
        line += (f", evacuated {rb['evacuated_slots']} slots "
                 f"({rb['evacuated_tokens']} tokens, "
                 f"{rb['rolled_back']} rolled back)")
    asc = st.get("autoscaler")
    if asc:
        line += (f", scale events {asc['scale_events']} "
                 f"(+{asc['grows']}/-{asc['shrinks']}, "
                 f"{asc['replicas_final']} final)")
    logger.info(line)


def _gpt_serve_disagg(config: Config, model, params, logger, trace,
                      engine_kw: dict) -> None:
    """``--serve --paged --disagg``: the same trace through the
    disaggregated engine (serve/disagg.py) — prefill worker pool +
    decode worker pool on disjoint devices, per-prompt KV-block
    migration handoff, greedy outputs bit-identical to the unified
    engine."""
    import jax

    from distributed_deep_learning_tpu.serve.disagg import DisaggEngine

    if config.draft:
        logger.info("serve(disagg): --draft ignored (speculation runs "
                    "on the unified engine only)")
    ndev = len(jax.local_devices())
    eng = DisaggEngine(
        model, params,
        prefill_workers=config.prefill_workers,
        decode_workers=max(1, ndev - config.prefill_workers),
        max_slots=engine_kw["max_slots"], max_len=engine_kw["max_len"],
        kv_block_size=engine_kw["kv_block_size"],
        prefill_chunk=engine_kw["prefill_chunk"],
        kv_dtype=engine_kw["kv_dtype"],
        weight_dtype=engine_kw["weight_dtype"])
    out = eng.run(list(trace))
    s = out["stats"]
    mig = s["migration"]
    logger.info(
        f"serve(disagg): {s['requests']} requests, "
        f"{s['generated_tokens']} tokens at "
        f"{s['tokens_per_sec']:.1f} tok/s over "
        f"{config.prefill_workers}P+{max(1, ndev - config.prefill_workers)}D, "
        f"prefill util {s['prefill_util']:.2f}, migrated "
        f"{mig['moves']} handoffs ({mig['wire_bytes']} B), compiles "
        f"chunk={s['chunk_compiles']} decode={s['decode_compiles']}")
    if config.pool_elastic:
        from distributed_deep_learning_tpu.serve.autoscaler import (
            PoolRebalancer)

        # judge the measured utilisation as a sustained signal: the
        # run-level prefill_util IS the whole run's average, so feed it
        # through the full patience window before actuating
        bal = PoolRebalancer()
        direction = None
        for _ in range(bal.patience):
            direction = bal.observe(s["prefill_util"])
        if direction and eng.reassign(direction):
            logger.info(
                f"serve(disagg): pool-elastic moved one worker "
                f"{direction.replace('_', ' ')} (prefill util "
                f"{s['prefill_util']:.2f}); pools now "
                f"{len(eng.prefill)}P+{len(eng.decode)}D")
        else:
            logger.info(
                f"serve(disagg): pool-elastic held the split "
                f"(prefill util {s['prefill_util']:.2f} inside the "
                f"hysteresis band, or no idle worker to move)")


def _gpt_post(config: Config, state, logger, dataset) -> None:
    if config.generate_tokens:
        _gpt_generate(config, state, logger, dataset)
    if config.serve:
        _gpt_serve(config, state, logger, dataset)


GPT_SPEC = WorkloadSpec(
    name="gpt",
    build_dataset=_gpt_dataset,
    build_model=_gpt_model,
    build_layers=_gpt_layers,
    partitioner=balanced_partition,
    build_loss=_token_ce_loss,
    build_optimizer=lambda c, steps: adamw(
        resolve_lr(c, steps, c.learning_rate)),
    example_input=lambda c, ds: jnp.zeros((1, ds.features.shape[1]),
                                          jnp.int32),
    tp_rules=lambda c: transformer_tp_rules(),
    build_pipelined=_gpt_pipelined,
    post_train=_gpt_post,
    pre_train_check=_gpt_pre_check,
)

SPECS = {"resnet": RESNET_SPEC, "transformer": TRANSFORMER_SPEC,
         "bert": BERT_SPEC, "moe": MOE_SPEC, "gpt": GPT_SPEC}


def main(argv=None, workload: str = "resnet"):
    config = parse_args(argv, workload=workload)
    return run_workload(SPECS[workload], config)
