"""Elastic training: restart-from-checkpoint on failure.

Closes the loop between :mod:`..utils.failures` (detect) and
:mod:`..utils.checkpoint` (preserve): when a step dies — a peer vanishes
mid-collective, the device runtime resets, a preemption lands mid-epoch —
the run restores the last epoch checkpoint and continues, instead of
losing the job.  The reference's failure model was "any rank failure hangs
or kills the job" (SURVEY.md §5); this is the TPU-pod answer, where the
scheduler restarting you is routine, not exceptional.

The unit of recovery is the epoch (matching the checkpoint cadence of
:func:`..loop.fit`); mid-epoch progress is repeated deterministically
(seeded loaders), so a recovered run equals an uninterrupted one.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from distributed_deep_learning_tpu.train.loop import EpochResult, fit
from distributed_deep_learning_tpu.utils.checkpoint import Checkpointer
from distributed_deep_learning_tpu.utils.failures import (FailureMonitor,
                                                          WorkerFailure)
from distributed_deep_learning_tpu.utils.logging import PhaseLogger


def fit_with_recovery(make_state: Callable[[], Any], train_step, eval_step,
                      loaders: Sequence, epochs: int,
                      checkpointer: Checkpointer, *,
                      logger: PhaseLogger | None = None,
                      monitor: FailureMonitor | None = None,
                      max_restarts: int = 2
                      ) -> tuple[Any, list[EpochResult]]:
    """Run :func:`..loop.fit` with checkpointed restart on failure.

    ``make_state`` builds a FRESH initial state (used as the restore
    target; called once per attempt so donated buffers from the failed
    attempt are never reused).  Failures caught: :class:`WorkerFailure`
    from the monitor and runtime errors surfaced by JAX; after
    ``max_restarts`` recoveries the last error propagates.
    """
    logger = logger or PhaseLogger(verbose=False)
    train_loader, val_loader, test_loader = loaders
    restarts = 0
    while True:
        state = make_state()
        last = checkpointer.latest_step()
        if last is not None:
            state = checkpointer.restore(state) or state
        start_epoch = (last or 0) + 1
        try:
            if monitor is not None:
                monitor.raise_if_failed()
                monitor.check()
            # fit polls the monitor before EVERY step, so a peer dying
            # mid-epoch aborts this attempt promptly rather than hanging
            # the next collective
            return fit(state, train_step, eval_step, train_loader,
                       val_loader, test_loader, epochs=epochs, logger=logger,
                       checkpointer=checkpointer, start_epoch=start_epoch,
                       monitor=monitor)
        except (WorkerFailure, RuntimeError) as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            logger.info(f"recovering from failure ({type(e).__name__}: {e}); "
                        f"restart {restarts}/{max_restarts} from epoch "
                        f"{checkpointer.latest_step() or 0}")
