"""MLP workload: MQTT intrusion detection (reference ``src/pytorch/MLP``).

CLI parity: ``python -m distributed_deep_learning_tpu mlp -l 2 -e 10 -b 32
-m data`` mirrors ``python MLP/main.py`` flags.  Input width is data-driven
(fixes quirk Q6: the reference hard-coded 48 against a model default of 52).
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.mqtt import load_mqtt
from distributed_deep_learning_tpu.models.mlp import MLP, mlp_layer_sequence
from distributed_deep_learning_tpu.parallel.partition import balanced_partition
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import reference_optimizer
from distributed_deep_learning_tpu.utils.config import Config, parse_args
from distributed_deep_learning_tpu.workloads.base import (
    WorkloadSpec, config_dtype, example_from_dataset, run_workload)

NUM_CLASSES = 5


def _dataset(config: Config):
    if config.data_dir:
        # an explicit --data-dir must fail loudly, not silently fall back
        import os

        return load_mqtt(os.path.join(config.data_dir, "dataset.csv"))
    try:
        return load_mqtt()
    except FileNotFoundError:
        return synthetic_mqtt(seed=config.seed)


def _model(config: Config, dataset):
    return MLP(hidden_size=config.size, num_hidden_layers=config.num_layers,
               num_classes=NUM_CLASSES, double_softmax=config.double_softmax,
               dtype=config_dtype(config))


def _layers(config: Config, dataset):
    return mlp_layer_sequence(config.size, config.num_layers, NUM_CLASSES,
                              config.double_softmax, config_dtype(config))


def _loss(config: Config):
    if config.double_softmax:
        return lambda p, t: cross_entropy_loss(p, t, from_probabilities=True)
    return cross_entropy_loss


SPEC = WorkloadSpec(
    name="mlp",
    build_dataset=_dataset,
    build_model=_model,
    build_layers=_layers,
    partitioner=balanced_partition,  # reference MLP/model.py:62-76
    build_loss=_loss,
    build_optimizer=lambda c, steps: reference_optimizer("mlp", c.learning_rate),
    example_input=example_from_dataset,
)


def main(argv=None):
    config = parse_args(argv, workload="mlp")
    return run_workload(SPEC, config)


if __name__ == "__main__":
    main()
