"""Real multi-process distributed paths: 2 OS processes rendezvous through
jax.distributed (CPU backend), covering bootstrap's distributed branch, the
``process_count() > 1`` loader branch, and cross-process gradient psum —
the launch path the reference covers with torch.multiprocessing.spawn
(reference CNN/main.py:202)."""

import os
import re

import pytest

from distributed_deep_learning_tpu.runtime.launch import (free_port,
                                                          launch_local)


@pytest.mark.slow
def test_two_process_cli_data_mode():
    """`mlp -m data -r 2 --spawn` semantics: both ranks finish rc=0 and the
    coordinator prints the reference log grammar."""
    res = launch_local(2, ["mlp", "-e", "1", "-b", "64", "-m", "data",
                           "-r", "2"],
                       extra_env={"DDL_DATA_LIMIT": "512"}, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)
    # rank 1 is not the coordinator: no phase logs
    assert "train epoch" not in res[1].stdout


@pytest.mark.slow
def test_two_process_gradients_stay_synchronised():
    """The distributed selftest: per-rank param checksums after fused-psum
    steps must be bit-identically equal (quirk Q1 — silently diverging
    replicas — is impossible by construction)."""
    res = launch_local(
        2, [], module="distributed_deep_learning_tpu.runtime.selftest",
        timeout=420)
    lines = [next(ln for ln in r.stdout.splitlines()
                  if ln.startswith("SELFTEST")) for r in res]
    parsed = [dict(kv.split("=") for kv in ln.split()[1:]) for ln in lines]
    assert [p["rank"] for p in parsed] == ["0", "1"]
    assert all(p["world"] == "2" for p in parsed)
    assert parsed[0]["loss"] == parsed[1]["loss"]
    assert parsed[0]["checksum"] == parsed[1]["checksum"]


def test_free_port_is_bindable():
    import socket

    port = free_port()
    with socket.socket() as s:
        s.bind(("127.0.0.1", port))


@pytest.mark.slow
def test_failing_rank_output_is_surfaced():
    """A rank that dies with copious output must not deadlock the launch;
    its log tail appears in the RuntimeError (review regression: rank-order
    pipe draining could block on a full 64KB buffer)."""
    with pytest.raises(RuntimeError, match="ranks failed"):
        launch_local(2, [], module="tests.helpers.noisy_rank",
                     force_cpu=True, timeout=60)


@pytest.mark.slow
def test_two_process_pipeline_mode():
    """VERDICT r4 item 6: the SPMD pipeline's `stage` axis SPANS processes
    — 2 processes x 2 devices = 4 pipeline stages, ppermute crossing the
    process boundary every tick."""
    res = launch_local(2, ["bert", "-l", "4", "-s", "32", "-e", "1",
                           "-b", "16", "-m", "pipeline", "--nstages", "4",
                           "-r", "2"],
                       extra_env={"DDL_DATA_LIMIT": "64"},
                       devices_per_process=2, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert "SPMD pipeline: 4 stages x 1-way data parallel" in res[0].stdout
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)


@pytest.mark.slow
def test_two_process_fsdp():
    """--zero fsdp with the shard axis spanning processes: parameters and
    optimizer state live sharded over 2 procs x 2 devices."""
    res = launch_local(2, ["mlp", "-e", "1", "-b", "64", "-m", "data",
                           "-r", "2", "--zero", "fsdp"],
                       extra_env={"DDL_DATA_LIMIT": "256"},
                       devices_per_process=2, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)


@pytest.mark.slow
def test_two_process_checkpoint_restart(tmp_path):
    """Checkpoint written by a 2-process run restores into a FRESH
    2-process run (the pod preemption/restart path): the relaunch resumes
    past the saved epoch instead of retraining it."""
    ck = str(tmp_path / "ck")
    args = ["mlp", "-e", "1", "-b", "64", "-m", "data", "-r", "2",
            "--checkpoint-dir", ck]
    res = launch_local(2, args, extra_env={"DDL_DATA_LIMIT": "256"},
                       timeout=420)
    assert all(r.returncode == 0 for r in res)

    args2 = ["mlp", "-e", "2", "-b", "64", "-m", "data", "-r", "2",
             "--checkpoint-dir", ck, "--resume"]
    res2 = launch_local(2, args2, extra_env={"DDL_DATA_LIMIT": "256"},
                        timeout=420)
    assert all(r.returncode == 0 for r in res2)
    out = res2[0].stdout
    assert "resumed from epoch 1" in out
    assert "train epoch 1 ends" not in out      # epoch 1 NOT retrained
    assert re.search(r'"train epoch 2 ends at .* with accuracy', out)


@pytest.mark.slow
def test_four_process_gradients_stay_synchronised():
    """VERDICT r4 item 6 (scale past 2): the reference ran 8-rank mpirun
    (CNN/main.py:192-196); here 4 OS processes rendezvous and the fused
    psum keeps all four replicas bit-identical."""
    res = launch_local(
        4, [], module="distributed_deep_learning_tpu.runtime.selftest",
        timeout=420)
    lines = [next(ln for ln in r.stdout.splitlines()
                  if ln.startswith("SELFTEST")) for r in res]
    parsed = [dict(kv.split("=") for kv in ln.split()[1:]) for ln in lines]
    assert [p["rank"] for p in parsed] == ["0", "1", "2", "3"]
    assert all(p["world"] == "4" for p in parsed)
    assert len({p["checksum"] for p in parsed}) == 1
    assert len({p["loss"] for p in parsed}) == 1


@pytest.mark.slow
def test_four_process_pipeline_stage_axis_spans_processes():
    """stage=8 over 4 processes x 2 devices: every pipeline ppermute tick
    crosses three process boundaries."""
    res = launch_local(4, ["bert", "-l", "8", "-s", "32", "-e", "1",
                           "-b", "16", "-m", "pipeline", "--nstages", "8",
                           "-r", "4"],
                       extra_env={"DDL_DATA_LIMIT": "64"},
                       devices_per_process=2, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert "SPMD pipeline: 8 stages x 1-way data parallel" in res[0].stdout
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)


@pytest.mark.slow
def test_four_process_fsdp_shards_span_processes():
    """--zero fsdp with the shard axis spanning 4 procs x 2 devices = 8
    shards: params/optimizer state live distributed across processes."""
    res = launch_local(4, ["mlp", "-e", "1", "-b", "64", "-m", "data",
                           "-r", "4", "--zero", "fsdp"],
                       extra_env={"DDL_DATA_LIMIT": "256"},
                       devices_per_process=2, timeout=420)
    assert all(r.returncode == 0 for r in res)
    assert re.search(r'"train epoch 1 ends at .* with accuracy',
                     res[0].stdout)


@pytest.mark.slow
def test_two_process_step_granular_mid_epoch_recovery(tmp_path):
    """VERDICT r4 item 5: both ranks die MID-EPOCH (step 8 = epoch 2,
    batch 3 of 5) under --checkpoint-every 2; recovery resumes from the
    step-7 boundary — not the epoch — and the finished run's final test
    metrics EQUAL an uninterrupted run's (bit-identical continuation)."""
    base = ["mlp", "-e", "2", "-b", "64", "-m", "data", "-r", "2",
            "--checkpoint-every", "2"]
    env = {"DDL_DATA_LIMIT": "512"}

    ref = launch_local(2, [*base, "--checkpoint-dir",
                           str(tmp_path / "ref")], extra_env=env,
                       timeout=420)
    assert all(r.returncode == 0 for r in ref)

    res = launch_local(2, [*base, "--elastic", "--checkpoint-dir",
                           str(tmp_path / "ck")],
                       extra_env={**env, "DDL_INJECT_STEP_FAILURE": "all:8"},
                       timeout=420)
    assert all(r.returncode == 0 for r in res)
    for rank, r in enumerate(res):
        assert f"CHAOS: injected failure on rank {rank} at step 8" in r.stdout
    out = res[0].stdout
    # recovery happened at STEP granularity (epoch 2, step 2 saved)
    assert "restart 1/2 from epoch 2 step 2" in out
    # and the result is the uninterrupted run's, to the last digit
    final = re.search(r'"test ends at .* with (accuracy .*)"', out)
    ref_final = re.search(r'"test ends at .* with (accuracy .*)"',
                          ref[0].stdout)
    assert final and ref_final and final.group(1) == ref_final.group(1)


@pytest.mark.slow
def test_two_process_elastic_recovery_preemption():
    """VERDICT r4 item 6: the whole 2-process job FAILS at epoch 2 (the
    pod-preemption drill — on a real pod the scheduler kills and restarts
    every process together; a single rank cannot restore solo because its
    peers' in-flight collectives and the checkpoint barriers both span the
    full world).  Every rank's fit_with_recovery restores the epoch-1
    checkpoint and the run completes rc=0 on both ranks."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        res = launch_local(
            2, ["mlp", "-e", "3", "-b", "64", "-m", "data", "-r", "2",
                "--elastic", "--checkpoint-dir", os.path.join(d, "ck")],
            extra_env={"DDL_DATA_LIMIT": "256",
                       "DDL_INJECT_FAILURE": "all:2"},
            timeout=420)
    assert all(r.returncode == 0 for r in res)
    # the drill actually fired on BOTH ranks (rc=0 thus proves recovery)
    for rank, r in enumerate(res):
        assert f"CHAOS: injected failure on rank {rank} at epoch 2" \
            in r.stdout
    # coordinator history is complete: every epoch trained + final test
    out = res[0].stdout
    for e in (1, 2, 3):
        assert re.search(rf'"train epoch {e} ends at .* with accuracy', out)
    assert re.search(r'"test ends at .* with accuracy', out)
