"""Transformer models — BASELINE configs[3,4] (WMT seq2seq, BERT MLM).

TPU-first design decisions:

* One :class:`TransformerLayer` definition serves encoder (bidirectional),
  decoder (causal + cross-attention) and BERT (bidirectional) — the
  homogeneous-stack shape that the SPMD pipeline
  (:mod:`..parallel.spmd_pipeline`) and tensor-parallel sharding rules
  (:mod:`..parallel.tp`) both want.
* ``attention_fn`` is pluggable: dense softmax attention by default;
  :mod:`..ops.ring_attention` (sequence-parallel ppermute ring) or the
  Pallas flash kernel slot in without touching the model.
* bf16 compute / f32 params via ``dtype``; logits always f32.
* Fixed shapes, no data-dependent control flow: causal masking is a static
  triangular mask, padding via additive masks — everything jit-tileable.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

AttentionFn = Callable[..., jnp.ndarray]
dense_init = nn.initializers.xavier_uniform()


def dot_product_attention(q, k, v, *, mask=None, key_valid=None,
                          causal=False, window=None, dtype=jnp.float32):
    """Plain softmax attention; q/k/v are (B, T, H, D).

    Masking follows the structured convention shared with the flash and
    ring implementations: ``key_valid`` is a (B, Tk) boolean padding mask,
    ``causal`` a flag, ``window`` an optional causal sliding-window size
    (each query sees its last ``window`` positions); a pre-built dense
    ``mask`` (broadcastable to (B, H, Tq, Tk)) is also accepted and
    combined.
    """
    depth = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(depth)
    if key_valid is not None:
        kv = key_valid[:, None, None, :]
        mask = kv if mask is None else jnp.logical_and(mask, kv)
    if causal:
        tril = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))[None, None]
        mask = tril if mask is None else jnp.logical_and(mask, tril)
    if window is not None:
        if not causal and mask is None:
            raise ValueError("window requires causal attention")
        qp = jnp.arange(q.shape[1])[:, None]
        kp = jnp.arange(k.shape[1])[None, :]
        band = ((qp - kp) < window)[None, None]
        mask = band if mask is None else jnp.logical_and(mask, band)
    if mask is not None:
        # -1e9, not finfo(f32).min: the latter overflows to -inf in bf16
        # (same exponent range, smaller mantissa → rounds past bf16 max) and
        # a fully-padded row would softmax to NaN; -1e9 degrades to uniform
        # attention on such rows, which the loss masks out anyway.
        logits = jnp.where(mask, logits, jnp.asarray(-1e9, logits.dtype))
    weights = nn.softmax(logits.astype(jnp.float32)).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               base: float = 10000.0) -> jnp.ndarray:
    """Rotary position embedding on ``(B, T, H, D)`` (D even).

    Rotates feature pairs ``(x[..., :D/2], x[..., D/2:])`` by
    ``position · base^(-2i/D)`` — attention then depends on RELATIVE
    positions only.  Parameter-free, so tensor-parallel sharding rules
    and the weight-tied head are untouched; the KV-cache decode path
    passes ``positions = cache_index + arange(T)`` so cached keys carry
    their absolute rotation.
    """
    if x.shape[-1] % 2:
        raise ValueError(f"RoPE requires an even head_dim, got "
                         f"{x.shape[-1]} (pick num_heads so that "
                         "d_model/num_heads is even)")
    d2 = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(d2, dtype=jnp.float32) / d2)    # (d2,)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None]   # (T, d2)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], -1).astype(x.dtype)


class MultiHeadAttention(nn.Module):
    """Projections + pluggable attention; ``decode=True`` adds a KV cache.

    The cache is created at init time (full-length call shapes the
    ``cached_key``/``cached_value`` buffers); each subsequent 1-token call
    appends its K/V at ``cache_index`` and attends the single query
    against the filled prefix — autoregressive decode costs O(T) per
    token instead of O(T²) recompute.
    """

    num_heads: int
    dtype: jnp.dtype = jnp.float32
    attention_fn: Optional[AttentionFn] = None
    decode: bool = False
    rope: bool = False
    window: Optional[int] = None   # causal sliding-window size
    num_kv_heads: Optional[int] = None  # < num_heads = grouped-query attn

    @nn.compact
    def __call__(self, x_q, x_kv, key_valid=None, *, causal: bool = False,
                 mask=None):
        d_model = x_q.shape[-1]
        head_dim = d_model // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        if self.num_heads % kv_heads:
            raise ValueError(f"num_kv_heads {kv_heads} must divide "
                             f"num_heads {self.num_heads}")
        proj = lambda name, h: nn.DenseGeneral(  # noqa: E731
            (h, head_dim), dtype=self.dtype,
            kernel_init=dense_init, name=name)
        q = proj("q", self.num_heads)(x_q)
        k = proj("k", kv_heads)(x_kv)
        v = proj("v", kv_heads)(x_kv)
        if self.rope:
            start = jnp.zeros((), jnp.int32)
            if self.decode and self.has_variable("cache", "cache_index"):
                start = self.get_variable("cache", "cache_index")
            positions = start + jnp.arange(q.shape[1])
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)  # cached K carry their rotation
        attn = self.attention_fn or dot_product_attention
        if self.decode:
            is_init = not self.has_variable("cache", "cached_key")
            ck = self.variable("cache", "cached_key", jnp.zeros, k.shape,
                               k.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros, v.shape,
                               v.dtype)
            # remember each cached position's padding validity too — the
            # full forward masks pad tokens, so decode must as well
            cvalid = self.variable(
                "cache", "cached_valid",
                lambda: jnp.zeros(k.shape[:2], jnp.bool_))
            idx = self.variable("cache", "cache_index",
                                lambda: jnp.zeros((), jnp.int32))
            if not is_init:
                T = q.shape[1]
                max_len = ck.value.shape[1]
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k, (0, idx.value, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v, (0, idx.value, 0, 0))
                step_valid = (key_valid if key_valid is not None
                              else jnp.ones(k.shape[:2], jnp.bool_))
                cvalid.value = jax.lax.dynamic_update_slice(
                    cvalid.value, step_valid, (0, idx.value))
                k, v = ck.value, cv.value
                key_valid = cvalid.value
                # causal prefix: query j (global position idx+j) sees key
                # positions <= idx+j — correct for 1-token steps AND
                # multi-token prefill chunks
                qpos = idx.value + jnp.arange(T)
                kpos = jnp.arange(max_len)[None, None, None, :]
                mask = kpos <= qpos[None, None, :, None]
                if self.window is not None:
                    # the trained model never attends beyond its window —
                    # decode must not either (train/inference parity)
                    mask = jnp.logical_and(
                        mask,
                        qpos[None, None, :, None] - kpos < self.window)
                idx.value = idx.value + T
                causal = False
                # dense direct: the flash adapter would route this dense
                # mask to the same path anyway, minus a spurious warning
                attn = dot_product_attention
        if kv_heads != self.num_heads and \
                not getattr(attn, "supports_gqa", False):
            # GQA: K/V carry kv_heads (and the KV cache stores only those
            # — the H/kv_heads memory win); expand to full heads for the
            # attention contraction (XLA fuses the broadcast).  A
            # GQA-native implementation (the flash kernel) takes the
            # unexpanded K/V and maps heads internally — group× less K/V
            # HBM traffic, which is the other half of the GQA win.
            group = self.num_heads // kv_heads
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        kw = {}
        if self.window is not None and mask is None:
            # structured convention: window rides alongside causal so the
            # flash kernel can bound its key loops instead of masking
            kw["window"] = self.window
        y = attn(q, k, v, mask=mask, key_valid=key_valid, causal=causal,
                 dtype=self.dtype, **kw)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               kernel_init=dense_init, name="out")(y)


class TransformerLayer(nn.Module):
    """Pre-LN block: [self-attn] → [cross-attn]? → [MLP], residuals.

    ``self_valid``/``cross_valid`` are (B, T) boolean padding masks handed
    to the attention implementation in structured form (never as a dense
    (T×T) tensor) so fused kernels can apply them in-block.
    """

    num_heads: int = 8
    mlp_dim: int = 2048
    dropout_rate: float = 0.1
    causal: bool = False
    cross_attention: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_fn: Optional[AttentionFn] = None
    decode: bool = False
    rope: bool = False
    window: Optional[int] = None
    num_kv_heads: Optional[int] = None
    ln_eps: float = 1e-6   # 1e-5 matches torch/HF LayerNorm (GPT-2 import)

    @nn.compact
    def __call__(self, x, encoded=None, *, self_valid=None, cross_valid=None,
                 train: bool = False):
        h = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps)(x)
        h = MultiHeadAttention(self.num_heads, self.dtype, self.attention_fn,
                               decode=self.decode, rope=self.rope,
                               window=self.window,
                               num_kv_heads=self.num_kv_heads,
                               name="self_attn")(h, h, self_valid,
                                                 causal=self.causal)
        h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        x = x + h
        if self.cross_attention:
            h = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps)(x)
            h = MultiHeadAttention(self.num_heads, self.dtype,
                                   self.attention_fn,
                                   name="cross_attn")(h, encoded, cross_valid)
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
            x = x + h
        h = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, kernel_init=dense_init)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype, kernel_init=dense_init)(h)
        h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return x + h


class Embed(nn.Module):
    vocab_size: int
    d_model: int
    max_len: int = 4096
    dtype: jnp.dtype = jnp.float32
    decode: bool = False
    use_pos: bool = True   # False: no learned positions (RoPE models)

    @nn.compact
    def __call__(self, tokens):
        emb = nn.Embed(self.vocab_size, self.d_model,
                       embedding_init=nn.initializers.normal(0.02),
                       dtype=self.dtype, name="tok")
        if not self.use_pos:
            return emb(tokens), emb
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (self.max_len, self.d_model))
        T = tokens.shape[1]
        if self.decode and self.has_variable("cache", "pos_index"):
            # single-token decode: position = running cache index
            idx = self.variable("cache", "pos_index",
                                lambda: jnp.zeros((), jnp.int32))
            p = jax.lax.dynamic_slice_in_dim(pos, idx.value, T)
            idx.value = idx.value + T
        else:
            if self.decode:  # init pass: create the counter
                self.variable("cache", "pos_index",
                              lambda: jnp.zeros((), jnp.int32))
            p = pos[:T]
        x = emb(tokens) + p[None].astype(self.dtype)
        return x, emb

    @staticmethod
    def logits(x, emb):
        """Weight-tied output projection, accumulated in f32.

        Not ``emb.attend``: Flax's attend re-casts both operands to the
        module dtype, so under bf16 the vocab-wide matmul would accumulate
        in bf16 — here the cast to f32 happens *before* the contraction.
        """
        table = jnp.asarray(emb.embedding, jnp.float32)
        return jnp.einsum("...d,vd->...v", x.astype(jnp.float32), table)


class TransformerSeq2Seq(nn.Module):
    """Transformer-base encoder-decoder (WMT14 en-de shape).

    ``__call__(batch)`` with ``batch = {"inputs": (B,S), "targets": (B,T)}``
    (token ids, 0 = pad) does teacher-forced training: returns logits over
    the target vocabulary at every target position.
    """

    vocab_size: int = 32000
    num_layers: int = 6
    d_model: int = 512
    num_heads: int = 8
    mlp_dim: int = 2048
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, batch, train: bool = False):
        inputs, targets = batch["inputs"], batch["targets"]
        src_valid = inputs != 0    # (B, S)
        tgt_valid = targets != 0   # (B, T)

        # one shared-vocabulary embedding for source, target and the
        # (weight-tied) output projection — the transformer-base recipe
        embed = Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                      name="embed")
        x, emb = embed(inputs)
        for i in range(self.num_layers):
            x = TransformerLayer(self.num_heads, self.mlp_dim,
                                 self.dropout_rate, dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 name=f"enc_{i}")(x, self_valid=src_valid,
                                                  train=train)
        encoded = nn.LayerNorm(dtype=self.dtype, name="enc_norm")(x)

        # shift right: BOS-from-zero teacher forcing
        y_in = jnp.pad(targets, ((0, 0), (1, 0)))[:, :-1]
        y, _ = embed(y_in)
        for i in range(self.num_layers):
            y = TransformerLayer(self.num_heads, self.mlp_dim,
                                 self.dropout_rate, causal=True,
                                 cross_attention=True, dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 name=f"dec_{i}")(y, encoded,
                                                  self_valid=tgt_valid,
                                                  cross_valid=src_valid,
                                                  train=train)
        y = nn.LayerNorm(dtype=self.dtype, name="dec_norm")(y)
        return Embed.logits(y, emb)


class CausalLM(nn.Module):
    """GPT-style decoder-only LM — the long-context flagship shape.

    ``__call__(tokens)`` returns the final hidden states ``(B, T, d)``;
    ``loss(params, hidden, targets)`` computes the weight-tied LM loss via
    :func:`..ops.fused_ce.fused_linear_cross_entropy` (never materialises
    the ``(B·T, V)`` logit matrix), and ``logits_from(params, hidden)``
    the explicit projection for eval/tests.  The reference has no autoregressive model
    at all (its only sequence model consumes 10-step windows,
    ``LSTM/dataset.py:25``); this is the shape ring attention / Ulysses /
    the SPMD pipeline and the flash kernels are built to scale.
    """

    vocab_size: int = 32000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    max_len: int = 8192
    with_logits: bool = False   # True: __call__ returns (B, T, V) logits
    decode: bool = False        # KV-cached autoregressive decode mode
    pos_embedding: str = "learned"   # learned | rope
    attention_window: Optional[int] = None  # causal sliding window
    num_kv_heads: Optional[int] = None      # grouped-query attention
    dtype: jnp.dtype = jnp.float32
    attention_fn: Optional[AttentionFn] = None
    ln_eps: float = 1e-6   # 1e-5 matches torch/HF LayerNorm (GPT-2 import)
    pad_id: Optional[int] = 0   # None: no padding id (GPT-2's id 0 is "!")

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        valid = tokens != self.pad_id if self.pad_id is not None else None
        rope = self.pos_embedding == "rope"
        x, emb = Embed(self.vocab_size, self.d_model, max_len=self.max_len,
                       dtype=self.dtype, decode=self.decode,
                       use_pos=not rope, name="embed")(tokens)
        for i in range(self.num_layers):
            x = TransformerLayer(self.num_heads, self.mlp_dim,
                                 self.dropout_rate, causal=True,
                                 dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 decode=self.decode, rope=rope,
                                 window=self.attention_window,
                                 num_kv_heads=self.num_kv_heads,
                                 ln_eps=self.ln_eps,
                                 name=f"layer_{i}")(x, self_valid=valid,
                                                    train=train)
        x = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps,
                         name="final_norm")(x)
        # the CLI/workload convention wants logits (token_cross_entropy +
        # argmax metrics); the bench path keeps hidden states and the
        # fused head (loss()) so (B·T, V) never materialises
        return Embed.logits(x, emb) if self.with_logits else x

    def _table(self, params):
        return params["params"]["embed"]["tok"]["embedding"]

    def loss(self, params, hidden, targets):
        """Mean next-token cross-entropy via the fused head; positions
        whose target equals ``self.pad_id`` are excluded, and with
        ``pad_id=None`` every position counts (e.g. imported GPT-2, whose
        id 0 is a real token).  Pass ``tokens[:, :-1]`` hidden vs
        ``tokens[:, 1:]``."""
        from distributed_deep_learning_tpu.ops.fused_ce import (
            fused_linear_cross_entropy)

        # -1 can never equal a vocab id, so it disables the exclusion
        ignore_id = self.pad_id if self.pad_id is not None else -1
        return fused_linear_cross_entropy(
            hidden.astype(jnp.float32),
            jnp.asarray(self._table(params), jnp.float32), targets,
            ignore_id)

    def logits_from(self, params, hidden):
        table = jnp.asarray(self._table(params), jnp.float32)
        return jnp.einsum("...d,vd->...v", hidden.astype(jnp.float32), table)


class BertEncoder(nn.Module):
    """BERT-base-shaped bidirectional encoder with an MLM head
    (BASELINE config[4]: MLM pretrain, pjit 2D mesh + ZeRO-1)."""

    vocab_size: int = 30522
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    attention_fn: Optional[AttentionFn] = None
    ln_eps: float = 1e-6   # HF BERT checkpoints use 1e-12

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        valid = tokens != 0  # (B, T)
        x, emb = Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       name="embed")(tokens)
        for i in range(self.num_layers):
            x = TransformerLayer(self.num_heads, self.mlp_dim,
                                 self.dropout_rate, dtype=self.dtype,
                                 attention_fn=self.attention_fn,
                                 ln_eps=self.ln_eps,
                                 name=f"layer_{i}")(x, self_valid=valid,
                                                    train=train)
        x = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps,
                         name="final_norm")(x)
        # MLM head: dense + gelu + norm, weight-tied vocab projection
        h = nn.Dense(self.d_model, dtype=self.dtype, name="mlm_dense")(x)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=self.dtype, epsilon=self.ln_eps,
                         name="mlm_norm")(h)
        return Embed.logits(h, emb)


def transformer_base(**kw) -> TransformerSeq2Seq:
    return TransformerSeq2Seq(**kw)


def bert_base(**kw) -> BertEncoder:
    return BertEncoder(**kw)


def make_decode_model(model: "CausalLM") -> "CausalLM":
    """The KV-cached inference twin of a trained :class:`CausalLM`:
    decode mode on, hidden-state output (the weight-tied head projects
    only the positions that are sampled), dropout off.  Both
    :func:`generate` and the continuous-batching engine
    (:mod:`..serve.engine`) decode through this one clone recipe."""
    return model.clone(decode=True, with_logits=False, dropout_rate=0.0)


def init_cache(lm: "CausalLM", batch: int, total_len: int,
               token_dtype=jnp.int32):
    """Zeroed decode-cache pytree for ``batch`` rows of ``total_len``.

    Cache buffers are zeros by construction, so they are shaped via
    ``eval_shape`` — no full-length forward, no throwaway parameter
    init.  ``lm`` must be a decode-mode model (:func:`make_decode_model`).
    """
    shapes = jax.eval_shape(lm.init, jax.random.key(0),
                            jax.ShapeDtypeStruct((batch, total_len),
                                                 token_dtype))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def cached_apply(lm: "CausalLM", params, cache, tokens):
    """One cached forward — a multi-token prefill chunk or a 1-token
    decode step (the decode-mode causal prefix mask keeps in-chunk
    attention causal either way).  Returns ``(hidden, new_cache)``.
    The single implementation under both :func:`generate` and the
    serving engine's prefill/decode programs."""
    hidden, upd = lm.apply({"params": params, "cache": cache}, tokens,
                           mutable=["cache"])
    return hidden, upd["cache"]


def validate_sampling(top_k: int | None, top_p: float | None) -> None:
    """Host-side bounds check shared by every sampling entry point."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def sample_tokens(model: "CausalLM", params, hidden_last, key, *,
                  temperature: float = 0.0, top_k: int | None = None,
                  top_p: float | None = None):
    """Project final hidden states ``(B, d)`` through the weight-tied
    head and pick one token per row; returns ``(tokens (B,), key)``.

    THE sampler — :func:`generate` and the serving engine both call it,
    so greedy/top-k/top-p semantics cannot drift between the batch and
    continuous-batching paths.  Greedy at ``temperature == 0.0``, else
    samples from ``softmax(logits / temperature)``; top-k and top-p
    (nucleus) filters compose, k first then p, as in the common HF
    semantics.  Top-k selection is ``jax.lax.top_k`` — O(V·k) partial
    selection instead of a full per-step vocab sort.
    """
    nl = model.logits_from({"params": params}, hidden_last)  # (B, V)
    if model.pad_id is not None:
        # never emit the pad id: the cache records a generated pad as
        # invalid (valid = tokens != pad_id), silently dropping that
        # position from all subsequent attention and skewing the
        # continuation (ADVICE r3).  pad_id=None (e.g. imported
        # GPT-2, whose id 0 is a real token) has no such hazard.
        nl = nl.at[:, model.pad_id].set(-jnp.inf)
    if top_k is not None and top_k < nl.shape[-1]:
        # mask everything below the k-th logit (static k — jit-safe)
        kth = jax.lax.top_k(nl, top_k)[0][:, -1][:, None]
        nl = jnp.where(nl >= kth, nl, -jnp.inf)
    if temperature == 0.0:
        return jnp.argmax(nl, axis=-1), key
    scaled = nl / temperature
    if top_p is not None and top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p (the crossing token included)
        order = jnp.argsort(-scaled, axis=-1)
        sp = jnp.take_along_axis(jax.nn.softmax(scaled, axis=-1),
                                 order, axis=-1)
        drop_sorted = jnp.cumsum(sp, axis=-1) - sp > top_p
        drop = jnp.zeros_like(drop_sorted).at[
            jnp.arange(nl.shape[0])[:, None], order].set(drop_sorted)
        scaled = jnp.where(drop, -jnp.inf, scaled)
    key, sub = jax.random.split(key)
    return jax.random.categorical(sub, scaled), key


def generate(model: "CausalLM", params, prompt: jnp.ndarray, *,
             max_new_tokens: int, temperature: float = 0.0,
             top_k: int | None = None, top_p: float | None = None,
             rng: jnp.ndarray | None = None) -> jnp.ndarray:
    """KV-cached autoregressive generation from a trained :class:`CausalLM`.

    ``prompt`` is (B, P) token ids; returns the (B, max_new_tokens)
    continuation.  Greedy at ``temperature == 0.0``, else samples from
    ``softmax(logits / temperature)``, optionally truncated to the top-k
    logits and/or the top-p (nucleus) mass — both filters compose, k
    first then p, as in the common HF semantics.  The whole loop is one
    ``lax.scan`` of 1-token cached decode steps (O(T) per token via the
    attention KV cache; positions follow the cache index) —
    jit-compatible, static shapes, TPU-friendly.

    The reference has no inference story at all (SURVEY.md: every run is
    train-then-test); this is part of the LM-family surface a complete
    framework owes its users.

    The prompt is prefilled in ONE multi-token cached call (the decode
    path's causal prefix mask keeps in-chunk attention causal), then each
    new token is a 1-token step.  Pad positions (id ``model.pad_id``)
    inside the prompt are masked out of attention via the cache's
    validity buffer (with ``pad_id=None`` — e.g. imported GPT-2 — every
    prompt position is attended and nothing is masked), but generation
    always proceeds from each row's FINAL position — prefer unpadded
    (or left-trimmed) prompts.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
    validate_sampling(top_k, top_p)
    # hidden-state mode: project ONLY the final position through the
    # weight-tied head — prefill never materialises the (B, P, V) logits
    lm = make_decode_model(model)
    B, P = prompt.shape
    total = P + max_new_tokens
    if total > model.max_len:
        raise ValueError(f"prompt {P} + {max_new_tokens} new tokens "
                         f"exceeds max_len {model.max_len}")
    cache = init_cache(lm, B, total, prompt.dtype)
    key0 = rng if rng is not None else jax.random.key(0)

    def pick(hidden_last, key):
        return sample_tokens(model, params, hidden_last, key,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p)

    # prefill: the whole prompt in ONE multi-token cached call (the
    # decode-mode causal prefix mask keeps in-chunk attention causal)
    hidden, cache = cached_apply(lm, params, cache, prompt)
    first, key0 = pick(hidden[:, -1], key0)
    first = first.astype(prompt.dtype)

    def step(carry, _):
        cache, tok, key = carry
        hidden, cache = cached_apply(lm, params, cache, tok[:, None])
        nxt, key = pick(hidden[:, -1], key)
        return (cache, nxt.astype(tok.dtype), key), nxt

    (_, _, _), toks = jax.lax.scan(
        step, (cache, first, key0), None, length=max_new_tokens - 1)
    return jnp.concatenate(
        [first[:, None], jnp.swapaxes(toks, 0, 1).astype(prompt.dtype)],
        axis=1)
