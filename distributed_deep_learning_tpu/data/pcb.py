"""PCB-defect bbox-crop dataset (reference ``CNN/dataset.py``).

Semantics reproduced (``CNN/dataset.py:32-111``):

* VOC-style tree: ``<root>/Annotations/<class>/*.xml`` bounding boxes paired
  with ``<root>/images/<class>/*.jpg``; one sample per (image, bbox);
* augmentation doubles the dataset: each bbox yields two virtual samples
  with independent random shifts ∈ [5, 10] applied to the crop origin
  (``:79, 91-96``);
* crop of the (shifted) bbox, padded with zeros where it leaves the image,
  resized to 64×64 bilinear (``:100``); one-hot class target.

Deliberate fixes over the reference (documented divergences):

* **Bbox coordinate order.** The reference's XML parser emits
  ``(xmin, xmax, ymin, ymax)`` (``CNN/dataset.py:38``) but the consumer
  unpacks ``(xmin, ymin, xmax, ymax)`` (``:94``) — so its "height" is
  ``ymin - xmax`` (often negative) and crops are scrambled.  We parse and
  consume ``(xmin, ymin, xmax, ymax)`` consistently.
* **Q7:** the empty-class error path referenced an undefined variable
  (``:66-67``); ours raises a well-formed error.
* XML via stdlib ``xml.etree`` (the reference used libxml2+XPath); output
  layout is NHWC float32.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET

import numpy as np

from distributed_deep_learning_tpu.data._threaded import ThreadedDecodeMixin

IMAGE_SIZE = 64


def bounding_boxes(path: str) -> list[tuple[int, int, int, int]]:
    """Parse ``/annotation/object/bndbox`` entries → (xmin, ymin, xmax, ymax)."""
    root = ET.parse(path).getroot()
    boxes = []
    for obj in root.findall("./object/bndbox"):
        vals = {k: int(float(obj.findtext(k))) for k in
                ("xmin", "ymin", "xmax", "ymax")}
        boxes.append((vals["xmin"], vals["ymin"], vals["xmax"], vals["ymax"]))
    return boxes


def find_classes(directory: str) -> tuple[list[str], dict[str, int]]:
    classes = sorted(e.name for e in os.scandir(directory) if e.is_dir())
    if not classes:
        raise FileNotFoundError(f"no class directories under {directory}")
    return classes, {c: i for i, c in enumerate(classes)}


def make_dataset(image_root: str, annotation_root: str,
                 class_to_idx: dict[str, int]) -> list[tuple[str, tuple, int]]:
    """(image_path, bbox, class_index) per bounding box."""
    instances = []
    available = set()
    for target_class in sorted(class_to_idx):
        class_index = class_to_idx[target_class]
        target_dir = os.path.join(image_root, target_class)
        if not os.path.isdir(target_dir):
            continue
        for root_dir, _, fnames in sorted(os.walk(target_dir, followlinks=True)):
            for fname in sorted(fnames):
                if not fname.endswith(".jpg"):
                    continue
                xml_path = os.path.join(annotation_root, target_class,
                                        os.path.splitext(fname)[0] + ".xml")
                for box in bounding_boxes(xml_path):
                    instances.append((os.path.join(root_dir, fname), box,
                                      class_index))
                    available.add(target_class)
    empty = set(class_to_idx) - available
    if empty:
        raise FileNotFoundError(
            f"found no valid .jpg files for classes: {', '.join(sorted(empty))}")
    return instances


class PCBDataset(ThreadedDecodeMixin):
    """ArrayDataset-API-compatible (``__len__``/``batch``) bbox-crop dataset."""

    def __init__(self, root: str = "/data/PCB_DATASET/", seed: int = 42,
                 image_size: int = IMAGE_SIZE, max_cached_images: int = 16,
                 workers: int | None = None):
        ann = os.path.join(root, "Annotations")
        if not os.path.isdir(ann):
            raise FileNotFoundError(
                f"{ann} not found — use data.datasets.synthetic_pcb for the "
                "shape-compatible synthetic twin")
        self.classes, self.class_to_idx = find_classes(ann)
        self.samples = make_dataset(os.path.join(root, "images"), ann,
                                    self.class_to_idx)
        self.image_size = image_size
        # augmentation doubling: one independent shift per VIRTUAL sample
        rng = np.random.default_rng(seed)
        self.shift = rng.integers(5, 11, size=len(self.samples) * 2)
        # Bounded LRU over decoded full-res images (PCB photos are ~14 MB
        # decoded; an unbounded cache would hold the whole corpus) plus
        # threaded batch decode, shared with ImageFolderDataset
        # (:class:`.._threaded.ThreadedDecodeMixin`).  The epoch is
        # JPEG-decode-bound (~125 decodes/s/core, scripts/data_soak.py at
        # reference scale): threads saturate the host's cores — flat on
        # the 2-core CI box (~250 samples/s either way, both cores busy),
        # ~8x headroom on a many-core TPU-VM host.
        self._init_decode(min(8, os.cpu_count() or 1) if workers is None
                          else workers, max_cached_images)

    def __len__(self) -> int:
        return len(self.samples) * 2          # reference __len__ = 2·samples

    @staticmethod
    def _decode(path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def _load_image(self, path: str) -> np.ndarray:
        return self._cached(path, self._decode)

    def _crop_resize(self, img: np.ndarray, top: int, left: int,
                     height: int, width: int) -> np.ndarray:
        """Zero-padded crop then bilinear resize (reference ``resized_crop``
        semantics); the resize runs in the native C++ library
        (:func:`..native.crop_resize_bilinear`, align_corners=False) rather
        than PIL — same convention as torchvision's functional resize."""
        from distributed_deep_learning_tpu import native

        h, w = img.shape[:2]
        height, width = max(height, 1), max(width, 1)
        out = np.zeros((height, width, 3), dtype=np.float32)
        y0, y1 = max(top, 0), min(top + height, h)
        x0, x1 = max(left, 0), min(left + width, w)
        if y1 > y0 and x1 > x0:
            out[y0 - top:y1 - top, x0 - left:x1 - left] = img[y0:y1, x0:x1]
        return native.crop_resize_bilinear(out, 0, 0, height, width,
                                           self.image_size, self.image_size)

    def item(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        path, (xmin, ymin, xmax, ymax), target = self.samples[index >> 1]
        shift = int(self.shift[index])
        top, left = ymin + shift, xmin + shift
        height, width = ymax - ymin, xmax - xmin
        x = self._crop_resize(self._load_image(path), top, left, height, width)
        y = np.zeros(len(self.classes), dtype=np.float32)
        y[target] = 1.0
        return x, y

    # batch() comes from ThreadedDecodeMixin (threaded item decode)
