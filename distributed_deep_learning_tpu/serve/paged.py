"""Paged KV cache: fixed-size blocks, refcounts, hash-keyed prefix reuse.

The slot table (:mod:`.cache`) gives every slot a private ``max_len``
stripe of KV — correct, but at planet scale fatally wasteful: a million
requests sharing one system prompt each re-prefill it, and each holds a
private copy of identical KV.  This module re-hosts the cache one level
lower, as vLLM-style PAGES:

* **Device side** — each sequence-axis cache leaf becomes a pool
  ``(num_blocks, block_size, ...)``; a slot's logical cache is the
  concatenation of the physical blocks its BLOCK TABLE names.
  :func:`gather_slot` materialises one slot back into the model's
  ``B=1`` cache layout (so the engine still runs the model's own tested
  cached decode — paging is invisible to the model), and
  :func:`scatter_span` writes freshly-computed KV positions back into
  their blocks.  All shapes are static; tables/positions are data, so
  the compile-once contract survives intact.
* **Host side** — :class:`BlockManager` owns the free list, per-block
  refcounts, per-slot tables, and a :class:`PrefixIndex` keyed by a
  ROLLING CHAIN HASH of token-prefix chunks: ``h_i = H(h_{i-1} ||
  tokens_i)`` identifies the entire prefix through block *i*, not just
  the block's own tokens, so a hash hit means the whole prefix matches
  (token equality is re-verified — a collision can never corrupt).
  Matching blocks are attached to the new slot's table by REFERENCE
  (refcount++), the prefill computes only the unshared tail, and a
  shared block is copied (:func:`copy_block`, copy-on-write) the moment
  a slot needs to write into it.

KV at position ``p`` depends only on tokens ``0..p`` (causal), so a
block whose prefix-chain matches holds bit-identical KV to what a fresh
prefill would compute — prefix reuse cannot change a single output
token, which is what lets the parity tests assert exact equality
against ``generate()``.

Physical block 0 is a TRASH block: gathers may read it (garbage in,
discarded out — free slots, tail padding) and masked writes are routed
to it, so real blocks only ever receive committed positions.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_deep_learning_tpu.models.transformer import init_cache
from distributed_deep_learning_tpu.serve.cache import (COUNTER_LEAVES,
                                                       KV_LEAVES,
                                                       _leaf_name)

#: physical id of the write-discard / read-garbage block (never allocated)
TRASH = 0


def is_counter(path) -> bool:
    return _leaf_name(path) in COUNTER_LEAVES


def chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    """Rolling prefix hash: digest of the previous chain digest plus this
    block's token ids.  ``h_i`` therefore commits to the ENTIRE token
    prefix through block *i* — equal hashes (plus the token-equality
    re-check) mean equal prefixes, hence bit-equal KV."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


# --- device-side pool ops (pure functions of pytrees) ---------------------


def build_pools(lm, num_blocks: int, block_size: int, padded_len: int,
                token_dtype=jnp.int32, kv_dtype: Optional[str] = None):
    """Zeroed block pools shaped from the decode model's own cache.

    ``eval_shape`` of a ``(1, padded_len)`` cache init gives the leaf
    vocabulary; sequence-axis leaves (``cached_key/value/valid``) become
    ``(num_blocks, block_size, ...)`` pools, counter leaves shrink to a
    placeholder (positions are host-owned — the host scheduler must know
    every slot's position anyway, so the device copy would only mirror
    it; :func:`gather_slot` injects the host value instead).

    ``kv_dtype`` picks the at-rest precision of the KV payload leaves:
    ``None`` keeps the model's own dtype, ``"bf16"`` halves it, and
    ``"int8"`` stores each KV leaf as a :class:`.quant.QuantTensor`
    (int8 pool + an f32 per-position-per-head scale pool with the same
    leading dims, so every tree-mapped pool op below indexes both
    coherently).  Bool validity and counters are exact regardless."""
    if padded_len != (padded_len // block_size) * block_size:
        raise ValueError(f"padded_len {padded_len} must be a multiple of "
                         f"block_size {block_size}")
    per_slot = init_cache(lm, 1, padded_len, token_dtype)

    def alloc(path, leaf):
        if is_counter(path):
            return jnp.zeros((), leaf.dtype)          # unused placeholder
        shape = (num_blocks, block_size) + leaf.shape[2:]
        if kv_dtype is not None and _leaf_name(path) in KV_LEAVES \
                and jnp.issubdtype(leaf.dtype, jnp.floating):
            if kv_dtype == "bf16":
                return jnp.zeros(shape, jnp.bfloat16)
            if kv_dtype == "int8":
                from distributed_deep_learning_tpu.serve.quant import \
                    QuantTensor
                return QuantTensor(
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:-1] + (1,), jnp.float32))
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
        return jnp.zeros(shape, leaf.dtype)

    return jax.tree_util.tree_map_with_path(alloc, per_slot)


def gather_slot(pools, table, pos):
    """One slot's logical cache in the model's ``B=1`` layout.

    ``table`` is the slot's ``(blocks_per_slot,)`` physical block ids and
    ``pos`` its position counter — both traced, so one compiled program
    serves every slot, table and position.  Trash entries gather garbage
    that the decode-path causal prefix mask (``kpos <= qpos``) keeps
    causally unreachable."""
    def g(path, leaf):
        if is_counter(path):
            return jnp.asarray(pos, leaf.dtype)
        got = leaf[table]                              # (Bps, bs, ...)
        return got.reshape((1, got.shape[0] * got.shape[1])
                           + got.shape[2:])

    return jax.tree_util.tree_map_with_path(g, pools)


def extract_span(cache, pos, n: int):
    """Positions ``[pos, pos+n)`` of a model-layout cache — the freshly
    written KV a program hands to :func:`scatter_span`.  ``n`` is static
    (the program's chunk width); ``pos`` is traced."""
    def e(path, leaf):
        if is_counter(path):
            return jnp.zeros((), jnp.int32)            # placeholder
        return jax.lax.dynamic_slice_in_dim(leaf[0], pos, n, axis=0)

    return jax.tree_util.tree_map_with_path(e, cache)


def scatter_span(pools, kv, blocks, offsets):
    """Write per-position KV back into the pools.

    ``blocks``/``offsets`` have shape ``(..., n)`` matching the leading
    dims of the ``kv`` leaves; entries routed to :data:`TRASH` discard
    their write (pad tails, inactive slots).  The host guarantees no two
    REAL (block, offset) pairs collide in one call — only trash may be
    written more than once, and trash is never read as truth."""
    def s(path, pool, upd):
        if is_counter(path):
            return pool
        if jnp.issubdtype(pool.dtype, jnp.integer) and \
                jnp.issubdtype(upd.dtype, jnp.floating):
            raise TypeError(
                f"scatter_span: float {upd.dtype} span into an integer "
                f"{pool.dtype} pool — a bare astype would truncate "
                "without a scale; quantize the span first "
                "(serve.quant.quantize_cache_span)")
        return pool.at[blocks, offsets].set(upd.astype(pool.dtype))

    return jax.tree_util.tree_map_with_path(s, pools, kv)


def copy_block(pools, src, dst):
    """Physical block copy ``dst <- src`` — the copy half of
    copy-on-write.  ``src``/``dst`` are traced scalars: one compiled
    program covers every COW for the engine's lifetime."""
    def c(path, pool):
        if is_counter(path):
            return pool
        return jax.lax.dynamic_update_slice_in_dim(
            pool, jax.lax.dynamic_slice_in_dim(pool, src, 1, axis=0),
            dst, axis=0)

    return jax.tree_util.tree_map_with_path(c, pools)


# --- host-side block manager ---------------------------------------------


@dataclasses.dataclass
class SharedPrefix:
    """Outcome of a prefix-index match for one prompt."""

    full_blocks: list       # physical ids of fully-matched blocks
    partial_block: Optional[int]   # physical id matched up to partial_len
    partial_len: int               # tokens matched inside partial_block
    chain: bytes                   # chain hash after the full blocks


@dataclasses.dataclass
class _IndexEntry:
    block: int
    tokens: tuple
    last_used: int


class PrefixIndex:
    """Chain-hash → block map with LRU bookkeeping.

    ``children`` maps a prefix chain hash to the hashes that extend it by
    one block — the partial-tail lookup (copy-on-write's entry point)
    walks it to find a cached block whose FIRST ``m`` tokens match the
    prompt's next tokens."""

    def __init__(self):
        self.entries: dict[bytes, _IndexEntry] = {}
        self.children: dict[bytes, list[bytes]] = {}
        self.by_block: dict[int, bytes] = {}
        self._clock = 0

    def __len__(self):
        return len(self.entries)

    def touch(self, h: bytes) -> None:
        self._clock += 1
        self.entries[h].last_used = self._clock

    def get(self, h: bytes):
        return self.entries.get(h)

    def add(self, parent: bytes, h: bytes, block: int,
            tokens: tuple) -> bool:
        """Register ``block`` as the completion of prefix ``parent`` with
        ``tokens``.  First registration wins (a concurrent slot that
        filled an identical block keeps its private copy)."""
        if h in self.entries or block in self.by_block:
            return False
        self._clock += 1
        self.entries[h] = _IndexEntry(block, tokens, self._clock)
        self.children.setdefault(parent, []).append(h)
        self.by_block[block] = h
        return True

    def remove(self, h: bytes) -> int:
        e = self.entries.pop(h)
        del self.by_block[e.block]
        for sibs in self.children.values():
            if h in sibs:
                sibs.remove(h)
                break
        self.children.pop(h, None)
        return e.block

    def lru(self):
        """Hashes in least-recently-used-first order."""
        return sorted(self.entries, key=lambda h: self.entries[h].last_used)


class BlockPoolExhausted(RuntimeError):
    """A single request needs more blocks than the pool will ever hold."""


class BlockManager:
    """Host truth for the paged pool: free list, refcounts, tables, index.

    Pure Python — no JAX.  The engine asks it three questions (can this
    request be admitted?  which physical blocks back slot *s*?  is this
    block writable, or must it be COW-copied first?) and tells it two
    facts (these positions are now committed; this slot retired)."""

    def __init__(self, num_blocks: int, block_size: int, max_slots: int,
                 blocks_per_slot: int):
        if num_blocks < blocks_per_slot:
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold even one slot "
                f"({blocks_per_slot} blocks)")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.blocks_per_slot = int(blocks_per_slot)
        # physical ids 1..num_blocks; 0 is TRASH
        self.free: list[int] = list(range(num_blocks, 0, -1))
        self.refs = np.zeros(num_blocks + 1, np.int32)
        self.tables = np.full((max_slots, blocks_per_slot), TRASH, np.int32)
        self.index = PrefixIndex()
        self._reserve: dict[int, int] = {}     # slot -> COW reserve block
        # slot -> (blocks hashed so far, chain hash after them)
        self._chain: dict[int, tuple[int, bytes]] = {}
        self.copies = 0
        self.evictions = 0
        self.peak_in_use = 0
        # optional observability hook: ``on_event(kind, **fields)`` fires
        # on evictions and COW detaches (the engine wires it to the
        # tracer/flight recorder; None costs nothing)
        self.on_event = None

    # --- accounting -------------------------------------------------------
    @property
    def in_use(self) -> int:
        return self.num_blocks - len(self.free)

    def _evictable(self) -> int:
        return int(sum(1 for h, e in self.index.entries.items()
                       if self.refs[e.block] == 1))

    def _alloc(self) -> int:
        b = self.free.pop()
        self.refs[b] = 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return b

    def _deref(self, b: int) -> None:
        if b == TRASH:
            return
        self.refs[b] -= 1
        if self.refs[b] < 0:
            raise AssertionError(f"block {b} refcount underflow")
        if self.refs[b] == 0:
            self.free.append(b)

    def evict(self, need: int) -> int:
        """Drop LRU index-only blocks until ``need`` are free (or no more
        are evictable).  Returns how many blocks were freed."""
        freed = 0
        for h in self.index.lru():
            if len(self.free) >= need:
                break
            b = self.index.entries[h].block
            if self.refs[b] != 1:       # some slot still references it
                continue
            self.index.remove(h)
            self._deref(b)
            self.evictions += 1
            freed += 1
        if freed and self.on_event is not None:
            self.on_event("evict", freed=freed, need=need)
        return freed

    def flush_index(self) -> int:
        """Drop EVERY prefix-index entry and the reference each holds.

        Blocks still owned by live slots stay alive (the slots' own
        refs remain); blocks the index alone retained return to the
        free list.  Hot weight swap calls this: indexed KV was computed
        under the OLD weights, so matching it as a prefix under the new
        weights would silently mix generations."""
        dropped = 0
        for h in list(self.index.entries):
            b = self.index.remove(h)
            self._deref(b)
            dropped += 1
        if dropped and self.on_event is not None:
            self.on_event("index_flush", dropped=dropped)
        return dropped

    # --- prefix matching --------------------------------------------------
    def match_prefix(self, prompt: np.ndarray) -> SharedPrefix:
        """Longest reusable prefix of ``prompt`` present in the index:
        a chain of fully-matched blocks plus at most one partially-
        matched tail block.  Capped at ``len(prompt) - 1`` — the final
        prompt token is always recomputed, because sampling the first
        output token needs its hidden state, which no KV cache stores."""
        bs = self.block_size
        toks = np.asarray(prompt)
        L = len(toks)
        h = b""
        full: list[int] = []
        i = 0
        while (i + 1) * bs <= L - 1:    # cap: never cover the last token
            blk = tuple(int(t) for t in toks[i * bs:(i + 1) * bs])
            h2 = chain_hash(h, blk)
            e = self.index.get(h2)
            if e is None or e.tokens != blk:
                break
            full.append(e.block)
            self.index.touch(h2)
            h = h2
            i += 1
        partial, m = None, 0
        rest = toks[i * bs:]
        cap = L - 1 - i * bs            # last token stays uncached
        if cap > 0:
            best = 0
            for ch in self.index.children.get(h, []):
                e = self.index.entries[ch]
                ct = np.asarray(e.tokens)
                n = int(min(len(ct), len(rest), cap))
                eq = ct[:n] == rest[:n]
                k = int(eq.argmin()) if not eq.all() else n
                if k > best:
                    best, partial = k, e.block
                    self.index.touch(ch)
            m = best
            if m == 0:
                partial = None
        sp = SharedPrefix(full, partial, m, h)
        return sp

    def shared_len(self, sp: SharedPrefix) -> int:
        return len(sp.full_blocks) * self.block_size + sp.partial_len

    # --- admission / release ----------------------------------------------
    def owned_needed(self, sp: SharedPrefix, total_len: int) -> int:
        """Fresh blocks a request needs: capacity for its whole stream
        minus the fully-shared blocks (a partially-shared block cancels
        against its COW reserve — referenced now, copied at first
        write)."""
        logical = -(-total_len // self.block_size)   # ceil
        logical = min(logical, self.blocks_per_slot)
        need = logical - len(sp.full_blocks)
        if need < 0:
            raise AssertionError("shared prefix longer than the request")
        return need

    def can_admit(self, sp: SharedPrefix, total_len: int) -> bool:
        need = self.owned_needed(sp, total_len)
        if need > self.num_blocks:
            raise BlockPoolExhausted(
                f"request needs {need} blocks; the pool holds "
                f"{self.num_blocks}")
        return len(self.free) + self._evictable() >= need

    def admit(self, slot: int, sp: SharedPrefix, total_len: int) -> int:
        """Build slot ``slot``'s block table: shared blocks by reference,
        fresh blocks for the rest, one fresh block held aside as the COW
        reserve when a partial block is referenced.  Returns the shared
        prefix length in tokens."""
        need = self.owned_needed(sp, total_len)
        if len(self.free) < need:
            self.evict(need)
        if len(self.free) < need:
            raise AssertionError("admit() called without can_admit()")
        row = self.tables[slot]
        row[:] = TRASH
        for j, b in enumerate(sp.full_blocks):
            row[j] = b
            self.refs[b] += 1
        logical = min(-(-total_len // self.block_size),
                      self.blocks_per_slot)
        j = len(sp.full_blocks)
        if sp.partial_block is not None:
            row[j] = sp.partial_block
            self.refs[sp.partial_block] += 1
            self._reserve[slot] = self._alloc()
            j += 1
            need -= 1
        while j < logical:
            row[j] = self._alloc()
            j += 1
        self._chain[slot] = (len(sp.full_blocks), sp.chain)
        return self.shared_len(sp)

    def release(self, slot: int) -> None:
        row = self.tables[slot]
        for b in row:
            self._deref(int(b))
        row[:] = TRASH
        r = self._reserve.pop(slot, None)
        if r is not None:
            self._deref(r)
        self._chain.pop(slot, None)

    # --- copy-on-write ----------------------------------------------------
    def writable(self, slot: int, logical: int) -> Optional[tuple[int, int]]:
        """Make logical block ``logical`` of ``slot`` safe to write.

        Exclusive blocks pass through (None).  A shared block (refcount
        > 1 — other slots and/or the prefix index still read it) is
        detached: a fresh physical block takes its table entry and the
        caller must device-copy ``src -> dst`` before writing.  This is
        the write fault of classic copy-on-write, reached whenever a
        prompt's shared prefix ends mid-block."""
        b = int(self.tables[slot, logical])
        if b == TRASH:
            raise AssertionError(
                f"slot {slot} writing unallocated logical block {logical}")
        if self.refs[b] == 1:
            # the slot's own reference is the only one: exclusive, and
            # (since the index always holds a reference to indexed
            # blocks) guaranteed unindexed
            return None
        dst = self._reserve.pop(slot, None)
        if dst is None:
            if not self.free:
                self.evict(1)
            dst = self._alloc()
        self.tables[slot, logical] = dst
        self._deref(b)
        self.copies += 1
        if self.on_event is not None:
            self.on_event("cow", slot=slot, logical=logical,
                          src=b, dst=dst)
        return b, dst

    # --- registration -----------------------------------------------------
    def register_committed(self, slot: int, tokens, committed: int) -> int:
        """Index every full block of ``slot`` whose tokens are final
        (all positions < ``committed``; committed positions are never
        rewritten, so the block's content is frozen).  ``tokens`` is the
        slot's whole stream (prompt + generated) as known to the host.
        The chain hash is a pure function of the token stream, so a
        COW-copied private block registers under its true prefix hash
        like any other.  Returns how many new blocks were indexed."""
        bs = self.block_size
        done, h = self._chain[slot]
        toks = np.asarray(tokens)
        added = 0
        while (done + 1) * bs <= committed:
            blk = tuple(int(t) for t in toks[done * bs:(done + 1) * bs])
            parent = h
            h = chain_hash(h, blk)
            b = int(self.tables[slot, done])
            if b != TRASH and self.index.add(parent, h, b, blk):
                self.refs[b] += 1          # the index holds a reference
                added += 1
            done += 1
        self._chain[slot] = (done, h)
        return added

    def adopt_prefix(self, tokens, n_blocks: int):
        """Register the first ``n_blocks`` full blocks of ``tokens`` as
        if a local slot had prefilled them, allocating fresh physical
        blocks for the chain links not already indexed — the
        destination half of cross-engine prefix cloning
        (:func:`..serve.migrate.clone_prefix`).

        Returns ``(start, new_block_ids)``: ``start`` chain links were
        already indexed here (nothing to copy), and ``new_block_ids``
        are freshly-allocated blocks for links ``start..`` — held ONLY
        by the index (refcount 1), so they age out under LRU eviction
        like any locally-prefilled prefix.  The caller MUST fill every
        returned block with the exact at-rest KV for its positions
        before anything admits against the chain.  Returns None when
        the pool cannot free enough blocks (sharing is best-effort and
        never steals from live slots)."""
        bs = self.block_size
        toks = np.asarray(tokens)
        chain = []
        h = b""
        for i in range(int(n_blocks)):
            blk = tuple(int(t) for t in toks[i * bs:(i + 1) * bs])
            if len(blk) < bs:
                break
            parent = h
            h = chain_hash(h, blk)
            chain.append((parent, h, blk))
        start = 0
        for parent, h2, blk in chain:
            e = self.index.get(h2)
            if e is None:
                break
            if e.tokens != blk:     # hash collision: never adopt over it
                return None
            self.index.touch(h2)    # protect the stem from our own evict
            start += 1
        todo = chain[start:]
        if not todo:
            return start, []
        if len(self.free) < len(todo):
            self.evict(len(todo))
        if len(self.free) < len(todo):
            return None
        ids = []
        for parent, h2, blk in todo:
            b = self._alloc()       # refcount 1: the index's reference
            if not self.index.add(parent, h2, b, blk):
                self._deref(b)
                return None
            ids.append(b)
        if self.on_event is not None:
            self.on_event("adopt", blocks=len(ids))
        return start, ids

    def unadopt(self, block_ids) -> int:
        """Roll back a failed adoption: drop the index entries holding
        the given freshly-adopted blocks and release the blocks back to
        the free list.  The inverse of :meth:`adopt_prefix` for blocks
        whose payload never arrived (a migration that tripped its
        digest) — adopted blocks are held ONLY by the index (refcount
        1), so removing the entry frees them and nothing downstream can
        ever admit against the half-filled chain.  Returns how many
        blocks were released."""
        dropped = 0
        for b in block_ids:
            h = self.index.by_block.get(int(b))
            if h is None:
                continue
            self.index.remove(h)
            self._deref(int(b))
            dropped += 1
        if dropped and self.on_event is not None:
            self.on_event("unadopt", blocks=dropped)
        return dropped

    def prefix_summary(self) -> frozenset:
        """Cheap export of this manager's prefix-index coverage: the set
        of chain hashes currently indexed.  Each hash commits to an
        entire token prefix (see :func:`chain_hash`), so a router can
        predict how many prompt tokens would hit this replica's cache
        without seeing any cached tokens — hand the summary to
        :func:`predict_shared_len`."""
        return frozenset(self.index.entries)

    def stats(self) -> dict:
        return {
            "blocks_total": self.num_blocks,
            "blocks_in_use": self.in_use,
            "blocks_peak_in_use": self.peak_in_use,
            "indexed_blocks": len(self.index),
            "cow_copies": self.copies,
            "evictions": self.evictions,
        }


def predict_shared_len(summary, prompt, block_size: int) -> int:
    """Predicted prefix-cache hit for ``prompt`` against a replica's
    :meth:`BlockManager.prefix_summary`: tokens covered by the longest
    chain of fully-matched blocks.  Mirrors the full-block walk of
    :meth:`BlockManager.match_prefix` but skips the token-equality
    re-check and the partial-tail search — the summary carries hashes
    only, so this is a *prediction* (collision-safe in practice: the
    chain digest commits to the whole prefix).  Partial-block hits are
    deliberately ignored; they are at most ``block_size - 1`` tokens."""
    bs = block_size
    toks = np.asarray(prompt)
    L = len(toks)
    h = b""
    i = 0
    while (i + 1) * bs <= L - 1:    # same cap as match_prefix
        h2 = chain_hash(h, tuple(int(t) for t in toks[i * bs:(i + 1) * bs]))
        if h2 not in summary:
            break
        h = h2
        i += 1
    return i * bs
