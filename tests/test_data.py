import numpy as np
import pytest

from distributed_deep_learning_tpu.data.datasets import (
    ArrayDataset, synthetic_mqtt, synthetic_pcb, synthetic_pdm,
)
from distributed_deep_learning_tpu.data.loader import DeviceLoader
from distributed_deep_learning_tpu.data.splits import (
    shard_indices, train_val_test_split,
)


def test_split_fractions_and_disjointness():
    s = train_val_test_split(1000, seed=42)
    assert len(s.train) == 700 and len(s.val) == 100 and len(s.test) == 200
    all_idx = np.concatenate([s.train, s.val, s.test])
    assert len(np.unique(all_idx)) == 1000  # disjoint, exhaustive (fixes Q3)


def test_split_deterministic():
    a = train_val_test_split(100, seed=42)
    b = train_val_test_split(100, seed=42)
    c = train_val_test_split(100, seed=7)
    assert np.array_equal(a.train, b.train)
    assert not np.array_equal(a.train, c.train)


def test_shard_indices_disjoint_equal_length():
    idx = np.arange(103)
    shards = [shard_indices(idx, 4, i) for i in range(4)]
    assert all(len(sh) == 25 for sh in shards)
    assert len(np.unique(np.concatenate(shards))) == 100


def test_synthetic_shapes():
    mq = synthetic_mqtt(64)
    assert mq.features.shape == (64, 48) and mq.targets.shape == (64, 5)
    pcb = synthetic_pcb(8)
    assert pcb.features.shape == (8, 64, 64, 3)
    pdm = synthetic_pdm(16)
    assert pdm.features.shape == (16, 10, 32) and pdm.targets.shape == (16, 5)


def test_loader_shards_batch_over_mesh(mesh8):
    ds = synthetic_mqtt(256)
    s = train_val_test_split(len(ds))
    loader = DeviceLoader(ds, s.train, 64, mesh8, shuffle=True)
    assert len(loader) == len(s.train) // 64
    batches = list(loader)
    assert len(batches) == len(s.train) // 64
    x, y = batches[0]
    assert x.shape == (64, 48)
    # batch dim split over 8 data-parallel devices
    assert x.sharding.shard_shape(x.shape) == (8, 48)
    assert not x.sharding.is_fully_replicated


def test_loader_epoch_shuffle_differs(mesh8):
    ds = synthetic_mqtt(256)
    s = train_val_test_split(len(ds))
    loader = DeviceLoader(ds, s.train, 64, mesh8, shuffle=True)
    loader.set_epoch(1)
    x1 = np.asarray(next(iter(loader))[0])
    loader.set_epoch(2)
    x2 = np.asarray(next(iter(loader))[0])
    loader.set_epoch(1)
    x1b = np.asarray(next(iter(loader))[0])
    assert not np.array_equal(x1, x2)
    assert np.array_equal(x1, x1b)  # deterministic per (seed, epoch)


def test_loader_rejects_indivisible_batch(mesh8):
    ds = synthetic_mqtt(64)
    with pytest.raises(ValueError):
        DeviceLoader(ds, np.arange(64), 12, mesh8)  # 12 % 8 != 0


def test_array_dataset_validates():
    with pytest.raises(ValueError):
        ArrayDataset(np.zeros((4, 2)), np.zeros((5, 2)))


def _load_data_soak():
    """Import scripts/data_soak.py as a module (side-effect-free: its jax
    setup only runs under main())."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "data_soak", os.path.join(os.path.dirname(__file__), "..",
                                  "scripts", "data_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    return soak


@pytest.mark.slow
def test_data_soak_script_micro(tmp_path):
    """scripts/data_soak.py at micro scale: the reference-scale soak
    harness (VERDICT r4 item 7) keeps running end to end."""
    soak = _load_data_soak()
    # batches sized below each micro corpus so the loader loop actually
    # runs (review finding: drop_remainder would otherwise yield nothing)
    soak.soak_pdm(str(tmp_path), machines=2, ipm=100, batch=64)
    soak.soak_mqtt(str(tmp_path), rows=500, batch=128)
    soak.soak_pcb(str(tmp_path), classes=2, per_class=4, batch=8)


def test_pcb_threaded_batch_matches_serial(tmp_path):
    """The round-5 threaded PCB batch decode is bit-identical to serial
    (same LRU dataset, workers=1 vs workers=4)."""
    soak = _load_data_soak()
    from distributed_deep_learning_tpu.data.pcb import PCBDataset

    soak.gen_pcb_tree(str(tmp_path / "pcb"), classes=2, per_class=3)
    serial = PCBDataset(str(tmp_path / "pcb"), workers=1)
    threaded = PCBDataset(str(tmp_path / "pcb"), workers=4)
    idx = np.arange(len(serial))
    xs, ys = serial.batch(idx)
    xt, yt = threaded.batch(idx)
    np.testing.assert_array_equal(xs, xt)
    np.testing.assert_array_equal(ys, yt)
