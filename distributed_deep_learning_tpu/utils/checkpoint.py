"""Checkpoint / resume on orbax, sharding-aware.

The reference has NO checkpointing — no ``torch.save`` anywhere; every run
is train-from-scratch (SURVEY.md §5).  A TPU framework can't ship without
it: pod jobs get preempted, and elastic resume is the failure-recovery
mechanism.  Because :class:`~..train.state.TrainState` is one pytree, a
checkpoint is one atomic orbax save; restore takes an *abstract* target
built from the live state, so arrays come back with the same shardings
they were saved under (each host restores only its addressable shards —
multi-host safe by construction).

Only pytree leaves (step/params/model_state/opt_state) are persisted;
``apply_fn``/``tx`` are code, re-supplied by the target state at restore.

**Integrity** (ISSUE 3): every save writes a ``manifest-<step>.json``
sidecar — per-leaf CRC32 checksums plus a finiteness summary, computed
from the in-memory state and written atomically.  Restores verify the
restored leaves against the manifest; :meth:`Checkpointer.restore_verified`
additionally falls back to the newest *verified-good* checkpoint when the
latest is torn, bit-flipped or non-finite, QUARANTINING (renaming, never
deleting) the bad step so recovery proceeds and the evidence survives for
forensics.  Pre-manifest checkpoints restore unverified (logged), keeping
old run directories resumable.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import zlib

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_deep_learning_tpu.train.state import TrainState

# works for TrainState AND any state holder exposing these fields (e.g. the
# staged trainer's StagedState)
_FIELDS = ("step", "params", "model_state", "opt_state")

# Format 2 adds the topology block (mesh shape + per-leaf PartitionSpec,
# see reshard/manifest.py).  Readers treat a missing block — format 1 or
# any pre-integrity checkpoint — as legacy-same-topology: warn, restore,
# never quarantine, so every pre-reshard run directory stays resumable.
MANIFEST_FORMAT = 2


class CheckpointCorruption(RuntimeError):
    """A restored checkpoint failed manifest verification."""

    def __init__(self, step: int, detail: str):
        self.step = step
        super().__init__(f"checkpoint step {step} failed integrity "
                         f"verification: {detail}")


def _leaf_records(tree) -> dict:
    """Per-leaf integrity records keyed by pytree path.

    CRC32 over the raw bytes plus shape/dtype and (for float leaves) an
    all-finite flag.  Leaves that are not fully addressable on this host
    (multi-host shards) record ``crc32: None`` — shard-local checksums
    would differ per host, so those leaves are exempt from verification."""
    records = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            records[key] = {"crc32": None}
            continue
        arr = np.asarray(jax.device_get(leaf))
        rec = {"crc32": zlib.crc32(arr.tobytes()),
               "shape": list(arr.shape), "dtype": str(arr.dtype)}
        try:
            finite = bool(np.isfinite(arr.astype(np.float32)).all()) \
                if arr.dtype.kind == "f" or arr.dtype.name == "bfloat16" \
                else True
        except (TypeError, ValueError):  # exotic dtype: skip the check
            finite = True
        rec["finite"] = finite
        records[key] = rec
    return records


def _as_pytree(state) -> dict:
    return {f: getattr(state, f) for f in _FIELDS}


def _with_fields(state, fields: dict):
    if hasattr(state, "replace"):  # flax.struct dataclass
        return state.replace(**fields)
    return dataclasses.replace(state, **fields)


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one run directory."""

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3):
        self._dir = os.path.abspath(os.fspath(directory))
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True),
        )

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state: TrainState, *, force: bool = False,
             wait: bool = False, extra: dict | None = None,
             manifest: bool = True,
             publish_dir: str | None = None) -> bool:
        """Persist `state` under `step`.  Async by default (the save runs
        while training continues); `wait` blocks until durable.

        ``extra`` is an optional small JSON-serialisable dict saved as a
        sidecar next to the orbax step (loader position, partial-phase
        totals — the mid-epoch resume metadata).  Only the coordinator
        writes it (process 0); every process reads it back identically
        from the shared run directory.  The sidecar is written BEFORE the
        orbax save so a finalised step always has its sidecar (a kill in
        between leaves a harmless orphan, collected below); an already-
        finalised ``step`` is skipped, not re-saved — ONLY safe because a
        run never reuses a dirty directory without ``--resume`` or
        ``--elastic`` (:func:`..workloads.base._maybe_checkpointer`
        rejects that, and elastic restores-then-continues, logging what it
        restored), so a replayed id within a run carries bit-identical
        state (the elastic retry).  ``force=True`` really overwrites
        (delete + save, sidecar included).

        ``manifest=True`` (default) also writes the per-leaf
        checksum/finiteness manifest sidecar — the integrity record
        restores verify against.  Like ``extra`` it is written BEFORE the
        orbax save (a finalised step always has its manifest; a kill in
        between leaves an orphan the GC collects).

        ``publish_dir`` (``--publish-weights``) additionally publishes
        the state's params to that directory in the
        :func:`..serve.reload.publish_weights` manifest format, for
        serving fleets watching it (``--reload-watch``) to hot-swap.
        Publishing happens AFTER the orbax save is durable (it forces a
        ``wait_until_finished``): only weights that a restart could also
        restore are ever offered to live engines."""
        if step in set(self._mgr.all_steps()):
            if not force:
                if wait:
                    self._mgr.wait_until_finished()
                return False
            self._mgr.delete(step)
            if jax.process_index() == 0:
                for path in (self._extra_path(step),
                             self._manifest_path(step)):
                    try:  # the old step's sidecars must not outlive it
                        os.remove(path)
                    except FileNotFoundError:
                        pass
        if extra is not None and jax.process_index() == 0:
            self._write_json(self._extra_path(step), extra)
        if manifest and jax.process_index() == 0:
            from distributed_deep_learning_tpu.reshard.manifest import capture

            tree = _as_pytree(state)
            records = _leaf_records(tree)
            self._write_json(self._manifest_path(step), {
                "format": MANIFEST_FORMAT,
                "all_finite": all(r.get("finite", True)
                                  for r in records.values()),
                "leaves": records,
                # metadata-only placement fingerprint: lets a restore on a
                # different topology know it must reshard
                "topology": capture(tree).to_json(),
            })
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(_as_pytree(state)), force=force)
        if jax.process_index() == 0:
            self._gc_sidecars(protect=step)
        if saved and publish_dir is not None:
            # durability gate: never offer weights to live engines that a
            # restart could not also restore
            self._mgr.wait_until_finished()
            if jax.process_index() == 0:
                from distributed_deep_learning_tpu.serve import reload

                reload.publish_weights(publish_dir, step, state.params)
        if wait:
            self._mgr.wait_until_finished()
        return saved

    def _extra_path(self, step: int) -> str:
        return os.path.join(self._dir, f"extra-{step}.json")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._dir, f"manifest-{step}.json")

    @staticmethod
    def _write_json(path: str, payload: dict) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # atomic on POSIX

    def _gc_sidecars(self, protect: int | None = None) -> None:
        """Drop sidecars whose checkpoint orbax has pruned (max_to_keep).

        Only steps BELOW the newest finalised one are candidates: steps are
        saved in increasing order, so anything above it is still in flight
        and must keep its (pre-written) sidecar.  ``protect`` exempts the
        step whose save is in flight RIGHT NOW — a ``force=True``
        re-save of a non-latest step sits below the newest finalised id
        and would otherwise lose its fresh sidecar (review finding)."""
        import glob

        finalised = set(self._mgr.all_steps())
        if not finalised:
            return
        newest = max(finalised)
        for kind in ("extra", "manifest"):
            for path in glob.glob(os.path.join(self._dir,
                                               f"{kind}-*.json")):
                name = os.path.basename(path)
                try:
                    step = int(name[len(kind) + 1:-len(".json")])
                except ValueError:
                    continue
                if step < newest and step not in finalised \
                        and step != protect:
                    try:
                        os.remove(path)
                    except OSError:  # pragma: no cover - concurrent cleanup
                        pass

    def read_extra(self, step: int | None = None) -> dict | None:
        """The `extra` sidecar saved with `step` (default: latest), or None
        (pre-sidecar checkpoints / never saved with extra)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        import json

        try:
            with open(self._extra_path(step)) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def read_manifest(self, step: int | None = None) -> dict | None:
        """The integrity manifest sidecar for `step` (default: latest), or
        None (legacy checkpoint / unreadable sidecar)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f)
        except (FileNotFoundError, OSError, json.JSONDecodeError):
            return None

    def read_topology(self, step: int | None = None):
        """The saved :class:`~...reshard.manifest.Topology` for `step`, or
        None for a legacy checkpoint (format-1 manifest, no manifest at
        all, or a malformed block) — callers treat None as "same topology
        as the writer", warn, and never quarantine."""
        from distributed_deep_learning_tpu.reshard.manifest import Topology

        manifest = self.read_manifest(step)
        if not manifest:
            return None
        return Topology.from_json(manifest.get("topology"))

    # -- restore ------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """Finalised step ids, ascending."""
        return sorted(self._mgr.all_steps())

    def restore(self, target: TrainState, step: int | None = None, *,
                verify: bool = True, shardings=None) -> TrainState | None:
        """Restore into the structure/shardings of `target`.

        Returns None when the directory holds no checkpoint (caller starts
        fresh) — the preemption-resume idiom::

            state = ckpt.restore(state) or state

        With ``verify`` (default) the restored leaves are checked against
        the step's manifest sidecar; a mismatch (bit-flip, torn write,
        non-finite values) raises :class:`CheckpointCorruption`.  Steps
        saved without a manifest (pre-integrity run dirs) restore
        unverified.  Use :meth:`restore_verified` for the full
        fallback-and-quarantine recovery path.

        ``shardings`` (a pytree of per-leaf Shardings shaped like the
        saved fields) overrides the abstract target's placement: orbax
        then reads only the slices each target shard needs — the on-disk
        chunked half of cross-topology resume (reshard/).  Verification
        still applies: the CRC is over the global array, placement-
        independent.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        # abstract target: arrays → ShapeDtypeStruct carrying their sharding
        # (so each host restores its addressable shards); python scalars
        # (e.g. a plain int step) pass through as-is
        if shardings is None:
            abstract = jax.tree.map(
                lambda x: ocp.utils.to_shape_dtype_struct(x)
                if isinstance(x, jax.Array) else x,
                _as_pytree(target))
        else:
            abstract = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                  sharding=s)
                if isinstance(x, jax.Array) else x,
                _as_pytree(target), shardings)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        if verify:
            self._verify(step, restored)
        return _with_fields(target, restored)

    def _verify(self, step: int, restored_tree) -> None:
        """Raise :class:`CheckpointCorruption` unless `restored_tree`
        matches `step`'s manifest (no manifest = legacy, passes)."""
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return  # pre-integrity checkpoint: nothing to verify against
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruption(step, f"unreadable manifest ({e})")
        if not manifest.get("all_finite", True):
            raise CheckpointCorruption(
                step, "manifest records non-finite values at save time")
        expected = manifest.get("leaves", {})
        actual = _leaf_records(restored_tree)
        if set(expected) != set(actual):
            raise CheckpointCorruption(
                step, f"leaf set changed: manifest has {len(expected)} "
                f"leaves, restore produced {len(actual)}")
        for key, rec in expected.items():
            got = actual[key]
            if rec.get("crc32") is None or got.get("crc32") is None:
                continue  # multi-host shard: exempt (see _leaf_records)
            if rec["crc32"] != got["crc32"]:
                raise CheckpointCorruption(
                    step, f"checksum mismatch at leaf {key!r}")
            if not got.get("finite", True):
                raise CheckpointCorruption(
                    step, f"non-finite values restored at leaf {key!r}")

    def restore_verified(self, target: TrainState,
                         step: int | None = None
                         ) -> tuple[TrainState | None, int | None]:
        """Restore the newest VERIFIED-GOOD checkpoint at or below `step`.

        The recovery-chain entry point: tries the newest candidate first;
        a step that fails to restore (torn orbax files) or fails manifest
        verification (bit-flip, non-finite save) is QUARANTINED — renamed
        under ``<dir>/quarantine/``, sidecars included, never deleted —
        and the next-newest step is tried.  Returns ``(state, step)``, or
        ``(None, None)`` when no checkpoint survives (caller starts
        fresh).  Every process must call this collectively (orbax restores
        are collective); quarantine renames happen on process 0."""
        self._mgr.wait_until_finished()
        candidates = sorted(self._mgr.all_steps(), reverse=True)
        if step is not None:
            candidates = [s for s in candidates if s <= step]
        for s in candidates:
            try:
                return self.restore(target, step=s, verify=True), s
            except Exception as e:
                # CheckpointCorruption, or backend-specific errors from a
                # torn orbax step: ANY restore failure here means "this
                # step is unusable", which is exactly what
                # quarantine-and-fall-back is for
                print(f"checkpoint: step {s} unusable "
                      f"({type(e).__name__}: {e}); quarantining and "
                      "falling back", file=sys.stderr, flush=True)
                self.quarantine(s, reason=f"{type(e).__name__}: {e}")
        return None, None

    # -- quarantine ---------------------------------------------------------
    def _step_path(self, step: int) -> str | None:
        """The directory orbax stores `step` under (name formats vary)."""
        direct = os.path.join(self._dir, str(step))
        if os.path.isdir(direct):
            return direct
        for name in os.listdir(self._dir):
            full = os.path.join(self._dir, name)
            if not os.path.isdir(full) or name == "quarantine":
                continue
            m = re.fullmatch(r"\D*?0*(\d+)", name)
            if m and int(m.group(1)) == step:
                return full
        return None

    def quarantine(self, step: int, reason: str = "") -> str | None:
        """Move `step`'s directory + sidecars under ``<dir>/quarantine/``.

        Rename, never delete: the corrupt artifact is evidence (what broke
        — storage, a torn write, a bad host?) and rename keeps it off the
        recovery path atomically.  Returns the quarantine path (None when
        the step has no directory).  Refreshes the orbax manager so
        ``latest_step``/``all_steps`` immediately reflect the removal."""
        dst = None
        if jax.process_index() == 0:
            src = self._step_path(step)
            if src is not None:
                qdir = os.path.join(self._dir, "quarantine")
                os.makedirs(qdir, exist_ok=True)
                dst = os.path.join(qdir, os.path.basename(src))
                n = 0
                while os.path.exists(dst):  # repeated corruption of one id
                    n += 1
                    dst = os.path.join(qdir, f"{os.path.basename(src)}.{n}")
                os.rename(src, dst)
                for side in (self._extra_path(step),
                             self._manifest_path(step)):
                    if os.path.exists(side):
                        os.rename(side, os.path.join(
                            qdir, os.path.basename(dst) + "-" +
                            os.path.basename(side)))
                if reason:
                    self._write_json(f"{dst}.reason.json",
                                     {"step": step, "reason": reason})
        self._reload_manager()
        return dst

    def _reload_manager(self) -> None:
        """Make the orbax manager re-scan the directory after an external
        change (quarantine rename)."""
        try:
            self._mgr.reload()
        except AttributeError:  # older orbax: rebuild the manager
            keep = self._mgr._options.max_to_keep  # pragma: no cover
            self._mgr.close()
            self._mgr = ocp.CheckpointManager(
                self._dir, options=ocp.CheckpointManagerOptions(
                    max_to_keep=keep, create=True))

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
