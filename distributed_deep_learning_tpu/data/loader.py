"""Host-side batched loader feeding device-sharded arrays.

Replaces the reference's DataLoader stack (``SubsetRandomSampler`` →
``DistributedSampler`` → ``DataLoader`` with per-item ``.to(device)``,
``CNN/main.py:165-179`` + ``CNN/dataset.py:107``) with the TPU-native
pattern: form the whole per-process batch on host, then do ONE
``device_put`` onto a :class:`~jax.sharding.NamedSharding` that splits the
batch dimension over the data-parallel mesh axes.  XLA then sees fully
sharded inputs and never inserts host transfers inside the step.

Multi-host: each process materialises only its addressable shard of the
global batch (`jax.make_array_from_process_local_data`), so the loader
scales to pods without any code change.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_learning_tpu.data.datasets import ArrayDataset

# Batch dimension is sharded over both data-parallel-ish axes; ZeRO/fsdp
# meshes reuse the same loader unchanged.
BATCH_AXES = ("data", "fsdp")


class DeviceLoader:
    """Iterates seeded, sharded, device-resident batches of one split."""

    def __init__(self, dataset: ArrayDataset, indices: np.ndarray,
                 global_batch_size: int, mesh: Mesh, *,
                 shuffle: bool = False, seed: int = 42,
                 drop_remainder: bool = True):
        self.dataset = dataset
        self.indices = np.asarray(indices)
        self.global_batch_size = int(global_batch_size)
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

        dp = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
        if self.global_batch_size % dp:
            raise ValueError(f"global batch {global_batch_size} not divisible "
                             f"by data-parallel size {dp}")
        self._sharding = NamedSharding(mesh, P(BATCH_AXES))
        # Which rows of the *global* batch this process must materialise:
        # derived from the sharding itself (covers replicated-batch meshes,
        # e.g. pure-stage meshes spanning several hosts, where every process
        # needs the full batch — not from a contiguous-even-slice assumption).
        imap = self._sharding.addressable_devices_indices_map(
            (self.global_batch_size,))
        rows = np.zeros(self.global_batch_size, dtype=bool)
        for (sl,) in imap.values():
            rows[sl] = True
        self._local_rows = np.flatnonzero(rows)

    def __len__(self) -> int:
        n = len(self.indices)
        if self.drop_remainder:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _epoch_indices(self) -> np.ndarray:
        idx = self.indices
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            idx = idx[rng.permutation(len(idx))]
        if self.drop_remainder:
            idx = idx[:len(idx) - len(idx) % self.global_batch_size]
        return idx

    def _to_device(self, host: np.ndarray) -> jax.Array:
        return jax.make_array_from_process_local_data(self._sharding, host)

    def __iter__(self) -> Iterator[tuple[jax.Array, jax.Array]]:
        return self.iter_batches()

    def iter_host_batches(self, skip: int = 0
                          ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """This epoch's HOST-side (x, y) batches — the pure batch-formation
        path (gather/decode, no device transfer), skipping the first
        ``skip`` without materialising them (mid-epoch resume: the skipped
        batches were already trained before the checkpoint — no gather, no
        decode, no device transfer for them).  ``scripts/feed_bench.py``
        times exactly this iterator."""
        idx = self._epoch_indices()
        for start in range(skip * self.global_batch_size, len(idx),
                           self.global_batch_size):
            batch_idx = idx[start:start + self.global_batch_size]
            if len(batch_idx) < self.global_batch_size and self.drop_remainder:
                break
            # materialise only this process's rows of the global batch
            local = batch_idx[self._local_rows] \
                if jax.process_count() > 1 else batch_idx
            yield self.dataset.batch(local)

    def iter_batches(self, skip: int = 0
                     ) -> Iterator[tuple[jax.Array, jax.Array]]:
        """Device-resident batches, double-buffered: batch k+1's sharded
        ``device_put`` is enqueued BEFORE batch k is handed to the caller,
        so its host→device transfer drains while the caller's step k
        dispatch runs — one batch of transfer latency is always hidden,
        even without :class:`PrefetchLoader`."""
        prev = None
        for x, y in self.iter_host_batches(skip):
            cur = (self._to_device(x), self._to_device(y))
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev


class PrefetchLoader:
    """Background-thread prefetch wrapper over any batch iterable.

    Overlaps host-side batch formation (gather / decode — the C++ library's
    territory) and the sharded ``device_put`` with device compute: while
    step *k* runs on the TPU, batch *k+1..k+depth* are being built.  The
    reference got this from DataLoader worker processes; a thread is the
    right tool here because the heavy lifting releases the GIL (memcpy in
    the native gather, IO, device transfer).
    """

    def __init__(self, loader, depth: int = 2):
        self.loader = loader
        self.depth = max(1, int(depth))

    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def iter_batches(self, skip: int = 0):
        """Mid-epoch resume passthrough: skip inside the WRAPPED loader
        (before materialisation) when it supports it, else drop the first
        ``skip`` prefetched items."""
        if hasattr(self.loader, "iter_batches"):
            return self._pump(self.loader.iter_batches(skip))
        import itertools

        return itertools.islice(self._pump(iter(self.loader)), skip, None)

    def __iter__(self):
        return self._pump(iter(self.loader))

    def _pump(self, source):
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        _END = object()
        stop = threading.Event()

        def put(item) -> bool:
            # bounded-wait put so an abandoned consumer (early `break` from
            # the epoch loop) never strands the producer on a full queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in source:
                    if not put(item):
                        return
                put(_END)
            except BaseException as e:  # surface in the consumer
                put(e)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            while True:  # unblock a producer mid-put
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)


def make_loaders(dataset: ArrayDataset, splits, global_batch_size: int,
                 mesh: Mesh, seed: int = 42, prefetch: int = 2):
    """(train, val, test) loaders with reference semantics: train shuffles
    per-epoch, eval splits iterate in fixed order.  The train loader is
    wrapped in :class:`PrefetchLoader` (``prefetch`` batches deep, 0 to
    disable) so host batch formation overlaps device compute — the analogue
    of the reference's DataLoader worker processes."""
    train = DeviceLoader(dataset, splits.train, global_batch_size, mesh,
                         shuffle=True, seed=seed)
    if prefetch:
        train = PrefetchLoader(train, depth=prefetch)
    val = DeviceLoader(dataset, splits.val, global_batch_size, mesh,
                       shuffle=False, seed=seed)
    test = DeviceLoader(dataset, splits.test, global_batch_size, mesh,
                        shuffle=False, seed=seed)
    return train, val, test
