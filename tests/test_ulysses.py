"""Ulysses (all-to-all) sequence parallelism vs full attention — the
second context-parallel scheme next to ring attention (SURVEY.md §2.5
lists SP/CP as absent from the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.parallel.ring_attention import (
    full_attention, ring_attention)
from distributed_deep_learning_tpu.parallel.ulysses import (make_attention_fn,
                                                            ulysses_attention)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh


@pytest.fixture(scope="module")
def mesh_seq8():
    return build_mesh({"seq": 8})


def _qkv(B=2, T=32, H=8, D=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D)) for k in ks)


def test_matches_full_attention(mesh_seq8):
    q, k, v = _qkv()
    with mesh_seq8:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8))(q, k, v)
    expected = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_matches_full_attention_causal(mesh_seq8):
    q, k, v = _qkv(seed=1)
    with mesh_seq8:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8, causal=True))(q, k, v)
    expected = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_matches_ring_attention(mesh_seq8):
    """Both context-parallel schemes compute the same exact attention."""
    q, k, v = _qkv(seed=2)
    with mesh_seq8:
        u = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8, causal=True))(q, k, v)
        r = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh_seq8, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r), rtol=2e-5,
                               atol=2e-5)


def test_gradients_match(mesh_seq8):
    q, k, v = _qkv(seed=3)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh_seq8,
                                         causal=True) ** 2)

    def loss_f(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    with mesh_seq8:
        gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5)


def test_indivisible_heads_raise(mesh_seq8):
    q, k, v = _qkv(H=4)  # 4 heads over 8 devices
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh=mesh_seq8)


def test_flash_inner_kernel(mesh_seq8):
    """The local attention can be the Pallas flash kernel (interpret mode
    on CPU) — the fused-kernel composition ring attention cannot offer."""
    from distributed_deep_learning_tpu.ops import attention_pallas

    q, k, v = _qkv(seed=4)
    inner = attention_pallas.make_attention_fn(block_q=8, block_k=8)
    with mesh_seq8:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8, causal=True,
            attention_fn=lambda qq, kk, vv, causal, dtype: inner(
                qq, kk, vv, causal=causal, dtype=dtype)))(q, k, v)
    expected = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_layer_adapter(mesh_seq8):
    """Plugs into MultiHeadAttention like the ring/flash adapters."""
    from distributed_deep_learning_tpu.models.transformer import (
        TransformerLayer)

    x = jax.random.normal(jax.random.key(5), (2, 32, 64))
    dense_layer = TransformerLayer(num_heads=8, mlp_dim=128)
    sp_layer = TransformerLayer(num_heads=8, mlp_dim=128,
                                attention_fn=make_attention_fn(mesh_seq8))
    params = dense_layer.init(jax.random.key(0), x)
    with mesh_seq8:
        got = jax.jit(lambda p, x: sp_layer.apply(p, x))(params, x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(dense_layer.apply(params, x)),
                               rtol=2e-4, atol=2e-5)


def test_adapter_rejects_dense_masks_only(mesh_seq8):
    """key_valid now threads through (VERDICT r4 item 4); only arbitrary
    dense mask tensors stay rejected."""
    fn = make_attention_fn(mesh_seq8)
    q, k, v = _qkv(seed=6)
    with pytest.raises(NotImplementedError):
        fn(q, k, v, mask=jnp.ones((1, 1, 32, 32), bool))
    with mesh_seq8:
        out = fn(q, k, v, key_valid=jnp.ones((2, 32), bool))
    assert out.shape == q.shape


from conftest import padded_valid as _padded_valid


def test_key_valid_matches_dense_masked(mesh_seq8):
    """Padding masks through the all-to-all: parity with the dense masked
    path on a padded batch, causal and not."""
    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)

    q, k, v = _qkv(seed=7)
    valid = _padded_valid()
    for causal in (False, True):
        expected = dot_product_attention(q, k, v, key_valid=valid,
                                         causal=causal)
        with mesh_seq8:
            got = jax.jit(lambda q, k, v: ulysses_attention(
                q, k, v, mesh=mesh_seq8, causal=causal,
                key_valid=valid))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"causal={causal}")


def test_key_valid_flash_inner(mesh_seq8):
    """key_valid reaches the Pallas flash inner kernel — the full padded
    default-TPU composition."""
    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)
    from distributed_deep_learning_tpu.ops import attention_pallas

    q, k, v = _qkv(seed=8)
    valid = _padded_valid()
    inner = attention_pallas.make_attention_fn(block_q=8, block_k=8)
    expected = dot_product_attention(q, k, v, key_valid=valid, causal=True)
    with mesh_seq8:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8, causal=True, key_valid=valid,
            attention_fn=inner))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_key_valid_gradients_match(mesh_seq8):
    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)

    q, k, v = _qkv(seed=11)
    valid = _padded_valid()
    w = valid[:, :, None, None].astype(q.dtype)

    def loss_u(q, k, v):
        out = ulysses_attention(q, k, v, mesh=mesh_seq8, causal=True,
                                key_valid=valid)
        return jnp.sum((out * w) ** 2)

    def loss_d(q, k, v):
        out = dot_product_attention(q, k, v, key_valid=valid, causal=True)
        return jnp.sum((out * w) ** 2)

    with mesh_seq8:
        gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5)


def test_key_valid_cross_length(mesh_seq8):
    """Tq != Tk with a padded source (the WMT decoder's cross-attention)."""
    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)

    ks = jax.random.split(jax.random.key(13), 3)
    q = jax.random.normal(ks[0], (2, 16, 8, 16))
    k = jax.random.normal(ks[1], (2, 32, 8, 16))
    v = jax.random.normal(ks[2], (2, 32, 8, 16))
    valid = _padded_valid(T=32, lengths=(20, 32))
    expected = dot_product_attention(q, k, v, key_valid=valid)
    with mesh_seq8:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh_seq8, key_valid=valid))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_indivisible_sequence_raises(mesh_seq8):
    q, k, v = _qkv(T=30)
    with pytest.raises(ValueError, match="sequence length"):
        ulysses_attention(q, k, v, mesh=mesh_seq8)


def test_sliding_window_matches_dense_band(mesh_seq8):
    """window= forwards through the all-to-all to the local kernel
    (ADVICE r3: adapters must accept the layer's window= kwarg)."""
    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)

    q, k, v = _qkv(seed=9)
    for W in (3, 8):
        expected = dot_product_attention(q, k, v, causal=True, window=W)
        with mesh_seq8:
            got = ulysses_attention(q, k, v, mesh=mesh_seq8, causal=True,
                                    window=W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"window={W}")


def test_windowed_layer_through_adapter_flash_inner(mesh_seq8):
    """window= through MultiHeadAttention -> ulysses adapter -> flash inner:
    the full default-TPU composition that r3 left untested."""
    from distributed_deep_learning_tpu.models.transformer import (
        MultiHeadAttention)
    from distributed_deep_learning_tpu.ops import attention_pallas

    x = jax.random.normal(jax.random.key(10), (2, 32, 64))
    inner = attention_pallas.make_attention_fn(block_q=8, block_k=8)
    dense = MultiHeadAttention(num_heads=8, window=4)
    sp = MultiHeadAttention(num_heads=8, window=4,
                            attention_fn=make_attention_fn(mesh_seq8,
                                                           inner=inner))
    params = dense.init(jax.random.key(0), x, x, causal=True)
    with mesh_seq8:
        got = jax.jit(lambda p, x: sp.apply(p, x, x, causal=True))(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(dense.apply(params, x, x, causal=True)),
        rtol=2e-4, atol=2e-5)
