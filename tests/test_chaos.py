"""Chaos drills: the detect→contain→recover chain under injected faults.

The load-bearing guarantees (ISSUE 3 acceptance):

* a NaN'd batch under ``policy=skip`` is detected within one step and the
  final params are BIT-IDENTICAL to a run that never trained that batch
  (containment happens on device, before the host even looks);
* a truncated / bit-flipped / non-finite latest checkpoint is detected at
  restore, quarantined (renamed, never deleted), and recovery proceeds
  from the previous verified-good save;
* a stale heartbeat mid-run triggers elastic restart and the drill
  completes within ``max_restarts``;
* a deterministic failure replaying at the same resume point fails fast
  instead of burning every restart.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.loader import make_loaders
from distributed_deep_learning_tpu.data.splits import train_val_test_split
from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.train.elastic import (RestartLoopError,
                                                         fit_with_recovery)
from distributed_deep_learning_tpu.train.loop import fit
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.sentinel import (AnomalyError,
                                                          SentinelConfig,
                                                          attach_sentinel)
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                      place_state)
from distributed_deep_learning_tpu.utils.chaos import (ChaosEvent, ChaosPlan,
                                                       run_resilience_drill)
from distributed_deep_learning_tpu.utils.checkpoint import (
    CheckpointCorruption, Checkpointer)
from distributed_deep_learning_tpu.utils.failures import (FailureMonitor,
                                                          Heartbeat,
                                                          MonitorUnhealthy,
                                                          WorkerFailure)

SPE = 11  # 1024 rows -> 716 train examples -> 11 steps of 64


def _setup(mesh, policy="skip"):
    ds = synthetic_mqtt(1024, seed=21)
    splits = train_val_test_split(len(ds), seed=42)
    loaders = make_loaders(ds, splits, 64, mesh)
    assert len(loaders[0]) == SPE
    model = MLP(hidden_size=16)
    cfg = SentinelConfig(policy=policy, warmup_steps=2)

    def make_state():
        state = create_train_state(model, jax.random.key(7),
                                   jnp.zeros((1, 48)), optax.sgd(0.05))
        return place_state(attach_sentinel(state), mesh)

    steps = make_step_fns(mesh, cross_entropy_loss, sentinel=cfg)
    return make_state, steps, loaders, cfg


def _params_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                               jax.tree.leaves(jax.device_get(b.params))))


# --- the plan itself --------------------------------------------------------

def test_plan_parse_and_validation():
    plan = ChaosPlan.parse("nan_batch@5,worker_failure@12", seed=3)
    assert [(e.step, e.kind) for e in plan.events] == \
        [(5, "nan_batch"), (12, "worker_failure")]
    with pytest.raises(ValueError, match="kind"):
        ChaosPlan([ChaosEvent(step=1, kind="meteor_strike")])
    with pytest.raises(ValueError, match="step"):
        ChaosPlan([ChaosEvent(step=0, kind="nan_batch")])
    with pytest.raises(ValueError, match="chaos spec"):
        ChaosPlan.parse("nan_batch")


def test_plan_poison_is_seeded_and_one_shot():
    x = np.zeros((4, 8), np.float32)
    a = ChaosPlan([ChaosEvent(step=2, kind="nan_batch", magnitude=0.25)])
    b = ChaosPlan([ChaosEvent(step=2, kind="nan_batch", magnitude=0.25)])
    xa, _ = a.batch_hook(2, x, None)
    xb, _ = b.batch_hook(2, x, None)
    assert np.array_equal(np.isnan(xa), np.isnan(xb))  # same seeded mask
    assert np.isnan(xa).sum() == 8  # 25% of 32
    x2, _ = a.batch_hook(2, x, None)  # one-shot: replay must not re-poison
    assert not np.isnan(x2).any()
    assert a.fired == [(2, "nan_batch")]


# --- sentinel containment ---------------------------------------------------

def test_nan_batch_skip_bit_identical(mesh8):
    """The acceptance headline: policy=skip + injected NaN at step 5 ends
    bit-identical to a run that never trained that batch."""
    make_state, (train_step, eval_step), loaders, cfg = _setup(mesh8)
    plan = ChaosPlan([ChaosEvent(step=5, kind="nan_batch")], seed=1)

    chaos_state, _ = fit(make_state(), train_step, eval_step, *loaders,
                         epochs=2, sentinel=cfg, chaos=plan)
    ref_state, _ = fit(make_state(), train_step, eval_step, *loaders,
                       epochs=2, sentinel=cfg, skip_steps={5})

    assert plan.fired == [(5, "nan_batch")]
    assert int(chaos_state.sentinel.anomalies) == 1
    assert _params_equal(chaos_state, ref_state)
    # the contained step left no trace in the counters either
    assert int(chaos_state.step) == int(ref_state.step) == 2 * SPE - 1


def test_grad_spike_contained_and_coded(mesh8):
    """A blown-up batch (finite but pathological) trips the spike code and
    leaves params untouched; the EMA ignores the anomalous norm."""
    make_state, (train_step, _), loaders, cfg = _setup(mesh8)
    state = make_state()
    it = iter(loaders[0])
    x, y = next(it)
    for _ in range(4):
        state, m = train_step(state, x, y)
    assert float(m["anomaly"]) == 0.0
    # host snapshot BEFORE the next step: the jitted step donates its
    # input state, so device references to it do not survive the call
    before = jax.device_get(state.params)
    ema_before = float(state.sentinel.grad_ema)
    state, m = train_step(state, jnp.asarray(np.asarray(x) * 1e6), y)
    assert float(m["anomaly"]) == 1.0
    assert float(m["anomaly_code"]) == 2.0  # GRAD_SPIKE
    assert float(m["count"]) == 0.0         # excluded from phase totals
    after = jax.device_get(state.params)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(jax.tree.leaves(before),
                               jax.tree.leaves(after)))
    assert float(state.sentinel.grad_ema) == ema_before


def test_halt_policy_raises_within_one_step(mesh8):
    make_state, (train_step, eval_step), loaders, cfg = _setup(
        mesh8, policy="halt")
    plan = ChaosPlan([ChaosEvent(step=7, kind="nan_batch")], seed=2)
    with pytest.raises(AnomalyError) as e:
        fit(make_state(), train_step, eval_step, *loaders, epochs=2,
            sentinel=cfg, chaos=plan)
    assert e.value.global_step == 7  # named the exact bad batch
    assert e.value.policy == "halt"


def test_rollback_recovery_bit_identical(tmp_path, mesh8):
    """policy=rollback: the anomaly restores the epoch-1 checkpoint and
    replays epoch 2 with the poisoned step skipped — final params equal a
    run that never saw the bad batch, within max_restarts."""
    make_state, (train_step, eval_step), loaders, _ = _setup(
        mesh8, policy="rollback")
    cfg = SentinelConfig(policy="rollback", warmup_steps=2)
    bad = SPE + 2  # epoch 2, batch 2
    plan = ChaosPlan([ChaosEvent(step=bad, kind="nan_batch")], seed=4)

    with Checkpointer(tmp_path / "rb") as ckpt:
        state, hist = fit_with_recovery(
            make_state, train_step, eval_step, loaders, epochs=2,
            checkpointer=ckpt, sentinel=cfg, chaos=plan, max_restarts=2)

    ref_state, _ = fit(make_state(), train_step, eval_step, *loaders,
                       epochs=2, skip_steps={bad})
    assert plan.fired == [(bad, "nan_batch")]
    assert _params_equal(state, ref_state)
    assert [h.epoch for h in hist if h.phase == "train"] == [1, 2]


# --- checkpoint integrity ---------------------------------------------------

def _mlp_state(seed=0):
    model = MLP(hidden_size=16, num_hidden_layers=1)
    return create_train_state(model, jax.random.key(seed),
                              jnp.zeros((1, 8)), optax.adam(1e-3))


def _corrupt_fallback_case(tmp_path, corrupt):
    state = _mlp_state()
    ck = Checkpointer(tmp_path / "ck")
    try:
        ck.save(1, state, wait=True)
        ck.save(2, state, wait=True)
        corrupt(str(tmp_path / "ck"))
        restored, used = ck.restore_verified(_mlp_state(seed=9))
        assert used == 1 and restored is not None
        assert ck.latest_step() == 1  # the bad step left the recovery path
        q = os.path.join(str(tmp_path / "ck"), "quarantine")
        assert any(n.startswith("2") for n in os.listdir(q))
        # round-trip values from the surviving step are the saved ones
        assert _params_equal(restored, state)
    finally:
        ck.close()


def test_truncated_latest_quarantined_and_fallback(tmp_path):
    _corrupt_fallback_case(
        tmp_path, lambda d: ChaosPlan.truncate_checkpoint(d, 2))


def test_bitflipped_latest_quarantined_and_fallback(tmp_path):
    """Same-size corruption: only the manifest checksums can catch it."""
    _corrupt_fallback_case(
        tmp_path, lambda d: ChaosPlan.bitflip_checkpoint(d, 2, seed=7))


def test_nonfinite_save_rejected_at_restore(tmp_path):
    """A checkpoint whose params went NaN BEFORE the save (no sentinel on
    that run) must not be the recovery point: the manifest's finiteness
    summary fails it and restore falls back."""
    good = _mlp_state()
    poisoned = good.replace(params=jax.tree.map(
        lambda p: jnp.full_like(p, jnp.nan), good.params))
    with Checkpointer(tmp_path / "nf") as ck:
        ck.save(1, good, wait=True)
        ck.save(2, poisoned, wait=True)
        with pytest.raises(CheckpointCorruption, match="non-finite"):
            ck.restore(_mlp_state(seed=9), step=2)
        restored, used = ck.restore_verified(_mlp_state(seed=9))
        assert used == 1 and _params_equal(restored, good)


def test_legacy_checkpoint_without_manifest_still_restores(tmp_path):
    """Pre-integrity run dirs (no manifest sidecar) stay resumable —
    verification is skipped, not failed."""
    state = _mlp_state()
    with Checkpointer(tmp_path / "legacy") as ck:
        ck.save(1, state, wait=True, manifest=False)
        assert not os.path.exists(ck._manifest_path(1))
        restored, used = ck.restore_verified(_mlp_state(seed=9))
        assert used == 1 and _params_equal(restored, state)


# --- failure monitor under I/O chaos ----------------------------------------

def test_monitor_tolerates_transient_io_errors(tmp_path):
    d = str(tmp_path / "hb")
    Heartbeat(d, rank=0).beat_once()
    mon = FailureMonitor(d, world_size=1, timeout=30.0, poll_interval=0.02,
                         io_error_tolerance=3)
    ChaosPlan.flaky_io(mon, failures=2)  # below tolerance: must survive
    with mon:
        time.sleep(0.3)
        assert mon.healthy and mon.failure is None
        mon.raise_if_failed()


def test_monitor_surfaces_persistent_io_failure(tmp_path):
    d = str(tmp_path / "hb2")
    Heartbeat(d, rank=0).beat_once()
    mon = FailureMonitor(d, world_size=1, timeout=30.0, poll_interval=0.02,
                         io_error_tolerance=3)
    ChaosPlan.flaky_io(mon, failures=50)  # persistent: must surface
    mon.start()
    try:
        deadline = time.time() + 5
        while mon.failure is None and time.time() < deadline:
            time.sleep(0.01)
        assert isinstance(mon.failure, MonitorUnhealthy)
        assert not mon.healthy  # "monitor dead", distinct from "no failures"
        with pytest.raises(MonitorUnhealthy):
            mon.raise_if_failed()
    finally:
        mon.stop()


def test_stale_heartbeat_is_mtime_based(tmp_path):
    """Staleness uses the shared FS clock (file mtime), not the writer's
    in-file stamp: a hostile in-file timestamp changes nothing."""
    from distributed_deep_learning_tpu.utils.failures import (detect_failures,
                                                              last_beat)

    d = str(tmp_path / "hb3")
    hb = Heartbeat(d, rank=0)
    hb.beat_once()
    # a writer clock running far AHEAD (in-file stamp in the future) used
    # to hide a real death; mtime ageing still detects it
    path = os.path.join(d, "hb-0")
    with open(path, "w") as f:
        f.write(f"{time.time() + 10_000:f}\n")
    assert last_beat(d, 0) > time.time() + 5_000  # debug stamp kept
    ChaosPlan.stale_heartbeat(d, rank=0, age=3600)
    assert detect_failures(d, world_size=1, timeout=30.0) == [0]


def test_stale_heartbeat_restart_drill(tmp_path, mesh8):
    """The pod drill: a peer's heartbeat goes stale mid-epoch-2, the
    monitor flags it, elastic restarts, the replacement worker rejoins
    (fresh beat at attempt start) and the run completes within
    max_restarts."""
    make_state, (train_step, eval_step), loaders, cfg = _setup(mesh8)
    d = str(tmp_path / "hb")
    Heartbeat(d, rank=0).beat_once()
    hb1 = Heartbeat(d, rank=1)
    hb1.beat_once()
    # timeout generous enough that natural elapsed time (compile + epoch 1
    # on a loaded CI box) can't fake a death — only the 3600 s injected
    # ageing crosses it
    monitor = FailureMonitor(d, world_size=2, timeout=20.0,
                             poll_interval=0.05, self_rank=0).start()
    plan = ChaosPlan([ChaosEvent(step=SPE + 2, kind="stale_heartbeat",
                                 target=d, magnitude=3600.0)])
    restarts = {"n": 0}

    class _Drill:
        """Chaos plan wrapper: after ageing the beat, wait for the monitor
        thread to notice (bounded), so the next step's poll raises
        deterministically instead of racing the scheduler."""

        def batch_hook(self, gstep, x, y):
            x, y = plan.batch_hook(gstep, x, y)
            if plan.fired and monitor.failure is None \
                    and restarts["n"] == 0:
                deadline = time.time() + 10
                while monitor.failure is None and time.time() < deadline:
                    time.sleep(0.01)
            return x, y

    def make_state_and_rejoin():
        if restarts["n"] or plan.fired:
            restarts["n"] += 1
        hb1.beat_once()  # the replacement worker announces itself
        return make_state()

    try:
        with Checkpointer(tmp_path / "ck") as ckpt:
            state, hist = fit_with_recovery(
                make_state_and_rejoin, train_step, eval_step, loaders,
                epochs=2, checkpointer=ckpt, monitor=monitor,
                sentinel=cfg, chaos=_Drill(), max_restarts=2)
    finally:
        monitor.stop()
    assert plan.fired == [(SPE + 2, "stale_heartbeat")]
    assert restarts["n"] >= 1          # a restart really happened
    assert restarts["n"] <= 2          # ...within max_restarts
    assert [h.epoch for h in hist if h.phase == "train"] == [1, 2]
    assert monitor.failure is None     # reset() cleared the latched death


# --- restart-loop fail-fast -------------------------------------------------

def test_deterministic_failure_fails_fast(tmp_path, mesh8):
    """A bug that dies identically at the same resume point must NOT burn
    every restart: two identical deaths end the run with the evidence."""
    make_state, (train_step, eval_step), loaders, cfg = _setup(mesh8)
    calls = {"n": 0}
    attempts = {"n": 0}

    def make_state_counting():
        attempts["n"] += 1
        calls["n"] = 0
        return make_state()

    def buggy_step(state, x, y):
        calls["n"] += 1
        if calls["n"] == 3:  # dies at batch 3 of every attempt
            raise RuntimeError("deterministic bug: bad op at batch 3")
        return train_step(state, x, y)

    with Checkpointer(tmp_path / "ff") as ckpt:
        with pytest.raises(RestartLoopError, match="same resume point"):
            fit_with_recovery(make_state_counting, buggy_step, eval_step,
                              loaders, epochs=2, checkpointer=ckpt,
                              max_restarts=50)
    # exactly two attempts: the first failure and its identical replay —
    # not 51 (the old behaviour burned every restart on the same bug)
    assert attempts["n"] == 2


# --- CLI wiring -------------------------------------------------------------

def test_sentinel_cli_flags():
    from distributed_deep_learning_tpu.utils.config import parse_args

    cfg = parse_args(["--sentinel", "skip", "--sentinel-window", "16",
                      "--sentinel-factor", "8"], workload="mlp")
    assert (cfg.sentinel, cfg.sentinel_window, cfg.sentinel_factor) == \
        ("skip", 16, 8.0)
    with pytest.raises(SystemExit, match="elastic"):
        parse_args(["--sentinel", "rollback"], workload="mlp")
    with pytest.raises(SystemExit, match="sentinel-factor"):
        parse_args(["--sentinel", "skip", "--sentinel-factor", "0.5"],
                   workload="mlp")


def test_sentinel_workload_end_to_end(monkeypatch, tmp_path):
    """`--sentinel skip` through the full CLI runner: trains, finishes
    with finite metrics, and the attached sentinel saw no anomalies on
    clean data."""
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import (get_spec,
                                                         run_workload)

    monkeypatch.setenv("DDL_DATA_LIMIT", "512")
    state, history = run_workload(
        get_spec("mlp"),
        parse_args(["-e", "1", "-b", "64", "-m", "data",
                    "--sentinel", "skip"], workload="mlp"))
    assert np.isfinite(history[-1].loss)
    assert int(state.sentinel.anomalies) == 0


# --- the full drill (slow) --------------------------------------------------

@pytest.mark.slow
def test_full_resilience_drill():
    rec = run_resilience_drill(seed=0)
    assert rec["containment_bit_identical"]
    assert rec["corrupt_restore_fell_back"]
    assert rec["recovered_bit_identical"]
    assert rec["detection_latency_steps"] <= 1
    assert rec["restarts_used"] == 1
    assert any(k == "nan_batch" for _, k in rec["faults_fired"])


@pytest.mark.slow
def test_chaos_drill_script_smoke():
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "chaos_drill.py")
    proc = subprocess.run(
        [sys.executable, script, "--seed", "1"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json

    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["drill_passed"]
