"""Instrumentation-overhead harness: metrics-on vs metrics-off steps/sec.

Drives the REAL ``train.loop._run_phase`` (not a mock of it) over a
list-backed in-memory loader with a jitted step sized so one step is
~1 ms of device work — big enough that per-step instrumentation cost
(a few ``perf_counter`` reads and dict adds) is measured against
realistic step granularity, small enough that the whole A/B fits a
bench section.  Off/on runs are INTERLEAVED and the median taken, so a
background-load blip cannot land entirely on one side.

The acceptance bar (ISSUE 7) is overhead < 2% of steps/sec; bench.py
records the measured ``obs_overhead_fraction`` under the
``{platform}:obs_overhead_fraction_v1`` baseline key and
``tests/test_obs.py`` guards a noise-tolerant bound.
"""

from __future__ import annotations

import time


def _build_step(dim: int, depth: int, batch: int, seed: int):
    """A jitted (state, x, y) -> (state, metrics) step with the train
    loop's metric contract, ~1 ms of matmul-chain grad work on CPU."""
    import jax
    import jax.numpy as jnp

    key = jax.random.key(seed)
    kw, kx, ky = jax.random.split(key, 3)
    w = jax.random.normal(kw, (dim, dim), jnp.float32) / dim ** 0.5
    x = jax.random.normal(kx, (batch, dim), jnp.float32)
    y = jax.random.normal(ky, (batch, dim), jnp.float32)

    @jax.jit
    def step(state, xb, yb):
        def loss_fn(wm):
            h = xb
            for _ in range(depth):
                h = jnp.tanh(h @ wm)
            return jnp.mean((h - yb) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(state["w"])
        correct = jnp.sum((xb[:, 0] > 0) == (yb[:, 0] > 0))
        return {"w": state["w"] - 1e-3 * g}, \
            {"loss": loss, "correct": correct,
             "count": jnp.asarray(xb.shape[0])}

    return step, {"w": w}, (x, y)


def _phase_sps(step, state, loader, steps: int, telemetry) -> float:
    from distributed_deep_learning_tpu.train.loop import _run_phase

    t0 = time.perf_counter()
    # _run_phase's end-of-phase _sum_totals host-fetches the metrics, so
    # the duration includes the device sync — honest steps/sec
    _run_phase(step, state, loader, train=True, telemetry=telemetry)
    return steps / (time.perf_counter() - t0)


def overhead_bench(*, steps: int = 48, repeats: int = 5, dim: int = 256,
                   depth: int = 4, batch: int = 64, seed: int = 0) -> dict:
    """Measure the telemetry hot path's cost on the real train loop.

    Returns ``steps_per_sec_off`` / ``steps_per_sec_on`` (medians over
    interleaved repeats), ``obs_overhead_fraction`` (1 - on/off) and the
    implied per-step cost in microseconds."""
    from distributed_deep_learning_tpu.obs import RunTelemetry

    step, state, (x, y) = _build_step(dim, depth, batch, seed)
    loader = [(x, y)] * steps
    # compile + cache warm OUTSIDE the measured window (telemetry's
    # steady-state cost is the claim; compile is charged separately to
    # the run's compile span in real runs)
    _phase_sps(step, state, loader[:2], 2, None)

    off, on = [], []
    for _ in range(repeats):
        off.append(_phase_sps(step, state, loader, steps, None))
        on.append(_phase_sps(step, state, loader, steps,
                             RunTelemetry(path=None)))
    off.sort()
    on.sort()
    sps_off, sps_on = off[len(off) // 2], on[len(on) // 2]
    frac = 1.0 - sps_on / sps_off
    return {
        "metric": "obs instrumentation overhead (steps/sec on vs off)",
        "steps": steps, "repeats": repeats,
        "step_geometry": {"dim": dim, "depth": depth, "batch": batch},
        "steps_per_sec_off": round(sps_off, 2),
        "steps_per_sec_on": round(sps_on, 2),
        "obs_overhead_fraction": round(frac, 5),
        "per_step_overhead_us": round(
            (1.0 / sps_on - 1.0 / sps_off) * 1e6, 2),
    }


def trace_overhead_bench(*, steps: int = 48, repeats: int = 5,
                         dim: int = 256, depth: int = 4, batch: int = 64,
                         seed: int = 0) -> dict:
    """Gen-2 A/B (ISSUE 11): telemetry WITH span tracing vs telemetry
    without, on the real train loop.

    ``overhead_bench`` prices the gen-1 instruments against a bare run;
    this prices the tracer increment — every Timeline.add now also
    records a causal span (one extra clock read + one Span append).
    The acceptance bar is < 2% of steps/sec; bench.py records
    ``obs_trace_overhead_fraction`` under the
    ``{platform}:obs_trace_overhead_fraction_v1`` baseline key."""
    from distributed_deep_learning_tpu.obs import RunTelemetry, Tracer

    step, state, (x, y) = _build_step(dim, depth, batch, seed)
    loader = [(x, y)] * steps
    _phase_sps(step, state, loader[:2], 2, None)   # compile warm

    plain, traced = [], []
    for _ in range(repeats):
        plain.append(_phase_sps(step, state, loader, steps,
                                RunTelemetry(path=None)))
        traced.append(_phase_sps(step, state, loader, steps,
                                 RunTelemetry(path=None,
                                              tracer=Tracer())))
    plain.sort()
    traced.sort()
    sps_plain = plain[len(plain) // 2]
    sps_traced = traced[len(traced) // 2]
    frac = 1.0 - sps_traced / sps_plain
    return {
        "metric": "span-tracing overhead (steps/sec traced vs untraced "
                  "telemetry)",
        "steps": steps, "repeats": repeats,
        "step_geometry": {"dim": dim, "depth": depth, "batch": batch},
        "steps_per_sec_plain": round(sps_plain, 2),
        "steps_per_sec_traced": round(sps_traced, 2),
        "obs_trace_overhead_fraction": round(frac, 5),
        "per_step_overhead_us": round(
            (1.0 / sps_traced - 1.0 / sps_plain) * 1e6, 2),
    }
