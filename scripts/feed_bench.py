"""Feed-rate microbenchmark: can the host form batches at device rate?

Times HOST batch formation only (``DeviceLoader.iter_host_batches`` — no
device transfer, no train step) three ways on the same image tree:

* **eager**   — ImageFolderDataset, cold LRU: PIL decode + native resize
  on the measured path, the per-epoch cost the reference pays;
* **packed**  — the same samples through a ``data/packed.py`` mmap cache:
  one fancy-index slab gather per batch, zero per-sample Python work;
* **pack**    — the one-off packing cost, amortised over every epoch.

The TPU train step consumes ~2,400 ResNet-50 img/s/chip (``BENCH_r05``
``recorded_tpu``); the eager path delivers ~35.  The packed path must
clear the chip's appetite on the CPU CI box — that is the whole point.

    JAX_PLATFORMS=cpu python scripts/feed_bench.py [--data-dir TREE]
        [--image-size 64] [--batch 64] [--epochs 3]

Prints one JSON line: eager/packed images-per-sec, speedup, pack cost.
Without ``--data-dir`` a synthetic JPEG tree is generated (6 classes,
matching the bench fixture).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _script_env() -> None:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def make_jpeg_tree(root: str, *, classes: int = 6, per_class: int = 24,
                   size: int = 72, seed: int = 4) -> None:
    """The bench.py input-pipeline fixture: random JPEGs per class dir."""
    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(seed)
    for c in range(classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"im{i}.jpg"))


def _formation_rate(dataset, *, batch: int, epochs: int, seed: int = 0
                    ) -> float:
    """images/sec through the loader's host batch-formation path (seeded
    shuffled epochs — the exact gather training performs)."""
    import jax
    import numpy as np

    from distributed_deep_learning_tpu.data.loader import DeviceLoader
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    mesh = build_mesh({"data": 1}, jax.devices()[:1])
    n_use = (len(dataset) // batch) * batch
    loader = DeviceLoader(dataset, np.arange(n_use), batch, mesh,
                          shuffle=True, seed=seed)
    done = 0
    t0 = time.perf_counter()
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for x, y in loader.iter_host_batches():
            done += len(x)
    dt = time.perf_counter() - t0
    return done / dt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="host batch-formation rate: eager decode vs packed "
                    "mmap cache")
    p.add_argument("--data-dir", default=None,
                   help="ImageFolder tree (default: generated JPEG "
                        "fixture)")
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--epochs", type=int, default=3,
                   help="measured epochs per path (packed additionally "
                        "gets one unmeasured page-cache warmup epoch)")
    p.add_argument("--eager-epochs", type=int, default=1,
                   help="measured epochs for the eager path (it is slow; "
                        "its cost is identical every epoch)")
    p.add_argument("--out", default=None, help="also write the JSON here")
    args = p.parse_args(argv)

    from distributed_deep_learning_tpu.data.imagefolder import (
        ImageFolderDataset)
    from distributed_deep_learning_tpu.data.packed import (PackedDataset,
                                                           pack_dataset)

    with tempfile.TemporaryDirectory() as tmp:
        root = args.data_dir
        if root is None:
            root = os.path.join(tmp, "images")
            make_jpeg_tree(root)
        # max_cached_images=1: the eager number must be the DECODE rate,
        # not the LRU hit rate (epoch 2+ of a small fixture would
        # otherwise measure the cache, which real corpora don't fit)
        eager = ImageFolderDataset(root, image_size=args.image_size,
                                   max_cached_images=1)
        batch = min(args.batch, len(eager))
        eager_ips = _formation_rate(eager, batch=batch,
                                    epochs=args.eager_epochs)

        cache = os.path.join(tmp, "cache.ddlpack")
        t0 = time.perf_counter()
        header = pack_dataset(eager, cache)
        pack_secs = time.perf_counter() - t0
        packed = PackedDataset(cache)
        _formation_rate(packed, batch=batch, epochs=1)  # page-cache warmup
        packed_ips = _formation_rate(packed, batch=batch,
                                     epochs=args.epochs)

    line = {
        "metric": "host batch formation images/sec",
        "image_size": args.image_size,
        "batch": batch,
        "num_samples": header["num_samples"],
        "eager_images_per_sec": round(eager_ips, 1),
        "packed_images_per_sec": round(packed_ips, 1),
        "speedup": round(packed_ips / eager_ips, 1) if eager_ips else None,
        "pack_seconds": round(pack_secs, 3),
        "packed_bytes": header["total_bytes"],
        "feature_dtype": header["feature_dtype"],
    }
    out = json.dumps(line)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    _script_env()
    sys.exit(main())
