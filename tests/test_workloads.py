"""End-to-end workload runner: all three workloads × all four modes behind
the reference CLI (the backend contract, SURVEY.md §2.6)."""

import os

import numpy as np
import pytest

from distributed_deep_learning_tpu.utils.config import Mode, parse_args
from distributed_deep_learning_tpu.workloads import get_spec, run_workload


def _run(workload, argv, limit=1024):
    """Run under a small DDL_DATA_LIMIT so staged (un-jitted outer loop)
    modes stay fast on the CPU test platform."""
    config = parse_args(argv, workload=workload)
    old = os.environ.get("DDL_DATA_LIMIT")
    os.environ["DDL_DATA_LIMIT"] = str(limit)
    try:
        return run_workload(get_spec(workload), config)
    finally:
        if old is None:
            os.environ.pop("DDL_DATA_LIMIT", None)
        else:
            os.environ["DDL_DATA_LIMIT"] = old


def _history_ok(history):
    phases = [h.phase for h in history]
    assert phases[-1] == "test"
    assert "train" in phases and "validation" in phases
    for h in history:
        assert np.isfinite(h.loss), f"{h.phase}: non-finite loss"


# --- the reference's 4 modes on the minimum workload (MLP) -----------------

def test_mlp_sequential():
    _, history = _run("mlp", ["-e", "3", "-b", "64", "-m", "sequential"],
                      limit=2048)
    _history_ok(history)
    train = [h for h in history if h.phase == "train"]
    assert train[-1].accuracy > train[0].accuracy  # learns on planted signal
    assert train[-1].accuracy > 40.0


def test_mlp_data_parallel():
    _, history = _run("mlp", ["-e", "2", "-b", "64", "-m", "data"])
    _history_ok(history)


def test_mlp_model_parallel():
    _, history = _run("mlp", ["-l", "2", "-e", "1", "-b", "64", "-m", "model",
                              "--nstages", "3"])
    _history_ok(history)


def test_mlp_pipeline():
    # reference -p semantics: microbatch SIZE 16 over batch 64
    _, history = _run("mlp", ["-l", "2", "-e", "1", "-b", "64", "-m",
                              "pipeline", "-p", "16", "--nstages", "2"])
    _history_ok(history)


# --- CNN and LSTM workloads (one cheap mode each + one staged mode) --------

def test_cnn_sequential_smoke():
    _, history = _run("cnn", ["-l", "1", "-e", "1", "-b", "16", "-m",
                              "sequential"])
    _history_ok(history)


def test_cnn_pipeline_smoke():
    _, history = _run("cnn", ["-l", "2", "-e", "1", "-b", "16", "-m",
                              "pipeline", "-p", "8", "--nstages", "2"])
    _history_ok(history)


def test_lstm_data_parallel():
    _, history = _run("lstm", ["-e", "1", "-b", "64", "-m", "data"])
    _history_ok(history)


def test_lstm_model_parallel():
    _, history = _run("lstm", ["-l", "3", "-e", "1", "-b", "64", "-m",
                               "model", "--nstages", "4"])
    _history_ok(history)


# --- mode equivalence: staged modes compute the same function --------------

def test_pipeline_mode_matches_model_mode():
    """Same seed + same staging ⇒ model and pipeline modes produce identical
    math (microbatching must not change results for BN-free models)."""
    _, h_mp = _run("mlp", ["-l", "2", "-e", "1", "-b", "64", "-m", "model",
                           "--nstages", "2"])
    _, h_pp = _run("mlp", ["-l", "2", "-e", "1", "-b", "64", "-m", "pipeline",
                           "-p", "16", "--nstages", "2"])
    mp_train = [h for h in h_mp if h.phase == "train"][0]
    pp_train = [h for h in h_pp if h.phase == "train"][0]
    np.testing.assert_allclose(mp_train.loss, pp_train.loss, rtol=1e-5)
    np.testing.assert_allclose(mp_train.accuracy, pp_train.accuracy, atol=1e-6)


# --- quirk replication flags ----------------------------------------------

def test_quirk_q1_no_sync_mode():
    _, history = _run("mlp", ["-e", "1", "-b", "32", "-m", "data", "-r", "4",
                              "--no-sync"])
    _history_ok(history)


def test_quirk_q4_double_softmax():
    _, history = _run("mlp", ["-e", "1", "-b", "64", "--double-softmax"])
    _history_ok(history)


# --- CLI surface -----------------------------------------------------------

def test_cli_defaults_match_reference():
    c = parse_args([], workload="cnn")
    assert c.epochs == 10 and c.batch_size == 32 and c.microbatch == 2
    assert c.mode is Mode.SEQUENTIAL
    assert c.num_layers == 2 and c.size == 4  # CNN/main.py:49-50


def test_unknown_workload_raises():
    with pytest.raises(ValueError):
        get_spec("resnet9000")


def test_clip_norm_and_metrics_file(tmp_path, monkeypatch):
    """--clip-norm trains; --metrics-file leaves a parseable JSONL event
    stream (phase begins/ends + throughput counters)."""
    import json

    import numpy as np

    from distributed_deep_learning_tpu.utils.config import Config, Mode
    from distributed_deep_learning_tpu.workloads import get_spec

    monkeypatch.setenv("DDL_DATA_LIMIT", "256")
    mf = tmp_path / "metrics.jsonl"
    config = Config(mode=Mode.DATA, epochs=1, batch_size=64, clip_norm=1.0,
                    metrics_file=str(mf))
    _, history = run_workload(get_spec("mlp"), config)
    assert np.isfinite(history[0].loss)
    events = [json.loads(ln) for ln in mf.read_text().splitlines()]
    kinds = {e["event"] for e in events}
    assert {"phase_begin", "phase_end", "metrics"} <= kinds
    ends = [e for e in events if e["event"] == "phase_end"
            and e.get("phase") == "train"]
    assert ends and "accuracy" in ends[0] and "loss" in ends[0]


def test_cli_parses_clip_and_metrics_flags():
    from distributed_deep_learning_tpu.utils.config import parse_args

    c = parse_args(["--clip-norm", "0.5", "--metrics-file", "/tmp/m.jsonl"],
                   workload="mlp")
    assert c.clip_norm == 0.5 and c.metrics_file == "/tmp/m.jsonl"
