"""Fused linear+cross-entropy vs the materialised logits path: values,
gradients, padding semantics — the (N, V) logit matrix never exists."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.ops.fused_ce import (
    fused_linear_cross_entropy)


def _reference(h, table, targets, ignore_id=0):
    logits = h.astype(jnp.float32) @ table.astype(jnp.float32).T
    per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    valid = targets != ignore_id
    return jnp.sum(jnp.where(valid, per, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


def _data(N=24, d=16, V=64, seed=0, pad_tail=4):
    ks = jax.random.split(jax.random.key(seed), 3)
    h = jax.random.normal(ks[0], (N, d))
    table = jax.random.normal(ks[1], (V, d)) * 0.1
    targets = jax.random.randint(ks[2], (N,), 1, V)
    targets = targets.at[-pad_tail:].set(0)
    return h, table, targets


def test_matches_reference_loss():
    h, table, targets = _data()
    got = fused_linear_cross_entropy(h, table, targets, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_matches_with_single_block():
    h, table, targets = _data(seed=1)
    got = fused_linear_cross_entropy(h, table, targets, 0, 64)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_gradients_match_reference():
    h, table, targets = _data(seed=2)

    g_fused = jax.grad(
        lambda h, w: fused_linear_cross_entropy(h, w, targets, 0, 16),
        argnums=(0, 1))(h, table)
    g_ref = jax.grad(lambda h, w: _reference(h, w, targets),
                     argnums=(0, 1))(h, table)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_batched_sequence_shape():
    """(B, T, d) activations + (B, T) targets — the LM calling shape."""
    h, table, targets = _data(N=32, seed=3)
    h3 = h.reshape(4, 8, -1)
    t3 = targets.reshape(4, 8)
    got = fused_linear_cross_entropy(h3, table, t3, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_all_padding_is_finite():
    h, table, _ = _data(seed=4)
    targets = jnp.zeros((24,), jnp.int32)  # everything ignored
    got = fused_linear_cross_entropy(h, table, targets, 0, 16)
    assert float(got) == 0.0
    g = jax.grad(lambda h: fused_linear_cross_entropy(
        h, table, targets, 0, 16))(h)
    assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_allclose(np.asarray(g), 0.0, atol=1e-8)


def test_indivisible_block_pads():
    """An indivisible block request works via zero-row vocab padding
    (odd vocab sizes come from real tokenizers)."""
    h, table, targets = _data()
    got = fused_linear_cross_entropy(h, table, targets, 0, 48)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_bf16_activations():
    h, table, targets = _data(seed=5)
    got = fused_linear_cross_entropy(h.astype(jnp.bfloat16), table,
                                     targets, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=2e-2)


def test_under_jit_and_grad_jit():
    h, table, targets = _data(seed=6)
    f = jax.jit(lambda h, w: fused_linear_cross_entropy(h, w, targets, 0, 16))
    g = jax.jit(jax.grad(f, argnums=(0, 1)))
    np.testing.assert_allclose(float(f(h, table)),
                               float(_reference(h, table, targets)),
                               rtol=1e-5)
    for a, b in zip(g(h, table),
                    jax.grad(lambda h, w: _reference(h, w, targets),
                             argnums=(0, 1))(h, table)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6)


def test_causal_lm_fused_loss_matches_logits_path():
    """Model-level: CausalLM.loss (fused head) == softmax-CE over
    CausalLM.logits_from, pad positions excluded."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_deep_learning_tpu.models.transformer import CausalLM

    model = CausalLM(vocab_size=97, num_layers=2, d_model=32, num_heads=4,
                     mlp_dim=64, max_len=64)
    toks = jax.random.randint(jax.random.key(0), (2, 17), 1, 97)
    toks = toks.at[1, 12:].set(0)  # padding tail
    params = model.init(jax.random.key(1), toks[:, :-1])
    h = model.apply(params, toks[:, :-1], train=False)
    targets = toks[:, 1:]

    fused = model.loss(params, h, targets)
    logits = model.logits_from(params, h)
    logp = jax.nn.log_softmax(logits, axis=-1)
    valid = targets != 0
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ref = -jnp.sum(jnp.where(valid, picked, 0.0)) / jnp.sum(valid)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_causal_lm_is_causal():
    """Hidden state at position t must not depend on tokens after t."""
    import jax
    import numpy as np

    from distributed_deep_learning_tpu.models.transformer import CausalLM

    model = CausalLM(vocab_size=50, num_layers=2, d_model=32, num_heads=4,
                     mlp_dim=64, max_len=32)
    t1 = jax.random.randint(jax.random.key(0), (1, 16), 1, 50)
    t2 = t1.at[0, 10:].set(1 + (t1[0, 10:] % 49))  # change the tail only
    params = model.init(jax.random.key(1), t1)
    h1 = model.apply(params, t1, train=False)
    h2 = model.apply(params, t2, train=False)
    np.testing.assert_allclose(np.asarray(h1[:, :10]),
                               np.asarray(h2[:, :10]), rtol=2e-5, atol=2e-5)


def test_prime_vocab_full_block_width():
    """Vocab padding (not divisor snapping): a prime vocab must still run
    at the requested block width — a largest-divisor scheme would
    degenerate to block=1 (GPT-2's V=50257 is prime). Values and grads
    must match the materialised reference exactly."""
    import jax

    V = 97  # prime
    h, table, targets = _data(V=V)

    got = fused_linear_cross_entropy(h, table, targets, 0, 32)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    gf = jax.grad(lambda h, t: fused_linear_cross_entropy(h, t, targets,
                                                          0, 32),
                  argnums=(0, 1))(h, table)
    gr = jax.grad(lambda h, t: _reference(h, t, targets),
                  argnums=(0, 1))(h, table)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_causal_lm_loss_threads_pad_id():
    """CausalLM.loss must exclude ``model.pad_id`` positions — and with
    ``pad_id=None`` count EVERY position (imported GPT-2, where id 0 is a
    real token), instead of hard-coding id 0."""
    import jax

    from distributed_deep_learning_tpu.models.transformer import CausalLM

    kw = dict(vocab_size=61, num_layers=1, d_model=16, num_heads=2,
              mlp_dim=32, max_len=32)
    toks = jax.random.randint(jax.random.key(0), (2, 13), 1, 61)
    toks = toks.at[1, 9:].set(0)  # tail of id-0 positions
    model0 = CausalLM(**kw)                 # pad_id=0 (default)
    model_none = CausalLM(**kw, pad_id=None)
    params = model0.init(jax.random.key(1), toks[:, :-1])
    h = model0.apply(params, toks[:, :-1], train=False)
    targets = toks[:, 1:]

    def ref(model, ignore):
        logp = jax.nn.log_softmax(model.logits_from(params, h), axis=-1)
        picked = jnp.take_along_axis(logp, targets[..., None],
                                     axis=-1)[..., 0]
        valid = targets != ignore
        return -jnp.sum(jnp.where(valid, picked, 0.0)) / jnp.sum(valid)

    np.testing.assert_allclose(float(model0.loss(params, h, targets)),
                               float(ref(model0, 0)), rtol=1e-5)
    # pad_id=None: id-0 sites now COUNT (denominator grows, value shifts);
    # hidden states come from model0 deliberately — same forward, only the
    # loss masking differs
    np.testing.assert_allclose(float(model_none.loss(params, h, targets)),
                               float(ref(model_none, -1)), rtol=1e-5)
    assert float(model0.loss(params, h, targets)) != pytest.approx(
        float(model_none.loss(params, h, targets)))


def test_token_cross_entropy_pad_id_param():
    """objectives.token_cross_entropy: the ignored id is a parameter now
    (``pad_id=None`` scores every position)."""
    import jax

    from distributed_deep_learning_tpu.train.objectives import (
        token_cross_entropy)

    logits = jax.random.normal(jax.random.key(0), (2, 6, 11))
    targets = jnp.array([[3, 0, 5, 0, 1, 2], [4, 4, 0, 0, 0, 9]])
    default = token_cross_entropy(logits, targets)
    explicit0 = token_cross_entropy(logits, targets, pad_id=0)
    np.testing.assert_allclose(float(default), float(explicit0))

    none = token_cross_entropy(logits, targets, pad_id=None)
    per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    np.testing.assert_allclose(float(none), float(jnp.mean(per)), rtol=1e-6)

    pad9 = token_cross_entropy(logits, targets, pad_id=9)
    valid = targets != 9
    want = jnp.sum(jnp.where(valid, per, 0.0)) / jnp.sum(valid)
    np.testing.assert_allclose(float(pad9), float(want), rtol=1e-6)
