"""KV-cached decode: per-step cached logits match the full forward, and
generate() reproduces uncached greedy decoding exactly."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_deep_learning_tpu.models.transformer import (CausalLM,
                                                              generate)

MODEL = dict(vocab_size=61, num_layers=2, d_model=32, num_heads=4,
             mlp_dim=64, max_len=32)


def _model(**kw):
    return CausalLM(**{**MODEL, **kw})


def test_cached_decode_matches_full_forward():
    """Feeding tokens one at a time through the cache reproduces the
    full-sequence logits at every position."""
    model = _model(with_logits=True)
    toks = jax.random.randint(jax.random.key(0), (2, 10), 1, 61)
    params = model.init(jax.random.key(1), toks)["params"]
    full = model.apply({"params": params}, toks)          # (2, 10, V)

    lm = model.clone(decode=True)
    cache = lm.init(jax.random.key(0), toks)["cache"]
    for t in range(toks.shape[1]):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)


def test_generate_matches_uncached_greedy():
    """generate() == the O(T^2) recompute loop, token for token."""
    model = _model(with_logits=True)
    prompt = jax.random.randint(jax.random.key(2), (2, 4), 1, 61)
    params = model.init(jax.random.key(3), prompt)["params"]

    got = generate(model, params, prompt, max_new_tokens=6)

    seq = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, seq)
        # generate() never emits pad id 0 — mirror that in the reference
        nxt = jnp.argmax(logits[:, -1].at[:, 0].set(-jnp.inf),
                         axis=-1).astype(seq.dtype)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq[:, 4:]))


def test_generate_sampling_shape_and_range():
    model = _model(with_logits=True)
    prompt = jax.random.randint(jax.random.key(4), (3, 2), 1, 61)
    params = model.init(jax.random.key(5), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=5,
                   temperature=1.0, rng=jax.random.key(6))
    assert out.shape == (3, 5)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < 61)).all()


def test_generate_respects_max_len():
    import pytest

    model = _model(with_logits=True)
    prompt = jnp.ones((1, 30), jnp.int32)
    params = model.init(jax.random.key(7), prompt)["params"]
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, max_new_tokens=10)


def test_cached_decode_with_padding_matches_full_forward():
    """Pad tokens (id 0) inside the sequence must be masked in cached
    decode exactly as the full forward masks them."""
    model = _model(with_logits=True)
    toks = jax.random.randint(jax.random.key(8), (2, 12), 1, 61)
    toks = toks.at[0, 5:8].set(0)  # interior padding on row 0
    params = model.init(jax.random.key(9), toks)["params"]
    full = model.apply({"params": params}, toks)

    lm = model.clone(decode=True)
    shapes = jax.eval_shape(lm.init, jax.random.key(0), toks)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])
    for t in range(toks.shape[1]):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)


def test_multi_token_prefill_matches_full_forward():
    """A single multi-token cached call (prompt prefill) must produce the
    same logits as the full forward — the in-chunk causal prefix mask."""
    model = _model(with_logits=True)
    toks = jax.random.randint(jax.random.key(10), (2, 9), 1, 61)
    params = model.init(jax.random.key(11), toks)["params"]
    full = model.apply({"params": params}, toks)

    lm = model.clone(decode=True)
    shapes = jax.eval_shape(lm.init, jax.random.key(0), toks)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])
    pre, upd = lm.apply({"params": params, "cache": cache}, toks[:, :6],
                        mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :6]),
                               rtol=2e-4, atol=2e-4)
    # continue token-by-token from the prefilled cache
    cache = upd["cache"]
    for t in range(6, 9):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)


def test_rope_causal_lm_trains_and_is_causal():
    """pos_embedding='rope': no learned position table, causality holds."""
    model = _model(with_logits=True, pos_embedding="rope")
    toks = jax.random.randint(jax.random.key(12), (2, 16), 1, 61)
    params = model.init(jax.random.key(13), toks)["params"]
    assert "pos" not in params["embed"], "rope must not create a pos table"
    t2 = toks.at[:, 10:].set(1 + (toks[:, 10:] % 60))
    h1 = model.apply({"params": params}, toks)
    h2 = model.apply({"params": params}, t2)
    np.testing.assert_allclose(np.asarray(h1[:, :10]),
                               np.asarray(h2[:, :10]), rtol=2e-4, atol=2e-4)


def test_rope_cached_decode_matches_full_forward():
    """RoPE + KV cache: cached keys carry their absolute rotation, so
    per-step decode logits must equal the full forward."""
    model = _model(with_logits=True, pos_embedding="rope")
    toks = jax.random.randint(jax.random.key(14), (2, 10), 1, 61)
    params = model.init(jax.random.key(15), toks)["params"]
    full = model.apply({"params": params}, toks)

    lm = model.clone(decode=True)
    shapes = jax.eval_shape(lm.init, jax.random.key(0), toks)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])
    for t in range(toks.shape[1]):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=3e-4, atol=3e-4)


def test_rope_generate_runs():
    model = _model(with_logits=True, pos_embedding="rope")
    prompt = jax.random.randint(jax.random.key(16), (2, 4), 1, 61)
    params = model.init(jax.random.key(17), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=5)
    assert out.shape == (2, 5)


def test_windowed_cached_decode_matches_full_forward():
    """Train/inference parity with --window: the KV-cache decode applies
    the same causal band as the full forward (review regression — decode
    previously attended the whole prefix)."""
    model = _model(with_logits=True, attention_window=4)
    toks = jax.random.randint(jax.random.key(18), (2, 12), 1, 61)
    params = model.init(jax.random.key(19), toks)["params"]
    full = model.apply({"params": params}, toks)

    lm = model.clone(decode=True)
    shapes = jax.eval_shape(lm.init, jax.random.key(0), toks)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])
    for t in range(toks.shape[1]):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=3e-4, atol=3e-4)


def test_gqa_matches_mha_when_equal_heads():
    """num_kv_heads == num_heads must be numerically identical to MHA
    (same parameter shapes, same math)."""
    m1 = _model(with_logits=True)
    m2 = _model(with_logits=True, num_kv_heads=4)  # == num_heads
    toks = jax.random.randint(jax.random.key(20), (2, 8), 1, 61)
    p1 = m1.init(jax.random.key(21), toks)["params"]
    np.testing.assert_allclose(
        np.asarray(m1.apply({"params": p1}, toks)),
        np.asarray(m2.apply({"params": p1}, toks)), rtol=1e-6)


def test_gqa_cache_is_small_and_decode_matches_full():
    """GQA: the KV cache stores num_kv_heads (the memory win), and cached
    decode still matches the full forward exactly."""
    model = _model(with_logits=True, num_kv_heads=2)  # 4 q heads, 2 kv
    toks = jax.random.randint(jax.random.key(22), (2, 10), 1, 61)
    params = model.init(jax.random.key(23), toks)["params"]
    assert params["layer_0"]["self_attn"]["k"]["kernel"].shape[-2] == 2
    full = model.apply({"params": params}, toks)

    lm = model.clone(decode=True)
    shapes = jax.eval_shape(lm.init, jax.random.key(0), toks)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         shapes["cache"])
    ck = cache["layer_0"]["self_attn"]["cached_key"]
    assert ck.shape[-2] == 2, f"cache stores kv heads, got {ck.shape}"
    for t in range(toks.shape[1]):
        step_logits, upd = lm.apply({"params": params, "cache": cache},
                                    toks[:, t:t + 1], mutable=["cache"])
        cache = upd["cache"]
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=3e-4, atol=3e-4)


def test_gqa_indivisible_heads_rejected():
    import pytest

    model = _model(with_logits=True, num_kv_heads=3)  # 4 % 3 != 0
    toks = jnp.ones((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        model.init(jax.random.key(0), toks)


def test_top_k_sampling():
    """top_k=1 with temperature reproduces greedy; top_k restricts the
    sampled support; top_k < 1 is rejected."""
    import pytest

    model = _model(with_logits=True)
    prompt = jax.random.randint(jax.random.key(24), (2, 4), 1, 61)
    params = model.init(jax.random.key(25), prompt)["params"]

    greedy = generate(model, params, prompt, max_new_tokens=5)
    k1 = generate(model, params, prompt, max_new_tokens=5,
                  temperature=1.0, top_k=1, rng=jax.random.key(26))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))

    out = generate(model, params, prompt, max_new_tokens=5,
                   temperature=2.0, top_k=5, rng=jax.random.key(27))
    assert out.shape == (2, 5)

    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_new_tokens=2, top_k=0)


def test_top_p_sampling():
    """Tiny top_p reproduces greedy (only the max token survives the
    nucleus); top_p composes with temperature; bounds are validated."""
    import pytest

    model = _model(with_logits=True)
    prompt = jax.random.randint(jax.random.key(40), (2, 4), 1, 61)
    params = model.init(jax.random.key(41), prompt)["params"]

    greedy = generate(model, params, prompt, max_new_tokens=5)
    nucleus = generate(model, params, prompt, max_new_tokens=5,
                       temperature=1.0, top_p=1e-9,
                       rng=jax.random.key(42))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(nucleus))

    out = generate(model, params, prompt, max_new_tokens=5,
                   temperature=1.5, top_p=0.9, rng=jax.random.key(43))
    assert out.shape == (2, 5)
    # top_p=1.0 is a no-op relative to plain temperature sampling
    plain = generate(model, params, prompt, max_new_tokens=5,
                     temperature=1.5, rng=jax.random.key(43))
    full = generate(model, params, prompt, max_new_tokens=5,
                    temperature=1.5, top_p=1.0, rng=jax.random.key(43))
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(full))

    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="top_p"):
            generate(model, params, prompt, max_new_tokens=2,
                     temperature=1.0, top_p=bad)


def test_generate_pad_free_model_can_emit_id_zero():
    """pad_id=None (imported GPT-2: id 0 is a real token) removes the
    never-emit-0 mask — id 0 must be sampleable again."""
    model = _model(with_logits=True).clone(pad_id=None)
    prompt = jax.random.randint(jax.random.key(50), (8, 4), 1, 61)
    params = model.init(jax.random.key(51), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=24,
                   temperature=50.0, rng=jax.random.key(52))
    # near-uniform sampling over 61 ids x 192 draws: id 0 shows up
    assert (np.asarray(out) == 0).any()


def test_generate_never_emits_pad_id():
    """ADVICE r3: a generated 0 would be recorded invalid in the KV cache
    (valid = tokens != 0) and silently vanish from later attention — so
    id 0 is masked out of every pick, greedy and sampled."""
    model = _model(with_logits=True)
    prompt = jax.random.randint(jax.random.key(30), (4, 4), 1, 61)
    params = model.init(jax.random.key(31), prompt)["params"]
    for kw in ({}, {"temperature": 1.5, "rng": jax.random.key(32)},
               {"temperature": 1.0, "top_k": 3, "rng": jax.random.key(33)}):
        out = generate(model, params, prompt, max_new_tokens=8, **kw)
        assert (np.asarray(out) != 0).all(), f"emitted pad id under {kw}"


def test_gpt_generate_too_long_rejected_before_training():
    """ADVICE r3: --generate N beyond what max_len admits must fail at
    validation time, not after the expensive training run."""
    import pytest

    from distributed_deep_learning_tpu.workloads.northstar import (
        _gpt_pre_check)
    from distributed_deep_learning_tpu.utils.config import Mode

    class DS:
        features = np.zeros((4, 64), np.int32)

    class Cfg:
        generate_tokens = 56
        mode = Mode.DATA
    _gpt_pre_check(Cfg(), DS())  # 8 + 56 == 64: fits

    Cfg.generate_tokens = 57
    with pytest.raises(ValueError, match="--generate"):
        _gpt_pre_check(Cfg(), DS())
