"""Hot weight reload: publish → watch → verify → canary → promote.

Closes the train→serve loop (the ROADMAP item): a trainer publishes
weights; a running engine picks them up BETWEEN ticks with no recompile
(the compiled programs take params as traced arguments, so any weights
of identical geometry slide into the donated buffers) and no restart.

The path is defensive at every hop, mirroring the checkpoint machinery:

* **Publish** is atomic-then-commit: the ``.npz`` payload lands under a
  temp name and is renamed into place; the integrity manifest (per-leaf
  CRC32 + shape/dtype/finiteness via :func:`..utils.checkpoint.
  _leaf_records`) is written LAST as the commit marker.  A torn publish
  leaves a payload without a manifest, which the watcher never sees.
* **Watch** polls the directory through the same
  :class:`..utils.failures.FlakyIOPolicy` seam the heartbeat monitor
  uses — transient I/O errors are tolerated up to a consecutive budget,
  then the watcher declares ITSELF unhealthy instead of silently going
  blind (no second flaky-IO policy).
* **Verify** recomputes every leaf record on load and compares against
  the manifest; any mismatch (bit flip, truncation, NaN) raises
  :class:`..utils.checkpoint.CheckpointCorruption` and the publication
  is QUARANTINED (renamed, never deleted — it is evidence).
* **Canary** routes a slot slice to the candidate weights
  (:meth:`..serve.engine.PagedEngine.begin_canary` — one extra call of
  the same compiled program per tick) and feeds old-vs-new argmax
  agreement and chosen-logprob drift into :mod:`..obs.window`
  histograms.  Good candidates PROMOTE (full swap, prefix index
  flushed); bad ones ROLL BACK: the candidate is quarantined, the
  flight recorder dumps, and :class:`CanaryRollback` carries the ledger
  snapshot taken at canary start so the supervisor rewinds and replays
  — outputs end up bit-identical to a run the canary never touched.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax
import numpy as np

from distributed_deep_learning_tpu.obs.window import WindowedHistogram
from distributed_deep_learning_tpu.utils.checkpoint import (
    CheckpointCorruption, _leaf_records)
from distributed_deep_learning_tpu.utils.failures import FlakyIOPolicy

WEIGHTS_FORMAT = 1


def _weights_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"weights-{step:08d}.npz")


def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"weights-{step:08d}.manifest.json")


def publish_weights(directory: str, step: int, params) -> str:
    """Atomically publish one weight set for live engines to pick up.

    Payload first (temp name + rename), manifest LAST — the manifest is
    the commit marker, so a reader never sees a half-written payload.
    Leaves are stored positionally (flatten order); the manifest's
    keyed records pin the tree they came from."""
    os.makedirs(directory, exist_ok=True)
    leaves = jax.tree_util.tree_leaves(params)
    payload = {f"leaf_{i:05d}": np.asarray(jax.device_get(x))
               for i, x in enumerate(leaves)}
    wpath = _weights_path(directory, step)
    tmp = f"{wpath}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, wpath)  # atomic on POSIX
    mpath = _manifest_path(directory, step)
    tmp = f"{mpath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"format": WEIGHTS_FORMAT, "step": step,
                   "n_leaves": len(leaves),
                   "leaves": _leaf_records(params)}, f)
    os.replace(tmp, mpath)
    return wpath


def latest_published(directory: str) -> Optional[int]:
    """Highest step with BOTH payload and manifest present (a payload
    alone is an uncommitted publish in flight)."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("weights-") and name.endswith(".manifest.json"):
            try:
                step = int(name[len("weights-"):-len(".manifest.json")])
            except ValueError:
                continue
            if os.path.exists(_weights_path(directory, step)):
                steps.append(step)
    return max(steps) if steps else None


def load_verified(directory: str, step: int, like):
    """Load a published weight set and verify it leaf by leaf.

    ``like`` supplies the target tree structure (the engine's current
    params).  Every leaf is checked against the manifest — CRC32 over
    raw bytes, shape, dtype, all-finite — and the whole set against the
    target geometry.  Any mismatch raises
    :class:`CheckpointCorruption`; the caller quarantines."""
    mpath = _manifest_path(directory, step)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruption(step, f"unreadable manifest: {e}")
    flat, treedef = jax.tree_util.tree_flatten(like)
    if manifest.get("n_leaves") != len(flat):
        raise CheckpointCorruption(
            step, f"manifest records {manifest.get('n_leaves')} leaves, "
            f"engine params have {len(flat)}")
    try:
        with np.load(_weights_path(directory, step)) as z:
            arrays = [z[f"leaf_{i:05d}"] for i in range(len(flat))]
    except Exception as e:  # torn zip, bad CRC, missing member
        raise CheckpointCorruption(step, f"unreadable payload: "
                                   f"{type(e).__name__}: {e}")
    new = jax.tree_util.tree_unflatten(treedef, arrays)
    want = manifest.get("leaves", {})
    got = _leaf_records(new)
    if sorted(want) != sorted(got):
        raise CheckpointCorruption(step, "manifest/payload leaf keys "
                                   "disagree")
    for key in sorted(got):
        for field in ("crc32", "shape", "dtype", "finite"):
            if got[key].get(field) != want[key].get(field):
                raise CheckpointCorruption(
                    step, f"leaf {key} {field} mismatch: payload has "
                    f"{got[key].get(field)!r}, manifest recorded "
                    f"{want[key].get(field)!r}")
        if not got[key].get("finite", True):
            raise CheckpointCorruption(step, f"leaf {key} contains "
                                       "non-finite values")
    for a, b in zip(flat, arrays):
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            raise CheckpointCorruption(
                step, f"leaf geometry {b.shape}/{b.dtype} does not "
                f"match the engine's {a.shape}/{a.dtype}")
    return new


def quarantine_weights(directory: str, step: int,
                       reason: str = "") -> Optional[str]:
    """Move a bad publication under ``<dir>/quarantine/`` — rename,
    never delete (mirrors ``Checkpointer.quarantine``): the corrupt
    artifact is evidence, and the rename atomically takes it off the
    watch path so the engine never retries it."""
    qdir = os.path.join(directory, "quarantine")
    moved = None
    for src in (_weights_path(directory, step),
                _manifest_path(directory, step)):
        if not os.path.exists(src):
            continue
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, os.path.basename(src))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"{os.path.basename(src)}.{n}")
        os.replace(src, dst)
        moved = dst
    if moved is not None and reason:
        with open(os.path.join(qdir,
                               f"weights-{step:08d}.reason.json"),
                  "w") as f:
            json.dump({"step": step, "reason": reason,
                       "quarantined_at": time.time()}, f)
    return moved


class WeightWatcher:
    """Directory poller with the shared flaky-IO tolerance seam.

    ``poll()`` returns a NEW committed step at most once (consumed
    steps are remembered); transient ``OSError`` s are tolerated up to
    the consecutive budget, after which ``healthy`` flips false and a
    latched failure explains why — same healthy/reset semantics as
    :class:`..utils.failures.FailureMonitor`."""

    def __init__(self, directory: str, io_error_tolerance: int = 3):
        self.directory = os.fspath(directory)
        self._io = FlakyIOPolicy(io_error_tolerance,
                                 what="weight-dir scan")
        self.failure: Optional[Exception] = None
        self.seen: set[int] = set()

    @property
    def healthy(self) -> bool:
        return self.failure is None

    def reset(self) -> None:
        self.failure = None
        self._io.reset()

    def poll(self) -> Optional[int]:
        if self.failure is not None:
            return None
        try:
            step = latest_published(self.directory)
            self._io.note_success()
        except OSError as e:
            self.failure = self._io.note_error(e)
            return None
        if step is None or step in self.seen:
            return None
        return step

    def mark(self, step: int) -> None:
        self.seen.add(step)


class CanaryRollback(RuntimeError):
    """A canary failed its verdict.  Carries the ledger snapshot taken
    at canary start; the supervisor truncates committed streams to it
    and replays, erasing every candidate-weight token."""

    def __init__(self, message: str, snapshot: dict):
        super().__init__(message)
        self.ledger_snapshot = snapshot


class ReloadManager:
    """Between-tick orchestration: watch → verify → canary → verdict.

    Wired into :class:`..serve.supervisor.ServeSupervisor` (which calls
    ``on_tick(report, ledger)`` after each tick commits).  With
    ``canary_slots=0`` verified weights swap in directly; otherwise a
    canary runs for at least ``canary_ticks`` decode ticks and
    ``min_compare`` comparison samples, then promotes or rolls back on
    the windowed acceptance/drift signals."""

    def __init__(self, directory: str, *, canary_slots: int = 2,
                 canary_ticks: int = 8, min_compare: int = 4,
                 min_acceptance: float = 0.7,
                 max_drift_p99: float = 2.0,
                 io_error_tolerance: int = 3,
                 window_s: float = 60.0, recorder=None,
                 clock=time.monotonic):
        if canary_slots < 0:
            raise ValueError(f"canary_slots must be >= 0, got "
                             f"{canary_slots}")
        if canary_ticks < 1 or min_compare < 1:
            raise ValueError("canary_ticks and min_compare must be >= 1")
        if not 0.0 <= min_acceptance <= 1.0:
            raise ValueError(f"min_acceptance must be in [0, 1], got "
                             f"{min_acceptance}")
        self.directory = os.fspath(directory)
        self.canary_slots = int(canary_slots)
        self.canary_ticks = int(canary_ticks)
        self.min_compare = int(min_compare)
        self.min_acceptance = float(min_acceptance)
        self.max_drift_p99 = float(max_drift_p99)
        self.recorder = recorder
        self._clock = clock
        self.watcher = WeightWatcher(self.directory, io_error_tolerance)
        # windowed comparison signals (obs/window): agreement is a 0/1
        # indicator stream, drift is |Δ logprob| of the chosen token
        self.h_accept = WindowedHistogram(window_s, lo=1e-3, hi=2.0,
                                          clock=clock)
        self.h_drift = WindowedHistogram(window_s, lo=1e-6, hi=1e3,
                                         clock=clock)
        self._candidate = None          # (step, params) under canary
        self._snapshot: Optional[dict] = None
        self._ticks_active = 0
        self._agree = 0
        self._compared = 0
        self._nonfinite = 0
        self._drift_sum = 0.0
        self.swaps = 0
        self.rollbacks = 0
        self.rejected = 0
        self.events: list[dict] = []

    # --- canary feed (engine observe hook) --------------------------------
    def _observe(self, *, agree: bool, drift: float, finite: bool,
                 now: float) -> None:
        self._compared += 1
        self._agree += int(agree)
        self._nonfinite += int(not finite)
        d = float(drift) if np.isfinite(drift) else self.h_drift._hi
        self._drift_sum += d
        t = self._clock()
        self.h_accept.observe(1.0 if agree else 1e-3, t)
        self.h_drift.observe(max(d, 1e-6), t)

    def _reset_canary_counters(self) -> None:
        self._ticks_active = 0
        self._agree = self._compared = self._nonfinite = 0
        self._drift_sum = 0.0

    def _note(self, action: str, step: int, **fields) -> None:
        ev = {"action": action, "step": step, **fields}
        self.events.append(ev)
        if self.recorder is not None:
            self.recorder.record("reload_" + action, step=step, **fields)

    # --- supervisor hook --------------------------------------------------
    def on_tick(self, report, ledger) -> None:
        eng = report.engine
        if self._candidate is None:
            self._maybe_start(eng, ledger, report)
            return
        step, params = self._candidate
        if getattr(eng, "_canary", None) is None:
            # the engine warm-restarted mid-canary (containment wiped
            # its canary state): re-arm against the fresh engine with a
            # fresh rollback anchor
            self._reset_canary_counters()
            self._snapshot = ledger.snapshot()
            eng.begin_canary(params, self._pick_slots(eng),
                             observe=self._observe)
            self._note("canary_rearm", step)
            return
        if report.kind != "decode":
            return
        self._ticks_active += 1
        if (self._ticks_active < self.canary_ticks
                or self._compared < self.min_compare):
            return
        self._verdict(eng, step)

    def _pick_slots(self, eng) -> tuple:
        n = min(self.canary_slots, eng.max_slots - 1)
        return tuple(range(n))

    def _maybe_start(self, eng, ledger, report) -> None:
        step = self.watcher.poll()
        if step is None:
            return
        self.watcher.mark(step)
        try:
            params = load_verified(self.directory, step, like=eng.params)
        except CheckpointCorruption as e:
            quarantine_weights(self.directory, step, str(e))
            self.rejected += 1
            self._note("reject", step, detail=str(e))
            return
        if self.canary_slots == 0 or not hasattr(eng, "begin_canary"):
            eng.swap_params(params)
            self.swaps += 1
            self._note("promote", step, canary=False)
            return
        self._reset_canary_counters()
        self._snapshot = ledger.snapshot()
        eng.begin_canary(params, self._pick_slots(eng),
                         observe=self._observe)
        self._candidate = (step, params)
        self._note("canary_begin", step,
                   slots=list(self._pick_slots(eng)),
                   anchor_tokens=sum(self._snapshot.values()))

    def _verdict(self, eng, step: int) -> None:
        acceptance = self._agree / self._compared
        drift_p99 = self.h_drift.percentile(99, self._clock())
        mean_drift = self._drift_sum / self._compared
        healthy = (self._nonfinite == 0
                   and acceptance >= self.min_acceptance
                   and drift_p99 <= self.max_drift_p99)
        summary = eng.end_canary(promote=healthy)
        verdict = dict(acceptance=acceptance, drift_p99=drift_p99,
                       mean_drift=mean_drift,
                       nonfinite=self._nonfinite,
                       compared=self._compared,
                       ticks=self._ticks_active,
                       engine_summary=summary)
        snapshot, self._snapshot = self._snapshot, None
        self._candidate = None
        if healthy:
            self.swaps += 1
            self._note("promote", step, canary=True, **verdict)
            return
        self.rollbacks += 1
        quarantine_weights(
            self.directory, step,
            f"canary rollback: acceptance {acceptance:.3f} (min "
            f"{self.min_acceptance}), drift p99 {drift_p99:.3g} (max "
            f"{self.max_drift_p99}), nonfinite {self._nonfinite}")
        dump = None
        if self.recorder is not None:
            dump = self.recorder.trip("canary_rollback")
        self._note("rollback", step, dump=dump, **verdict)
        raise CanaryRollback(
            f"canary step {step} rolled back (acceptance "
            f"{acceptance:.3f}, drift p99 {drift_p99:.3g}, nonfinite "
            f"{self._nonfinite}); replaying from the pre-canary anchor",
            snapshot or {})

    def stats(self) -> dict:
        now = self._clock()
        return {
            "watch_dir": self.directory,
            "watcher_healthy": self.watcher.healthy,
            "watcher_failure": (str(self.watcher.failure)
                                if self.watcher.failure else None),
            "steps_seen": sorted(self.watcher.seen),
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "rejected": self.rejected,
            "canary_active": self._candidate is not None,
            "events": self.events,
            "signals": {
                "accept_window_count": self.h_accept.count(now),
                "accept_window_rate_per_s": self.h_accept.rate(now),
                "drift_p50": self.h_drift.percentile(50, now),
                "drift_p99": self.h_drift.percentile(99, now),
            },
        }
