"""ZeRO-1 / FSDP sharding rules: numerics match pure DP, state is sharded."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.parallel.zero import (
    fsdp_state_spec, leaf_shard_spec, zero1_state_spec,
)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import create_train_state
from distributed_deep_learning_tpu.train.step import make_step_fns, place_state
from jax.sharding import PartitionSpec as P


def _setup(mesh, state_spec_fn=None):
    model = MLP(hidden_size=64, num_hidden_layers=2, num_classes=8)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (16, 32), np.float32))
    y = jax.nn.one_hot(jnp.arange(16) % 8, 8)
    state = create_train_state(model, jax.random.key(0), x[:1],
                               optax.adam(1e-2))
    spec = (state_spec_fn(state, mesh) if state_spec_fn else P())
    state = place_state(state, mesh, spec)
    train_step, _ = make_step_fns(mesh, cross_entropy_loss, state_spec=spec)
    return state, train_step, x, y


class TestLeafSpec:
    def test_shards_largest_divisible_dim(self):
        leaf = jnp.zeros((3, 256))
        assert leaf_shard_spec(leaf, 4, min_leaf_size=1) == P(None, "fsdp")

    def test_small_or_indivisible_replicated(self):
        assert leaf_shard_spec(jnp.zeros((4, 4)), 4) == P()  # too small
        assert leaf_shard_spec(jnp.zeros((3, 5)), 4, min_leaf_size=1) == P()
        assert leaf_shard_spec(jnp.zeros(()), 4, min_leaf_size=0) == P()


class TestZero1:
    def test_opt_state_is_sharded_params_replicated(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        state, step, x, y = _setup(
            mesh, lambda s, m: zero1_state_spec(s, m, min_leaf_size=16))
        state, _ = step(state, x, y)
        # adam mu for a (64,64) kernel must live sharded over fsdp
        mu = state.opt_state[0].mu["DenseReLU_1"]["Dense_0"]["kernel"]
        assert "fsdp" in jax.tree.leaves(
            [mu.sharding.spec], is_leaf=lambda s: isinstance(s, P))[0]
        kernel = state.params["DenseReLU_1"]["Dense_0"]["kernel"]
        assert kernel.sharding.spec == P()

    def test_numerics_match_pure_dp(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        s_dp, step_dp, x, y = _setup(mesh)
        s_z1, step_z1, _, _ = _setup(
            mesh, lambda s, m: zero1_state_spec(s, m, min_leaf_size=16))
        for _ in range(3):
            s_dp, m_dp = step_dp(s_dp, x, y)
            s_z1, m_z1 = step_z1(s_z1, x, y)
        np.testing.assert_allclose(float(m_dp["loss"]), float(m_z1["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(s_dp.params),
                        jax.tree.leaves(s_z1.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


class TestFsdp:
    def test_params_sharded_and_numerics(self):
        mesh = build_mesh({"data": 2, "fsdp": 4})
        s_dp, step_dp, x, y = _setup(mesh)
        s_fs, step_fs, _, _ = _setup(
            mesh, lambda s, m: fsdp_state_spec(s, m, min_leaf_size=16))
        kernel = s_fs.params["DenseReLU_1"]["Dense_0"]["kernel"]
        assert kernel.sharding.spec != P()
        for _ in range(2):
            s_dp, m_dp = step_dp(s_dp, x, y)
            s_fs, m_fs = step_fs(s_fs, x, y)
        np.testing.assert_allclose(float(m_dp["loss"]), float(m_fs["loss"]),
                                   rtol=1e-5)
