"""Stage partitioners: pure, unit-testable layer→stage assignment functions.

The reference buries three partitioning algorithms inside model constructors
(SURVEY.md C12a-c); here they are standalone functions returning an
assignment array ``stage_of_layer[i] ∈ [0, n_stages)``.  All three reference
contracts are preserved:

* :func:`balanced_partition` — contiguous split with remainder spread
  (reference ``MLP/model.py:62-76``).
* :func:`block_partition` — fixed-size blocks per stage, generalising the
  hard-coded ``{i: i//4}`` (reference ``CNN/model.py:196-201``, noted there
  as "currently always 8,1 or 8,2").
* :func:`lstm_aware_partition` — structure-aware: stem pinned to stage 0,
  head to the next stage after the last hidden layer's, hidden LSTM layers
  spread, mid-model pooling placed midway (reference ``LSTM/model.py:98-124``).
"""

from __future__ import annotations

import numpy as np


def validate_assignment(assignment: np.ndarray, n_stages: int) -> np.ndarray:
    """Check an assignment is usable for staged execution: values in range,
    non-decreasing (stages are contiguous layer runs), starting at stage 0."""
    a = np.asarray(assignment, dtype=np.int64)
    if a.ndim != 1 or len(a) == 0:
        raise ValueError("assignment must be a non-empty 1-D array")
    if a[0] != 0:
        raise ValueError("first layer must be on stage 0")
    if (np.diff(a) < 0).any():
        raise ValueError("stage assignment must be non-decreasing")
    if a.max() >= n_stages or a.min() < 0:
        raise ValueError(f"stage ids must lie in [0,{n_stages})")
    return a


def stage_slices(assignment: np.ndarray, n_stages: int) -> list[tuple[int, int]]:
    """Per-stage contiguous [start, end) layer ranges (empty stages allowed)."""
    a = validate_assignment(assignment, n_stages)
    slices = []
    for s in range(n_stages):
        idx = np.flatnonzero(a == s)
        slices.append((int(idx[0]), int(idx[-1]) + 1) if len(idx) else
                      (len(a), len(a)))
    return slices


def balanced_partition(n_layers: int, n_stages: int) -> np.ndarray:
    """Contiguous balanced split; stage sizes differ by at most 1.

    Same contract as the reference MLP partitioner (``MLP/model.py:62-76``):
    every stage gets ``n_layers // n_stages`` layers and the remainder is
    spread one-per-stage.
    """
    if n_stages < 1:
        raise ValueError("n_stages must be >= 1")
    if n_layers < n_stages:
        raise ValueError(f"cannot split {n_layers} layers into {n_stages} stages")
    sizes = np.full(n_stages, n_layers // n_stages, dtype=np.int64)
    sizes[:n_layers % n_stages] += 1
    return np.repeat(np.arange(n_stages), sizes)


def block_partition(n_layers: int, n_stages: int, block_size: int = 4) -> np.ndarray:
    """``stage = min(layer // block_size, n_stages-1)`` — the generalised form
    of the reference CNN's hard-coded ``{i: i//4}`` (``CNN/model.py:200``),
    clamped so it works for any stage count, with the reference's exact
    behaviour at its "8 layers, 1-2 devices" operating point."""
    if n_stages < 1 or block_size < 1:
        raise ValueError("n_stages and block_size must be >= 1")
    a = np.minimum(np.arange(n_layers) // block_size, n_stages - 1)
    return a.astype(np.int64)


def lstm_aware_partition(n_layers: int, n_stages: int) -> np.ndarray:
    """Structure-aware split for the CNN-LSTM layer sequence
    ``[stem, pool, lstm_1..lstm_H, head]`` (reference ``LSTM/model.py:98-124``).

    Contract (matching the reference's intent, not its arithmetic):

    * one layer per stage when ``n_layers == n_stages``;
    * the stem starts on stage 0 and the head lands on the stage after the
      last hidden layer's (clamped to ``n_stages-1``);
    * the hidden LSTM layers are spread contiguously and balanced;
    * the pooling layer (index 1) sits midway between the stem's stage and
      the first LSTM's stage.
    """
    if n_layers < 3:
        raise ValueError("lstm layer sequence needs >= 3 layers (stem/pool/head)")
    if n_stages < 1:
        raise ValueError("n_stages must be >= 1")
    if n_layers == n_stages:
        return np.arange(n_layers, dtype=np.int64)
    n_hidden = n_layers - 3
    a = np.zeros(n_layers, dtype=np.int64)
    if n_hidden > 0:
        # spread hidden layers over stages, balanced, non-decreasing
        hidden_stages = (np.arange(n_hidden) * n_stages) // n_hidden
        hidden_stages = np.minimum(hidden_stages, n_stages - 1)
        a[2:2 + n_hidden] = hidden_stages
    a[-1] = min(n_stages - 1, a[-2] + 1)
    first_lstm_stage = a[2] if n_hidden > 0 else a[-1]
    a[1] = first_lstm_stage // 2  # pooling midway (reference LSTM/model.py:123)
    return validate_assignment(a, n_stages)
