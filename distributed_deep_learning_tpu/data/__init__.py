from distributed_deep_learning_tpu.data.datasets import ArrayDataset  # noqa: F401
from distributed_deep_learning_tpu.data.splits import Splits, train_val_test_split  # noqa: F401
from distributed_deep_learning_tpu.data.loader import DeviceLoader  # noqa: F401
from distributed_deep_learning_tpu.data.packed import (  # noqa: F401
    PackedDataset, pack_dataset)
