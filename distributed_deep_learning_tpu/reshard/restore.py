"""The resharding restore: verified checkpoint -> any target placement.

Decision tree per candidate step (newest first, same fallback-and-
quarantine chain as ``Checkpointer.restore_verified``):

* no topology manifest -> **legacy**: warn, restore as same-topology,
  never quarantine (pre-reshard run directories stay resumable);
* saved topology == target topology -> plain verified restore;
* different topology -> **chunked** restore (orbax reads only the slices
  each target shard needs, straight from disk) with a **host-gather**
  fallback (restore fully replicated on the new mesh, then redistribute
  each leaf onto its target sharding) when the backend cannot do sliced
  reads.

A *geometry* mismatch (the checkpoint's leaf shapes/dtypes don't match
the target state — wrong model, not wrong mesh) raises
:class:`ReshardGeometryError` immediately instead of quarantining: the
checkpoint is fine, the request is wrong.
"""

from __future__ import annotations

import sys
import time

from distributed_deep_learning_tpu.reshard import manifest as _manifest
from distributed_deep_learning_tpu.reshard.redistribute import (
    redistribute, tree_shardings)
from distributed_deep_learning_tpu.utils.checkpoint import (
    CheckpointCorruption, _as_pytree, _with_fields)


class ReshardGeometryError(RuntimeError):
    """The checkpoint's leaf geometry doesn't match the restore target —
    a model mismatch, not a topology mismatch; nothing is quarantined."""


def _check_geometry(ckpt, step: int, target_tree) -> None:
    """Compare the integrity manifest's per-leaf shape/dtype against the
    target's.  Only leaves the manifest recorded fully (single-host CRC
    records) are checked; a legacy manifest checks nothing."""
    import jax

    record = ckpt.read_manifest(step) or {}
    saved = record.get("leaves") or {}
    if not saved:
        return
    flat, _ = jax.tree_util.tree_flatten_with_path(target_tree)
    actual = {jax.tree_util.keystr(p): leaf for p, leaf in flat}
    bad = []
    for key, rec in saved.items():
        if rec.get("crc32") is None or "shape" not in rec:
            continue
        leaf = actual.get(key)
        if leaf is None:
            bad.append(f"{key} missing from target")
            continue
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if tuple(rec["shape"]) != shape:
            bad.append(f"{key}: saved {tuple(rec['shape'])} vs "
                       f"target {shape}")
    if bad:
        raise ReshardGeometryError(
            f"checkpoint step {step} cannot reshard onto this state — "
            f"leaf geometry differs ({'; '.join(bad[:4])}"
            f"{'; ...' if len(bad) > 4 else ''})")


def restore_resharded(ckpt, target, *, mesh, state_spec, step=None,
                      method: str = "auto", logger=None):
    """Restore the newest usable checkpoint at/below ``step`` into
    ``target`` placed per ``state_spec`` on ``mesh``.

    Returns ``(state, step, info)`` — or ``(None, None, info)`` when no
    checkpoint survives (caller starts fresh).  ``info['mode']`` is one of
    ``legacy | same | chunked | gather``; cross-topology restores also
    carry source/target descriptions and timing.  ``method`` forces a
    redistribution path (``chunked``/``gather``); ``auto`` tries chunked
    and falls back.
    """
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def log(msg: str) -> None:
        if logger is not None:
            logger.info(msg)
        else:
            print(msg, file=sys.stderr, flush=True)

    ckpt.wait_until_finished()
    target_tree = _as_pytree(target)
    shardings = tree_shardings(mesh, state_spec, target_tree)
    current = _manifest.of_placement(mesh, shardings)
    info: dict = {"mode": None}

    candidates = sorted(ckpt.all_steps(), reverse=True)
    if step is not None:
        candidates = [s for s in candidates if s <= step]
    for s in candidates:
        topo = ckpt.read_topology(s)
        if topo is not None:
            # fail fast on a model mismatch — NOT a quarantine offence
            _check_geometry(ckpt, s, target_tree)
        try:
            if topo is None:
                log(f"reshard: checkpoint step {s} has no topology "
                    "manifest (pre-reshard save); restoring as "
                    "same-topology (legacy)")
                return ckpt.restore(target, step=s, verify=True), s, \
                    {"mode": "legacy"}
            if _manifest.same_topology(topo, current):
                return ckpt.restore(target, step=s, verify=True), s, \
                    {"mode": "same"}

            info = {"mode": None, "source": topo.describe(),
                    "target": current.describe()}
            start = time.perf_counter()
            restored = None
            if method in ("auto", "chunked"):
                try:
                    restored = ckpt.restore(target, step=s, verify=True,
                                            shardings=shardings)
                    info["mode"] = "chunked"
                except CheckpointCorruption:
                    raise  # real corruption: quarantine-and-fall-back
                except Exception as exc:
                    if method == "chunked":
                        raise
                    log("reshard: sliced on-disk restore unavailable "
                        f"({type(exc).__name__}: {exc}); "
                        "host-gather fallback")
            if restored is None:
                # gather path: pull the step fully replicated onto the
                # new mesh, then redistribute leaf by leaf
                replicated = jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), shardings,
                    is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
                gathered = ckpt.restore(target, step=s, verify=True,
                                        shardings=replicated)
                moved, stats = redistribute(_as_pytree(gathered), shardings,
                                            method="gather")
                restored = _with_fields(target, moved)
                info["mode"] = "gather"
                info["redistribute"] = stats.to_dict()
            info["seconds"] = round(time.perf_counter() - start, 4)
            log(f"reshard: restored step {s} across topologies "
                f"[{info['source']} -> {info['target']}] via "
                f"{info['mode']} in {info['seconds']}s")
            return restored, s, info
        except ReshardGeometryError:
            raise
        except Exception as exc:
            print(f"reshard: step {s} unusable "
                  f"({type(exc).__name__}: {exc}); quarantining and "
                  "falling back", file=sys.stderr, flush=True)
            ckpt.quarantine(s, reason=f"{type(exc).__name__}: {exc}")
    return None, None, {"mode": None}


def make_restore_fn(ckpt, mesh, state_spec, *, method: str = "auto",
                    logger=None):
    """A drop-in replacement for ``Checkpointer.restore_verified`` bound
    to a target placement — the hook ``fit_with_recovery`` calls on every
    (re)start, so elastic restarts reshard transparently."""

    def restore_fn(target, step=None):
        state, used, info = restore_resharded(
            ckpt, target, mesh=mesh, state_spec=state_spec, step=step,
            method=method, logger=logger)
        restore_fn.last_info = info
        return state, used

    restore_fn.last_info = {}
    return restore_fn


__all__ = ["ReshardGeometryError", "restore_resharded", "make_restore_fn"]
