"""LR schedules: shapes of the standard recipes."""

import numpy as np
import pytest

from distributed_deep_learning_tpu.train.schedules import (step_decay,
                                                           warmup_cosine,
                                                           warmup_rsqrt)


def test_warmup_cosine_shape():
    sched = warmup_cosine(1.0, warmup_steps=10, total_steps=100)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    assert float(sched(55)) < 1.0
    np.testing.assert_allclose(float(sched(100)), 0.0, atol=1e-6)
    # monotone decay after the peak
    vals = [float(sched(s)) for s in range(10, 101, 10)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))


def test_warmup_cosine_validates():
    with pytest.raises(ValueError):
        warmup_cosine(1.0, warmup_steps=100, total_steps=50)


def test_warmup_rsqrt_noam():
    d = 512
    sched = warmup_rsqrt(d, warmup_steps=4000)
    # rises during warmup, peaks at warmup, then decays as step^-0.5
    assert float(sched(100)) < float(sched(4000))
    np.testing.assert_allclose(float(sched(4000)),
                               d ** -0.5 * 4000 ** -0.5, rtol=1e-5)
    np.testing.assert_allclose(float(sched(16000)),
                               d ** -0.5 * 16000 ** -0.5, rtol=1e-5)


def test_step_decay_matches_reference_steplr():
    sched = step_decay(0.01, steps_per_drop=7, factor=0.1)
    np.testing.assert_allclose(float(sched(0)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(6)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(7)), 0.001, rtol=1e-6)
    np.testing.assert_allclose(float(sched(14)), 0.0001, rtol=1e-6)


def test_cli_schedule_wiring(monkeypatch):
    """--schedule cosine trains a north star end-to-end and the optimizer
    really follows a schedule (loss still improves)."""
    import os

    import numpy as np

    from distributed_deep_learning_tpu.utils.config import Config, Mode, parse_args
    from distributed_deep_learning_tpu.workloads.base import resolve_lr, run_workload
    from distributed_deep_learning_tpu.workloads.northstar import RESNET_SPEC

    c = parse_args(["--schedule", "cosine", "--warmup", "3"], workload="resnet")
    assert c.lr_schedule == "cosine" and c.warmup_steps == 3
    sched = resolve_lr(c.replace(epochs=2), 10, 0.1)
    assert callable(sched)
    assert float(sched(0)) < float(sched(3))      # warms up
    assert float(sched(19)) < float(sched(3))     # decays

    monkeypatch.setenv("DDL_DATA_LIMIT", "64")
    config = Config(mode=Mode.DATA, size=18, epochs=1, batch_size=16,
                    lr_schedule="cosine", warmup_steps=2)
    _, history = run_workload(RESNET_SPEC, config)
    assert "train" in [h.phase for h in history]
    assert np.isfinite(history[0].loss)


def test_resolve_lr_variants():
    from distributed_deep_learning_tpu.utils.config import Config
    from distributed_deep_learning_tpu.workloads.base import resolve_lr

    assert resolve_lr(Config(), 10, 0.1) == 0.1  # none → scalar
    rs = resolve_lr(Config(lr_schedule="rsqrt", size=64, epochs=2), 100, 1e-3)
    assert float(rs(10)) > 0
    st = resolve_lr(Config(lr_schedule="step", epochs=20), 10, 0.1)
    assert abs(float(st(0)) - 0.1) < 1e-6
    assert float(st(71)) < 0.011  # dropped after 7 "epochs"
