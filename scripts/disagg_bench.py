"""Disaggregated-serving bench CLI: prefill/decode pools vs unified.

Thin driver over ``serve/bench.py``'s ``disagg_serving_bench`` — the
load shape (``DEFAULT_LOAD``) and the A/B harness live there; this
script parses flags, guarantees a multi-device host (disaggregation
needs one device per pool — on a single-device CPU box it forces the
emulated topology via ``XLA_FLAGS`` BEFORE jax imports) and prints ONE
JSON line to stdout.

    python scripts/disagg_bench.py                       # 1P + 1D
    python scripts/disagg_bench.py --prefill-workers 2 \
        --decode-workers 2 --devices 4                   # wider pools
    python scripts/disagg_bench.py --kv-dtype int8       # int8 pools

``bench.py`` shells out to this script for its ``serving_disagg``
section when the worker process only sees one device (the usual
CPU-fallback worker), the same way ``comm_bench.py`` backs the
``collectives`` section.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="disaggregated prefill/decode serving vs the "
                    "unified paged engine")
    p.add_argument("--requests", type=int, default=None,
                   help="trace size (default: DEFAULT_LOAD's 24)")
    p.add_argument("--prefill-workers", type=int, default=1)
    p.add_argument("--decode-workers", type=int, default=1)
    p.add_argument("--prefill-streams", type=int, default=4,
                   help="prompts batched per prefill-worker chunk call")
    p.add_argument("--max-slots", type=int, default=8,
                   help="decode slots per decode worker")
    p.add_argument("--decode-passes", type=int, default=2,
                   help="decode ticks per scheduler iteration")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--prefill-chunk", type=int, default=32)
    p.add_argument("--kv-dtype", type=str, default=None,
                   help="block-pool dtype (bf16/int8; unset = fp32)")
    p.add_argument("--devices", type=int, default=None,
                   help="force this many emulated CPU devices (default: "
                        "just enough for the worker pools)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    need = args.devices or (args.prefill_workers + args.decode_workers)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{max(need, 2)}").strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    from distributed_deep_learning_tpu.serve.bench import (
        disagg_serving_bench)

    rec = disagg_serving_bench(
        seed=args.seed,
        load_kw=(dict(n_requests=args.requests)
                 if args.requests is not None else None),
        prefill_workers=args.prefill_workers,
        decode_workers=args.decode_workers,
        prefill_streams=args.prefill_streams,
        max_slots=args.max_slots,
        kv_block_size=args.kv_block_size,
        prefill_chunk=args.prefill_chunk,
        kv_dtype=args.kv_dtype,
        decode_passes=args.decode_passes)
    print(json.dumps(rec))
    u, d = rec["unified"], rec["disagg"]
    print(f"disagg {d['tokens_per_sec']:.0f} tok/s vs unified "
          f"{u['tokens_per_sec']:.0f} tok/s = {rec['speedup']}x | "
          f"itl p99 {d['itl_p99_s'] * 1e3:.2f}ms vs "
          f"{u['itl_p99_s'] * 1e3:.2f}ms | migration "
          f"{rec['migration_gbps']} GB/s | agreement "
          f"{rec['token_agreement']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
