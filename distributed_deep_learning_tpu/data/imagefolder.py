"""Generic directory-per-class image dataset (ImageFolder semantics).

The reference's image pipeline is PCB-specific (VOC XML + bbox crops,
:mod:`.pcb`); this is the general-purpose sibling for ImageNet-style
layouts ``root/<class>/<image>``, matching torchvision ``ImageFolder``
class-discovery semantics (sorted class names → indices).  Decode uses
PIL, resize uses the native C++ bilinear kernel
(:func:`..native.crop_resize_bilinear`), batches decode in parallel
threads (PIL decode releases the GIL), and everything downstream is the
standard ``ArrayDataset`` contract (``__len__``/``batch``) feeding the
sharded :class:`..loader.DeviceLoader`.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


def find_classes(root: str) -> tuple[list[str], dict[str, int]]:
    """Sorted class subdirectories → contiguous indices (torchvision
    ``ImageFolder`` semantics)."""
    classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
    if not classes:
        raise FileNotFoundError(f"no class directories under {root}")
    return classes, {c: i for i, c in enumerate(classes)}


class ImageFolderDataset:
    """``root/<class>/*.jpg`` → (image, one-hot) batches."""

    def __init__(self, root: str, image_size: int = 224, *,
                 num_workers: int = 8, max_cached_images: int = 1024):
        self.root = os.fspath(root)
        self.image_size = image_size
        self.classes, self.class_to_idx = find_classes(self.root)
        self.samples: list[tuple[str, int]] = []
        for cls in self.classes:
            cdir = os.path.join(self.root, cls)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for name in sorted(files):
                    if name.lower().endswith(IMAGE_EXTENSIONS):
                        self.samples.append((os.path.join(dirpath, name),
                                             self.class_to_idx[cls]))
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")
        self._pool = ThreadPoolExecutor(max(1, num_workers)) \
            if num_workers > 1 else None
        self._cache: OrderedDict[str, np.ndarray] = OrderedDict()
        self._cache_lock = threading.Lock()  # decode threads share the LRU
        self._max_cached = max_cached_images

    def __len__(self) -> int:
        return len(self.samples)

    def _decode(self, path: str) -> np.ndarray:
        with self._cache_lock:
            img = self._cache.get(path)
            if img is not None:
                self._cache.move_to_end(path)
                return img
        from PIL import Image

        from distributed_deep_learning_tpu import native

        # decode outside the lock (PIL releases the GIL; a rare duplicate
        # decode of the same path is cheaper than serialising the pool)
        with Image.open(path) as im:
            raw = np.asarray(im.convert("RGB"), dtype=np.float32)
        h, w = raw.shape[:2]
        img = native.crop_resize_bilinear(np.ascontiguousarray(raw), 0, 0,
                                          h, w, self.image_size,
                                          self.image_size)
        with self._cache_lock:
            self._cache[path] = img
            while len(self._cache) > self._max_cached:
                self._cache.popitem(last=False)
        return img

    def item(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        path, target = self.samples[index]
        y = np.zeros(len(self.classes), dtype=np.float32)
        y[target] = 1.0
        return self._decode(path), y

    def batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        idx = [int(i) for i in np.asarray(indices)]
        if self._pool is not None:
            items = list(self._pool.map(self.item, idx))
        else:
            items = [self.item(i) for i in idx]
        return (np.stack([x for x, _ in items]),
                np.stack([y for _, y in items]))
