import numpy as np
import pytest

from distributed_deep_learning_tpu.parallel.partition import (
    balanced_partition, block_partition, lstm_aware_partition, stage_slices,
    validate_assignment,
)


def _sizes(a, n_stages):
    return np.bincount(a, minlength=n_stages)


class TestBalanced:
    def test_even_split(self):
        a = balanced_partition(8, 4)
        assert _sizes(a, 4).tolist() == [2, 2, 2, 2]

    def test_remainder_spread(self):
        for n_layers in range(1, 30):
            for n_stages in range(1, n_layers + 1):
                a = balanced_partition(n_layers, n_stages)
                sizes = _sizes(a, n_stages)
                assert sizes.max() - sizes.min() <= 1
                assert sizes.sum() == n_layers
                validate_assignment(a, n_stages)  # contiguous, starts at 0

    def test_too_few_layers(self):
        with pytest.raises(ValueError):
            balanced_partition(2, 3)


class TestBlock:
    def test_reference_operating_point(self):
        # reference CNN: {i: i//4} for 8 layers on 2 devices (CNN/model.py:200)
        a = block_partition(8, 2, block_size=4)
        assert a.tolist() == [0, 0, 0, 0, 1, 1, 1, 1]

    def test_single_stage(self):
        assert block_partition(9, 1).tolist() == [0] * 9

    def test_clamped(self):
        a = block_partition(12, 2, block_size=4)
        assert a.max() == 1 and validate_assignment(a, 2) is not None


class TestLSTMAware:
    def test_identity_when_equal(self):
        a = lstm_aware_partition(5, 5)
        assert a.tolist() == [0, 1, 2, 3, 4]

    def test_structure_contract(self):
        # [stem, pool, lstm*4, head] over 3 stages
        a = lstm_aware_partition(7, 3)
        validate_assignment(a, 3)
        assert a[0] == 0                     # stem pinned to stage 0
        assert a[-1] >= a[-2]                # head after last hidden
        hidden = a[2:-1]
        sizes = np.bincount(hidden, minlength=3)
        assert sizes.max() - sizes.min() <= 2  # hidden spread
        assert a[1] <= hidden[0]             # pooling not after first lstm

    def test_many_shapes_valid(self):
        for n_layers in range(3, 12):
            for n_stages in range(1, n_layers + 1):
                a = lstm_aware_partition(n_layers, n_stages)
                validate_assignment(a, n_stages)


def test_stage_slices():
    a = np.array([0, 0, 1, 2, 2])
    assert stage_slices(a, 3) == [(0, 2), (2, 3), (3, 5)]
    # empty stage allowed
    assert stage_slices(np.array([0, 0]), 2)[1] == (2, 2)


def test_validate_rejects():
    with pytest.raises(ValueError):
        validate_assignment(np.array([1, 1]), 2)      # must start at 0
    with pytest.raises(ValueError):
        validate_assignment(np.array([0, 2, 1]), 3)   # decreasing
    with pytest.raises(ValueError):
        validate_assignment(np.array([0, 3]), 3)      # out of range
