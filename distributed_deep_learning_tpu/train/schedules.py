"""Learning-rate schedules for the workload families.

The reference's only schedule is StepLR(7 epochs, ×0.1) on the CNN
(``CNN/main.py:161``, reproduced in
:func:`..state.reference_optimizer`).  The north-star families need the
standard TPU-era recipes, provided here as optax schedules:

* :func:`warmup_cosine` — linear warmup → cosine decay (ResNet/BERT).
* :func:`warmup_rsqrt` — the transformer-base "Noam" schedule
  (Vaswani et al.): lr ∝ d_model^-0.5 · min(step^-0.5, step·warmup^-1.5).
* :func:`step_decay` — the reference's StepLR, generalised.
"""

from __future__ import annotations

import optax


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  end_factor: float = 0.0) -> optax.Schedule:
    """Linear 0→peak over `warmup_steps`, cosine peak→end over the rest."""
    if total_steps <= warmup_steps:
        raise ValueError(f"total_steps {total_steps} must exceed "
                         f"warmup_steps {warmup_steps}")
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr, warmup_steps=warmup_steps,
        decay_steps=total_steps, end_value=peak_lr * end_factor)


def warmup_rsqrt(d_model: int, warmup_steps: int = 4000,
                 scale: float = 1.0) -> optax.Schedule:
    """Transformer-base (Noam) schedule."""

    def schedule(step):
        import jax.numpy as jnp

        step = jnp.maximum(step, 1).astype(jnp.float32)
        return scale * d_model ** -0.5 * jnp.minimum(
            step ** -0.5, step * warmup_steps ** -1.5)

    return schedule


def step_decay(base_lr: float, steps_per_drop: int,
               factor: float = 0.1) -> optax.Schedule:
    """The reference's StepLR as an optax schedule (drop every
    `steps_per_drop` optimizer steps)."""
    return optax.exponential_decay(base_lr, transition_steps=steps_per_drop,
                                   decay_rate=factor, staircase=True)
