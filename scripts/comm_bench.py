"""Microbench for the quantized + ring-overlapped FSDP collectives.

Measures the three claims ``parallel/collectives.py`` makes, on whatever
devices are present (8 fake CPU devices when run standalone):

* **wire bytes** — analytic per-step bytes for the explicit FSDP
  dataflow (param all-gather + grad reduce-scatter) under each wire
  format, and the int8/bf16 reduction vs fp32 (the >= 3x acceptance
  gate for int8);
* **overlap** — wall time of the fused ring ``gather_matmul`` (one
  program, transfer k+1 in flight during matmul k) vs the sum of a
  blocking all-gather and the consumer matmul run separately; the
  overlap fraction is how much of the gather's wire time the fused
  schedule hides, recorded through :class:`..obs.timeline.Timeline`
  spans and a ``comm_overlap_fraction`` gauge;
* **parity** — the explicit FSDP step with ``method="none"`` against
  the :mod:`..parallel.zero` annotation path (same mesh, same model,
  same optimizer — losses must agree), plus the int8+error-feedback
  loss drift against that reference.

    python scripts/comm_bench.py            # JSON record to stdout

``bench.py`` embeds the same :func:`run` as its ``collectives``
sub-record; ``scripts/tpu_validation.py`` re-runs it on real chips.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _script_env() -> None:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _timed(fn, *args, steps: int, reps: int = 3) -> float:
    """Best-of-``reps`` mean seconds/call after one warm (compile) call,
    sync-honest; the min over repeats rejects scheduler-noise outliers."""
    import jax

    jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def run(rows: int = 512, cols: int = 2048, inner: int = 256,
        steps: int = 5, parity_steps: int = 3, registry=None) -> dict:
    """The collectives microbench record (see module docstring).

    ``rows`` is the per-shard block height for the overlap timing;
    ``registry`` (an ``obs.metrics.MetricsRegistry``) receives the
    ``comm_bytes{op,method}`` counters and the overlap gauge.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from distributed_deep_learning_tpu.models.mlp import MLP
    from distributed_deep_learning_tpu.obs.timeline import Timeline
    from distributed_deep_learning_tpu.parallel import collectives as coll
    from distributed_deep_learning_tpu.parallel.zero import fsdp_state_spec
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    from distributed_deep_learning_tpu.runtime.shmap import shard_map
    from distributed_deep_learning_tpu.train.objectives import (
        cross_entropy_loss)
    from distributed_deep_learning_tpu.train.state import create_train_state
    from distributed_deep_learning_tpu.train.step import (make_step_fns,
                                                          place_state)

    devices = jax.devices()
    S = len(devices)
    if S < 2:
        raise RuntimeError(
            "comm_bench needs >= 2 devices to shard anything; run the "
            "standalone script (it forces an 8-way host CPU mesh) or set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "jax initialises")
    mesh1d = build_mesh({"data": S})
    axis = "data"
    rng = np.random.default_rng(7)

    # ---- wire bytes: the explicit FSDP dataflow on an MLP's params ------
    geom_state = create_train_state(
        MLP(hidden_size=256, num_hidden_layers=2, num_classes=8),
        jax.random.key(0), jnp.zeros((1, 64)), optax.sgd(0.1))
    geom_spec = fsdp_state_spec(geom_state, mesh1d, axis=axis,
                                min_leaf_size=16)
    gdims = jax.tree.map(lambda s: coll._spec_dim(s, axis),
                         geom_spec.params)
    bytes_rec: dict = {}
    for method in coll.METHODS:
        st = coll.fsdp_wire_stats(geom_state.params, gdims, S, method)
        key = "fp32" if method == "none" else method
        bytes_rec[key] = {
            "all_gather": st["all_gather_bytes"],
            "reduce_scatter": st["reduce_scatter_bytes"],
        }
        if registry is not None and method != "none":
            registry.counter("comm_bytes", op="all_gather",
                             method=method).inc(st["all_gather_bytes"])
            registry.counter("comm_bytes", op="reduce_scatter",
                             method=method).inc(st["reduce_scatter_bytes"])
    total = {k: v["all_gather"] + v["reduce_scatter"]
             for k, v in bytes_rec.items()}
    bytes_rec["int8_reduction_x"] = round(total["fp32"] / total["int8"], 2)
    bytes_rec["bf16_reduction_x"] = round(total["fp32"] / total["bf16"], 2)

    # ---- numerics: quantized ring collectives vs the fp32 primitives ----
    # integer-valued floats: sums are exact, so the ring's different
    # reduction order must be BIT-equal to XLA's (the exactness gate);
    # the quantized rel-errs measure the wire format, not float reassoc
    blk = jnp.asarray(rng.integers(-8, 9, (S * 4, 32)), jnp.float32)

    def gathered(method, overlap):
        @partial(shard_map, mesh=mesh1d, in_specs=P(axis), out_specs=P(),
                 check_vma=False)
        def f(b):
            return coll.all_gather(b, axis, size=S, method=method,
                                   overlap=overlap)
        return np.asarray(f(blk))

    def scattered(method, overlap):
        @partial(shard_map, mesh=mesh1d, in_specs=P(), out_specs=P(axis),
                 check_vma=False)
        def f(b):
            c = b * (1.0 + jax.lax.axis_index(axis))
            return coll.reduce_scatter(c, axis, size=S, method=method,
                                       overlap=overlap)
        return np.asarray(f(blk))

    ref_g, ref_s = gathered("none", False), scattered("none", False)
    scale_g = float(np.max(np.abs(ref_g))) or 1.0
    scale_s = float(np.max(np.abs(ref_s))) or 1.0
    numerics = {
        "ring_all_gather_exact":
            bool((gathered("none", True) == ref_g).all()),
        "ring_reduce_scatter_exact":
            bool((scattered("none", True) == ref_s).all()),
    }
    for method in ("bf16", "int8"):
        numerics[f"{method}_all_gather_rel_err"] = round(float(
            np.max(np.abs(gathered(method, True) - ref_g))) / scale_g, 5)
        numerics[f"{method}_reduce_scatter_rel_err"] = round(float(
            np.max(np.abs(scattered(method, True) - ref_s))) / scale_s, 5)

    # ---- overlap: fused ring gather_matmul vs gather-then-matmul --------
    a = jnp.asarray(rng.standard_normal((S * rows, cols)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((cols, inner)), jnp.float32)

    gather_only = jax.jit(partial(
        shard_map, mesh=mesh1d, in_specs=P(axis), out_specs=P(),
        check_vma=False)(
            lambda x: coll.all_gather(x, axis, size=S, method="none")))
    matmul_only = jax.jit(lambda x, y: x @ y)

    def fused(overlap):
        return jax.jit(partial(
            shard_map, mesh=mesh1d, in_specs=(P(axis), P()), out_specs=P(),
            check_vma=False)(
                lambda x, y: coll.gather_matmul(x, y, axis, size=S,
                                                method="none",
                                                overlap=overlap)))

    tl = Timeline()
    with tl.span("comm_gather"):
        t_comm = _timed(gather_only, a, steps=steps)
    full = gather_only(a)
    with tl.span("comm_matmul"):
        t_mm = _timed(matmul_only, full, b, steps=steps)
    with tl.span("comm_ring"):
        t_ring = _timed(fused(True), a, b, steps=steps)
    with tl.span("comm_sequential"):
        t_seq = _timed(fused(False), a, b, steps=steps)
    # how much of the gather's time the ring schedule hides, measured
    # against the like-for-like sequential program (full all-gather, then
    # one matmul over the materialised operand): same bytes moved, same
    # FLOPs, only the schedule differs.  1.0 = the whole transfer fits
    # under the matmuls.  On CPU (sync collectives) the win comes from
    # consuming each chunk while hot instead of materialising the
    # (size*rows, cols) gathered operand; on TPU the double-buffered
    # ppermutes also pipeline the actual wire time
    fraction = max(0.0, min(1.0, (t_seq - t_ring) / t_comm)) \
        if t_comm > 0 else 0.0
    if registry is not None:
        registry.gauge("comm_overlap_fraction").set(fraction)
    overlap_rec = {
        "gather_seconds": round(t_comm, 6),
        "matmul_seconds": round(t_mm, 6),
        "ring_fused_seconds": round(t_ring, 6),
        "sequential_fused_seconds": round(t_seq, 6),
        "overlap_fraction": round(fraction, 4),
        "timeline_seconds": {k: round(v, 6)
                             for k, v in tl.seconds.items()},
    }

    # ---- parity: explicit FSDP step vs the zero.py annotation path ------
    shape = {"data": 2, "fsdp": S // 2} if S >= 4 and S % 2 == 0 \
        else {"data": 1, "fsdp": S}
    mesh = build_mesh(shape)
    model = MLP(hidden_size=64, num_hidden_layers=2, num_classes=8)
    x = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(16) % 8, 8)
    sh_axis = "fsdp" if mesh.shape.get("fsdp", 1) > 1 else "data"

    def fresh(attach=False):
        st = create_train_state(model, jax.random.key(0), x[:1],
                                optax.adam(1e-2))
        if attach:
            n = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
            st = coll.attach_residual(st, n)
        spec = fsdp_state_spec(st, mesh, axis=sh_axis, min_leaf_size=16)
        return place_state(st, mesh, spec), spec

    s_ann, spec_ann = fresh()
    step_ann, _ = make_step_fns(mesh, cross_entropy_loss,
                                state_spec=spec_ann)
    losses = {"annotation": [], "explicit_none": [], "explicit_int8_ef": []}
    for _ in range(parity_steps):
        s_ann, m = step_ann(s_ann, x, y)
        losses["annotation"].append(float(m["loss"]))
    for name, method, overlap, attach in (
            ("explicit_none", "none", False, False),
            ("explicit_int8_ef", "int8", True, True)):
        st, spec = fresh(attach=attach)
        step, _ = coll.make_fsdp_step_fns(
            mesh, cross_entropy_loss, state_spec=spec, method=method,
            overlap=overlap, axis=sh_axis)
        for _ in range(parity_steps):
            st, m = step(st, x, y)
            losses[name].append(float(m["loss"]))
    ref = losses["annotation"]
    parity = {
        "steps": parity_steps,
        "losses": {k: [round(v, 6) for v in vs] for k, vs in losses.items()},
        "explicit_none_max_abs_delta": round(max(
            abs(a - b) for a, b in zip(ref, losses["explicit_none"])), 8),
        "int8_ef_max_abs_delta": round(max(
            abs(a - b) for a, b in zip(ref, losses["explicit_int8_ef"])), 6),
    }

    return {
        "metric": "quantized + ring-overlapped FSDP collectives",
        "n_devices": S,
        "bytes": bytes_rec,
        "numerics": numerics,
        "overlap": overlap_rec,
        "parity": parity,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="microbench the quantized/ring FSDP collectives")
    p.add_argument("--rows", type=int, default=512,
                   help="per-shard block rows for the overlap timing")
    p.add_argument("--cols", type=int, default=2048)
    p.add_argument("--inner", type=int, default=256,
                   help="matmul output width")
    p.add_argument("--steps", type=int, default=5,
                   help="timed iterations per variant")
    p.add_argument("--parity-steps", type=int, default=3,
                   help="train steps for the loss-parity gate")
    args = p.parse_args(argv)
    rec = run(rows=args.rows, cols=args.cols, inner=args.inner,
              steps=args.steps, parity_steps=args.parity_steps)
    print(json.dumps(rec, indent=1))
    return 0


if __name__ == "__main__":
    _script_env()
    sys.exit(main())
