"""Elastic training: restart-from-checkpoint on failure.

Closes the loop between :mod:`..utils.failures` (detect) and
:mod:`..utils.checkpoint` (preserve): when a step dies — a peer vanishes
mid-collective, the device runtime resets, a preemption lands mid-epoch —
the run restores the last epoch checkpoint and continues, instead of
losing the job.  The reference's failure model was "any rank failure hangs
or kills the job" (SURVEY.md §5); this is the TPU-pod answer, where the
scheduler restarting you is routine, not exceptional.

The unit of recovery is the latest checkpoint: the epoch by default, or
the last ``checkpoint_every`` step boundary when step-granular saves are
on (round 5 — at ImageNet scale an epoch-level redo after preemption is
hours).  Progress past the checkpoint is repeated deterministically
(seeded loaders replay the epoch's batch order), so a recovered run
equals an uninterrupted one bit for bit.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from distributed_deep_learning_tpu.train.loop import EpochResult, fit
from distributed_deep_learning_tpu.train.sentinel import AnomalyError
from distributed_deep_learning_tpu.utils.checkpoint import Checkpointer
from distributed_deep_learning_tpu.utils.failures import (FailureMonitor,
                                                          WorkerFailure)
from distributed_deep_learning_tpu.utils.logging import PhaseLogger


class RestartLoopError(RuntimeError):
    """The same resume point died twice with the identical failure —
    replaying it further could only repeat it (deterministic bug, or a
    permanently dead peer), so elastic recovery gives up early instead of
    burning ``max_restarts`` on the loop."""


def resume_point(checkpointer: Checkpointer, step: int | None = None
                 ) -> tuple[int | None, int, int, dict | None]:
    """Decode a checkpoint (default: latest) into a resume point.

    Returns ``(ckpt_step, start_epoch, resume_batch, resume_totals)``:
    ``ckpt_step`` is the orbax id to restore (None = start fresh);
    ``resume_batch > 0`` means mid-epoch — skip that many batches of
    ``start_epoch`` and seed the phase totals with ``resume_totals``.
    Sidecar-less checkpoints (pre-round-5 run dirs) keep the legacy
    convention step == completed epoch.  Pass ``step`` when integrity
    fallback restored an OLDER step than latest — the resume point must
    describe the checkpoint actually restored, not the quarantined one."""
    last = checkpointer.latest_step() if step is None else step
    if last is None:
        return None, 1, 0, None
    extra = checkpointer.read_extra(last)
    if extra is None:  # legacy epoch-id checkpoint
        return last, last + 1, 0, None
    if extra.get("epoch_complete"):
        return last, int(extra["epoch"]) + 1, 0, None
    return last, int(extra["epoch"]), int(extra["batch"]), \
        extra.get("totals")


def _merge_history(sink: list[EpochResult]) -> list[EpochResult]:
    """Cross-attempt history: keep the LAST record per (phase, epoch) — a
    phase re-run after a mid-validation failure supersedes its first
    (identical, deterministic) record."""
    seen: set = set()
    merged: list[EpochResult] = []
    for h in reversed(sink):
        key = (h.phase, h.epoch)
        if key in seen:
            continue
        seen.add(key)
        merged.append(h)
    return list(reversed(merged))


def fit_with_recovery(make_state: Callable[[], Any], train_step, eval_step,
                      loaders: Sequence, epochs: int,
                      checkpointer: Checkpointer, *,
                      logger: PhaseLogger | None = None,
                      monitor: FailureMonitor | None = None,
                      max_restarts: int = 2,
                      checkpoint_every: int | None = None,
                      sentinel=None, chaos=None, restore_fn=None,
                      telemetry=None) -> tuple[Any, list[EpochResult]]:
    """Run :func:`..loop.fit` with checkpointed restart on failure.

    ``make_state`` builds a FRESH initial state (used as the restore
    target; called once per attempt so donated buffers from the failed
    attempt are never reused).  Failures caught: :class:`WorkerFailure`
    from the monitor, runtime errors surfaced by JAX, and transient
    shared-FS ``OSError``; after ``max_restarts`` recoveries the last
    error propagates.  ``checkpoint_every=N`` saves every N train steps
    and recovers from the last step boundary (loader position rides the
    checkpoint sidecar).

    Robustness wiring (ISSUE 3):

    * Restores go through :meth:`Checkpointer.restore_verified` — a torn
      or bit-flipped latest save is quarantined and recovery proceeds
      from the previous verified-good step, resume point included.
    * A **restart loop** — the same ``(ckpt_step, epoch, batch)`` resume
      point dying twice with the identical error — fails fast instead of
      burning every restart replaying a deterministic bug.
    * ``sentinel`` with ``policy="rollback"``: an
      :class:`..train.sentinel.AnomalyError` restores the last checkpoint
      and replays with the offending global step in the run's skip set
      (the poisoned data window is consumed, never trained).
    * The ``monitor`` is :meth:`~..utils.failures.FailureMonitor.reset`
      between attempts, so a recorded failure from the dead attempt does
      not permanently poison the retries (the replacement worker is
      expected to heartbeat again).
    * ``restore_fn`` swaps the restore implementation — same contract as
      ``restore_verified`` (``(target, step=None) -> (state, step)``).
      The cross-topology resume path (:mod:`..reshard`) passes
      :func:`..reshard.restore.make_restore_fn` here so a restart on a
      different surviving mesh reshards the checkpoint transparently;
      every quarantine/fallback guarantee above still holds.

    ``telemetry`` (:class:`..obs.RunTelemetry`) attributes every restore
    to the ``recovery`` span (reshard restores separately record their
    redistribution under ``reshard``), counts restarts, and rides into
    :func:`..loop.fit` for step-span recording.
    """
    logger = logger or PhaseLogger(verbose=False)
    train_loader, val_loader, test_loader = loaders
    restarts = 0
    skip_steps: set[int] = set()  # rollback policy's poisoned data windows
    prev_failure = None           # (resume point, error) of the last attempt
    sink: list[EpochResult] = []  # survives attempts (round-5 fix: the
    # returned history used to hold only the FINAL attempt's epochs)
    while True:
        state = make_state()
        # restore_verified flushes in-flight async saves BEFORE reading the
        # resume point: a step save scheduled just before the failure must
        # be visible to this retry, or it would resume from an older
        # boundary and try to re-save an id that then finalises under it
        if telemetry is None:
            restored, ckpt_step = (restore_fn or
                                   checkpointer.restore_verified)(state)
        else:
            with telemetry.timeline.span("recovery"):
                restored, ckpt_step = (restore_fn or
                                       checkpointer.restore_verified)(state)
        if ckpt_step is not None:
            state = restored
            _, start_epoch, resume_batch, resume_totals = \
                resume_point(checkpointer, step=ckpt_step)
            # loud on purpose: an elastic (re)launch over an existing dir
            # silently continuing the OLD run would be the dirty-dir
            # hazard _maybe_checkpointer refuses for non-elastic runs
            at = f"epoch {start_epoch} step {resume_batch}" \
                if resume_batch else f"epoch {start_epoch}"
            logger.info(f"elastic: restored checkpoint step {ckpt_step}; "
                        f"continuing from {at}")
        else:
            start_epoch, resume_batch, resume_totals = 1, 0, None
        try:
            if monitor is not None:
                monitor.raise_if_failed()
                monitor.check()
            # fit polls the monitor before EVERY step, so a peer dying
            # mid-epoch aborts this attempt promptly rather than hanging
            # the next collective
            state, _ = fit(state, train_step, eval_step, train_loader,
                           val_loader, test_loader, epochs=epochs,
                           logger=logger, checkpointer=checkpointer,
                           start_epoch=start_epoch, monitor=monitor,
                           checkpoint_every=checkpoint_every,
                           resume_batch=resume_batch,
                           resume_totals=resume_totals, history_sink=sink,
                           sentinel=sentinel, chaos=chaos,
                           skip_steps=skip_steps or None,
                           telemetry=telemetry)
            return state, _merge_history(sink)
        except AnomalyError as e:
            if e.policy != "rollback":
                raise  # halt: clean state as of the last good step
            restarts += 1
            if restarts > max_restarts:
                raise
            if telemetry is not None:
                telemetry.registry.counter(
                    "elastic_restarts", cause="sentinel_rollback").inc()
            skip_steps.add(e.global_step)
            checkpointer.wait_until_finished()
            logger.info(f"sentinel rollback ({e}); restart "
                        f"{restarts}/{max_restarts} with global step "
                        f"{e.global_step} in the skip window")
            if monitor is not None and hasattr(monitor, "reset"):
                monitor.reset()
        except (WorkerFailure, RuntimeError, OSError) as e:
            failure = ((ckpt_step, start_epoch, resume_batch),
                       type(e).__name__, str(e))
            if failure == prev_failure:
                # deterministic bug, not a transient fault: replaying it
                # max_restarts times would reach the identical state and
                # die identically — say so now, with the evidence
                raise RestartLoopError(
                    "restart loop — same failure at the same resume point "
                    f"(checkpoint {ckpt_step}, epoch {start_epoch}, batch "
                    f"{resume_batch}) twice in a row: {type(e).__name__}: "
                    f"{e}") from e
            prev_failure = failure
            restarts += 1
            if restarts > max_restarts:
                raise
            if telemetry is not None:
                telemetry.registry.counter(
                    "elastic_restarts", cause=type(e).__name__).inc()
            # flush BEFORE reading the point for the log too, or a save
            # still in flight makes the message claim an older boundary
            # than the retry will actually use (review finding)
            checkpointer.wait_until_finished()
            _, ep, b, _ = resume_point(checkpointer)
            at = f"epoch {ep} step {b}" if b else f"epoch {ep}"
            logger.info(f"recovering from failure ({type(e).__name__}: {e}); "
                        f"restart {restarts}/{max_restarts} from {at}")
            if monitor is not None and hasattr(monitor, "reset"):
                monitor.reset()
