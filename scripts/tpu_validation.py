"""One-shot TPU validation batch for the round-3 perf work.

Run on a healthy TPU window: times flash-vs-dense attention (fwd+bwd,
long context), the s2d-vs-plain ResNet stem, and prints the full bench
line. Each section is independently guarded — partial hardware windows
still yield partial numbers. Results print as one JSON object per line
for easy collection into PERFORMANCE.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

# runnable as `python scripts/tpu_validation.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync(x):
    import jax.numpy as jnp

    return float(jnp.sum(x.astype(jnp.float32)))


def _time_grad(scalar_loss, q, steps):
    """Seconds/step of ``jit(grad(scalar_loss))``: one warmup compile,
    ``steps`` dispatches, one trailing sync — the SHARED timing protocol,
    so every section's ms numbers stay comparable (review finding: three
    diverging copies)."""
    import jax

    loss = jax.jit(jax.grad(scalar_loss))
    _sync(loss(q))
    t0 = time.perf_counter()
    for _ in range(steps):
        g = loss(q)
    _sync(g)
    return (time.perf_counter() - t0) / steps


def flash_vs_dense(B=4, T=2048, H=8, D=64, steps=20):
    import jax
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.models.transformer import (
        dot_product_attention)
    from distributed_deep_learning_tpu.ops.attention_pallas import (
        flash_attention)

    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
               for kk in ks)

    def bench(fn):
        return _time_grad(lambda q: jnp.sum(fn(q, k, v) ** 2), q, steps)

    td = bench(lambda q, k, v: dot_product_attention(
        q, k, v, causal=True, dtype=jnp.bfloat16))
    tf = bench(lambda q, k, v: flash_attention(
        q, k, v, causal=True).astype(jnp.bfloat16))
    tw = bench(lambda q, k, v: flash_attention(
        q, k, v, causal=True, window=512).astype(jnp.bfloat16))
    return {"section": "flash_vs_dense", "T": T,
            "dense_ms": round(td * 1e3, 3), "flash_ms": round(tf * 1e3, 3),
            "windowed512_ms": round(tw * 1e3, 3),
            "speedup": round(td / tf, 3)}


def s2d_vs_plain(batch=128, steps=10):
    import jax

    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    from bench import _train_throughput
    from distributed_deep_learning_tpu.models.resnet import resnet50
    import jax.numpy as jnp

    mesh = build_mesh({"data": len(jax.devices())})
    ips_plain, _ = _train_throughput(
        resnet50(dtype=jnp.bfloat16), image_size=224, num_classes=1000,
        batch=batch, steps=steps, mesh=mesh)
    ips_s2d, _ = _train_throughput(
        resnet50(dtype=jnp.bfloat16, stem_s2d=True), image_size=224,
        num_classes=1000, batch=batch, steps=steps, mesh=mesh)
    return {"section": "s2d_stem", "batch": batch,
            "plain_ips": round(ips_plain, 1), "s2d_ips": round(ips_s2d, 1),
            "speedup": round(ips_s2d / ips_plain, 4)}


def batch_sweep(steps=10):
    """MFU playbook step 1 (PERFORMANCE.md): per-chip batch 64/128/256 on
    the headline ResNet-50 — the knee is where arithmetic intensity
    saturates the MXU."""
    import jax
    import jax.numpy as jnp

    from bench import chip_peak_flops, _train_throughput
    from distributed_deep_learning_tpu.models.resnet import resnet50
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)})
    peak = chip_peak_flops(devices[0].device_kind)
    rows = []
    for per_chip in (64, 128, 256):
        batch = per_chip * len(devices)
        ips, fps = _train_throughput(
            resnet50(dtype=jnp.bfloat16, stem_s2d=True), image_size=224,
            num_classes=1000, batch=batch, steps=steps, mesh=mesh)
        mfu = ips * fps / batch / peak if fps and peak else None
        rows.append({"per_chip_batch": per_chip, "ips": round(ips, 1),
                     "mfu": round(mfu, 4) if mfu else None})
    return {"section": "batch_sweep", "rows": rows}


def lm_tokens(steps=10):
    """CausalLM tokens/sec/chip + MFU at the bench shape."""
    import jax
    import jax.numpy as jnp

    from bench import chip_peak_flops, _lm_throughput
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)})
    peak = chip_peak_flops(devices[0].device_kind)
    batch, seq = 8 * len(devices), 2048
    tps, fps = _lm_throughput(batch=batch, seq_len=seq, steps=steps,
                              mesh=mesh, dtype=jnp.bfloat16)
    mfu = tps * (fps / (batch * seq)) / peak if fps and peak else None
    return {"section": "lm", "tokens_per_sec_per_chip": round(tps, 1),
            "mfu": round(mfu, 4) if mfu else None}


def flash_block_sweep(B=4, T=2048, H=8, D=64, steps=10):
    """Tune the flash kernel's (block_q, block_k) on this hardware — the
    first lever if the kernel lands below dense parity.  Records the best
    config so :func:`..ops.attention_pallas.flash_attention` picks it up
    as its TPU default (``tpu:flash_best_blocks``)."""
    import jax
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.ops.attention_pallas import (
        flash_attention)

    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
               for kk in ks)
    rows = []
    best = None
    for bq, bk in ((128, 128), (128, 256), (256, 128), (256, 256),
                   (512, 128), (128, 512), (512, 512)):
        try:
            ms = _time_grad(
                lambda q, bq=bq, bk=bk: jnp.sum(flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk) ** 2),
                q, steps) * 1e3
        except Exception as exc:  # a VMEM-overflowing config is a data
            rows.append({"bq": bq, "bk": bk,      # point, not an abort
                         "error": f"{type(exc).__name__}"})
            continue
        rows.append({"bq": bq, "bk": bk, "ms": round(ms, 3)})
        if best is None or ms < best[2]:
            best = (bq, bk, ms)
    if best is None:
        return {"section": "flash_block_sweep", "T": T, "rows": rows,
                "best": None}
    if jax.default_backend() == "tpu":
        from distributed_deep_learning_tpu.utils.bench_records import (
            record_flash_blocks)

        record_flash_blocks(best[0], best[1])
    return {"section": "flash_block_sweep", "T": T, "rows": rows,
            "best": {"bq": best[0], "bk": best[1],
                     "ms": round(best[2], 3)}}


def gqa_speedup(B=4, T=2048, H=8, Hkv=2, D=64, steps=10):
    """GQA-native vs full-MHA flash at the bench shape: quantifies what
    the group× K/V HBM saving buys on this chip (the kernel maps query
    heads onto shared K/V heads in-kernel — round 5)."""
    import jax
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.ops.attention_pallas import (
        flash_attention)

    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.bfloat16)

    def bench(hkv):
        k = jax.random.normal(ks[1], (B, T, hkv, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, T, hkv, D), jnp.bfloat16)
        return _time_grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=True) ** 2), q, steps)

    t_mha = bench(H)
    t_gqa = bench(Hkv)
    return {"section": "gqa_speedup", "T": T, "H": H, "Hkv": Hkv,
            "mha_ms": round(t_mha * 1e3, 3),
            "gqa_ms": round(t_gqa * 1e3, 3),
            "speedup": round(t_mha / t_gqa, 3)}


def lm_sweep(configs=((16, False), (32, False), (32, True),
                      (32, "dots_no_batch"), (64, True),
                      (64, "dots_no_batch")),
             seq=2048, steps=10, **model_kw):
    """LM MFU playbook: per-chip batch × remat on the bench LM shape.
    The first hardware datum (batch 8, from the lm_tokens section —
    deliberately NOT re-measured here: 26.7% MFU) is likely
    under-batched at T=2048; remat rows test whether trading ~⅓ more
    FLOPs for activation residency lets a bigger batch raise MFU.

    Each row PRINTS as its own JSON line the moment it completes: six
    cold tunnel compiles WILL cross a single 420 s section watchdog, so
    the parent grants this section a doubled budget AND keeps whole
    printed lines on timeout — completed rows always survive.  MFU for remat rows uses the model FLOPs/token from the
    first successful non-remat row — cost_analysis FLOPs on a remat
    program include the recompute, which is HFU, not MFU; both are
    recorded.  Failing configs (OOM at 64×2048 is plausible) record the
    full exception text as rows."""
    import jax
    import jax.numpy as jnp

    from bench import chip_peak_flops, _lm_throughput
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)})
    peak = chip_peak_flops(devices[0].device_kind)
    model_flops_per_token = None
    done = 0
    for per_chip, remat in configs:
        batch = per_chip * len(devices)
        try:
            tps, fps = _lm_throughput(batch=batch, seq_len=seq,
                                      steps=steps, mesh=mesh,
                                      dtype=jnp.bfloat16, remat=remat,
                                      **model_kw)
        except Exception as exc:
            print(json.dumps({"section": "lm_sweep", "seq": seq,
                              "per_chip_batch": per_chip, "remat": remat,
                              "error": f"{type(exc).__name__}: {exc}"}),
                  flush=True)
            continue
        own_fpt = fps / (batch * seq) if fps else None
        if own_fpt and not remat and model_flops_per_token is None:
            model_flops_per_token = own_fpt
        row = {"section": "lm_sweep", "seq": seq,
               "per_chip_batch": per_chip, "remat": remat,
               "tokens_per_sec_per_chip": round(tps, 1)}
        mfu_fpt = own_fpt if not remat else model_flops_per_token
        if mfu_fpt and peak:
            row["mfu"] = round(tps * mfu_fpt / peak, 4)
        if remat and own_fpt and peak:
            # hardware FLOP/s utilisation incl. the remat recompute
            row["hfu"] = round(tps * own_fpt / peak, 4)
        print(json.dumps(row), flush=True)
        done += 1
    return {"section": "lm_sweep", "rows_completed": done,
            "configs": len(configs)}


def mfu_diag(batches=(128, 256)):
    """Roofline diagnosis of the headline step (VERDICT r4 #3: 29.6% MFU
    needs either a fix or a written analysis).  Pulls XLA ``cost_analysis``
    on the EXACT compiled train step: FLOPs, bytes accessed, arithmetic
    intensity, and the roofline-implied MFU ceiling for this chip
    (peak_flops / hbm_bw ridge point ≈ 240 FLOPs/byte on v5e)."""
    import jax
    import jax.numpy as jnp

    from bench import _build_train_step, chip_peak_flops
    from distributed_deep_learning_tpu.models.resnet import resnet50
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)})
    on_tpu = devices[0].platform == "tpu"
    peak = chip_peak_flops(devices[0].device_kind) if on_tpu else None
    # v5e/v5p/v4 HBM GB/s by device_kind substring (public chip specs)
    hbm = None
    kind = devices[0].device_kind.lower()
    for sub, bw in (("v6", 1640e9), ("v5 lite", 819e9), ("v5e", 819e9),
                    ("v5p", 2765e9), ("v5", 2765e9), ("v4", 1228e9)):
        if sub in kind:
            hbm = bw
            break
    from bench import _cost_analysis

    rows = []
    for batch in batches:
        try:  # a failing batch (256/chip can OOM) is a data point, not
            step, state, x, y = _build_train_step(  # an abort — keep the
                resnet50(dtype=jnp.bfloat16 if on_tpu else jnp.float32,  # rows
                         stem_s2d=on_tpu), image_size=224,  # already earned
                num_classes=1000, batch=batch * len(devices), mesh=mesh)
            analysis = _cost_analysis(step.lower(state, x, y).compile())
        except Exception as exc:
            rows.append({"per_chip_batch": batch,
                         "error": f"{type(exc).__name__}: {exc}"})
            continue
        flops = float(analysis.get("flops", 0.0))
        byt = float(analysis.get("bytes accessed", 0.0))
        ai = flops / byt if byt else None
        row = {"per_chip_batch": batch, "flops": flops,
               "bytes_accessed": byt,
               "arith_intensity": round(ai, 1) if ai else None}
        opt_s = float(analysis.get("optimal_seconds", 0.0))
        if opt_s and peak:
            # XLA's own roofline estimate -> the MFU it thinks is possible
            row["xla_optimal_seconds"] = opt_s
            row["xla_implied_mfu"] = round(flops / opt_s / peak, 3)
        if ai and peak and hbm:
            ridge = peak / hbm
            # roofline ceiling: HBM-bound below the ridge point
            row["ridge_flops_per_byte"] = round(ridge, 1)
            row["roofline_mfu_ceiling"] = round(
                min(1.0, ai / ridge), 3)
        rows.append(row)
    return {"section": "mfu_diag", "device": devices[0].device_kind,
            "rows": rows}


def serving(n_requests=48, max_slots=16):
    """Continuous-batching engine vs naive generate() at a TPU-shaped
    geometry (GPT-2-small-ish trunk, long mixed-length trace).  On TPU
    the per-tick device time is small, so this also measures the host
    round-trip share of the tick — the datum that decides whether the
    next engine iteration needs multi-tick device loops."""
    import jax

    from distributed_deep_learning_tpu.serve.bench import serving_bench

    on_tpu = jax.default_backend() == "tpu"
    model_kw = (dict(vocab_size=32768, num_layers=12, d_model=768,
                     num_heads=12, mlp_dim=3072, max_len=1024)
                if on_tpu else
                dict(vocab_size=512, num_layers=2, d_model=128,
                     num_heads=4, mlp_dim=256, max_len=192))
    rec = serving_bench(
        n_requests=n_requests if on_tpu else 8,
        max_slots=max_slots if on_tpu else 4,
        model_kw=model_kw,
        prompt_lens=(16, 256) if on_tpu else (4, 32),
        new_tokens=(16, 256) if on_tpu else (4, 16))
    return {"section": "serving", "on_tpu": on_tpu, **rec}


def serving_paged(n_requests=48, max_slots=16):
    """Paged engine under trace-driven SLO load at a TPU-shaped geometry
    (ISSUE 9): shared-system-prompt Poisson trace, chunked prefill,
    1-layer speculative draft, A/B'd against the v1 engine on the same
    trace.  On TPU the interesting harvest is whether prefix reuse and
    speculation still pay once the per-token device time shrinks — the
    host-side block bookkeeping is a fixed cost per tick, so this section
    decides how much of the paged win is compute saved vs host overhead
    moved."""
    import jax

    from distributed_deep_learning_tpu.serve.bench import paged_serving_bench

    on_tpu = jax.default_backend() == "tpu"
    model_kw = (dict(vocab_size=32768, num_layers=12, d_model=768,
                     num_heads=12, mlp_dim=3072, max_len=1024)
                if on_tpu else
                dict(vocab_size=512, num_layers=2, d_model=128,
                     num_heads=4, mlp_dim=256, max_len=192))
    load_kw = (dict(n_requests=n_requests, arrival="poisson", rate=4.0,
                    prompt_short=(16, 64), prompt_long=(128, 384),
                    long_frac=0.3, shared_prefix_len=128, shared_frac=0.6,
                    new_tokens=(16, 128), slo_ttft_ms=500.0,
                    slo_e2e_ms=5000.0)
               if on_tpu else
               dict(n_requests=10))
    rec = paged_serving_bench(
        load_kw=load_kw,
        model_kw=model_kw,
        max_slots=max_slots if on_tpu else 4,
        kv_block_size=32 if on_tpu else 16,
        prefill_chunk=128 if on_tpu else 32,
        draft_layers=2 if on_tpu else 1,
        spec_k=4)
    return {"section": "serving_paged", "on_tpu": on_tpu, **rec}


def serving_quant(n_requests=48, max_slots=16):
    """Quantized serving hot path at a TPU-shaped geometry (ISSUE 14):
    the full-precision vs int8-KV+int8-weight A/B on one trace, PLUS the
    block-table-aware flash-decode Pallas kernel
    (ops/paged_decode_pallas.py) timed against the gather-then-mask lax
    reference on the real pools.  On TPU the kernel number is the
    harvest: scalar-prefetch block indexing replaces the HBM gather, so
    kernel-vs-lax is a direct read of how much of the decode tick was
    the gather — and the int8 variant measures whether in-register
    dequant keeps the 3.5x wire-byte cut free of MXU stalls."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_deep_learning_tpu.ops.paged_decode_pallas import (
        paged_decode_reference, paged_flash_decode)
    from distributed_deep_learning_tpu.serve.bench import (
        quantized_serving_bench)
    from distributed_deep_learning_tpu.serve.quant import quantize_rows

    on_tpu = jax.default_backend() == "tpu"
    model_kw = (dict(vocab_size=32768, num_layers=12, d_model=768,
                     num_heads=12, mlp_dim=3072, max_len=1024)
                if on_tpu else
                dict(vocab_size=512, num_layers=2, d_model=128,
                     num_heads=4, mlp_dim=256, max_len=192))
    load_kw = (dict(n_requests=n_requests, arrival="poisson", rate=4.0,
                    prompt_short=(16, 64), prompt_long=(128, 384),
                    long_frac=0.3, shared_prefix_len=128, shared_frac=0.6,
                    new_tokens=(16, 128), slo_ttft_ms=500.0,
                    slo_e2e_ms=5000.0)
               if on_tpu else
               dict(n_requests=10))
    rec = quantized_serving_bench(
        load_kw=load_kw, model_kw=model_kw,
        max_slots=max_slots if on_tpu else 4,
        kv_block_size=32 if on_tpu else 16,
        prefill_chunk=128 if on_tpu else 32)

    # kernel vs lax reference on pool shapes matching the A/B geometry
    B = max_slots if on_tpu else 4
    Hkv = model_kw["num_heads"]
    D = model_kw["d_model"] // Hkv
    bs = 32 if on_tpu else 16
    Bps = (model_kw["max_len"] // bs)
    N = B * Bps + 1
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hkv, 1, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(N, bs, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(N, bs, Hkv, D)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(N - 1)[:B * Bps].reshape(B, Bps).astype(np.int32))
    lens = jnp.asarray(rng.integers(1, Bps * bs + 1, B), jnp.int32)
    kq, vq = quantize_rows(kp), quantize_rows(vp)

    def timed(fn, *a, **kw):
        out = jax.block_until_ready(fn(*a, **kw))   # compile
        reps = 20 if on_tpu else 3
        t0 = _time.perf_counter()
        for _ in range(reps):
            out = jax.block_until_ready(fn(*a, **kw))
        return out, (_time.perf_counter() - t0) / reps

    interp = None if on_tpu else True    # CPU smoke: interpret mode
    ref, t_lax = timed(jax.jit(paged_decode_reference), q, kp, vp,
                       tables, lens)
    out, t_kern = timed(paged_flash_decode, q, kp, vp, tables, lens,
                        interpret=interp)
    outq, t_kern_q = timed(paged_flash_decode, q, kq, vq, tables, lens,
                           interpret=interp)
    kernel = {
        "shapes": {"slots": B, "heads": Hkv, "head_dim": D,
                   "block_size": bs, "blocks_per_slot": Bps},
        "lax_reference_ms": round(t_lax * 1e3, 3),
        "kernel_ms": round(t_kern * 1e3, 3),
        "kernel_int8_ms": round(t_kern_q * 1e3, 3),
        "kernel_speedup_vs_lax": round(t_lax / t_kern, 3) if t_kern else None,
        "max_abs_err_vs_lax": float(jnp.max(jnp.abs(out - ref))),
        "interpret_mode": bool(interp),
    }
    return {"section": "serving_quant", "on_tpu": on_tpu,
            "kernel": kernel, **rec}


def serving_fleet(n_requests=64, replicas=3):
    """Fleet serving at a TPU-shaped geometry (ISSUE 15): N paged
    replicas behind the health-checked prefix-affinity router on one
    shared-prefix Poisson trace with priority classes.  On TPU the
    harvest is throughput and routing quality at real decode speeds —
    predicted prefix-hit tokens, per-priority SLO attainment and the
    per-replica compile counts (decode_compiles staying 1 per replica
    is the compile-once discipline surviving the router)."""
    import jax

    from distributed_deep_learning_tpu.serve.bench import (
        fleet_serving_bench)

    on_tpu = jax.default_backend() == "tpu"
    model_kw = (dict(vocab_size=32768, num_layers=12, d_model=768,
                     num_heads=12, mlp_dim=3072, max_len=1024)
                if on_tpu else
                dict(vocab_size=512, num_layers=2, d_model=128,
                     num_heads=4, mlp_dim=256, max_len=192))
    load_kw = (dict(n_requests=n_requests, arrival="poisson", rate=4.0,
                    prompt_short=(16, 64), prompt_long=(128, 256),
                    long_frac=0.3, shared_prefix_len=128, shared_frac=0.6,
                    new_tokens=(16, 128), slo_ttft_ms=500.0,
                    slo_e2e_ms=5000.0)
               if on_tpu else
               dict(n_requests=12, prompt_long=(16, 32),
                    shared_prefix_len=16, new_tokens=(4, 16)))
    rec = fleet_serving_bench(
        replicas=replicas, load_kw=load_kw, model_kw=model_kw,
        max_slots=16 if on_tpu else 4,
        kv_block_size=32 if on_tpu else 16,
        prefill_chunk=128 if on_tpu else 32)
    return {"section": "serving_fleet", "on_tpu": on_tpu, **rec}


def serving_disagg(n_requests=48):
    """Disaggregated prefill/decode serving at a TPU-shaped geometry
    (ISSUE 16): prefill worker pool + decode worker pool on separate
    chips, joined by device-to-device KV-block migration, A/B'd against
    the unified paged engine on the same shared-prefix Poisson trace.
    On TPU this is the first run where the migration primitive moves
    blocks over real ICI (the CPU number times emulated-host
    device_put) and where the prefill pool's batched chunk program runs
    on silicon the decode pool never shares — the interference-free ITL
    DistServe buys.  Greedy outputs must stay bit-identical to the
    unified engine (``token_agreement`` 1.0) and every compile counter
    must read 1."""
    # one device per pool: on the CPU smoke box force an emulated pair
    # before backend init (no-op on TPU — the flag only shapes the
    # host platform)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    from distributed_deep_learning_tpu.serve.bench import (
        disagg_serving_bench)

    on_tpu = jax.default_backend() == "tpu"
    model_kw = (dict(vocab_size=32768, num_layers=12, d_model=768,
                     num_heads=12, mlp_dim=3072, max_len=1024)
                if on_tpu else None)
    load_kw = (dict(n_requests=n_requests, arrival="poisson", rate=4.0,
                    prompt_short=(16, 64), prompt_long=(128, 256),
                    long_frac=0.3, shared_prefix_len=128, shared_frac=0.6,
                    new_tokens=(16, 128), slo_ttft_ms=500.0,
                    slo_e2e_ms=5000.0)
               if on_tpu else dict(n_requests=12))
    rec = disagg_serving_bench(
        seed=17, load_kw=load_kw, model_kw=model_kw,
        prefill_workers=1, decode_workers=1,
        prefill_streams=4, max_slots=16 if on_tpu else 8,
        kv_block_size=32 if on_tpu else 16,
        prefill_chunk=128 if on_tpu else 32)
    return {"section": "serving_disagg", "on_tpu": on_tpu, **rec}


def serving_rebalance(seed=0):
    """Live fleet rebalancing on real hardware (ISSUE 18): the full
    rebalance gauntlet — mid-request slot evacuation off a degraded
    replica with digest-verified committed-KV migration (bit-identical
    resume over fp32 AND int8 pools), ``evac_drop`` payload corruption
    rolled back with zero loss, a target crash mid-evacuation aborted
    and ledger-replayed, elastic autoscaling with the drain-protocol
    shrink, ``scale_thrash`` hysteresis damping, and disaggregated
    prefill/decode pool reassignment.  On TPU the evacuation path moves
    committed KV over real ICI instead of emulated-host device_put —
    the first measurement of mid-request drain latency at silicon
    transfer rates."""
    # the pool-elasticity scenario needs a reassignable third device:
    # on the CPU smoke box force an emulated quad before backend init
    # (no-op on TPU — the flag only shapes the host platform)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()

    import jax

    from distributed_deep_learning_tpu.utils.chaos import (
        run_rebalance_drill)

    on_tpu = jax.default_backend() == "tpu"
    rec = run_rebalance_drill(seed=seed)
    return {"section": "serving_rebalance", "on_tpu": on_tpu, **rec}


def autotune(workload="gpt"):
    """Auto-parallelism planner on real hardware: search the plan lattice
    for a TPU-shaped LM geometry (small-GPT on TPU, toy on CPU smoke) and
    report the winning plan + measured best-vs-default step rate.  On TPU
    this is the first run where the analytic HBM model has a real
    ``bytes_limit`` budget to prune against and ``memory_analysis()``
    reports device bytes — the cross-check data the CPU box cannot
    produce."""
    import jax

    from distributed_deep_learning_tpu.tune.artifact import plan_hash
    from distributed_deep_learning_tpu.tune.memory import hbm_budget
    from distributed_deep_learning_tpu.tune.search import run_search
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec

    on_tpu = jax.default_backend() == "tpu"
    argv = (["-e", "1", "-b", "64", "-m", "data", "-l", "4", "-s", "256"]
            if on_tpu else
            ["-e", "1", "-b", "16", "-m", "data", "-l", "2", "-s", "64"])
    os.environ.setdefault("DDL_DATA_LIMIT", "512")
    spec = get_spec(workload)
    config = parse_args(argv, workload=workload)
    result = run_search(
        spec, config, trial_steps=4 if on_tpu else 2,
        max_trials=8 if on_tpu else 4,
        space_options=dict(zero_options=("none", "fsdp"),
                           compress_options=("none",),
                           grad_accum_options=(1,)))
    best_trial = next((t for t in result.trials
                       if t.plan == result.best and not t.infeasible), None)
    return {
        "section": "autotune", "on_tpu": on_tpu, "workload": workload,
        "plan_hash": plan_hash(result.best),
        "plan": result.best.describe(),
        "best_steps_per_sec": round(result.best_sps, 3),
        "baseline_steps_per_sec": round(result.baseline_sps, 3),
        "speedup": round(result.best_sps / result.baseline_sps, 4)
            if result.baseline_sps else None,
        "n_candidates": result.n_candidates,
        "n_pruned_analytic": result.n_pruned,
        "n_infeasible": result.n_infeasible,
        "hbm_budget_bytes": hbm_budget(jax.devices()),
        "xla_memory_analysis": best_trial.memory if best_trial else {},
        "search_seconds": round(result.search_seconds, 1),
    }


def reshard():
    """Cross-topology reshard on real hardware: redistribution bandwidth
    for the host-gather and chunked per-shard paths across an N → N-2
    mesh change, plus the full shrink drill (kill 2, re-plan, reshard,
    continue).  On TPU this is the first run where the chunked path's
    point — the host never materialises the full array, and shard slices
    move at real ICI/PCIe bandwidth — shows up in seconds/GB; the CPU
    numbers in bench.py only time the slicing logic."""
    import jax

    from bench import _reshard

    return {"section": "reshard", "on_tpu": jax.default_backend() == "tpu",
            **(_reshard() or {})}


def collectives():
    """Quantized + ring-overlapped FSDP collectives on real hardware: the
    full ``scripts/comm_bench.py`` record — int8/bf16 wire-byte cut, ring
    bit-parity, fused ``gather_matmul`` overlap fraction, explicit-FSDP
    loss parity.  On TPU the overlap fraction measures actual ICI wire
    time pipelined under matmuls (the double-buffered ppermutes); the CPU
    number in bench.py only sees the materialisation win."""
    import jax

    from bench import _collectives

    return {"section": "collectives",
            "on_tpu": jax.default_backend() == "tpu",
            **(_collectives() or {})}


def observability(steps_hint=10):
    """Unified telemetry e2e on real hardware: a short ``--obs`` training
    run, then harvest the goodput breakdown + MFU straight from the
    emitted JSONL stream — the numbers PERFORMANCE.md §Observability
    records.  On TPU the MFU field is live (the chip is in the peak
    table); on CPU smoke it exercises the same path via
    ``DDL_OBS_PEAK_FLOPS``.  Also runs the instrumentation-overhead A/B
    (the <2% acceptance bar) on this box.

    Generation 2 (ISSUE 11): the run also exports the per-step span
    trace (``--obs-trace``) so the harvest proves the Perfetto export
    path on real hardware (span count + dropped count from the
    ``obs_trace`` event), and the tracing-overhead A/B
    (:func:`obs.bench.trace_overhead_bench`, its own <2% bar) runs
    beside the gen-1 one."""
    import tempfile

    import jax

    from distributed_deep_learning_tpu.obs.bench import (
        overhead_bench, trace_overhead_bench)
    from distributed_deep_learning_tpu.obs.export import read_events
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import (get_spec,
                                                         run_workload)

    on_tpu = jax.default_backend() == "tpu"
    os.environ.setdefault("DDL_DATA_LIMIT", "512" if on_tpu else "256")
    if not on_tpu:
        # exercise the full MFU path on the smoke box (arbitrary peak)
        os.environ.setdefault("DDL_OBS_PEAK_FLOPS", "1e12")
    tmpdir = tempfile.mkdtemp(prefix="obs_val_")
    stream = os.path.join(tmpdir, "obs_events.jsonl")
    trace = os.path.join(tmpdir, "trace.json")
    argv = ["-e", "2", "-b", "64" if on_tpu else "32", "-m", "data",
            "--obs", "--obs-file", stream, "--obs-trace", trace]
    run_workload(get_spec("mlp"), parse_args(argv, workload="mlp"))

    events = list(read_events(stream))
    run_gp = next((e for e in events if e.get("event") == "obs_goodput"
                   and e.get("scope") == "run"), {})
    mfu = next((e for e in events if e.get("event") == "obs_mfu"), {})
    tr = next((e for e in events if e.get("event") == "obs_trace"), {})
    return {
        "section": "observability", "on_tpu": on_tpu,
        "goodput_fractions": run_gp.get("fractions"),
        "wall_seconds": run_gp.get("wall_seconds"),
        "steps": run_gp.get("steps"),
        "mfu": mfu.get("mfu"),
        "steps_per_sec": mfu.get("steps_per_sec"),
        "step_flops": mfu.get("step_flops"),
        "device_kind": mfu.get("device_kind"),
        "trace_spans": tr.get("spans"),
        "trace_dropped": tr.get("dropped"),
        "overhead": overhead_bench(
            steps=48, repeats=5 if on_tpu else 3),
        "trace_overhead": trace_overhead_bench(
            steps=48, repeats=5 if on_tpu else 3),
    }


def _record_flash_gate(result: dict) -> None:
    """Persist the measured ratio as the `--attention auto` gate datum."""
    from distributed_deep_learning_tpu.utils.bench_records import (
        record_flash_speedup)

    record_flash_speedup(result["speedup"])


SECTIONS = ("flash_block_sweep", "flash_vs_dense", "gqa_speedup",
            "s2d_vs_plain", "batch_sweep", "lm_tokens", "serving",
            "serving_paged", "serving_quant", "serving_fleet",
            "serving_disagg", "serving_rebalance", "autotune", "reshard",
            "observability", "collectives", "mfu_diag", "lm_sweep")


def _run_section(name: str) -> None:
    import jax

    from bench import _enable_compile_cache

    # persistent XLA cache: a re-harvest after a transport drop (or the
    # driver's bench that follows) skips the 60-90 s tunnel compiles
    _enable_compile_cache()
    if os.environ.get("TPU_VALIDATION_CPU") == "1":
        # CPU smoke: the env var alone is not enough when a site plugin
        # pins the platform — force via jax.config pre-backend-init
        jax.config.update("jax_platforms", "cpu")
    fn = globals()[name]
    try:
        result = fn()
        print(json.dumps(result), flush=True)
        if name == "flash_vs_dense" and jax.default_backend() == "tpu":
            _record_flash_gate(result)
    except Exception as exc:  # partial windows yield partial numbers
        print(json.dumps({"section": name,
                          "error": f"{type(exc).__name__}: {exc}"}),
              flush=True)


def main():
    """Each section runs in ITS OWN watchdogged subprocess (round 5): the
    axon transport can hang mid-compile, and a hang in section 1 must not
    eat the whole healthy window — later sections still get their shot.
    ``--section NAME`` runs one section inline (the child mode).
    ``TPU_VALIDATION_SECTION_TIMEOUT`` (default 420 s) bounds each."""
    import subprocess

    if len(sys.argv) > 2 and sys.argv[1] == "--section":
        _run_section(sys.argv[2])
        return
    budget = float(os.environ.get("TPU_VALIDATION_SECTION_TIMEOUT", 420))
    # lm_sweep runs 6 cold compiles; a single default budget would cut
    # its tail rows (the 64-per-chip data the sweep exists to collect)
    budgets = {"lm_sweep": 2 * budget}
    for name in SECTIONS:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--section", name],
                timeout=budgets.get(name, budget),
                stdout=subprocess.PIPE, text=True)
            sys.stdout.write(proc.stdout)
            sys.stdout.flush()
            if proc.returncode != 0 and not proc.stdout.strip():
                # crashed (OOM-kill, segfault in the TPU runtime, import
                # error) rather than hung: record it like the old inline
                # loop did instead of silently dropping the section
                print(json.dumps({"section": name,
                                  "error": f"child rc={proc.returncode}"}),
                      flush=True)
        except subprocess.TimeoutExpired as exc:
            if exc.stdout:  # results printed before the hang still count
                out = exc.stdout if isinstance(exc.stdout, str) \
                    else exc.stdout.decode(errors="replace")
                # keep whole lines only: a child killed mid-write must not
                # corrupt the one-JSON-object-per-line contract
                out = out[:out.rfind("\n") + 1]
                sys.stdout.write(out)
            print(json.dumps({"section": name,
                              "error": f"timeout after "
                                       f"{budgets.get(name, budget):.0f}s"}),
                  flush=True)


if __name__ == "__main__":
    main()
