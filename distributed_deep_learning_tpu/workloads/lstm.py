"""LSTM workload: CNN-LSTM on predictive maintenance (reference
``src/pytorch/LSTM``).

``-l`` = hidden LSTM layers, ``-s`` = hidden width (``LSTM/main.py:55-56``).
Loss is L1 over the 5 raw sensor targets while "accuracy" reports argmax
matches — reference quirk Q5, replicated as the workload definition.
The reference *never* synced gradients for this workload even under MPI
(quirk Q2); here `data` mode syncs like every other workload — pass
``--no-sync`` to reproduce the reference behaviour.
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_deep_learning_tpu.data.datasets import synthetic_pdm
from distributed_deep_learning_tpu.data.pdm import load_pdm
from distributed_deep_learning_tpu.models.cnn_lstm import (
    CNNLSTM, cnn_lstm_layer_sequence)
from distributed_deep_learning_tpu.parallel.partition import lstm_aware_partition
from distributed_deep_learning_tpu.train.objectives import l1_loss
from distributed_deep_learning_tpu.train.state import reference_optimizer
from distributed_deep_learning_tpu.utils.config import Config, parse_args
from distributed_deep_learning_tpu.workloads.base import (
    WorkloadSpec, config_dtype, example_from_dataset, run_workload)

NUM_TARGETS = 5


def _dataset(config: Config):
    if config.data_dir:
        # an explicit --data-dir must fail loudly, not silently fall back;
        # instances_per_machine=None: whole file = one machine (fixtures /
        # arbitrary CSVs; the reference's 8759 is its dataset's constant)
        import os

        return load_pdm(os.path.join(config.data_dir, "dataset.csv"),
                        instances_per_machine=None)
    try:
        return load_pdm()
    except FileNotFoundError:
        return synthetic_pdm(seed=config.seed)


def _model(config: Config, dataset):
    return CNNLSTM(hidden_layers=config.num_layers, hidden_size=config.size,
                   num_targets=NUM_TARGETS, dtype=config_dtype(config))


def _layers(config: Config, dataset):
    return cnn_lstm_layer_sequence(config.num_layers, config.size,
                                   NUM_TARGETS, dtype=config_dtype(config))


SPEC = WorkloadSpec(
    name="lstm",
    build_dataset=_dataset,
    build_model=_model,
    build_layers=_layers,
    partitioner=lstm_aware_partition,  # reference LSTM/model.py:98-124
    build_loss=lambda c: l1_loss,
    build_optimizer=lambda c, steps: reference_optimizer("lstm", c.learning_rate),
    example_input=example_from_dataset,
)


def main(argv=None):
    config = parse_args(argv, workload="lstm")
    return run_workload(SPEC, config)


if __name__ == "__main__":
    main()
