"""Unified run telemetry (ISSUE 7): metrics primitives, goodput math,
event stream, MFU accounting, serve latency histograms, overhead guard.

The load-bearing claims:

* log-bucketed histogram percentiles land within the bucket-growth error
  bound of the exact sample quantiles, clamped to observed [min, max];
* snapshot/merge is lossless for counters and bucket-exact for
  histograms, and refuses to merge mismatched bounds;
* goodput fractions sum to <= 1.0 whatever the span bookkeeping did;
* a staggered-arrival serve trace yields per-request TTFT/e2e
  percentiles anchored at arrival (queue wait counts);
* telemetry on the real train loop costs < a noise-tolerant bound of
  steps/sec (bench.py records the tight number under
  ``obs_overhead_fraction_v1``; the acceptance bar there is 2%).
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_deep_learning_tpu.obs import RunTelemetry
from distributed_deep_learning_tpu.obs.export import (EventWriter,
                                                      prometheus_text,
                                                      read_events)
from distributed_deep_learning_tpu.obs.metrics import (Histogram,
                                                       MetricsRegistry,
                                                       log_bounds,
                                                       merge_snapshots)
from distributed_deep_learning_tpu.obs.mfu import (chip_peak_flops,
                                                   mfu_record)
from distributed_deep_learning_tpu.obs.timeline import CATEGORIES, Timeline


# --- histograms -----------------------------------------------------------

def test_log_bounds_geometric_and_cover():
    b = log_bounds(1e-3, 10.0, 2.0)
    assert b[0] == 1e-3 and b[-1] >= 10.0
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
    assert all(abs(r - 2.0) < 1e-9 for r in ratios)


@pytest.mark.parametrize("lo,hi,growth", [(0, 1, 2), (1, 1, 2), (1, 2, 1)])
def test_log_bounds_rejects_degenerate(lo, hi, growth):
    with pytest.raises(ValueError):
        log_bounds(lo, hi, growth)


def test_histogram_bucketing_edges():
    h = Histogram(bounds=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0):       # v <= bounds[0] -> bucket 0
        h.observe(v)
    h.observe(1.5)             # (1, 2]  -> bucket 1
    h.observe(4.0)             # (2, 4]  -> bucket 2
    h.observe(100.0)           # overflow
    assert h.counts == [2, 1, 1, 1]
    assert h.count == 5 and h.min == 0.5 and h.max == 100.0


def test_histogram_percentiles_within_bucket_error():
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-4.0, sigma=1.0, size=5000)
    h = Histogram()  # default growth 1.25 => <= ~12% relative error
    for v in samples:
        h.observe(v)
    for p in (50, 90, 99):
        exact = float(np.percentile(samples, p))
        est = h.percentile(p)
        assert abs(est - exact) / exact < 0.13, (p, est, exact)
    # tails clamp to the exact observed extremes
    assert h.percentile(0) == samples.min()
    assert h.percentile(100) == samples.max()


def test_histogram_percentile_monotone_and_empty():
    h = Histogram()
    assert h.percentile(50) == 0.0
    for v in (0.001, 0.01, 0.1, 1.0):
        h.observe(v)
    ps = [h.percentile(p) for p in (10, 50, 90, 99)]
    assert ps == sorted(ps)
    with pytest.raises(ValueError):
        h.percentile(101)


def test_histogram_roundtrip():
    h = Histogram(lo=1e-4, hi=10.0, growth=1.5)
    for v in (2e-4, 3e-2, 0.5, 20.0):
        h.observe(v)
    h2 = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert h2.bounds == h.bounds and h2.counts == h.counts
    assert h2.percentile(50) == h.percentile(50)
    assert math.isclose(h2.mean, h.mean)


# --- registry + merge -----------------------------------------------------

def test_registry_get_or_create_and_labels():
    reg = MetricsRegistry()
    c1 = reg.counter("requests", route="prefill")
    c1.inc(3)
    assert reg.counter("requests", route="prefill") is c1
    assert reg.counter("requests", route="decode") is not c1
    reg.gauge("depth").set(7)
    reg.histogram("lat").observe(0.5)
    snap = reg.snapshot()
    assert snap["counters"]["requests{route=prefill}"] == 3.0
    assert snap["gauges"]["depth"] == 7.0
    assert snap["histograms"]["lat"]["count"] == 1


def test_merge_snapshots_semantics():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("n").inc(2)
    b.counter("n").inc(5)
    a.gauge("g").set(1)
    b.gauge("g").set(9)
    for v in (0.01, 0.02):
        a.histogram("h").observe(v)
    for v in (0.04, 0.08, 0.16):
        b.histogram("h").observe(v)
    m = merge_snapshots(a.snapshot(), b.snapshot())
    assert m["counters"]["n"] == 7.0
    assert m["gauges"]["g"] == 9.0          # latest wins
    hm = Histogram.from_dict(m["histograms"]["h"])
    assert hm.count == 5 and hm.min == 0.01 and hm.max == 0.16
    assert sum(hm.counts) == 5


def test_merge_rejects_mismatched_bounds():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("h", lo=1e-5).observe(0.1)
    b.histogram("h", lo=1e-3).observe(0.1)
    with pytest.raises(ValueError, match="bounds"):
        merge_snapshots(a.snapshot(), b.snapshot())


# --- timeline / goodput ---------------------------------------------------

def _fake_clock(start=100.0):
    state = {"t": start}

    def clock(advance=None):
        if advance is not None:
            state["t"] += advance
        return state["t"]

    return clock


def test_goodput_attribution_deterministic():
    clock = _fake_clock()
    tl = Timeline(clock=clock)
    tl.add("compile", 2.0)
    tl.add("dispatch", 1.0, n=4)
    tl.add("device_sync", 1.0)
    tl.add("data_wait", 0.5)
    tl.add("checkpoint", 0.5)
    tl.step(4)
    clock(advance=10.0)  # wall = 10s, attributed = 5s
    gp = tl.goodput()
    assert gp["steps"] == 4
    assert math.isclose(gp["wall_seconds"], 10.0)
    assert math.isclose(gp["fractions"]["productive"], 0.2)
    assert math.isclose(gp["fractions"]["compile"], 0.2)
    assert math.isclose(gp["fractions"]["input_stall"], 0.05)
    assert math.isclose(gp["fractions"]["checkpoint"], 0.05)
    assert math.isclose(gp["fractions"]["other"], 0.5)
    assert gp["goodput_fraction"] == gp["fractions"]["productive"]


def test_goodput_fractions_never_exceed_one():
    # spans over-covering wall (coarse clocks / overlapping attribution)
    clock = _fake_clock()
    tl = Timeline(clock=clock)
    tl.add("dispatch", 8.0)
    tl.add("data_wait", 5.0)
    clock(advance=10.0)  # wall 10 < attributed 13
    gp = tl.goodput()
    assert sum(gp["fractions"].values()) <= 1.0 + 1e-9
    assert all(0.0 <= gp["fractions"][c] <= 1.0 for c in CATEGORIES)


def test_goodput_since_delta():
    clock = _fake_clock()
    tl = Timeline(clock=clock)
    tl.add("dispatch", 1.0)
    tl.step()
    clock(advance=4.0)
    mark = tl.snapshot()
    tl.add("dispatch", 3.0)
    tl.step(2)
    clock(advance=4.0)
    gp = tl.goodput(since=mark)
    assert gp["steps"] == 2
    assert math.isclose(gp["wall_seconds"], 4.0)
    assert math.isclose(gp["seconds"]["productive"], 3.0)


def test_timeline_span_contextmanager():
    clock = _fake_clock()
    tl = Timeline(clock=clock)
    with tl.span("recovery"):
        clock(advance=2.5)
    assert math.isclose(tl.seconds["recovery"], 2.5)
    assert tl.counts["recovery"] == 1


# --- export ---------------------------------------------------------------

def test_event_writer_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = EventWriter(path)
    w.emit("obs_goodput", scope="run", steps=3)
    w.emit("obs_mfu", mfu=float("nan"))  # non-finite must not corrupt JSON
    w.close()
    with open(path, "a") as f:
        f.write('{"torn line')  # a crash mid-write must not kill readers
    evs = list(read_events(path))
    assert len(evs) == 2
    assert evs[0]["scope"] == "run" and evs[0]["steps"] == 3
    assert evs[1]["mfu"] is None
    assert [e["event"] for e in read_events(path, event="obs_mfu")] \
        == ["obs_mfu"]


def test_event_writer_none_path_is_noop():
    w = EventWriter(None)
    w.emit("anything", x=1)
    w.close()


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.counter("steps", phase="train").inc(12)
    reg.gauge("queue_depth").set(3)
    h = reg.histogram("ttft", lo=0.01, hi=1.0, growth=2.0)
    for v in (0.02, 0.3, 5.0):
        h.observe(v)
    text = prometheus_text(reg.snapshot())
    assert 'steps_total{phase="train"} 12' in text
    assert "queue_depth 3" in text
    assert 'le="+Inf"} 3' in text
    assert "ttft_count 3" in text
    # cumulative bucket counts are monotone
    cum = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
           if line.startswith("ttft_bucket")]
    assert cum == sorted(cum) and cum[-1] == 3


# --- MFU ------------------------------------------------------------------

def test_chip_peak_table_and_override(monkeypatch):
    assert chip_peak_flops("TPU v4") == 275e12
    assert chip_peak_flops("TPU v4 lite") == 138e12
    assert chip_peak_flops("cpu") is None
    monkeypatch.setenv("DDL_OBS_PEAK_FLOPS", "2e12")
    assert chip_peak_flops("cpu") == 2e12


def test_mfu_record_math():
    rec = mfu_record(step_flops=1e12, steps=100, seconds=10.0,
                     n_devices=4, device_kind="TPU v4")
    assert math.isclose(rec["steps_per_sec"], 10.0)
    assert math.isclose(rec["achieved_flops_per_sec"], 1e13)
    # 1e13 achieved / (4 chips * 275e12 peak)
    assert math.isclose(rec["mfu"], 1e13 / (4 * 275e12))
    # degrades field-by-field, never raises
    rec = mfu_record(step_flops=None, steps=0, seconds=0.0,
                     n_devices=1, device_kind="cpu")
    assert rec["mfu"] is None and rec["steps_per_sec"] is None


# --- RunTelemetry ---------------------------------------------------------

def test_dispatch_kind_compile_once_per_fn():
    t = RunTelemetry()
    f, g = object(), object()
    assert t.dispatch_kind(f) == "compile"
    assert t.dispatch_kind(f) == "dispatch"
    assert t.dispatch_kind(g) == "compile"


def test_run_telemetry_close_emits_and_is_idempotent(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("DDL_OBS_PEAK_FLOPS", "1e12")
    path = str(tmp_path / "run.jsonl")
    t = RunTelemetry(path=path)
    t.registry.counter("sentinel_anomalies").inc()
    t.timeline.add("dispatch", 0.2)
    t.timeline.step(5)
    t.note_train(5, 0.2)
    summary = t.close()
    assert t.close() == {}  # idempotent
    assert summary["goodput"]["steps"] == 5
    events = {e["event"] for e in read_events(path)}
    assert {"obs_goodput", "obs_mfu", "obs_snapshot"} <= events
    snap = next(read_events(path, event="obs_snapshot"))["snapshot"]
    assert snap["counters"]["sentinel_anomalies"] == 1.0


# --- serve latency under staggered arrivals -------------------------------

def test_serve_latency_staggered_arrivals():
    from distributed_deep_learning_tpu.serve.bench import (build_model,
                                                           make_trace,
                                                           run_engine)

    model, params = build_model(
        seed=3, vocab_size=61, num_layers=1, d_model=32, num_heads=4,
        mlp_dim=64, max_len=48)
    trace = make_trace(8, vocab_size=61, seed=3, prompt_lens=(4, 12),
                       new_tokens=(4, 8), stagger=2)
    assert any(r.arrival_tick > 0 for r in trace)  # genuinely staggered
    out = run_engine(model, params, trace, max_slots=3)
    lat = out["stats"]["latency"]
    assert lat["measured_requests"] == 8
    for k in ("ttft", "e2e"):
        assert 0.0 < lat[f"{k}_p50_s"] <= lat[f"{k}_p99_s"]
    # e2e covers TTFT plus decode, so its p99 can't be below TTFT's p50
    assert lat["e2e_p99_s"] >= lat["ttft_p50_s"]
    assert lat["e2e_max_s"] >= lat["e2e_p99_s"]


def test_serve_stream_records_obs_serve(tmp_path):
    from distributed_deep_learning_tpu.serve.bench import (build_model,
                                                           make_trace,
                                                           run_engine)

    t = RunTelemetry(path=str(tmp_path / "serve.jsonl"))
    model, params = build_model(
        seed=3, vocab_size=61, num_layers=1, d_model=32, num_heads=4,
        mlp_dim=64, max_len=48)
    trace = make_trace(4, vocab_size=61, seed=4, prompt_lens=(4, 8),
                       new_tokens=(4, 6))
    run_engine(model, params, trace, max_slots=2, telemetry=t)
    t.close()
    ev = next(read_events(str(tmp_path / "serve.jsonl"),
                          event="obs_serve"))
    assert ev["stats"]["latency"]["measured_requests"] == 4
    # engine instruments landed in the run's shared registry
    assert any(k.startswith("serve_ttft_seconds")
               for k in t.registry.histograms)


# --- end-to-end: --obs run -> report -------------------------------------

def test_obs_cli_run_and_report(tmp_path):
    stream = tmp_path / "obs_events.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu", DDL_DATA_LIMIT="192",
               DDL_OBS_PEAK_FLOPS="1e12",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    run = subprocess.run(
        [sys.executable, "-m", "distributed_deep_learning_tpu", "mlp",
         "-e", "1", "-b", "32", "--obs", "--obs-file", str(stream)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=300)
    assert run.returncode == 0, run.stderr[-2000:]
    events = list(read_events(str(stream)))
    gp = next(e for e in events if e.get("event") == "obs_goodput"
              and e.get("scope") == "run")
    assert gp["steps"] > 0
    assert sum(gp["fractions"].values()) <= 1.0 + 1e-9
    mfu = next(e for e in events if e.get("event") == "obs_mfu")
    assert mfu["step_flops"] and mfu["mfu"] is not None

    report = subprocess.run(
        [sys.executable,
         os.path.join(env["PYTHONPATH"], "scripts", "obs_report.py"),
         str(stream), "--phases"],
        env=env, capture_output=True, text=True, timeout=120)
    assert report.returncode == 0, report.stderr[-2000:]
    assert "goodput (run)" in report.stdout
    assert "model FLOP utilization" in report.stdout


# --- overhead guard -------------------------------------------------------

def test_per_step_instrumentation_cost_bounded():
    # The per-step telemetry sequence _run_phase executes — clock reads,
    # dispatch_kind, two Timeline.add calls, step() — measured raw.
    # ~1.4 us/step on the CI box; the bound leaves >10x headroom so the
    # test never flakes, yet catches a regression that puts formatting,
    # allocation, or I/O on the hot path.  The wall-clock A/B against
    # the real train loop (the <2% acceptance bar) lives in bench.py's
    # ``observability`` section, where shared-runner noise is handled by
    # interleaved repeats + recorded baselines rather than an assert.
    import time

    t = RunTelemetry()
    tl = t.timeline
    fn = object()
    n = 5000
    t0 = time.perf_counter()
    for _ in range(n):
        d0 = tl.clock()
        kind = t.dispatch_kind(fn)
        tl.add("data_wait", tl.clock() - d0)
        d1 = tl.clock()
        tl.add(kind, tl.clock() - d1)
        tl.step()
    per_step_us = (time.perf_counter() - t0) / n * 1e6
    assert per_step_us < 25.0, per_step_us


def test_overhead_bench_record_shape():
    from distributed_deep_learning_tpu.obs.bench import overhead_bench

    rec = overhead_bench(steps=16, repeats=3, dim=64, depth=2, batch=16)
    assert rec["steps_per_sec_off"] > 0 and rec["steps_per_sec_on"] > 0
    # catastrophe guard only — tight numbers are bench.py's job (wall
    # clock A/B on a 2-core shared box swings a few percent either way)
    assert rec["obs_overhead_fraction"] < 0.5, rec


# --- satellite regressions (utils/profiling, utils/logging) ---------------

def test_measure_async_overlap_forwards_kwargs():
    from distributed_deep_learning_tpu.utils.profiling import (
        measure_async_overlap)

    seen = []

    def fn(x, *, scale):
        seen.append(scale)
        return x * scale

    measure_async_overlap(fn, 2.0, scale=3.0)
    assert seen and all(s == 3.0 for s in seen)


def test_step_timer_summary_sync_after_reset():
    import jax.numpy as jnp

    from distributed_deep_learning_tpu.utils.profiling import StepTimer

    times = iter([0.0, 1.0, 2.0, 100.0, 101.0, 102.0])
    t = StepTimer(warmup=1, clock=lambda: next(times))
    t.tick()
    t.tick()
    t.reset()
    # after reset there is no open window: a sync'd summary must not
    # plant a _last that would precede the next window's _t0 (which
    # used to corrupt the next window's rates)
    s = t.summary(sync=jnp.zeros(()))
    assert s == {"steps_per_sec": 0.0, "examples_per_sec": 0.0,
                 "seconds": 0.0}
    assert t._last is None
    t.tick()           # warmup tick re-opens the window
    t.tick(examples=8)
    assert t.summary()["steps_per_sec"] > 0


def test_phase_logger_jsonl_decoupled_from_verbose(tmp_path):
    from distributed_deep_learning_tpu.utils.logging import PhaseLogger

    path = str(tmp_path / "phases.jsonl")
    lg = PhaseLogger(verbose=False, jsonl_path=path)
    lg.phase_begin("train", epoch=1)
    lg.metrics(examples_per_sec=42.0)
    lg.close()
    events = [json.loads(line)["event"] for line in open(path)]
    assert events == ["phase_begin", "metrics"]
