"""Memory observability: live HBM tracking, buffer attribution, OOM
postmortems.

The obs/ layer measures *time* everywhere (spans, goodput, MFU, traces);
this module is the matching *memory* ledger.  Three surfaces:

* :class:`MemoryTracker` — polls ``device.memory_stats()`` into
  watermark / in-use gauges with a bounded per-step peak-delta timeline,
  plus host RSS.  TPU runtimes report the stats dict; the CPU backend
  reports nothing, so the tracker disarms itself after the first empty
  sample and the per-step hook degrades to one attribute read (the
  <2% hot-loop bar stays intact on every backend).
* :func:`buffer_attribution` / :func:`top_leaves` /
  :func:`donation_audit` — the static view from the compiled step's
  ``memory_analysis()``: argument/output/temp/alias breakdown, the
  largest pytree leaves by shape, and a donation audit that flags
  donated bytes that failed to alias (donated-but-copied inputs double
  their footprint — the exact crash class the bare-``P()`` placement
  bug in the ``--grad-compress int8`` path hit).
* :func:`record_oom_postmortem` — dumps watermark timeline + top
  buffers + active plan into a :class:`~.recorder.FlightRecorder` when
  ``RESOURCE_EXHAUSTED`` surfaces, so an OOM leaves an attributed black
  box instead of a bare stack trace.  With a seq-only recorder clock
  the dump bytes are bit-identical across runs.

Everything here is host Python; jax is imported lazily and only when a
device is actually polled.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

#: gauge names the tracker maintains (the JSONL/Prometheus surface)
GAUGE_IN_USE = "mem_hbm_bytes_in_use"
GAUGE_LIMIT = "mem_hbm_bytes_limit"
GAUGE_PEAK = "mem_hbm_peak_bytes"
GAUGE_HOST_RSS = "mem_host_rss_bytes"


def is_oom_error(err: BaseException) -> bool:
    """Does this exception smell like device memory exhaustion?  XLA
    surfaces OOM as ``XlaRuntimeError`` with RESOURCE_EXHAUSTED status —
    matched on the message because the exception class moved across
    jaxlib versions."""
    msg = str(err)
    return ("RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()
            or "OOM" in msg)


def host_rss_bytes() -> int | None:
    """Resident set size of this process, from ``/proc/self/status``
    (exact, linux) falling back to ``resource.getrusage`` (portable);
    None when neither source works."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes; linux is the deployed target
        return int(ru) * 1024
    except Exception:
        return None


def device_memory_stats(device: Any) -> dict[str, int]:
    """``device.memory_stats()`` as a plain dict, ``{}`` when the backend
    reports nothing (CPU) or the call itself raises."""
    try:
        stats = device.memory_stats()
    except Exception:
        return {}
    return dict(stats) if stats else {}


def pytree_bytes(tree: Any) -> int:
    """Exact byte footprint of a pytree of arrays: Σ size × itemsize over
    leaves that carry shape/dtype (ShapeDtypeStructs count too — the
    analytic and allocated views agree by construction)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * int(dtype.itemsize)
    return total


def top_leaves(tree: Any, n: int = 10) -> list[dict[str, Any]]:
    """The ``n`` largest leaves of a pytree by bytes, with their tree
    paths — "which buffer is eating HBM" by name.  Deterministic order:
    bytes descending, then path (ties can't reshuffle a postmortem)."""
    import jax

    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        size = 1
        for d in shape:
            size *= int(d)
        rows.append({
            "path": jax.tree_util.keystr(path),
            "shape": list(shape),
            "dtype": str(dtype),
            "bytes": size * int(dtype.itemsize),
        })
    rows.sort(key=lambda r: (-r["bytes"], r["path"]))
    return rows[:n]


class MemoryTracker:
    """Live device-memory gauges + a bounded per-step timeline.

    Construct once per run (``RunTelemetry`` owns one), then call
    :meth:`sample` at span boundaries and :meth:`on_step` from the hot
    loop.  The first sample decides whether the backend reports memory
    at all; when it doesn't (CPU), ``on_step`` collapses to a single
    attribute read and only explicit :meth:`sample` calls refresh host
    RSS.

    ``every`` subsamples the hot loop (a ``memory_stats()`` call is a
    runtime round-trip; once every N steps bounds the cost while the
    peak-delta per sample still covers the window since the last one).
    """

    def __init__(self, registry, *, device: Any = None, every: int = 8,
                 capacity: int = 256) -> None:
        self.registry = registry
        self.device = device
        self.every = max(1, int(every))
        self.capacity = max(1, int(capacity))
        self.timeline: list[dict[str, Any]] = []
        self.samples = 0
        self.steps = 0
        self.peak_bytes = 0
        self._last_peak: int | None = None
        self._armed: bool | None = None   # unknown until the first sample

    @property
    def enabled(self) -> bool:
        """True until the backend proves it reports nothing."""
        return self._armed is not False

    def _resolve_device(self) -> Any:
        if self.device is None:
            import jax

            self.device = jax.devices()[0]
        return self.device

    def on_step(self) -> None:
        """Hot-loop hook: sample every ``self.every`` trained steps.
        One int increment + compare when disarmed or off-cadence."""
        self.steps += 1
        if self._armed is False or self.steps % self.every:
            return
        self.sample(step=self.steps)

    def sample(self, step: int | None = None) -> dict[str, Any] | None:
        """Poll the device once; update gauges and the timeline.

        Returns the sample dict, or None when the backend reports no
        memory stats (host RSS is still gauged on the FIRST empty
        sample, so CPU runs export it once without paying per step)."""
        stats = device_memory_stats(self._resolve_device())
        if not stats:
            if self._armed is None:
                self._armed = False
                rss = host_rss_bytes()
                if rss is not None:
                    self.registry.gauge(GAUGE_HOST_RSS).set(rss)
            return None
        self._armed = True
        in_use = int(stats.get("bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
        peak = int(stats.get("peak_bytes_in_use", in_use))
        self.peak_bytes = max(self.peak_bytes, peak)
        delta = peak - self._last_peak if self._last_peak is not None else 0
        self._last_peak = peak
        self.registry.gauge(GAUGE_IN_USE).set(in_use)
        self.registry.gauge(GAUGE_PEAK).set(self.peak_bytes)
        if limit:
            self.registry.gauge(GAUGE_LIMIT).set(limit)
        rss = host_rss_bytes()
        if rss is not None:
            self.registry.gauge(GAUGE_HOST_RSS).set(rss)
        sample = {"step": step if step is not None else self.steps,
                  "bytes_in_use": in_use, "peak_bytes": peak,
                  "peak_delta": delta, "host_rss_bytes": rss}
        self.timeline.append(sample)
        if len(self.timeline) > self.capacity:
            del self.timeline[:len(self.timeline) - self.capacity]
        self.samples += 1
        return sample

    def summary(self) -> dict[str, Any]:
        """The run-level memory rollup (the ``obs_memory`` event body)."""
        return {
            "samples": self.samples,
            "steps": self.steps,
            "device_reports_memory": bool(self._armed),
            "peak_bytes": self.peak_bytes or None,
            "host_rss_bytes": host_rss_bytes(),
            "timeline_tail": self.timeline[-16:],
        }


def donation_audit(memory: dict[str, int],
                   donated_bytes: int | None) -> dict[str, Any]:
    """Flag donated input bytes that failed to alias an output.

    ``memory`` is a :func:`~..utils.profiling.normalize_memory_analysis`
    dict; ``donated_bytes`` the byte size of the arguments the caller
    donated (e.g. the train state).  When XLA honours a donation the
    bytes show up in ``alias_size_in_bytes``; donated bytes above the
    aliased count were silently copied — the program holds BOTH the old
    and new buffer, which is exactly how a "should fit" step OOMs.
    """
    aliased = int(memory.get("alias_size_in_bytes", 0))
    out: dict[str, Any] = {"aliased_bytes": aliased,
                           "donated_bytes": donated_bytes}
    if donated_bytes is None:
        out["unaliased_donated_bytes"] = None
        out["ok"] = None
        return out
    unaliased = max(0, int(donated_bytes) - aliased)
    out["unaliased_donated_bytes"] = unaliased
    # tolerate counter-sized slack: tiny scalar leaves are often folded
    # into the program rather than aliased, and that is not a leak
    out["ok"] = unaliased <= max(4096, int(donated_bytes) * 0.01)
    return out


def buffer_attribution(memory: dict[str, int], *, state: Any = None,
                       donated_bytes: int | None = None,
                       top_n: int = 10) -> dict[str, Any]:
    """The static memory story of one compiled step.

    ``memory`` — normalized ``memory_analysis()`` fields; ``state`` — an
    optional pytree (train state, KV cache) whose largest leaves get
    named; ``donated_bytes`` — what the caller donated, for the audit.
    """
    breakdown = {k: memory.get(k, 0) for k in (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "alias_size_in_bytes",
        "generated_code_size_in_bytes")}
    if donated_bytes is None and state is not None:
        donated_bytes = pytree_bytes(state)
    return {
        "breakdown": breakdown,
        "total_bytes": sum(v for v in breakdown.values()
                           if isinstance(v, int)),
        "missing_fields": list(memory.get("memory_fields_missing", ())),
        "top_leaves": top_leaves(state, top_n) if state is not None else [],
        "donation": donation_audit(memory, donated_bytes),
    }


def record_oom_postmortem(recorder, *, error: BaseException | str,
                          plan: dict | None = None,
                          top_buffers: Sequence[dict] | None = None,
                          watermarks: Iterable[dict] | None = None,
                          attribution: dict | None = None,
                          context: str = "train") -> bool:
    """Write the OOM story into a flight recorder and trip it.

    Returns True when a postmortem was recorded (the error actually was
    an OOM and a recorder exists).  Every field is JSON-plain and
    deterministically ordered, so a seq-clock recorder dumps
    bit-identical bytes for identical failures."""
    if recorder is None:
        return False
    if isinstance(error, BaseException):
        if not is_oom_error(error):
            return False
        error = f"{type(error).__name__}: {error}"[:500]
    elif "RESOURCE_EXHAUSTED" not in error and "OOM" not in error \
            and "out of memory" not in error.lower():
        return False
    recorder.record(
        "oom_postmortem",
        context=context,
        error=error,
        plan=plan,
        top_buffers=list(top_buffers or ()),
        watermark_timeline=list(watermarks or ()),
        attribution=attribution,
    )
    recorder.trip("oom_postmortem")
    return True
