"""End-to-end: MLP workload, data-parallel over 8 emulated devices.

The TPU analogue of the reference's flagship path (SURVEY.md §3.1):
CLI → mesh → loader → model → jitted step → psum-DP — asserting that
training actually learns and that DP matches single-device numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from distributed_deep_learning_tpu.data.datasets import synthetic_mqtt
from distributed_deep_learning_tpu.data.loader import DeviceLoader, make_loaders
from distributed_deep_learning_tpu.data.splits import train_val_test_split
from distributed_deep_learning_tpu.models.mlp import MLP
from distributed_deep_learning_tpu.runtime.mesh import build_mesh
from distributed_deep_learning_tpu.train.loop import fit
from distributed_deep_learning_tpu.train.objectives import cross_entropy_loss
from distributed_deep_learning_tpu.train.state import (
    create_train_state, reference_optimizer,
)
from distributed_deep_learning_tpu.train.step import make_step_fns, place_state
from distributed_deep_learning_tpu.utils.logging import PhaseLogger


def _init_state(model, example, tx, seed=42):
    return create_train_state(model, jax.random.key(seed), example, tx)


def test_mlp_dp_learns(mesh8, capsys):
    ds = synthetic_mqtt(2048, seed=1)
    splits = train_val_test_split(len(ds), seed=42)
    train_loader, val_loader, test_loader = make_loaders(ds, splits, 128, mesh8)

    model = MLP(hidden_size=38, num_hidden_layers=1, num_classes=5)
    state = _init_state(model, jnp.zeros((1, 48)), reference_optimizer("mlp"))
    state = place_state(state, mesh8)
    train_step, eval_step = make_step_fns(mesh8, cross_entropy_loss)

    logger = PhaseLogger(verbose=True)
    state, history = fit(state, train_step, eval_step, train_loader,
                         val_loader, test_loader, epochs=12, logger=logger)

    train_results = [h for h in history if h.phase == "train"]
    assert train_results[-1].accuracy > train_results[0].accuracy
    assert train_results[-1].accuracy > 60.0
    test_res = history[-1]
    assert test_res.phase == "test" and test_res.accuracy > 60.0

    # the reference log grammar, rank-0 gated, quote-delimited
    out = capsys.readouterr().out
    assert '"train epoch 1 begins at ' in out
    assert ' with accuracy ' in out and ' and loss ' in out
    assert '"test ends at ' in out
    # beyond-reference observability: per-phase throughput counters
    assert '"metrics phase=train epoch=1 examples_per_sec=' in out


def test_dp_matches_single_device_numerics(mesh8):
    """Gradient-sync correctness: 8-way DP must equal 1-device training on
    the same global batch (the property the reference's quirk Q1/Q2 broke)."""
    ds = synthetic_mqtt(512, seed=3)
    model = MLP(num_hidden_layers=2)
    tx = optax.sgd(0.1)
    mesh1 = build_mesh({"data": 1}, jax.devices()[:1])

    def run(mesh, steps=4):
        state = _init_state(model, jnp.zeros((1, 48)), tx)
        state = place_state(state, mesh)
        train_step, _ = make_step_fns(mesh, cross_entropy_loss)
        loader = DeviceLoader(ds, np.arange(256), 64, mesh, shuffle=False)
        it = iter(loader)
        for _ in range(steps):
            x, y = next(it)
            state, m = train_step(state, x, y)
        return jax.device_get(state.params)

    p1 = run(mesh1)
    p8 = run(mesh8)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
                 p1, p8)


def test_double_softmax_quirk_mode(mesh8):
    """Quirk Q4 replication: Softmax head + CE-of-probabilities still trains."""
    ds = synthetic_mqtt(512, seed=5)
    model = MLP(double_softmax=True)
    state = _init_state(model, jnp.zeros((1, 48)), optax.adam(1e-3))
    state = place_state(state, mesh8)
    loss = lambda p, t: cross_entropy_loss(p, t, from_probabilities=True)
    train_step, _ = make_step_fns(mesh8, loss)
    loader = DeviceLoader(ds, np.arange(512), 64, mesh8, shuffle=True)
    last = None
    for x, y in loader:
        state, m = last = train_step(state, x, y)
    assert np.isfinite(float(last[1]["loss"]))
