"""MLP workload model (reference ``src/pytorch/MLP/model.py:23-76``).

Reference architecture: ``Linear(input, hidden) → ReLU →
[Linear(hidden, hidden) → ReLU] × num_layers → Linear(hidden, classes) →
Softmax`` (Sigmoid head when ``classes < 2``).  Defaults hidden=38,
classes=5.  Differences by design:

* input width is data-driven (fixes quirk Q6's 52-vs-48 mismatch);
* the model emits **logits**; the softmax lives in the loss. The reference
  feeds Softmax output into CrossEntropyLoss (quirk Q4) — set
  ``double_softmax=True`` for bit-faithful replication of that behaviour.
* the layer list is exposed via :meth:`layer_sequence` so the model-parallel
  partitioners (:mod:`..parallel.partition`) can stage it exactly like the
  reference's constructor-time partitioning (``MLP/model.py:41-45``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden_size: int = 38
    num_hidden_layers: int = 1
    num_classes: int = 5
    double_softmax: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.Dense(self.hidden_size, dtype=self.dtype, name="in_proj")(x)
        x = nn.relu(x)
        for i in range(self.num_hidden_layers):
            x = nn.Dense(self.hidden_size, dtype=self.dtype, name=f"hidden_{i}")(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="out_proj")(x)
        if self.double_softmax:
            # reference quirk Q4: Softmax output fed to a softmax-based loss
            x = nn.sigmoid(x) if self.num_classes < 2 else nn.softmax(x)
        return x.astype(jnp.float32)

    # --- stage partitioning support (model/pipeline modes) -----------------
    @property
    def num_partitionable_layers(self) -> int:
        """Layer count as the reference counts it: in + hidden + out
        (``MLP/model.py:62-76`` partitions ``hidden_layers + 2`` layers)."""
        return self.num_hidden_layers + 2
