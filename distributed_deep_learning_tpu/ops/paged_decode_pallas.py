"""Paged flash-decode Pallas kernel: block-table indexing IN the kernel.

The paged engine's decode path today materialises each slot's logical KV
with a host-shaped gather (``paged.gather_slot``: ``leaf[table]`` then
reshape) before the attention matmul ever runs — at long context that
gather IS the decode bill: it copies the slot's entire KV history
through HBM once per token just to linearise it.  This kernel deletes
the copy.  The grid walks ``(slot, logical_block)`` and the BLOCK TABLE
rides in scalar-prefetch memory (SMEM), so each program's index map
points Pallas' own pipeline DMA at physical block ``tables[b, j]`` of
the resident pool — K/V stream straight from where they live, the
"gather" degenerates to address arithmetic, and the online-softmax
running statistics (max ``m``, denominator ``l``, accumulator ``acc``)
carry across the block loop in VMEM scratch exactly like the training
flash kernel (:mod:`.attention_pallas`), O(D) memory per query.

Quantization composes in-register: int8 pools arrive with their
per-position-per-head f32 scales (:class:`..serve.quant.QuantTensor`
payload + ``s``), the scale tile rides the same block index map as its
payload tile, and ``k.astype(f32) * scale`` happens on the VPU between
the DMA and the MXU contraction — the dequantized KV never touches HBM.
That pairing is what turns the 3.5-4x at-rest shrink into 3.5-4x less
decode wire traffic, which on a memory-bound decode is throughput.

GQA-native like the training kernel: q arrives grouped ``(B, Hkv, G,
D)`` and contracts against unexpanded ``Hkv``-headed K/V tiles — the
group-times-smaller pool is what streams.

Masking: position ``j*bs + i`` attends iff it is ``< seq_lens[b]``, so
trash-backed tail entries of the table are read (garbage) and masked —
the same causal-prefix discipline as ``gather_slot``.  One padded slot
(``seq_lens == 0``) degrades to uniform weights over garbage, never
NaN; callers ignore those rows (the engine's free slots).

Off-TPU the dispatcher (:func:`paged_flash_decode`) routes to
:func:`paged_decode_reference` — the same gather-then-mask lax math the
engine compiles today — and the CPU parity tests run the REAL kernel in
interpreter mode against it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _contract_qk(q, k):
    """(Hkv, G, D) x (bs, Hkv, D) -> (Hkv, G, bs), f32 accumulate."""
    return lax.dot_general(q, k, (((2,), (2,)), ((0,), (1,))),
                           preferred_element_type=jnp.float32)


def _contract_pv(p, v):
    """(Hkv, G, bs) x (bs, Hkv, D) -> (Hkv, G, D), f32 accumulate."""
    return lax.dot_general(p, v, (((2,), (0,)), ((0,), (1,))),
                           preferred_element_type=jnp.float32)


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref,
                   vs_ref, o_ref, m_ref, l_ref, acc_ref, *,
                   sm_scale: float, block_size: int, n_blocks: int):
    """One (slot, logical block) step of the online softmax.

    ``tables_ref``/``lens_ref`` are the scalar-prefetch refs (SMEM);
    the BlockSpec index maps below already used ``tables_ref`` to land
    ``k_ref``/``v_ref`` on physical block ``tables[b, j]``, so the
    kernel body never sees a physical id — only its tile.  ``ks_ref``/
    ``vs_ref`` are the per-position-per-head scale tiles (None on the
    full-precision variant; the tile dequantizes in-register)."""
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)                  # (Hkv, G, D)
    k = k_ref[0]                                      # (bs, Hkv, D)
    v = v_ref[0]
    if ks_ref is not None:
        k = k.astype(jnp.float32) * ks_ref[0]         # in-register dequant
        v = v.astype(jnp.float32) * vs_ref[0]

    s = _contract_qk(q, k.astype(q.dtype)) * sm_scale   # (Hkv, G, bs)
    kpos = j * block_size + lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=2)
    s = jnp.where(kpos < lens_ref[b], s, NEG_INF)

    m = m_ref[...]                                    # (Hkv, G, 1)
    l = l_ref[...]
    blk_max = jnp.max(s, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m)
    m_ref[...] = new_m
    l_ref[...] = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + _contract_pv(
        p.astype(v.dtype), v.astype(p.dtype))

    @pl.when(j == n_blocks - 1)
    def _writeout():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _drop_scales(kern):
    def wrapped(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest, **kw):
        return kern(tables_ref, lens_ref, q_ref, k_ref, v_ref, None, None,
                    *rest, **kw)
    return wrapped


def _split_quant(pool, scale):
    """Accept either a raw array + explicit scale or a
    :class:`..serve.quant.QuantTensor` carrying both."""
    from distributed_deep_learning_tpu.serve.quant import is_quant

    if is_quant(pool):
        if scale is not None:
            raise ValueError("pass scales either inside the QuantTensor "
                             "or as an explicit argument, not both")
        return pool.q, pool.s
    return pool, scale


def paged_flash_decode(q, k_pool, v_pool, block_tables, seq_lens, *,
                       k_scale=None, v_scale=None,
                       sm_scale: float | None = None,
                       interpret: bool | None = None):
    """Decode attention straight off the paged pools.

    ``q``: ``(B, Hkv, G, D)`` grouped queries (``H = Hkv * G``; pass
    ``G = 1`` slices for plain MHA).  ``k_pool``/``v_pool``: the
    engine's resident ``(N, bs, Hkv, D)`` block pools — floating, or
    int8 with ``(N, bs, Hkv, 1)`` f32 scales (explicit ``k_scale``/
    ``v_scale`` or a :class:`..serve.quant.QuantTensor` per pool).
    ``block_tables``: ``(B, Bps)`` int32 physical ids (trash-padded
    tails fine); ``seq_lens``: ``(B,)`` int32 valid KV positions per
    slot.  Returns ``(B, Hkv, G, D)`` in ``q``'s dtype.

    On TPU this is the scalar-prefetch Pallas kernel (the gather
    disappears into block index maps); elsewhere it falls back to
    :func:`paged_decode_reference` — identical math on the engine's
    existing gather-then-mask lax path.  ``interpret=True`` forces the
    kernel through the Pallas interpreter (the CPU parity tests).
    """
    k_pool, k_scale = _split_quant(k_pool, k_scale)
    v_pool, v_scale = _split_quant(v_pool, v_scale)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k and v pools must agree on quantization")
    if interpret is None:
        if jax.default_backend() != "tpu":
            return paged_decode_reference(
                q, k_pool, v_pool, block_tables, seq_lens,
                k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)
        interpret = False

    B, Hkv, G, D = q.shape
    N, bs = k_pool.shape[:2]
    Bps = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)

    quantized = k_scale is not None
    kern = functools.partial(
        _decode_kernel if quantized else _drop_scales(_decode_kernel),
        sm_scale=sm_scale, block_size=bs, n_blocks=Bps)

    # index maps see (*grid_indices, *scalar_refs); the pool tiles chase
    # the block table through scalar-prefetch memory — this line is the
    # whole kernel, everything else is flash bookkeeping
    def pool_map(b, j, tables_ref, lens_ref):
        return (tables_ref[b, j], 0, 0, 0)

    def q_map(b, j, tables_ref, lens_ref):
        return (b, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, Hkv, G, D), q_map),
        pl.BlockSpec((1, bs, Hkv, D), pool_map),
        pl.BlockSpec((1, bs, Hkv, D), pool_map),
    ]
    args = [q, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, bs, Hkv, 1), pool_map),
                     pl.BlockSpec((1, bs, Hkv, 1), pool_map)]
        args += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Bps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hkv, G, D), q_map),
        scratch_shapes=[pltpu.VMEM((Hkv, G, 1), jnp.float32),
                        pltpu.VMEM((Hkv, G, 1), jnp.float32),
                        pltpu.VMEM((Hkv, G, D), jnp.float32)],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32), *args)


def paged_decode_reference(q, k_pool, v_pool, block_tables, seq_lens, *,
                           k_scale=None, v_scale=None,
                           sm_scale: float | None = None):
    """The existing lax path: gather the logical KV (``leaf[table]``,
    exactly :func:`..serve.paged.gather_slot`'s move), dequantize, mask
    to ``seq_lens`` and take one dense softmax — the semantics the
    kernel must reproduce and the off-TPU execution path."""
    k_pool, k_scale = _split_quant(k_pool, k_scale)
    v_pool, v_scale = _split_quant(v_pool, v_scale)
    B, Hkv, G, D = q.shape
    bs = k_pool.shape[1]
    Bps = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)

    def logical(pool, scale):
        got = pool[block_tables]                 # (B, Bps, bs, Hkv, D)
        got = got.reshape(B, Bps * bs, Hkv, D)
        if scale is not None:
            sc = scale[block_tables].reshape(B, Bps * bs, Hkv, 1)
            got = got.astype(jnp.float32) * sc
        return got

    k = logical(k_pool, k_scale)
    v = logical(v_pool, v_scale)
    s = jnp.einsum("bhgd,bthd->bhgt", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    kpos = jnp.arange(Bps * bs)[None, None, None, :]
    s = jnp.where(kpos < seq_lens[:, None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgt,bthd->bhgd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
